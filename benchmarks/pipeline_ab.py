"""A/B bench: the dispatch-ahead async pipeline vs the blocking chunk loop.

Runs the REAL ``mega_soup`` entry point — capture to the native ``.traj``
store AND per-chunk orbax checkpoints enabled — twice per repeat in ONE
process with the SAME shapes and seed: once with the default async
pipeline, once with ``--no-pipeline`` (the blocking reference).  Repeats
are INTERLEAVED (B, A, B, A, …) and the headline ``improvement_pct`` is
the MEDIAN OF PER-PAIR SPEEDUPS — adjacent runs share host load, so
box-level drift cancels pairwise (the per-side medians ride along).

Two claims, one JSON line:

  * **parity** — the warm-up pair's captured ``.traj`` streams are
    byte-identical and every per-chunk checkpoint restores to exactly
    equal arrays (the pipeline reorders WHEN host work runs, never WHAT
    is written).
  * **throughput** — end-to-end gens/sec (wall time around the whole
    ``run()``, warm jit cache) per mode, plus the pipelined runs' overlap
    attribution (``pipeline_*`` gauges: device-wait vs host-I/O seconds)
    so the improvement is explainable, not just asserted.

Usage:  python benchmarks/pipeline_ab.py [--size N] [--generations G]
            [--repeats R] [--train T] [--json-only]
"""

import argparse
import json
import os
import sys
import tempfile
import time

HERE = os.path.abspath(__file__)
REPO = os.path.dirname(os.path.dirname(HERE))
if REPO not in sys.path:  # runnable as `python benchmarks/pipeline_ab.py`
    sys.path.insert(0, REPO)


def _common_args(args, root, tag):
    return ["--size", str(args.size),
            "--generations", str(args.generations),
            "--checkpoint-every", str(args.checkpoint_every),
            "--capture-every", str(args.capture_every),
            "--train", str(args.train),
            "--seed", str(args.seed),
            "--root", os.path.join(root, tag)]


def _run(args, root, tag, pipelined):
    """One full mega_soup run; returns (run_dir, end-to-end seconds)."""
    from srnn_tpu.setups import REGISTRY

    argv = _common_args(args, root, tag)
    if not pipelined:
        argv.append("--no-pipeline")
    t0 = time.perf_counter()
    run_dir = REGISTRY["mega_soup"](argv)
    return run_dir, time.perf_counter() - t0


def _pipeline_event(run_dir):
    """The run's ``kind=pipeline`` overlap-attribution row (events.jsonl)."""
    with open(os.path.join(run_dir, "events.jsonl")) as f:
        rows = [json.loads(line) for line in f if line.strip()]
    for row in reversed(rows):
        if row.get("kind") == "pipeline":
            return row
    return None


def _file_bytes_equal(a, b):
    with open(a, "rb") as fa, open(b, "rb") as fb:
        return fa.read() == fb.read()


def _checkpoints_equal(dir_a, dir_b):
    """Every per-chunk checkpoint restores to exactly equal arrays."""
    import numpy as np
    import orbax.checkpoint as ocp

    names_a = sorted(d for d in os.listdir(dir_a) if d.startswith("ckpt-gen"))
    names_b = sorted(d for d in os.listdir(dir_b) if d.startswith("ckpt-gen"))
    if names_a != names_b or not names_a:
        return False, f"checkpoint sets differ: {names_a} vs {names_b}"
    with ocp.PyTreeCheckpointer() as ckptr:
        for name in names_a:
            ta = ckptr.restore(os.path.join(dir_a, name))
            tb = ckptr.restore(os.path.join(dir_b, name))
            if sorted(ta) != sorted(tb):
                return False, f"{name}: tree keys differ"
            for k in ta:
                if not np.array_equal(np.asarray(ta[k]), np.asarray(tb[k])):
                    return False, f"{name}: leaf {k!r} differs"
    return True, f"{len(names_a)} checkpoints restore identically"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    # default shape: a capture-heavy 32k-particle soup where per-frame
    # host transfers (24 device_get round trips in the blocking loop) and
    # the per-chunk orbax checkpoint are a large, steady fraction of the
    # chunk — the regime the pipeline exists for.  At toy scale (N~512)
    # there is nothing to hide and the snapshot/queue overhead shows up
    # as a small loss; crank --train to shift the balance toward device
    # compute instead
    p.add_argument("--size", type=int, default=32768)
    p.add_argument("--generations", type=int, default=24)
    p.add_argument("--checkpoint-every", type=int, default=4)
    p.add_argument("--capture-every", type=int, default=1)
    p.add_argument("--train", type=int, default=0,
                   help="imitation-SGD steps per attack (cranks device "
                        "compute relative to host I/O)")
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--repeats", type=int, default=5,
                   help="timed interleaved B/A pairs; improvement is the "
                        "median of per-pair speedups (adjacent runs share "
                        "host load, so drift cancels pairwise)")
    p.add_argument("--json-only", action="store_true",
                   help="suppress the human-readable summary")
    args = p.parse_args(argv)

    # measurement tool: stay off flaky tunnels unless the operator
    # overrides explicitly (must land before the first jax import)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import statistics

    with tempfile.TemporaryDirectory(prefix="srnn_pipeline_ab_") as root:
        # warm-up pair: pays the jit compiles once for both sides AND
        # provides the parity evidence
        dir_p, _ = _run(args, root, "warm_p", pipelined=True)
        dir_b, _ = _run(args, root, "warm_b", pipelined=False)
        traj_same = _file_bytes_equal(os.path.join(dir_p, "soup.traj"),
                                      os.path.join(dir_b, "soup.traj"))
        ckpt_same, ckpt_detail = _checkpoints_equal(dir_p, dir_b)

        timed = {"pipelined": [], "blocking": []}
        pair_speedups = []
        overlap = None
        for i in range(args.repeats):
            d, sp = _run(args, root, f"t{i}_p", pipelined=True)
            timed["pipelined"].append(sp)
            overlap = _pipeline_event(d) or overlap
            _, sb = _run(args, root, f"t{i}_b", pipelined=False)
            timed["blocking"].append(sb)
            pair_speedups.append(sb / sp)

    gps = {side: args.generations / statistics.median(times)
           for side, times in timed.items()}
    doc = {
        "bench": "pipeline_ab",
        "n": args.size,
        "generations": args.generations,
        "checkpoint_every": args.checkpoint_every,
        "capture_every": args.capture_every,
        "train": args.train,
        "repeats": args.repeats,
        "parity": {"traj_bytes_identical": traj_same,
                   "checkpoints_identical": ckpt_same,
                   "checkpoint_detail": ckpt_detail},
        "pipelined_gens_per_sec": round(gps["pipelined"], 3),
        "blocking_gens_per_sec": round(gps["blocking"], 3),
        # median of ADJACENT-pair speedups: each pair runs back-to-back
        # under the same host load, so box-level drift (which swings the
        # side medians by more than the effect on a shared machine)
        # cancels pairwise
        "improvement_pct": round(
            100 * (statistics.median(pair_speedups) - 1), 2),
        "pair_speedups": [round(r, 3) for r in pair_speedups],
        "pipelined_run_s": [round(s, 3) for s in timed["pipelined"]],
        "blocking_run_s": [round(s, 3) for s in timed["blocking"]],
    }
    if overlap is not None:
        doc["overlap"] = {k: overlap[k] for k in
                          ("chunks", "wall_s", "device_wait_s", "host_io_s",
                           "device_idle_bound_s", "overlap_ratio")
                          if k in overlap}
    print(json.dumps(doc), flush=True)
    if not args.json_only:
        print(f"# pipeline A/B (N={args.size}, G={args.generations}, "
              f"capture_every={args.capture_every}): "
              f"pipelined {doc['pipelined_gens_per_sec']:.2f} gens/s vs "
              f"blocking {doc['blocking_gens_per_sec']:.2f} gens/s "
              f"({doc['improvement_pct']:+.1f}%)", file=sys.stderr)
        print(f"# parity: traj bytes identical={traj_same}, "
              f"{ckpt_detail}", file=sys.stderr)
    return 0 if (traj_same and ckpt_same) else 1


if __name__ == "__main__":
    sys.exit(main())
