"""Micro-benchmark: compile-time, dispatch-overhead, and peak-memory rows
for the soup hot path, before/after the AOT + donation subsystem.

One JSON line of rows (plus ``telemetry``/``health``/``lineage``/
``fused``: the in-scan carries' dispatch overhead, ``spans``: the fleet
observatory's per-chunk span emission on top of ``metered.health``,
``trace_propagation``: the fleet-tracing header/journal/span work per
traced request on top of ``metered.health``, ``adaptive``: the
continuous-batching controller's per-dispatch turn on
top of ``metered.health``, and ``stacked``: the serve tenant-axis
amortization — K=8 stacked dispatch vs 8 solo dispatches — all on the
shared interleaved median-of-medians protocol; see their docstrings):

  * ``compile``: wall time of the soup hot path's BACKEND COMPILE (the
    generation step + the 100-generation chunk run, full dynamics) in a
    fresh process, cold persistent cache vs warm (``srnn_tpu.utils.aot``'s
    on-disk executable cache).  ``speedup`` is cold/warm — the factor a
    bench child or restarted mega-run no longer pays.
  * ``dispatch``: per-call overhead of dispatching the already-compiled
    step through the jit front end vs calling the AOT ``Compiled`` object
    directly (tiny population, so the delta is dominated by dispatch, not
    math).
  * ``memory``: ``memory_analysis()`` of the 1M-particle weightwise
    generation step, donated vs not.  With donation the population input
    aliases the output (``alias ≈ args``), i.e. generation N+1 rewrites
    generation N's buffers in place and no second population-sized output
    buffer exists; without donation the output is a fresh allocation on
    top of the argument.

Usage:  python benchmarks/micro_dispatch.py [--mega-size N] [--json-only]
The child stages re-exec this file (``--stage compile``).
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

HERE = os.path.abspath(__file__)
REPO = os.path.dirname(os.path.dirname(HERE))
if REPO not in sys.path:  # runnable as `python benchmarks/micro_dispatch.py`
    sys.path.insert(0, REPO)
_SENTINEL = "@@MICRO "

# the compile-row config: mega-soup dynamics at a compile-representative
# (shape-independent) population size
COMPILE_N = 8192
DISPATCH_N = 256
DISPATCH_CALLS = 200


def _config(n, train=0):
    from srnn_tpu.soup import SoupConfig
    from srnn_tpu.topology import Topology

    return SoupConfig(
        topo=Topology("weightwise", width=2, depth=2), size=n,
        attacking_rate=0.1, train=train, remove_divergent=True,
        remove_zero=True, layout="popmajor", respawn_draws="fused")


# ---------------------------------------------------------------------------
# child: one timed compile in a fresh process (the only way to measure the
# persistent cache — in-process recompiles hit jax's live jit cache)
# ---------------------------------------------------------------------------


def _child_compile() -> None:
    from srnn_tpu.soup import evolve_donated, evolve_step_donated
    from srnn_tpu.utils import aot

    aot.ensure_compilation_cache()  # dir comes from the parent's env
    # full dynamics (train=10) over the two entry points a mega-run chunk
    # actually dispatches: the programs whose compile time ate the
    # accelerator bench windows.  Summing both entries also smooths
    # machine-load variance out of the cold/warm ratio.
    cfg = _config(COMPILE_N, train=10)
    st = aot.abstract_soup_state(cfg)
    e1 = aot.aot_compile("micro.evolve_step.donated", evolve_step_donated,
                         (cfg, st))
    e2 = aot.aot_compile("micro.evolve.donated", evolve_donated, (cfg, st),
                         {"generations": 100})
    print(_SENTINEL + json.dumps(
        {"lower_s": e1.lower_s + e2.lower_s,
         "compile_s": e1.compile_s + e2.compile_s}), flush=True)


def _run_child(cache_dir: str, timeout: float = 600.0):
    env = dict(os.environ)
    env["JAX_COMPILATION_CACHE_DIR"] = cache_dir
    env.setdefault("JAX_PLATFORMS", "cpu")  # measurement tool: stay off
    # flaky tunnels unless the operator overrides explicitly
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, HERE, "--stage", "compile"],
                          stdout=subprocess.PIPE, timeout=timeout, env=env)
    for line in reversed(proc.stdout.decode(errors="replace").splitlines()):
        if line.startswith(_SENTINEL):
            return json.loads(line[len(_SENTINEL):])
    raise RuntimeError(
        f"compile child produced no result (rc={proc.returncode})")


# ---------------------------------------------------------------------------
# parent rows
# ---------------------------------------------------------------------------


def row_compile() -> dict:
    """Cold vs warm second-process compile of the soup step."""
    with tempfile.TemporaryDirectory(prefix="srnn_micro_cache_") as d:
        cold = _run_child(d)
        warm = _run_child(d)
    return {
        "row": "compile",
        "n": COMPILE_N,
        "cold_compile_s": round(cold["compile_s"], 4),
        "warm_compile_s": round(warm["compile_s"], 4),
        "lower_s": round(warm["lower_s"], 4),  # tracing is never cached
        "speedup": round(cold["compile_s"] / max(warm["compile_s"], 1e-9), 1),
    }


def row_dispatch() -> dict:
    """jit-front-end dispatch vs direct AOT-executable call, per step."""
    import jax

    from srnn_tpu.soup import evolve_step_donated, seed
    from srnn_tpu.utils import aot

    cfg = _config(DISPATCH_N)
    entry = aot.aot_compile("micro.dispatch.evolve_step",
                            evolve_step_donated,
                            (cfg, aot.abstract_soup_state(cfg)))

    def bench(invoke):
        # the donated step CONSUMES its input, so the warm-up call gets its
        # own throwaway state and the timed chain always rebinds
        invoke(seed(cfg, jax.random.key(1)))
        st = seed(cfg, jax.random.key(0))
        t0 = time.perf_counter()
        for _ in range(DISPATCH_CALLS):
            st, _ev = invoke(st)
        jax.block_until_ready(st.weights)
        return (time.perf_counter() - t0) / DISPATCH_CALLS

    jit_s = bench(lambda st: evolve_step_donated(cfg, st))
    aot_s = bench(entry.compiled)
    return {
        "row": "dispatch",
        "n": DISPATCH_N,
        "calls": DISPATCH_CALLS,
        "jit_us_per_call": round(jit_s * 1e6, 1),
        "aot_us_per_call": round(aot_s * 1e6, 1),
    }


def row_memory(mega_size: int) -> dict:
    """Static memory analysis of the mega-scale step, donated vs not —
    donation must leave NO second population-sized output buffer."""
    from srnn_tpu.soup import evolve_step, evolve_step_donated
    from srnn_tpu.utils import aot

    cfg = _config(mega_size)
    pop_bytes = mega_size * cfg.topo.num_weights * 4
    out = {"row": "memory", "n": mega_size, "population_bytes": pop_bytes}
    for tag, fn in (("plain", evolve_step), ("donated", evolve_step_donated)):
        # persistent=False: cache-deserialized executables report empty
        # memory stats, which would fake alias_bytes=0 on a warm machine
        ma = aot.aot_compile(f"micro.memory.{tag}", fn,
                             (cfg, aot.abstract_soup_state(cfg)),
                             persistent=False).compiled.memory_analysis()
        out[tag] = {
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "alias_bytes": int(ma.alias_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
        }
    out["donated_population_aliased"] = \
        out["donated"]["alias_bytes"] >= pop_bytes
    out["plain_extra_output_bytes"] = \
        out["plain"]["output_bytes"] - out["plain"]["alias_bytes"]
    return out


TELEMETRY_N = 2048
TELEMETRY_GENS = 50


def _interleaved_medians(fns, calls=20, passes=3):
    """Shared measurement protocol of every overhead row: the variants in
    ``fns`` (name -> zero-arg callable, each forcing completion via a
    scalar readback) run INTERLEAVED call-by-call, per-pass medians are
    taken, and each variant reports its MEDIAN-OF-MEDIANS plus the
    per-pass medians.

    Why interleaved + median-of-medians: on a shared host, back-to-back
    blocks drift by more than the effects being measured (observed ±10%
    block-to-block on idle-ish CPU; PR 5 recorded the BASELINE itself
    swinging 420-700ms session-to-session).  Interleaving puts every
    variant under the same instantaneous load, and — since round 6 — the
    PLAIN baseline is re-measured inside every row's passes, so rows are
    comparable within one session instead of against a baseline measured
    minutes earlier."""
    import statistics

    for _ in range(2):  # compile + warm every variant
        for f in fns.values():
            f()
    meds = {name: [] for name in fns}
    for _ in range(passes):
        ts = {name: [] for name in fns}
        for _ in range(calls):
            for name, f in fns.items():
                t0 = time.perf_counter()
                f()
                ts[name].append(time.perf_counter() - t0)
        for name in fns:
            meds[name].append(statistics.median(ts[name]))
    return {name: (statistics.median(m), m) for name, m in meds.items()}


def _overhead_row(row, fns, base, feature, calls=20, passes=3, extra=None):
    """One overhead row: every variant in ``fns`` measured interleaved
    (the carry-overhead rows ALWAYS include 'plain' — the unmetered chunk
    — as the in-row session baseline; the ``stacked`` row's in-row
    baseline is its solo8 variant); ``overhead_pct`` compares ``feature``
    vs ``base``."""
    res = _interleaved_medians(fns, calls, passes)
    out = {"row": row, "n": TELEMETRY_N, "generations": TELEMETRY_GENS,
           "calls": calls, "passes": passes}
    for name, (med, per_pass) in res.items():
        out[f"{name}_ms_per_chunk"] = round(med * 1e3, 3)
    base_s, base_meds = res[base]
    feat_s, feat_meds = res[feature]
    out["pass_overhead_pct"] = [
        round(100 * (f / b - 1), 2) for b, f in zip(base_meds, feat_meds)]
    out["overhead_pct"] = round(100 * (feat_s / base_s - 1), 2)
    if extra:
        out.update(extra)
    return out


def _chunk_fns():
    """The chunk-program variants the overhead rows sample from (each
    returns a closure whose scalar readback forces completion)."""
    import jax

    from srnn_tpu.soup import evolve, seed
    from srnn_tpu.telemetry.dynamics import seed_lineage

    cfg = _config(TELEMETRY_N)
    st = seed(cfg, jax.random.key(0))
    lin = seed_lineage(cfg.size)
    fcfg = cfg._replace(generation_impl="fused")

    def plain():
        s = evolve(cfg, st, generations=TELEMETRY_GENS)
        return float(s.next_uid)

    def metered():
        s, _m = evolve(cfg, st, generations=TELEMETRY_GENS, metrics=True)
        return float(s.next_uid)

    def health():
        s, _m, _h = evolve(cfg, st, generations=TELEMETRY_GENS,
                           metrics=True, health=True)
        return float(s.next_uid)

    def lineage():
        s, _m, _h, _lt = evolve(cfg, st, generations=TELEMETRY_GENS,
                                metrics=True, health=True, lineage=True,
                                lineage_state=lin, lineage_capacity=4096)
        return float(s.next_uid)

    def fused():
        s = evolve(fcfg, st, generations=TELEMETRY_GENS)
        return float(s.next_uid)

    return {"plain": plain, "metered": metered, "health": health,
            "lineage": lineage, "fused": fused}


def row_telemetry() -> dict:
    """Walltime overhead of the in-scan telemetry metrics carry
    (``metrics=True`` vs plain, acceptance bound <= ~2%), protocol per
    :func:`_interleaved_medians`."""
    fns = _chunk_fns()
    return _overhead_row("telemetry",
                         {"plain": fns["plain"], "metered": fns["metered"]},
                         base="plain", feature="metered")


def row_health() -> dict:
    """Walltime overhead of the flight recorder's in-scan HEALTH sentinel
    carry on top of the metered chunk (``metrics+health`` vs ``metrics``,
    acceptance bound <= ~5%); the plain baseline rides in the same passes
    for cross-row session comparison."""
    fns = _chunk_fns()
    return _overhead_row("health",
                         {"plain": fns["plain"], "metered": fns["metered"],
                          "health": fns["health"]},
                         base="metered", feature="health")


def row_lineage() -> dict:
    """Walltime overhead of the replication-dynamics lineage carry on top
    of the ``metered.health`` spelling (documented bound <= ~5%); plain
    baseline interleaved per the shared protocol."""
    fns = _chunk_fns()
    return _overhead_row("lineage",
                         {"plain": fns["plain"], "health": fns["health"],
                          "lineage": fns["lineage"]},
                         base="health", feature="lineage")


def row_spans() -> dict:
    """Walltime overhead of the fleet observatory's structured span
    emission on top of the ``metered.health`` chunk (documented bound
    <= ~5%): the ``spans`` variant runs the SAME chunk program and then
    emits the per-chunk span family (root + device_wait/host_io
    children) through a real file-backed event channel — proving
    ticket/chunk span emission is pure host work off the device hot
    path.  Plain baseline interleaved per the shared protocol."""
    import tempfile

    from srnn_tpu.telemetry.tracing import SpanStream

    fns = _chunk_fns()
    tmp = tempfile.NamedTemporaryFile(  # noqa: SIM115 - closed at exit
        mode="w", suffix=".jsonl", prefix="srnn_micro_spans_",
        delete=False)

    class _Events:
        def event(self, **fields):
            tmp.write(json.dumps(fields, default=str) + "\n")
            tmp.flush()

    stream = SpanStream(_Events(), trace_id="micro", process=0)
    health = fns["health"]

    def spans():
        value = health()
        end = stream.now()
        root = stream.emit("micro.chunk", end - 0.1, 0.1, generation=1,
                           generations=TELEMETRY_GENS)
        stream.emit("micro.device_wait", end - 0.1, 0.08, parent=root,
                    generation=1)
        stream.emit("micro.host_io", end - 0.02, 0.02, parent=root,
                    generation=1)
        return value

    try:
        return _overhead_row("spans",
                             {"plain": fns["plain"], "health": health,
                              "spans": spans},
                             base="health", feature="spans")
    finally:
        tmp.close()
        os.unlink(tmp.name)


def row_export() -> dict:
    """Walltime overhead of the live telemetry plane's per-chunk turn —
    registry snapshot into the history ring, metrics_history.jsonl
    append (flush-per-row), and alert-rule evaluation — on top of the
    ``metered.health`` chunk (documented bound <= ~5%, like the other
    host-side planes): the sample is pure host work off the device hot
    path.  Plain baseline interleaved per the shared protocol."""
    import tempfile

    from srnn_tpu.telemetry.alerts import AlertEngine, default_run_rules
    from srnn_tpu.telemetry.metrics import MetricsRegistry
    from srnn_tpu.telemetry.timeseries import MetricHistory

    fns = _chunk_fns()
    registry = MetricsRegistry()
    tmp = tempfile.NamedTemporaryFile(  # noqa: SIM115 - closed at exit
        mode="w", suffix=".jsonl", prefix="srnn_micro_export_",
        delete=False)
    tmp.close()
    history = MetricHistory(registry, capacity=512, path=tmp.name)
    engine = AlertEngine(default_run_rules(), registry, history)
    health = fns["health"]

    def export():
        value = health()
        # the gauge/counter churn a real chunk finisher performs before
        # its sample, so the snapshot is a representative size
        registry.counter("soup_generations_total",
                         help="generations").inc(TELEMETRY_GENS)
        registry.gauge("gens_per_sec", help="rate").set(
            123.0, stage="micro")
        registry.gauge("soup_health_nan_frac", help="nan").set(0.0)
        history.sample()
        engine.evaluate()
        return value

    try:
        return _overhead_row("export",
                             {"plain": fns["plain"], "health": health,
                              "export": export},
                             base="health", feature="export")
    finally:
        history.close()
        os.unlink(tmp.name)


def row_profile() -> dict:
    """Walltime overhead of the continuous profiling plane on the
    ``metered.health`` chunk (documented bound <= ~5%): the 50Hz sampler
    runs in ITS OWN daemon thread — the chunk pays only GIL contention
    with the frame walks plus the per-chunk gauge fold, never the
    sampling itself.  The profiled variant runs chunks with a live
    sampler + per-chunk ``update_gauges``; the baseline is the same
    chunk with no sampler thread."""
    from srnn_tpu.telemetry.metrics import MetricsRegistry
    from srnn_tpu.telemetry.profiler import SamplingProfiler

    fns = _chunk_fns()
    registry = MetricsRegistry()
    prof = SamplingProfiler(hz=50.0, ring_s=5.0).start()
    health = fns["health"]

    def profiled():
        value = health()
        prof.update_gauges(registry)
        return value

    try:
        return _overhead_row("profile",
                             {"plain": fns["plain"], "health": health,
                              "profile": profiled},
                             base="health", feature="profile")
    finally:
        prof.stop()


def row_archive() -> dict:
    """Walltime of folding one cross-run-observatory ingest pass into the
    per-chunk turn on top of the ``metered.health`` chunk (documented
    bound <= ~5%, expected ~0%): once the store exists, a pass over an
    unchanged results root is watermark ``stat`` calls only
    (``telemetry.archive`` re-ingest is O(new bytes)) — the longitudinal
    index stays off the hot path by construction.  Plain baseline
    interleaved per the shared protocol."""
    import shutil
    import tempfile

    from srnn_tpu.telemetry.archive import ingest

    fns = _chunk_fns()
    health = fns["health"]
    root = tempfile.mkdtemp(prefix="srnn_micro_archive_")
    run_dir = os.path.join(root, "exp-micro")
    os.makedirs(run_dir)
    with open(os.path.join(run_dir, "config.json"), "w") as f:
        json.dump({"n": TELEMETRY_N, "seed": 0}, f)
    with open(os.path.join(run_dir, "events.jsonl"), "w") as f:
        for i in range(64):
            f.write(json.dumps({"kind": "heartbeat", "stage": "micro",
                                "generation": i, "gens_per_sec": 100.0,
                                "t": float(i)}) + "\n")
    with open(os.path.join(run_dir, "meta.json"), "w") as f:
        json.dump({"name": "micro", "seed": 0, "wall_seconds": 1.0,
                   "error": None}, f)
    ingest(root)  # build the store; later passes are watermark no-ops

    def archive():
        value = health()
        ingest(root)
        return value

    try:
        return _overhead_row("archive",
                             {"plain": fns["plain"], "health": health,
                              "archive": archive},
                             base="health", feature="archive")
    finally:
        shutil.rmtree(root, ignore_errors=True)


def row_trace() -> dict:
    """Walltime overhead of fleet trace-context propagation on top of
    the ``metered.health`` chunk (documented bound <= ~5%): the
    ``trace`` variant runs the SAME chunk program and then performs the
    full host-side propagation work one traced request costs the serve
    path — mint a trace id, build the submit message with the trace
    header fields, build the journal row with them, and emit one
    admit-style span row through a real file-backed channel.  Everything
    here is host dict/string work off the device hot path; the A/B
    oracle (``--no-spans`` bitwise identity) already proves the device
    program never sees these fields.  Plain baseline interleaved per
    the shared protocol."""
    import itertools
    import tempfile

    from srnn_tpu.serve.client import mint_trace_id

    fns = _chunk_fns()
    tmp = tempfile.NamedTemporaryFile(  # noqa: SIM115 - closed at exit
        mode="w", suffix=".jsonl", prefix="srnn_micro_trace_",
        delete=False)
    health = fns["health"]
    span_ids = itertools.count(1)

    def trace():
        value = health()
        trace_id = mint_trace_id()
        msg = {"op": "submit", "kind": "fixpoint_density", "params": {},
               "tenant": "micro", "trace_id": trace_id,
               "parent_span": next(span_ids)}
        journal_row = {"e": "submit", "ticket": "t0", "kind": msg["kind"],
                       "params": {}, "tenant": "micro", "key": None,
                       "deadline_wall": None, "wall": 0.0,
                       "trace_id": trace_id,
                       "parent_span": msg["parent_span"]}
        row = {"kind": "span", "span": "serve.admit",
               "span_id": next(span_ids), "trace_id": trace_id,
               "remote_parent": msg["parent_span"], "ticket": "t0",
               "process": 0, "start_s": 0.0, "seconds": 0.0}
        tmp.write(json.dumps(journal_row) + "\n")
        tmp.write(json.dumps(row) + "\n")
        tmp.flush()
        return value

    try:
        return _overhead_row("trace_propagation",
                             {"plain": fns["plain"], "health": health,
                              "trace": trace},
                             base="health", feature="trace")
    finally:
        tmp.close()
        os.unlink(tmp.name)


#: groups per controller turn — wider than any real serve round (the
#: bench load legs run 1-2 spellings); overstating the fold keeps the
#: bound honest
ADAPTIVE_GROUPS = 8


def row_adaptive() -> dict:
    """Walltime overhead of the continuous-batching controller's
    per-dispatch turn — the ``window_s`` fold over the pending groups
    plus one ``observe_dispatch`` per retired group — on top of the
    ``metered.health`` chunk (documented bound <= ~5%, like the other
    host-side planes): the control law is pure dict arithmetic under a
    lock, off the device hot path, so the adaptive dispatcher costs
    (nearly) nothing over the ``--no-adaptive`` oracle per dispatch
    beyond the window it then chooses to sleep.  The turn alternates
    burning and clean rounds so both law branches (shrink and grow) are
    inside the measurement.  Plain baseline interleaved per the shared
    protocol."""
    import itertools

    from srnn_tpu.serve.controller import AdaptiveWindowController

    fns = _chunk_fns()
    ctrl = AdaptiveWindowController(ceiling_s=0.25, slo_p95_ms=500.0)
    groups = [("fixpoint_density", (16 * (i + 1), 16))
              for i in range(ADAPTIVE_GROUPS)]
    health = fns["health"]
    turn = itertools.count()

    def adaptive():
        value = health()
        ctrl.window_s(groups)
        t = next(turn)
        for i, g in enumerate(groups):
            ctrl.observe_dispatch(g, violations=int((t + i) % 3 == 0),
                                  completed=2)
        return value

    return _overhead_row("adaptive",
                         {"plain": fns["plain"], "health": health,
                          "adaptive": adaptive},
                         base="health", feature="adaptive",
                         extra={"groups": ADAPTIVE_GROUPS})


def row_fused() -> dict:
    """``generation_impl='fused'`` vs the phase chain at the micro config
    (same dynamics, same draws).  On Mosaic backends this measures the
    megakernel's dispatch/glue win; on non-Mosaic backends the fused
    spelling IS the phase-chain program (bit-identical XLA fallback), so
    the row should read ~0% and anything beyond is pure cache/session
    noise — the in-row plain baseline makes that visible."""
    from srnn_tpu.ops.pallas_ww import native_mosaic_backend

    fns = _chunk_fns()
    return _overhead_row(
        "fused", {"plain": fns["plain"], "fused": fns["fused"]},
        base="plain", feature="fused",
        extra={"mosaic_kernel": native_mosaic_backend()})


def row_int8() -> dict:
    """``population_dtype='int8'`` vs the f32 chunk at the micro config
    (same dynamics, same draws — int8 quantizes ONCE per generation at
    the same point both spellings share).  Measures the per-generation
    quantize/dequantize tax next to the 4x storage win; informational
    like every overhead row (at mega shapes the tax amortizes against
    memory bandwidth — bench.py's leg is the authoritative number)."""
    import jax

    from srnn_tpu.soup import evolve, seed

    cfg = _config(TELEMETRY_N)
    icfg = cfg._replace(population_dtype="int8")
    st = seed(cfg, jax.random.key(0))
    ist = seed(icfg, jax.random.key(0))

    def plain():
        s = evolve(cfg, st, generations=TELEMETRY_GENS)
        return float(s.next_uid)

    def int8():
        s = evolve(icfg, ist, generations=TELEMETRY_GENS)
        return float(s.next_uid)

    return _overhead_row("int8", {"plain": plain, "int8": int8},
                         base="plain", feature="int8")


#: run length the autotune grid cost amortizes over: a 10k-generation
#: mega run at the default --checkpoint-every=20 dispatches ~500 chunks,
#: and the grid is paid once per (shape, backend) key per CACHE lifetime
#: (tuning.json memo-hits every later run)
AUTOTUNE_NOMINAL_CHUNKS = 500


def row_autotune() -> dict:
    """The block autotuner's two costs on the shared protocol:

      * per-dispatch: the public ``apply_chain_blocked`` wrapper's
        tuning-table lookup vs an explicit-block call (same compiled
        program; measures pure host lookup/indirection — should read ~0%)
      * one-time: the candidate-grid measurement wall (``grid_s``),
        reported amortized over a nominal 500-chunk run
        (``amortized_over_run_pct``, documented bound <= ~5%; the grid is
        ~20 dispatches of the measured shape, so this holds by
        construction for any run past ~400 chunks — and later runs pay
        ZERO, the tuning.json memo)."""
    import jax

    from srnn_tpu import autotune, init_population
    from srnn_tpu.ops.pallas_generation import (_apply_chain_blocked,
                                                apply_chain_blocked)
    from srnn_tpu.topology import Topology

    topo = Topology("weightwise", width=2, depth=2)
    n, steps = TELEMETRY_N, 40
    wT = (init_population(topo, jax.random.key(0), n) * 0.05).T

    def plain():
        out = _apply_chain_blocked(topo, wT, steps, block=min(2048, n))
        return float(out.sum())

    def tuned():
        out = apply_chain_blocked(topo, wT, steps)
        return float(out.sum())

    out = _overhead_row("autotune", {"plain": plain, "autotune": tuned},
                        base="plain", feature="autotune")
    out["n"], out["generations"] = n, steps
    # the one-time grid wall, measured directly (bypassing tuning.json so
    # a memo hit cannot fake a zero)
    cands = tuple(min(b, n) for b in autotune.APPLY_CHAIN_CANDIDATES)

    def grid_run(block):
        jax.block_until_ready(_apply_chain_blocked(topo, wT, steps,
                                                   block=block))

    t0 = time.perf_counter()
    autotune._measure_walls(grid_run, cands)
    grid_s = time.perf_counter() - t0
    # amortization denominator: the REAL chunk program a mega run
    # dispatches (the telemetry rows' plain chunk), not the apply chain —
    # the grid is paid once per cache lifetime, against a whole run
    import statistics

    chunk = _chunk_fns()["plain"]
    chunk()  # compile + warm
    t_ch = []
    for _ in range(3):
        t0 = time.perf_counter()
        chunk()
        t_ch.append(time.perf_counter() - t0)
    chunk_s = statistics.median(t_ch)
    run_s = chunk_s * AUTOTUNE_NOMINAL_CHUNKS
    out["grid_s"] = round(grid_s, 3)
    out["chunk_s"] = round(chunk_s, 3)
    out["nominal_run_chunks"] = AUTOTUNE_NOMINAL_CHUNKS
    out["amortized_over_run_pct"] = round(100 * grid_s / max(run_s, 1e-9),
                                          2)
    return out


STACKED_K = 8
#: tiny-population shape, deliberately: the service's clientele is the
#: paper's experiment suite (soups of 10-20), where per-dispatch overhead
#: is a first-order cost — at mega shapes the stacked win trends to 1x
#: (compute dominates) and the interesting amortization (process startup
#: + compile) is bench.py's serve leg, not this row
STACKED_N = 64
STACKED_GENS = 20


def row_stacked() -> dict:
    """K=8 tenant-stacked dispatch (``serve.tenant.evolve_stacked``) vs 8
    sequential solo dispatches of the same 8 soups — the experiment
    service's amortization win, measured on the shared interleaved-medians
    protocol.  Row-major config (the tenant axis's bitwise envelope);
    ``per_tenant`` numbers are the amortized cost of one tenant's chunk
    under each regime."""
    import jax
    import jax.numpy as jnp

    from srnn_tpu.serve.tenant import evolve_stacked, stack_tenants
    from srnn_tpu.soup import SoupConfig, evolve, seed
    from srnn_tpu.topology import Topology

    cfg = SoupConfig(
        topo=Topology("weightwise", width=2, depth=2), size=STACKED_N,
        attacking_rate=0.1, remove_divergent=True, remove_zero=True)
    states = [seed(cfg, jax.random.key(t)) for t in range(STACKED_K)]
    stacked = stack_tenants(states)

    def solo8():
        acc = 0.0
        for st in states:
            s = evolve(cfg, st, generations=STACKED_GENS)
            acc += float(s.next_uid)
        return acc

    def stacked8():
        s = evolve_stacked(cfg, stacked, generations=STACKED_GENS)
        return float(jnp.sum(s.next_uid))

    out = _overhead_row("stacked", {"solo8": solo8, "stacked": stacked8},
                        base="solo8", feature="stacked",
                        extra={"k": STACKED_K})
    out["n"] = STACKED_N
    out["generations"] = STACKED_GENS
    out["solo_per_tenant_ms"] = round(out["solo8_ms_per_chunk"]
                                      / STACKED_K, 3)
    out["stacked_per_tenant_ms"] = round(out["stacked_ms_per_chunk"]
                                         / STACKED_K, 3)
    out["amortization_x"] = round(out["solo8_ms_per_chunk"]
                                  / max(out["stacked_ms_per_chunk"], 1e-9),
                                  2)
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--stage", default=None, help=argparse.SUPPRESS)
    p.add_argument("--mega-size", type=int, default=1_000_000,
                   help="population size of the memory row")
    p.add_argument("--json-only", action="store_true",
                   help="suppress the human-readable summary")
    args = p.parse_args(argv)

    if args.stage == "compile":
        _child_compile()
        return 0

    rows = [row_compile(), row_dispatch(), row_memory(args.mega_size),
            row_telemetry(), row_health(), row_lineage(), row_spans(),
            row_export(), row_profile(), row_trace(), row_adaptive(),
            row_fused(), row_int8(), row_autotune(), row_archive(),
            row_stacked()]
    doc = {"bench": "micro_dispatch", "rows": rows}
    print(json.dumps(doc), flush=True)
    if not args.json_only:
        (c, d, m, t, h, l, sp, ex, pf, tr, ad, fu, i8, au, ar,
         sk) = rows
        print(f"# compile(N={c['n']}): cold {c['cold_compile_s']:.2f}s -> "
              f"warm {c['warm_compile_s']:.2f}s ({c['speedup']}x via "
              "persistent cache)", file=sys.stderr)
        print(f"# dispatch(N={d['n']}): jit {d['jit_us_per_call']:.0f}us "
              f"vs aot {d['aot_us_per_call']:.0f}us per call",
              file=sys.stderr)
        print(f"# memory(N={m['n']}): donated aliases "
              f"{m['donated']['alias_bytes']} B of args "
              f"(population={m['population_bytes']} B, aliased="
              f"{m['donated_population_aliased']}); plain allocates "
              f"{m['plain_extra_output_bytes']} B of fresh outputs",
              file=sys.stderr)
        print(f"# telemetry(N={t['n']}, G={t['generations']}): metered "
              f"{t['metered_ms_per_chunk']:.1f}ms vs plain "
              f"{t['plain_ms_per_chunk']:.1f}ms per chunk "
              f"({t['overhead_pct']:+.1f}% overhead)", file=sys.stderr)
        print(f"# health(N={h['n']}, G={h['generations']}): +sentinels "
              f"{h['health_ms_per_chunk']:.1f}ms vs metered "
              f"{h['metered_ms_per_chunk']:.1f}ms per chunk "
              f"({h['overhead_pct']:+.1f}% overhead)", file=sys.stderr)
        print(f"# lineage(N={l['n']}, G={l['generations']}): +dynamics "
              f"{l['lineage_ms_per_chunk']:.1f}ms vs metered.health "
              f"{l['health_ms_per_chunk']:.1f}ms per chunk "
              f"({l['overhead_pct']:+.1f}% overhead)", file=sys.stderr)
        print(f"# spans(N={sp['n']}, G={sp['generations']}): +span rows "
              f"{sp['spans_ms_per_chunk']:.1f}ms vs metered.health "
              f"{sp['health_ms_per_chunk']:.1f}ms per chunk "
              f"({sp['overhead_pct']:+.1f}% overhead)", file=sys.stderr)
        print(f"# export(N={ex['n']}, G={ex['generations']}): +live plane "
              f"{ex['export_ms_per_chunk']:.1f}ms vs metered.health "
              f"{ex['health_ms_per_chunk']:.1f}ms per chunk "
              f"({ex['overhead_pct']:+.1f}% overhead)", file=sys.stderr)
        print(f"# profile(N={pf['n']}, G={pf['generations']}): +50Hz "
              f"sampler {pf['profile_ms_per_chunk']:.1f}ms vs "
              f"metered.health {pf['health_ms_per_chunk']:.1f}ms per "
              f"chunk ({pf['overhead_pct']:+.1f}% overhead)",
              file=sys.stderr)
        print(f"# trace(N={tr['n']}, G={tr['generations']}): +propagation "
              f"{tr['trace_ms_per_chunk']:.1f}ms vs metered.health "
              f"{tr['health_ms_per_chunk']:.1f}ms per chunk "
              f"({tr['overhead_pct']:+.1f}% overhead)", file=sys.stderr)
        print(f"# adaptive(N={ad['n']}, G={ad['generations']}, "
              f"groups={ad['groups']}): +controller turn "
              f"{ad['adaptive_ms_per_chunk']:.1f}ms vs metered.health "
              f"{ad['health_ms_per_chunk']:.1f}ms per chunk "
              f"({ad['overhead_pct']:+.1f}% overhead)", file=sys.stderr)
        print(f"# fused(N={fu['n']}, G={fu['generations']}): "
              f"{fu['fused_ms_per_chunk']:.1f}ms vs phases "
              f"{fu['plain_ms_per_chunk']:.1f}ms per chunk "
              f"({fu['overhead_pct']:+.1f}%, "
              f"mosaic_kernel={fu['mosaic_kernel']})", file=sys.stderr)
        print(f"# int8(N={i8['n']}, G={i8['generations']}): "
              f"{i8['int8_ms_per_chunk']:.1f}ms vs f32 "
              f"{i8['plain_ms_per_chunk']:.1f}ms per chunk "
              f"({i8['overhead_pct']:+.1f}% quantize/dequant tax)",
              file=sys.stderr)
        print(f"# autotune(N={au['n']}, steps={au['generations']}): "
              f"lookup {au['autotune_ms_per_chunk']:.1f}ms vs explicit "
              f"{au['plain_ms_per_chunk']:.1f}ms "
              f"({au['overhead_pct']:+.1f}%); grid {au['grid_s']:.2f}s "
              f"= {au['amortized_over_run_pct']:.1f}% of a "
              f"{au['nominal_run_chunks']}-chunk run", file=sys.stderr)
        print(f"# archive(N={ar['n']}, G={ar['generations']}): +re-ingest "
              f"{ar['archive_ms_per_chunk']:.1f}ms vs metered.health "
              f"{ar['health_ms_per_chunk']:.1f}ms per chunk "
              f"({ar['overhead_pct']:+.1f}% overhead)", file=sys.stderr)
        print(f"# stacked(K={sk['k']}, N={sk['n']}, G={sk['generations']}): "
              f"one stacked dispatch {sk['stacked_ms_per_chunk']:.1f}ms vs "
              f"8 solo dispatches {sk['solo8_ms_per_chunk']:.1f}ms "
              f"({sk['amortization_x']}x; per tenant "
              f"{sk['stacked_per_tenant_ms']:.2f}ms vs "
              f"{sk['solo_per_tenant_ms']:.2f}ms)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
