"""Measure the popmajor TRAIN phase for the configs the Pallas SGD kernel
fences out, against the fenced weightwise-linear case.

VERDICT r4 item 6: ``train_impl='pallas'`` is fenced to weightwise /
linear / sequential / P<=64 (``soup.py:324-349``).  Is that fence leaving
>2x on the table anywhere?  This harness times a train-only soup
generation (attack/learn_from off, train=10 — isolating the batch-1
sequential SGD chain plus respawn, reference ``network.py:613-617``
semantics) at the mega-soup scale for:

  ww-linear/pallas     the fused VMEM kernel (the yardstick)
  ww-linear/xla        same math under the XLA scan
  ww-sigmoid/xla       fenced out: nonlinear backward
  aggregating/xla      fenced out: k-vector forward (popmajor_kvec path)
  fft/xla              fenced out: FFT round trip per epoch
  recurrent/xla        fenced out: sequential-in-P scan (popmajor_rnn path)

Output: one JSON line per config with per-particle-generation cost; the
decision rule from the VERDICT ("extend the kernel if any fenced-out case
is >2x off the weightwise-pallas per-particle cost, else document the
non-goal") reads straight off the ``x_vs_ww_pallas`` field.
"""

import argparse
import json
import os
import sys
import time

import jax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from srnn_tpu import Topology
from srnn_tpu.soup import SoupConfig, evolve, seed

CONFIGS = (
    ("ww-linear/pallas", Topology("weightwise", width=2, depth=2), "pallas"),
    ("ww-linear/xla", Topology("weightwise", width=2, depth=2), "xla"),
    ("ww-sigmoid/xla",
     Topology("weightwise", width=2, depth=2, activation="sigmoid"), "xla"),
    ("aggregating/xla", Topology("aggregating", width=2, depth=2), "xla"),
    ("fft/xla", Topology("fft", width=2, depth=2), "xla"),
    ("recurrent/xla", Topology("recurrent", width=2, depth=2), "xla"),
)


def bench_config(name, topo, train_impl, n, generations, repeats):
    cfg = SoupConfig(
        topo=topo, size=n, attacking_rate=-1.0, learn_from_rate=-1.0,
        train=10, remove_divergent=True, remove_zero=True,
        layout="popmajor", train_impl=train_impl)
    state = seed(cfg, jax.random.key(0))

    def run(s):
        out = evolve(cfg, s, generations=generations)
        return float(out.weights.sum())  # scalar readback = real sync on axon

    run(state)  # compile + settle
    t0 = time.perf_counter()
    for _ in range(repeats):
        run(state)
    dt = (time.perf_counter() - t0) / repeats
    return {
        "metric": "train-phase gens/sec", "config": name,
        "particles": n, "generations": generations, "train": 10,
        "value": round(generations / dt, 3),
        "ns_per_particle_generation": round(dt / generations / n * 1e9, 2),
        "unit": "generations/s",
    }


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=1_000_000)
    p.add_argument("--generations", type=int, default=20)
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--configs", nargs="*",
                   choices=[c[0] for c in CONFIGS],
                   default=[c[0] for c in CONFIGS])
    args = p.parse_args(argv)

    import os

    from srnn_tpu.utils.backend import ensure_backend
    platform, _ = ensure_backend(retries=3, sleep_s=10.0, fallback_cpu=False)
    if platform == "cpu" and int(os.environ.get("SRNN_REQUIRE_TPU", "0")):
        print(json.dumps({"error": f"SRNN_REQUIRE_TPU: live platform is "
                                   f"{platform!r}"}), flush=True)
        raise SystemExit(3)

    rows = []
    for name, topo, impl in CONFIGS:
        if name not in args.configs:
            continue
        row = bench_config(name, topo, impl, args.n,
                           args.generations, args.repeats)
        row["platform"] = platform
        rows.append(row)
        print(json.dumps(row), flush=True)
    yard = next((r for r in rows if r["config"] == "ww-linear/pallas"), None)
    if yard:
        for r in rows:
            r["x_vs_ww_pallas"] = round(
                r["ns_per_particle_generation"]
                / yard["ns_per_particle_generation"], 2)
        print(json.dumps({"summary": {
            r["config"]: r["x_vs_ww_pallas"] for r in rows}}), flush=True)


if __name__ == "__main__":
    main()
