"""Measure the popmajor TRAIN phase per variant: fused Pallas kernel vs
the XLA scan path.

History: VERDICT r4 item 6 asked whether the then weightwise-linear-only
kernel fence left >2x on the table; the first round-5 TPU campaign
answered yes everywhere (recurrent 118x, ww-sigmoid 11.5x, fft 2.9x,
aggregating 2.4x), so the kernels now cover every variant
(``ops/pallas_{ww,rnn,kvec}_train.py``) and this harness times BOTH impls
for each.  Workload: a train-only soup generation (attack/learn_from off,
train=10 — isolating the batch-1 SGD chain plus respawn, reference
``network.py:613-617`` semantics) at the mega-soup scale.

Output: one JSON line per config with per-particle-generation cost;
``x_vs_ww_pallas`` is each row's per-particle cost relative to the
weightwise-linear kernel yardstick.
"""

import argparse
import json
import os
import sys
import time

import jax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from srnn_tpu import Topology
from srnn_tpu.soup import SoupConfig, evolve, seed

_WW = Topology("weightwise", width=2, depth=2)
_WWSIG = Topology("weightwise", width=2, depth=2, activation="sigmoid")
_AGG = Topology("aggregating", width=2, depth=2)
_FFT = Topology("fft", width=2, depth=2)
_RNN = Topology("recurrent", width=2, depth=2)

CONFIGS = (
    ("ww-linear/pallas", _WW, "pallas"),
    ("ww-linear/xla", _WW, "xla"),
    ("ww-sigmoid/pallas", _WWSIG, "pallas"),
    ("ww-sigmoid/xla", _WWSIG, "xla"),
    ("aggregating/pallas", _AGG, "pallas"),
    ("aggregating/xla", _AGG, "xla"),
    ("fft/pallas", _FFT, "pallas"),
    ("fft/xla", _FFT, "xla"),
    ("recurrent/pallas", _RNN, "pallas"),
    ("recurrent/xla", _RNN, "xla"),
)


def bench_config(name, topo, train_impl, n, generations, repeats):
    cfg = SoupConfig(
        topo=topo, size=n, attacking_rate=-1.0, learn_from_rate=-1.0,
        train=10, remove_divergent=True, remove_zero=True,
        layout="popmajor", train_impl=train_impl)
    state = seed(cfg, jax.random.key(0))

    def run(s):
        out = evolve(cfg, s, generations=generations)
        return float(out.weights.sum())  # scalar readback = real sync on axon

    run(state)  # compile + settle
    t0 = time.perf_counter()
    for _ in range(repeats):
        run(state)
    dt = (time.perf_counter() - t0) / repeats
    return {
        "metric": "train-phase gens/sec", "config": name,
        "particles": n, "generations": generations, "train": 10,
        "value": round(generations / dt, 3),
        "ns_per_particle_generation": round(dt / generations / n * 1e9, 2),
        "unit": "generations/s",
    }


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=1_000_000)
    p.add_argument("--generations", type=int, default=20)
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--configs", nargs="*",
                   choices=[c[0] for c in CONFIGS],
                   default=[c[0] for c in CONFIGS])
    args = p.parse_args(argv)

    import os

    from srnn_tpu.utils.backend import ensure_backend
    platform, _ = ensure_backend(retries=3, sleep_s=10.0, fallback_cpu=False)
    if platform == "cpu" and int(os.environ.get("SRNN_REQUIRE_TPU", "0")):
        print(json.dumps({"error": f"SRNN_REQUIRE_TPU: live platform is "
                                   f"{platform!r}"}), flush=True)
        raise SystemExit(3)

    rows = []
    for name, topo, impl in CONFIGS:
        if name not in args.configs:
            continue
        row = bench_config(name, topo, impl, args.n,
                           args.generations, args.repeats)
        row["platform"] = platform
        rows.append(row)
        print(json.dumps(row), flush=True)
    yard = next((r for r in rows if r["config"] == "ww-linear/pallas"), None)
    if yard:
        for r in rows:
            r["x_vs_ww_pallas"] = round(
                r["ns_per_particle_generation"]
                / yard["ns_per_particle_generation"], 2)
        print(json.dumps({"summary": {
            r["config"]: r["x_vs_ww_pallas"] for r in rows}}), flush=True)


if __name__ == "__main__":
    main()
