"""Opportunistic TPU capture: grab real-TPU numbers whenever the tunnel is
healthy, not only at end-of-round bench time.

Round-4 postmortem (VERDICT r4, "What's missing" #2): every perf lever of
that round went TPU-unmeasured because the tunneled backend was wedged at
the one moment the driver ran ``bench.py``.  This harness decouples
measurement from that moment: invoke it repeatedly throughout a build
session (cheap when the tunnel is down — one bounded probe subprocess);
on ANY healthy window it captures the full TPU row set and appends
timestamped JSONL evidence either way.

Usage:
  python benchmarks/opportunistic.py --probe-only      # log tunnel state
  python benchmarks/opportunistic.py                   # probe, then rows
  python benchmarks/opportunistic.py --rows kernel soup_apply
  python benchmarks/opportunistic.py --log PATH        # default
                                                       # results_tpu/opportunistic_log.jsonl

Design rules (inherited from ``bench.py``'s round-4 rework):
  * the parent process NEVER imports jax — it cannot wedge;
  * every child is a fresh subprocess with its own timeout (tunnel init
    luck is per-process), killed on hang, its last JSON stdout line kept;
  * children must come up on the accelerator or die: ``SRNN_REQUIRE_TPU=1``
    makes the probe child exit nonzero on a CPU backend, so a silent
    axon→cpu fallback can never masquerade as a TPU measurement.

The row set covers every round-4/5 perf lever that lacks TPU evidence
(workload: reference ``soup.py:51-87`` at BASELINE.json scale):
  kernel          bench.py Pallas apply kernel @ N=1M
  soup_apply      apply-only gens/s, rowmajor vs popmajor
  soup_fused      apply-only popmajor, respawn_draws fused vs perparticle
  soup_full       full dynamics popmajor, train_impl xla vs pallas
  soup_mixed      heterogeneous multisoup: rowmajor, popmajor, popmajor +
                  per-type fused SGD kernels, + fused recurrent-attacker
                  forward (round 5)
  soup_rnn_apply  recurrent apply-only soup: XLA serial scan vs the fused
                  VMEM forward (round 5)
  train_generality popmajor train phase per variant, fused Pallas kernel
                  vs XLA scan (reference train semantics:
                  ``network.py:613-617``)
  profile         TPU phase attribution of the apply-only and
                  full-dynamics generations (``profile_soup.py``)
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_LOG = os.path.join(REPO, "results_tpu", "opportunistic_log.jsonl")

PROBE_TIMEOUT_S = 240.0
ROW_TIMEOUT_S = 1500.0

_PROBE_SRC = r"""
import os, sys, time
t0 = time.time()
from srnn_tpu.utils.backend import ensure_backend
platform, fell_back = ensure_backend(retries=2, sleep_s=5.0,
                                     fallback_cpu=False)
import jax
import jax.numpy as jnp
x = jnp.ones((256, 256), jnp.float32)
val = float((x @ x)[0, 0])  # forces a real device round-trip
ok = platform not in ("cpu",)
print(f"@@PROBE {platform} {val} {time.time()-t0:.1f}", flush=True)
sys.exit(0 if ok or not int(os.environ.get("SRNN_REQUIRE_TPU", "0")) else 3)
"""


# The axon PJRT plugin registers via a sitecustomize on this path
# (``SRNN_AXON_SITE`` overrides the conventional default for hosts that
# mount the tunnel elsewhere).  Children need it on PYTHONPATH to reach
# the TPU; the PARENT should be started WITHOUT it
# (``PYTHONPATH= python benchmarks/opportunistic.py``), because that
# sitecustomize dials the relay at interpreter startup and a wedged
# tunnel then blocks the parent in recvfrom() before main() ever runs
# (observed round 5).  _spawn composes the child PYTHONPATH explicitly —
# repo root first (children import srnn_tpu; ~10 rows were lost in the
# round-5 capture window to a missing repo root) — so it does not matter
# what the parent was started with.
_AXON_SITE = os.environ.get("SRNN_AXON_SITE", "/root/.axon_site")


def _spawn(cmd, timeout_s, extra_env=None):
    """Run one child; return (status, seconds, stdout_lines, stderr_tail)."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # let the axon plugin register
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_opportunistic_cache")
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + ([_AXON_SITE] if os.path.isdir(_AXON_SITE) else []))
    if extra_env:
        env.update(extra_env)
    t0 = time.time()
    try:
        proc = subprocess.run(
            cmd, cwd=REPO, env=env, timeout=timeout_s,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        status = "ok" if proc.returncode == 0 else f"exit:{proc.returncode}"
        out, err = proc.stdout, proc.stderr
    except subprocess.TimeoutExpired as e:
        status = "timeout"
        out = (e.stdout or b"").decode() if isinstance(e.stdout, bytes) \
            else (e.stdout or "")
        err = (e.stderr or b"").decode() if isinstance(e.stderr, bytes) \
            else (e.stderr or "")
    return status, time.time() - t0, out.splitlines(), err[-2000:]


def probe():
    """Bounded tunnel-health probe in a throwaway child."""
    status, dt, lines, err = _spawn(
        [sys.executable, "-c", _PROBE_SRC], PROBE_TIMEOUT_S,
        {"SRNN_REQUIRE_TPU": "1"})
    platform = None
    for line in lines:
        if line.startswith("@@PROBE "):
            platform = line.split()[1]
    return {"event": "probe", "status": status, "platform": platform,
            "seconds": round(dt, 1), "stderr": err if status != "ok" else ""}


def _json_rows(lines):
    rows = []
    for line in lines:
        line = line.strip()
        if line.startswith("{"):
            try:
                rows.append(json.loads(line))
            except ValueError:
                pass
    return rows


def _soup_cmd(preset, **kw):
    cmd = [sys.executable, "benchmarks/soup_throughput.py",
           "--preset", preset, "--sizes", str(kw.pop("n", 1_000_000)),
           "--generations", str(kw.pop("generations", 50)),
           "--repeats", str(kw.pop("repeats", 3))]
    for flag, val in kw.items():
        cmd += [f"--{flag.replace('_', '-')}", str(val)]
    return cmd


ROWS = {
    "kernel": [
        ([sys.executable, "bench.py"],
         {"SRNN_BENCH_DEADLINE_S": "1200", "SRNN_BENCH_RAMP_TIMEOUT_S": "240",
          "SRNN_BENCH_FULL_TIMEOUT_S": "600"}),
    ],
    "soup_apply": [
        (_soup_cmd("apply", layout="rowmajor"), None),
        (_soup_cmd("apply", layout="popmajor"), None),
    ],
    "soup_fused": [
        (_soup_cmd("apply", layout="popmajor", respawn_draws="fused"), None),
        (_soup_cmd("apply", layout="popmajor", respawn_draws="fused",
                   attack_impl="compact"), None),
    ],
    "soup_full": [
        (_soup_cmd("full", layout="popmajor", train_impl="xla"), None),
        (_soup_cmd("full", layout="popmajor", train_impl="pallas"), None),
        (_soup_cmd("full", layout="popmajor", train_impl="pallas",
                   attack_impl="compact", learn_from_impl="compact"), None),
    ],
    "soup_mixed": [
        (_soup_cmd("mixed", layout="rowmajor"), None),
        (_soup_cmd("mixed", layout="popmajor"), None),
        # round 5: per-type fused SGD kernels (incl. the recurrent member
        # whose serial train scan dominated the 2.48 gens/s plateau),
        # then + the fused recurrent-attacker forward on top
        (_soup_cmd("mixed", layout="popmajor", train_impl="pallas"), None),
        (_soup_cmd("mixed", layout="popmajor", train_impl="pallas",
                   apply_impl="pallas"), None),
    ],
    "soup_rnn_apply": [
        # round 5: the recurrent apply-only soup, XLA serial scan vs the
        # fused VMEM forward (ops/pallas_rnn_apply.py)
        (_soup_cmd("apply", layout="popmajor", topo="recurrent"), None),
        (_soup_cmd("apply", layout="popmajor", topo="recurrent",
                   apply_impl="pallas"), None),
    ],
    "train_generality": [
        ([sys.executable, "benchmarks/train_generality.py"], None),
    ],
    "profile": [
        # TPU phase attribution of the apply-only generation (the CPU
        # profile that motivated the round-5 compact phases mis-transferred
        # — next-round levers need the TPU-side decomposition)
        ([sys.executable, "benchmarks/profile_soup.py", "--preset", "apply"],
         None),
        ([sys.executable, "benchmarks/profile_soup.py", "--preset", "full"],
         None),
    ],
}


def append_log(log_path, record):
    os.makedirs(os.path.dirname(log_path), exist_ok=True)
    record = {"ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
              **record}
    with open(log_path, "a") as fh:
        fh.write(json.dumps(record) + "\n")
    return record


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--log", default=DEFAULT_LOG)
    p.add_argument("--probe-only", action="store_true")
    p.add_argument("--rows", nargs="*", choices=sorted(ROWS),
                   default=sorted(ROWS))
    p.add_argument("--row-timeout", type=float, default=ROW_TIMEOUT_S)
    args = p.parse_args(argv)

    pr = append_log(args.log, probe())
    print(json.dumps(pr), flush=True)
    if args.probe_only or pr["status"] != "ok":
        return 0 if pr["status"] == "ok" else 1

    failures = 0
    for row in args.rows:
        for cmd, extra_env in ROWS[row]:
            env = {"SRNN_REQUIRE_TPU": "1", **(extra_env or {})}
            status, dt, lines, err = _spawn(cmd, args.row_timeout, env)
            rec = append_log(args.log, {
                "event": "capture", "row": row, "cmd": " ".join(cmd[1:]),
                "status": status, "seconds": round(dt, 1),
                "results": _json_rows(lines),
                "stderr": err if status != "ok" else ""})
            print(json.dumps(rec), flush=True)
            failures += status != "ok"
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
