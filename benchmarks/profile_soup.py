"""Phase breakdown of one popmajor soup generation (VERDICT r3 item 2:
"profile, then close, the soup-generation gap").

The mega-soup generation runs ~100x below the raw self-application
kernel's rate; this tool attributes the gap by timing ISOLATED jitted
sub-programs of the generation at mega-N, plus the composed generation
itself:

  * ``rng``      — key splits + gate/target draws (uniform + randint)
  * ``resolve``  — last-attacker-wins victim resolution (segment_max
                   scatter over N)
  * ``gather``   — attacker-column gather wT[:, att] (14 x N rows)
  * ``apply``    — the popmajor weightwise forward on pre-gathered inputs
  * ``attack``   — gather + apply + select (the full attack phase)
  * ``freshinit`` — init_population(N).T (respawn replacement draws —
                   ~14M threefry floats per generation at N=1M)
  * ``respawn``  — death masks + fresh init + select + uid cumsum
  * ``generation`` — the real evolve step (scan of G amortized)

Timing uses scalar readback (the tunneled backend's block_until_ready
does not synchronize — same convention as bench.py).  Optionally wraps
the composed generation in a ``jax.profiler`` trace for offline viewing.

Run: ``python benchmarks/profile_soup.py [--n 1000000] [--gens 20]
[--trace DIR] [--preset apply|full]``.  Prints one JSON line per phase.
"""

import argparse
import os
import sys
import functools
import json
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from srnn_tpu import Topology, init_population
from srnn_tpu.ops.popmajor import ww_forward_popmajor
from srnn_tpu.ops.predicates import is_diverged, is_zero
from srnn_tpu.soup import SoupConfig, evolve, seed


def _time(fn, *args, repeats=5):
    """Median seconds per call of a jitted fn returning (out..., scalar)."""
    out = fn(*args)
    _sync = float(jax.tree.leaves(out)[-1])  # compile + warm
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        _sync = float(jax.tree.leaves(fn(*args))[-1])
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


def _gen_cfg(n: int, preset: str) -> SoupConfig:
    """The composed-generation config — ONE source for both the timed rows
    and the optional profiler trace, so the trace shows the same dynamics
    the JSON rows measure."""
    dyn = dict(attacking_rate=0.1, learn_from_rate=-1.0, train=0) \
        if preset == "apply" else \
        dict(attacking_rate=0.1, learn_from_rate=0.1, learn_from_severity=1,
             train=10)
    return SoupConfig(topo=Topology("weightwise", width=2, depth=2), size=n,
                      remove_divergent=True, remove_zero=True,
                      layout="popmajor", **dyn)


def phase_breakdown(n: int, gens: int, preset: str):
    topo = Topology("weightwise", width=2, depth=2)
    key = jax.random.key(0)
    wT = (init_population(topo, key, n) * 0.05).T

    rows = []

    def report(phase, seconds):
        rows.append({"phase": phase, "n": n,
                     "ms": round(seconds * 1e3, 3)})

    # rng: the per-generation draw set
    @jax.jit
    def rng(key):
        key, k_ag, k_at, k_lg, k_lt, k_re = jax.random.split(key, 6)
        gate = jax.random.uniform(k_ag, (n,)) < 0.1
        tgt = jax.random.randint(k_at, (n,), 0, n)
        return key, gate, tgt, (gate.sum() + tgt.sum()).astype(jnp.float32)

    report("rng", _time(rng, key))
    _, gate, tgt, _ = rng(key)

    @jax.jit
    def resolve(gate, tgt):
        att = jax.ops.segment_max(
            jnp.where(gate, jnp.arange(n), -1), tgt, num_segments=n)
        return att, att.sum().astype(jnp.float32)

    report("resolve", _time(resolve, gate, tgt))
    att, _ = resolve(gate, tgt)
    att_c = jnp.clip(att, 0)

    @jax.jit
    def gather(wT, att_c):
        g = wT[:, att_c]
        return g, g.sum()

    report("gather", _time(gather, wT, att_c))
    attacker, _ = gather(wT, att_c)

    @jax.jit
    def apply_only(attacker, wT):
        out = ww_forward_popmajor(topo, attacker, wT)
        return out, out.sum()

    report("apply", _time(apply_only, attacker, wT))

    @jax.jit
    def attack(wT, att, att_c):
        out = ww_forward_popmajor(topo, wT[:, att_c], wT)
        new = jnp.where((att >= 0)[None, :], out, wT)
        return new, new.sum()

    report("attack", _time(attack, wT, att, att_c))

    @jax.jit
    def freshinit(key):
        f = init_population(topo, key, n).T
        return f, f.sum()

    report("freshinit", _time(freshinit, key))

    @jax.jit
    def respawn(wT, key):
        dead = is_diverged(wT, axis=0) | is_zero(wT, 1e-4, axis=0)
        fresh = init_population(topo, key, n).T
        new = jnp.where(dead[None, :], fresh, wT)
        rank = jnp.cumsum(dead) - 1
        return new, rank, new.sum() + rank.sum().astype(wT.dtype)

    report("respawn", _time(respawn, wT, key))

    # the composed real generation, amortized over a scan
    cfg = _gen_cfg(n, preset)
    state = seed(cfg, jax.random.key(1))

    # fused respawn-draw twin of the composed generation
    cfg_fused = cfg._replace(respawn_draws="fused")

    @functools.partial(jax.jit, static_argnames=())
    def gen_scan(state):
        fin = evolve(cfg, state, generations=gens)
        return fin, fin.weights.sum()

    secs = _time(gen_scan, state, repeats=3) / gens
    rows.append({"phase": f"generation[{preset}]", "n": n,
                 "ms": round(secs * 1e3, 3),
                 "gens_per_sec": round(1.0 / secs, 2)})

    @functools.partial(jax.jit, static_argnames=())
    def gen_scan_fused(state):
        fin = evolve(cfg_fused, state, generations=gens)
        return fin, fin.weights.sum()

    secs = _time(gen_scan_fused, state, repeats=3) / gens
    rows.append({"phase": f"generation[{preset},fused-respawn]", "n": n,
                 "ms": round(secs * 1e3, 3),
                 "gens_per_sec": round(1.0 / secs, 2)})
    return rows


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=1_000_000)
    p.add_argument("--gens", type=int, default=20)
    p.add_argument("--preset", choices=("apply", "full"), default="apply")
    p.add_argument("--trace", default=None, metavar="DIR",
                   help="also record a jax.profiler trace of the composed "
                        "generation scan into DIR")
    args = p.parse_args()

    from srnn_tpu.utils.backend import ensure_backend, watchdog

    cancel = watchdog(1800.0, on_fire=lambda: print(json.dumps(
        {"phase": "profile_soup", "error": "watchdog: wedged > 1800s"}),
        flush=True))
    platform, _ = ensure_backend(retries=5, sleep_s=15.0, fallback_cpu=False)
    if platform == "cpu" and os.environ.get(
            "SRNN_REQUIRE_TPU", "0") not in ("", "0"):
        # same honesty gate as the other benchmarks: a silent axon->cpu
        # fallback must not masquerade as an accelerator profile
        print(json.dumps({"error": f"SRNN_REQUIRE_TPU: live platform is "
                                   f"{platform!r}"}), flush=True)
        raise SystemExit(3)
    rows = phase_breakdown(args.n, args.gens, args.preset)
    for r in rows:
        r["platform"] = platform
        print(json.dumps(r), flush=True)
    if args.trace:
        cfg = _gen_cfg(args.n, args.preset)
        state = seed(cfg, jax.random.key(1))
        fin = evolve(cfg, state, generations=args.gens)  # compiled above
        float(fin.weights.sum())
        with jax.profiler.trace(args.trace):
            fin = evolve(cfg, state, generations=args.gens)
            float(fin.weights.sum())
        print(json.dumps({"trace": args.trace}), flush=True)
    cancel()


if __name__ == "__main__":
    main()
