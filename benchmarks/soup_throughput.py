"""Mega-soup generation throughput (BASELINE.json north-star workload:
1M-particle soup over many generations).

Measures full soup generations/sec at increasing population sizes on the
current accelerator.  Distinct from ``bench.py`` (raw self-application
throughput for the driver); this is the end-to-end dynamics number.

Three presets, so numbers are comparable to what they claim to measure:

  * ``apply``  — attack + respawn only (train/learn_from off): upper bound,
    the pure self-application dynamics.
  * ``full``   — attack 0.1 + learn_from 0.1 (severity 1) + 10 self-training
    epochs per particle per generation (batch-1 SGD parity mode): the
    dynamics the paper's soup experiments actually run
    (``mixed-soup.py:80-84``, ``soup_trajectorys.py:22-27``).
  * ``mixed``  — the BASELINE.json mega-soup config: heterogeneous
    weightwise/aggregating/recurrent subpopulations with cross-type attacks
    (``srnn_tpu.multisoup``), full dynamics.

Run: ``python benchmarks/soup_throughput.py [--preset apply|full|mixed]
[--sizes 10000 100000 1000000] [--generations 50]``
Prints one JSON line per size.
"""

import argparse
import json
import os
import sys
import time

import jax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from srnn_tpu import Topology
from srnn_tpu.multisoup import MultiSoupConfig, evolve_multi, seed_multi
from srnn_tpu.soup import SoupConfig, evolve, seed

PRESETS = ("apply", "full", "mixed")


def _dynamics(preset: str, train_mode: str = "sequential") -> dict:
    if preset == "apply":
        return dict(attacking_rate=0.1, learn_from_rate=-1.0, train=0)
    return dict(attacking_rate=0.1, learn_from_rate=0.1,
                learn_from_severity=1, train=10, train_mode=train_mode)


def bench_size(preset: str, n: int, generations: int = 50,
               repeats: int = 3, layout: str = "rowmajor",
               train_mode: str = "sequential", sharded: bool = False,
               respawn_draws: str = "perparticle",
               train_impl: str = "xla", attack_impl: str = "full",
               learn_from_impl: str = "full", apply_impl: str = "xla",
               topo_variant: str = "weightwise") -> dict:
    dyn = _dynamics(preset, train_mode)
    dyn["respawn_draws"] = respawn_draws
    dyn["train_impl"] = train_impl
    dyn["apply_impl"] = apply_impl
    if preset != "mixed":
        # the heterogeneous config has no attack_impl knob (per-type
        # cross-attack gathers are structural); homogeneous soups do
        dyn["attack_impl"] = attack_impl
        dyn["learn_from_impl"] = learn_from_impl
    if preset == "mixed":
        third = n // 3
        cfg = MultiSoupConfig(
            topos=(Topology("weightwise", width=2, depth=2),
                   Topology("aggregating", width=2, depth=2),
                   Topology("recurrent", width=2, depth=2)),
            sizes=(n - 2 * third, third, third),
            remove_divergent=True, remove_zero=True, layout=layout, **dyn)
        if sharded:
            from srnn_tpu.parallel import (make_sharded_multi_state,
                                           sharded_evolve_multi, soup_mesh)

            mesh = soup_mesh()
            state = make_sharded_multi_state(cfg, mesh, jax.random.key(0))

            def run(s):
                return sharded_evolve_multi(cfg, mesh, s,
                                            generations=generations)
        else:
            state = seed_multi(cfg, jax.random.key(0))

            def run(s):
                return evolve_multi(cfg, s, generations=generations)

        def sync(out):
            return float(out.weights[0].sum())
    else:
        cfg = SoupConfig(
            topo=Topology(topo_variant, width=2, depth=2), size=n,
            remove_divergent=True, remove_zero=True, layout=layout, **dyn)
        if sharded:
            from srnn_tpu.parallel import (make_sharded_state, sharded_evolve,
                                           soup_mesh)

            mesh = soup_mesh()
            state = make_sharded_state(cfg, mesh, jax.random.key(0))

            def run(s):
                return sharded_evolve(cfg, mesh, s, generations=generations)
        else:
            state = seed(cfg, jax.random.key(0))

            def run(s):
                return evolve(cfg, s, generations=generations)

        def sync(out):
            return float(out.weights.sum())

    sync(run(state))  # compile + settle (scalar readback sync)
    t0 = time.perf_counter()
    for _ in range(repeats):
        sync(run(state))
    dt = (time.perf_counter() - t0) / repeats
    gens_per_sec = generations / dt
    return {
        "metric": f"soup-generations/sec[{preset}]",
        "layout": layout,
        "topo": topo_variant if preset != "mixed" else "mixed",
        "respawn_draws": respawn_draws,
        "train_impl": train_impl,
        "apply_impl": apply_impl,
        "attack_impl": attack_impl if preset != "mixed" else "n/a",
        "learn_from_impl": learn_from_impl if preset != "mixed" else "n/a",
        "sharded_devices": jax.device_count() if sharded else 0,
        "particles": n,
        "generations": generations,
        "value": round(gens_per_sec, 2),
        "particle_generations_per_sec": round(gens_per_sec * n),
        "unit": "generations/s",
    }


def main():
    from srnn_tpu.utils.backend import ensure_backend

    p = argparse.ArgumentParser()
    p.add_argument("--preset", choices=PRESETS, default="apply")
    p.add_argument("--sizes", type=int, nargs="*",
                   default=[10_000, 100_000, 1_000_000])
    p.add_argument("--generations", type=int, default=50)
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--layout", choices=("rowmajor", "popmajor"),
                   default="rowmajor",
                   help="popmajor: (P, N) lane-major generation — all "
                        "presets incl. the heterogeneous 'mixed' "
                        "(see srnn_tpu/ops/popmajor*.py)")
    p.add_argument("--train-mode", choices=("sequential", "full_batch"),
                   default="sequential",
                   help="train/learn_from SGD mode for the 'full'/'mixed' presets")
    p.add_argument("--sharded", action="store_true",
                   help="run the soup sharded over ALL visible devices "
                        "(all presets incl. the heterogeneous 'mixed'; "
                        "shard_map data parallel)")
    p.add_argument("--respawn-draws", choices=("perparticle", "fused"),
                   default="perparticle",
                   help="'fused': one-call respawn replacement draw (same "
                        "iid glorot law, different stream) — the mega-soup "
                        "fast path; see SoupConfig.respawn_draws")
    p.add_argument("--train-impl", choices=("xla", "pallas"),
                   default="xla",
                   help="'pallas': fused VMEM batch-1 SGD chain for the "
                        "weightwise popmajor train/learn phases "
                        "(ops/pallas_ww_train.py)")
    p.add_argument("--attack-impl", choices=("full", "compact"),
                   default="full",
                   help="'compact': transform only the attacked lanes "
                        "(fixed-capacity compaction + scatter; popmajor, "
                        "non-mixed presets)")
    p.add_argument("--learn-from-impl", choices=("full", "compact"),
                   default="full",
                   help="'compact': imitation-SGD on learner lanes only "
                        "(same mechanics as --attack-impl)")
    p.add_argument("--apply-impl", choices=("xla", "pallas"),
                   default="xla",
                   help="'pallas': fused VMEM forward for the recurrent "
                        "attack transform (ops/pallas_rnn_apply.py; "
                        "recurrent topos / mixed preset)")
    p.add_argument("--topo", choices=("weightwise", "aggregating", "fft",
                                      "recurrent"),
                   default="weightwise",
                   help="homogeneous-preset particle variant (the 'mixed' "
                        "preset keeps its fixed ww/agg/rnn blend)")
    args = p.parse_args()
    # the tunneled TPU backend flakes at init (sometimes raising, sometimes
    # wedging): probe with retries AND bound each phase with a watchdog that
    # still emits a JSON line (no CPU fallback — perf must be honest).  The
    # watchdog is re-armed per size so the bound scales with the sweep and a
    # wedge in one size doesn't discard the rows already printed, and
    # cancelled after the last size so a long legitimate sweep is never
    # hard-killed post-measurement.
    from srnn_tpu.utils.backend import watchdog

    def arm(phase: str, seconds: float):
        return watchdog(seconds, on_fire=lambda: print(json.dumps(
            {"metric": f"soup-generations/sec[{args.preset}]", "value": 0,
             "unit": "generations/s",
             "error": f"watchdog: {phase} wedged > {seconds:.0f}s"}),
            flush=True))

    cancel = arm("backend init", 600.0)
    platform, _ = ensure_backend(retries=5, sleep_s=15.0, fallback_cpu=False)
    if platform == "cpu" and int(os.environ.get("SRNN_REQUIRE_TPU", "0")):
        # a plugin that registers-then-falls-back leaves a healthy CPU
        # backend with no exception — without this gate, CPU timings would
        # be appended under an accelerator label
        print(json.dumps({"error": f"SRNN_REQUIRE_TPU: live platform is "
                                   f"{platform!r}"}), flush=True)
        raise SystemExit(3)
    for n in args.sizes:
        cancel()
        cancel = arm(f"size {n}", 2400.0)
        row = bench_size(args.preset, n, args.generations,
                         args.repeats, args.layout,
                         args.train_mode, args.sharded,
                         args.respawn_draws, args.train_impl,
                         args.attack_impl, args.learn_from_impl,
                         args.apply_impl, args.topo)
        row["platform"] = platform
        print(json.dumps(row))
    cancel()


if __name__ == "__main__":
    main()
