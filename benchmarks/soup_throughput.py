"""Mega-soup generation throughput (BASELINE.json north-star workload:
1M-particle soup over many generations).

Measures full soup generations/sec — attack draws + collision resolution +
vmapped self-application + respawn — at increasing population sizes on the
current accelerator, and reports particle-updates/sec.  Distinct from
``bench.py`` (raw self-application throughput for the driver); this is the
end-to-end dynamics number.

Run: ``python benchmarks/soup_throughput.py [--sizes 10000 100000 1000000]``
Prints one JSON line per size.
"""

import argparse
import json
import time

import jax

from srnn_tpu import Topology
from srnn_tpu.soup import SoupConfig, evolve, seed


def bench_size(n: int, generations: int = 50, repeats: int = 3) -> dict:
    cfg = SoupConfig(
        topo=Topology("weightwise", width=2, depth=2),
        size=n, attacking_rate=0.1, learn_from_rate=-1.0, train=0,
        remove_divergent=True, remove_zero=True)
    state = seed(cfg, jax.random.key(0))

    def run(s):
        return evolve(cfg, s, generations=generations)

    out = run(state)
    float(out.weights.sum())  # compile + settle (scalar readback sync)
    t0 = time.perf_counter()
    for _ in range(repeats):
        out = run(state)
        float(out.weights.sum())
    dt = (time.perf_counter() - t0) / repeats
    gens_per_sec = generations / dt
    return {
        "metric": "soup-generations/sec",
        "particles": n,
        "generations": generations,
        "value": round(gens_per_sec, 2),
        "particle_updates_per_sec": round(gens_per_sec * n),
        "unit": "generations/s",
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--sizes", type=int, nargs="*",
                   default=[10_000, 100_000, 1_000_000])
    p.add_argument("--generations", type=int, default=50)
    args = p.parse_args()
    for n in args.sizes:
        print(json.dumps(bench_size(n, args.generations)))


if __name__ == "__main__":
    main()
