"""Seed-sweep error bars for the two statistically soft parity rows
(RESULTS.md): the reference committed ONE run of each experiment, so
single-seed comparisons conflate attractor identity with seed noise.  This
sweep reruns each config over many seeds and reports per-class mean +- sd,
so RESULTS.md can state parity (or honest deviation) with distributions.

Rows swept:
  * soup_trajectorys  — Soup(20, WW, train=30, attack 0.1), 100 generations
    (reference ``setups/soup_trajectorys.py:22-27``; committed artifact
    ``results/Soup/log.txt:1`` = 13 fix_other / 7 other).
  * training_fixpoints RNN arm — 50 trials x 1000 batch-1 epochs
    (reference ``setups/training-fixpoints.py:36-38``; committed
    ``results/exp-training_fixpoint-*/log.txt`` RNN row = 38 divergent /
    12 other).

Run: ``python benchmarks/parity_sweep.py [--seeds 10] [--rows soup rnn]``
Prints one JSON line per row.
"""

import argparse
import os
import sys
import json

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from srnn_tpu import Topology
from srnn_tpu.engine import run_training
from srnn_tpu.init import init_population
from srnn_tpu.ops.predicates import CLASS_NAMES
from srnn_tpu.soup import SoupConfig, count, evolve, seed


def sweep_soup_trajectorys(n_seeds: int) -> dict:
    cfg = SoupConfig(
        topo=Topology("weightwise", width=2, depth=2), size=20,
        attacking_rate=0.1, learn_from_rate=-1.0, train=30,
        remove_divergent=True, remove_zero=True)
    states = [seed(cfg, jax.random.key(s)) for s in range(n_seeds)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *states)
    finals = jax.vmap(lambda s: evolve(cfg, s, generations=100))(stacked)
    rows = np.stack([
        np.asarray(count(cfg, jax.tree.map(lambda x: x[i], finals)))
        for i in range(n_seeds)])
    return _report("soup_trajectorys[N=20,train=30,100gen]", rows,
                   reference={"fix_other": 13, "other": 7})


def sweep_training_rnn(n_seeds: int) -> dict:
    topo = Topology("recurrent", width=2, depth=2)
    rows = []
    for s in range(n_seeds):
        pop = init_population(topo, jax.random.key(1000 + s), 50)
        res = run_training(topo, pop, epochs=1000, train_mode="sequential")
        rows.append(np.asarray(res.counts))
    return _report("training_fixpoints[RNN,50x1000]", np.stack(rows),
                   reference={"divergent": 38, "other": 12})


def sweep_rnn_hypotheses(n_seeds: int) -> dict:
    """Interrogate the r3 honest-deviation row (training_fixpoints RNN:
    divergent 46% here vs 76% in the reference's single run, z = 5.4).

    (a) The round-3 hypothesis — keras ``fit``'s unseeded per-epoch sample
        shuffling — is STRUCTURALLY IMPOSSIBLE for this arm: the recurrent
        variant's sample set is ONE sequence (x = y = the whole weight
        vector, reference ``network.py:566-574``), and shuffling a
        single-element set is the identity.  Verified live: a key-shuffled
        epoch is bitwise identical to the enumeration-order epoch.
    (b) The remaining in-framework candidate is float32 numerics: sweep the
        same 50x1000 arm at float64.  If the divergent fraction is stable,
        the deviation is pinned on the only out-of-framework difference —
        the 2019 TF RNG stream behind the reference's inits, which the
        committed artifacts do not record.
    """
    topo = Topology("recurrent", width=2, depth=2)

    # (a) shuffled-order no-op, bitwise
    from srnn_tpu.train import train_step
    pop = init_population(topo, jax.random.key(77), 8)
    plain = jax.vmap(lambda w: train_step(topo, w)[0])(pop)
    keys = jax.random.split(jax.random.key(78), 8)
    shuf = jax.vmap(lambda w, k: train_step(topo, w, key=k)[0])(pop, keys)
    shuffle_noop = bool(np.array_equal(np.asarray(plain), np.asarray(shuf)))

    # (b) float64 sweep (x64 must be enabled process-wide)
    jax.config.update("jax_enable_x64", True)
    try:
        rows = []
        for s in range(n_seeds):
            pop64 = init_population(topo, jax.random.key(1000 + s), 50,
                                    dtype=jnp.float64)
            res = run_training(topo, pop64, epochs=1000,
                               train_mode="sequential")
            rows.append(np.asarray(res.counts))
    finally:
        jax.config.update("jax_enable_x64", False)
    out = _report("training_fixpoints[RNN,50x1000,float64]", np.stack(rows),
                  reference={"divergent": 38, "other": 12})
    out["shuffled_order_bitwise_noop"] = shuffle_noop
    return out


BF16_GENS = 100
BF16_N = 256
BF16_PER_GEN_GENS = 30


def _bf16_cfgs():
    cfg32 = SoupConfig(
        topo=Topology("weightwise", width=2, depth=2), size=BF16_N,
        attacking_rate=0.1, learn_from_rate=-1.0, train=5,
        remove_divergent=True, remove_zero=True, layout="popmajor",
        respawn_draws="fused", generation_impl="fused")
    return cfg32, cfg32._replace(population_dtype="bf16")


def _as_f32_state(st):
    return st._replace(weights=st.weights.astype(jnp.float32))


def per_gen_bf16_drift(gens: int = BF16_PER_GEN_GENS) -> float:
    """Worst single-generation relative L-inf between the bf16 mode and an
    f32 generation started from the SAME (bf16-cast) state, re-synced
    every generation — the tolerance Chang & Lipson's *Neural Network
    Quine* needed to define self-reproduction under finite precision: one
    step of the dynamic loses at most one bf16 rounding per weight per
    phase, so the bound is O(2^-8) relative (PARITY.md bf16 table).
    Trajectory-LEVEL divergence over many generations is a property of
    the chaotic dynamic, not of the precision mode — measured separately
    as statistical agreement below."""
    cfg32, cfg16 = _bf16_cfgs()
    st16 = seed(cfg16, jax.random.key(0))
    worst = 0.0
    for _ in range(gens):
        n32 = evolve(cfg32, _as_f32_state(st16), generations=1)
        st16 = evolve(cfg16, st16, generations=1)
        w32 = np.asarray(n32.weights, np.float32)
        w16 = np.asarray(st16.weights, np.float32)
        fin = np.isfinite(w32).all(1) & np.isfinite(w16).all(1)
        scale = max(float(np.abs(w32[fin]).max()), 1e-9)
        worst = max(worst, float(np.abs(w32[fin] - w16[fin]).max()) / scale)
    return worst


def sweep_bf16_parity(n_seeds: int) -> dict:
    """f32 <-> bf16 population-mode parity (the PARITY.md bf16 rows).

    Two claims, measured separately because the full soup dynamic is
    chaotic (a 1-ulp difference is amplified by attack/train until
    trajectories decorrelate — the same reason the repo compares
    parallel-vs-sequential soups distributionally, PARITY.md L3):

      * per-generation: worst relative L-inf of ONE generation from a
        shared state (:func:`per_gen_bf16_drift`) — the documented
        tolerance, bounded by bf16 rounding (O(2^-8));
      * 100-generation: integer state stays EXACT int32 arithmetic
        (draws, uids, counters are never quantized), uid agreement and
        the end-state class-census L1 distance quantify statistical
        agreement of the decorrelated trajectories; the end-state weight
        gap over uid-matching lanes rides along as the observational
        (NOT tolerance-bounded) number.
    """
    cfg32, cfg16 = _bf16_cfgs()
    uid_agree, linf, census_l1, exact = [], [], [], True
    for s in range(n_seeds):
        f32 = evolve(cfg32, seed(cfg32, jax.random.key(s)),
                     generations=BF16_GENS)
        b16 = evolve(cfg16, seed(cfg16, jax.random.key(s)),
                     generations=BF16_GENS)
        exact = exact and b16.uids.dtype == jnp.int32 \
            and int(b16.time) == BF16_GENS \
            and int(jnp.max(b16.uids)) < int(b16.next_uid)
        u32, u16 = np.asarray(f32.uids), np.asarray(b16.uids)
        match = u32 == u16
        uid_agree.append(float(match.mean()))
        w32 = np.asarray(f32.weights, np.float32)
        w16 = np.asarray(b16.weights, np.float32)
        finite = np.isfinite(w32).all(1) & np.isfinite(w16).all(1)
        lanes = match & finite
        linf.append(float(np.abs(w32[lanes] - w16[lanes]).max())
                    if lanes.any() else 0.0)
        c32 = np.asarray(count(cfg32, f32))
        c16 = np.asarray(count(cfg16, b16))
        census_l1.append(int(np.abs(c32 - c16).sum()))
    return {
        "row": f"bf16_parity[N={BF16_N},train=5,{BF16_GENS}gen]",
        "seeds": n_seeds,
        "per_gen_rel_linf": round(per_gen_bf16_drift(), 6),
        "integer_state_exact": bool(exact),
        "uid_agreement_mean": round(float(np.mean(uid_agree)), 4),
        "census_l1_mean": round(float(np.mean(census_l1)), 2),
        "end_state_linf_matched_median": round(float(np.median(linf)), 5),
        "end_state_linf_matched_max": round(float(np.max(linf)), 5),
    }


INT8_GENS = 100
INT8_N = 256
INT8_PER_GEN_GENS = 30


def _int8_cfgs():
    cfg32, _ = _bf16_cfgs()
    return cfg32, cfg32._replace(population_dtype="int8")


def _int8_as_f32_state(st):
    """Dequantized f32 twin of an int8 state (codes x per-particle scale
    — the same view every compute path takes at generation start)."""
    from srnn_tpu.soup import _upcast

    cfg8 = _int8_cfgs()[1]
    return st._replace(weights=_upcast(cfg8, st.weights, st.scales),
                       scales=None)


def per_gen_int8_drift(gens: int = INT8_PER_GEN_GENS) -> float:
    """Worst single-generation relative L-inf between the int8 mode and
    an f32 generation started from the SAME (dequantized) state,
    re-synced every generation.  One generation quantizes exactly once
    (the quantize-point contract), losing at most half a step of the
    per-particle scale ``amax/127`` — so the bound is O(2^-8) relative,
    the same magnitude class as the bf16 row (PARITY.md int8 table)."""
    cfg32, cfg8 = _int8_cfgs()
    st8 = seed(cfg8, jax.random.key(0))
    worst = 0.0
    for _ in range(gens):
        n32 = evolve(cfg32, _int8_as_f32_state(st8), generations=1)
        st8 = evolve(cfg8, st8, generations=1)
        w32 = np.asarray(n32.weights, np.float32)
        w8 = np.asarray(_int8_as_f32_state(st8).weights, np.float32)
        fin = np.isfinite(w32).all(1) & np.isfinite(w8).all(1)
        scale = max(float(np.abs(w32[fin]).max()), 1e-9)
        worst = max(worst, float(np.abs(w32[fin] - w8[fin]).max()) / scale)
    return worst


def sweep_int8_parity(n_seeds: int) -> dict:
    """f32 <-> int8 population-mode parity (the PARITY.md int8 rows),
    measured exactly like the bf16 sweep: a per-generation tolerance
    bound from shared state, then distributional agreement of the
    decorrelated 100-generation trajectories (the dynamic is chaotic —
    a half-step quantization difference decorrelates trajectories just
    like a bf16 rounding does; claims at trajectory level are
    statistical, never elementwise)."""
    cfg32, cfg8 = _int8_cfgs()
    uid_agree, linf, census_l1, exact = [], [], [], True
    for s in range(n_seeds):
        f32 = evolve(cfg32, seed(cfg32, jax.random.key(s)),
                     generations=INT8_GENS)
        q8 = evolve(cfg8, seed(cfg8, jax.random.key(s)),
                    generations=INT8_GENS)
        exact = exact and q8.uids.dtype == jnp.int32 \
            and q8.weights.dtype == jnp.int8 \
            and q8.scales is not None \
            and int(q8.time) == INT8_GENS \
            and int(jnp.max(q8.uids)) < int(q8.next_uid)
        u32, u8 = np.asarray(f32.uids), np.asarray(q8.uids)
        match = u32 == u8
        uid_agree.append(float(match.mean()))
        w32 = np.asarray(f32.weights, np.float32)
        w8 = np.asarray(_int8_as_f32_state(q8).weights, np.float32)
        finite = np.isfinite(w32).all(1) & np.isfinite(w8).all(1)
        lanes = match & finite
        linf.append(float(np.abs(w32[lanes] - w8[lanes]).max())
                    if lanes.any() else 0.0)
        c32 = np.asarray(count(cfg32, f32))
        c8 = np.asarray(count(cfg8, q8))
        census_l1.append(int(np.abs(c32 - c8).sum()))
    return {
        "row": f"int8_parity[N={INT8_N},train=5,{INT8_GENS}gen]",
        "seeds": n_seeds,
        "per_gen_rel_linf": round(per_gen_int8_drift(), 6),
        "integer_state_exact": bool(exact),
        "uid_agreement_mean": round(float(np.mean(uid_agree)), 4),
        "census_l1_mean": round(float(np.mean(census_l1)), 2),
        "end_state_linf_matched_median": round(float(np.median(linf)), 5),
        "end_state_linf_matched_max": round(float(np.max(linf)), 5),
    }


def _report(name: str, rows: np.ndarray, reference: dict) -> dict:
    mean = rows.mean(0)
    sd = rows.std(0, ddof=1 if rows.shape[0] > 1 else 0)
    out = {
        "row": name,
        "seeds": rows.shape[0],
        "mean": {c: round(float(m), 2) for c, m in zip(CLASS_NAMES, mean)},
        "sd": {c: round(float(v), 2) for c, v in zip(CLASS_NAMES, sd)},
        "reference": reference,
    }
    # z-score of the reference's single committed run under the sweep
    # distribution: |ref - mean| / sd per nonzero class
    z = {}
    for c, ref_v in reference.items():
        i = CLASS_NAMES.index(c)
        z[c] = round(abs(ref_v - float(mean[i])) / max(float(sd[i]), 1e-9), 2)
    out["ref_z"] = z
    return out


def main():
    from srnn_tpu.utils.backend import ensure_backend, watchdog

    p = argparse.ArgumentParser()
    p.add_argument("--seeds", type=int, default=10)
    p.add_argument("--rows", nargs="*",
                   default=["soup", "rnn", "rnn_hypotheses", "bf16", "int8"],
                   choices=["soup", "rnn", "rnn_hypotheses", "bf16",
                            "int8"])
    args = p.parse_args()
    watchdog(2400.0, on_fire=lambda: print(json.dumps(
        {"row": "parity_sweep", "error": "watchdog: wedged > 2400s"}),
        flush=True))
    ensure_backend(retries=5, sleep_s=15.0, fallback_cpu=True)
    if "soup" in args.rows:
        print(json.dumps(sweep_soup_trajectorys(args.seeds)))
    if "rnn" in args.rows:
        print(json.dumps(sweep_training_rnn(args.seeds)))
    if "rnn_hypotheses" in args.rows:
        print(json.dumps(sweep_rnn_hypotheses(args.seeds)))
    if "bf16" in args.rows:
        print(json.dumps(sweep_bf16_parity(args.seeds)))
    if "int8" in args.rows:
        print(json.dumps(sweep_int8_parity(args.seeds)))


if __name__ == "__main__":
    main()
