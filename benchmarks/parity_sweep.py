"""Seed-sweep error bars for the two statistically soft parity rows
(RESULTS.md): the reference committed ONE run of each experiment, so
single-seed comparisons conflate attractor identity with seed noise.  This
sweep reruns each config over many seeds and reports per-class mean +- sd,
so RESULTS.md can state parity (or honest deviation) with distributions.

Rows swept:
  * soup_trajectorys  — Soup(20, WW, train=30, attack 0.1), 100 generations
    (reference ``setups/soup_trajectorys.py:22-27``; committed artifact
    ``results/Soup/log.txt:1`` = 13 fix_other / 7 other).
  * training_fixpoints RNN arm — 50 trials x 1000 batch-1 epochs
    (reference ``setups/training-fixpoints.py:36-38``; committed
    ``results/exp-training_fixpoint-*/log.txt`` RNN row = 38 divergent /
    12 other).

Run: ``python benchmarks/parity_sweep.py [--seeds 10] [--rows soup rnn]``
Prints one JSON line per row.
"""

import argparse
import os
import sys
import json

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from srnn_tpu import Topology
from srnn_tpu.engine import run_training
from srnn_tpu.init import init_population
from srnn_tpu.ops.predicates import CLASS_NAMES
from srnn_tpu.soup import SoupConfig, count, evolve, seed


def sweep_soup_trajectorys(n_seeds: int) -> dict:
    cfg = SoupConfig(
        topo=Topology("weightwise", width=2, depth=2), size=20,
        attacking_rate=0.1, learn_from_rate=-1.0, train=30,
        remove_divergent=True, remove_zero=True)
    states = [seed(cfg, jax.random.key(s)) for s in range(n_seeds)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *states)
    finals = jax.vmap(lambda s: evolve(cfg, s, generations=100))(stacked)
    rows = np.stack([
        np.asarray(count(cfg, jax.tree.map(lambda x: x[i], finals)))
        for i in range(n_seeds)])
    return _report("soup_trajectorys[N=20,train=30,100gen]", rows,
                   reference={"fix_other": 13, "other": 7})


def sweep_training_rnn(n_seeds: int) -> dict:
    topo = Topology("recurrent", width=2, depth=2)
    rows = []
    for s in range(n_seeds):
        pop = init_population(topo, jax.random.key(1000 + s), 50)
        res = run_training(topo, pop, epochs=1000, train_mode="sequential")
        rows.append(np.asarray(res.counts))
    return _report("training_fixpoints[RNN,50x1000]", np.stack(rows),
                   reference={"divergent": 38, "other": 12})


def sweep_rnn_hypotheses(n_seeds: int) -> dict:
    """Interrogate the r3 honest-deviation row (training_fixpoints RNN:
    divergent 46% here vs 76% in the reference's single run, z = 5.4).

    (a) The round-3 hypothesis — keras ``fit``'s unseeded per-epoch sample
        shuffling — is STRUCTURALLY IMPOSSIBLE for this arm: the recurrent
        variant's sample set is ONE sequence (x = y = the whole weight
        vector, reference ``network.py:566-574``), and shuffling a
        single-element set is the identity.  Verified live: a key-shuffled
        epoch is bitwise identical to the enumeration-order epoch.
    (b) The remaining in-framework candidate is float32 numerics: sweep the
        same 50x1000 arm at float64.  If the divergent fraction is stable,
        the deviation is pinned on the only out-of-framework difference —
        the 2019 TF RNG stream behind the reference's inits, which the
        committed artifacts do not record.
    """
    topo = Topology("recurrent", width=2, depth=2)

    # (a) shuffled-order no-op, bitwise
    from srnn_tpu.train import train_step
    pop = init_population(topo, jax.random.key(77), 8)
    plain = jax.vmap(lambda w: train_step(topo, w)[0])(pop)
    keys = jax.random.split(jax.random.key(78), 8)
    shuf = jax.vmap(lambda w, k: train_step(topo, w, key=k)[0])(pop, keys)
    shuffle_noop = bool(np.array_equal(np.asarray(plain), np.asarray(shuf)))

    # (b) float64 sweep (x64 must be enabled process-wide)
    jax.config.update("jax_enable_x64", True)
    try:
        rows = []
        for s in range(n_seeds):
            pop64 = init_population(topo, jax.random.key(1000 + s), 50,
                                    dtype=jnp.float64)
            res = run_training(topo, pop64, epochs=1000,
                               train_mode="sequential")
            rows.append(np.asarray(res.counts))
    finally:
        jax.config.update("jax_enable_x64", False)
    out = _report("training_fixpoints[RNN,50x1000,float64]", np.stack(rows),
                  reference={"divergent": 38, "other": 12})
    out["shuffled_order_bitwise_noop"] = shuffle_noop
    return out


def _report(name: str, rows: np.ndarray, reference: dict) -> dict:
    mean = rows.mean(0)
    sd = rows.std(0, ddof=1 if rows.shape[0] > 1 else 0)
    out = {
        "row": name,
        "seeds": rows.shape[0],
        "mean": {c: round(float(m), 2) for c, m in zip(CLASS_NAMES, mean)},
        "sd": {c: round(float(v), 2) for c, v in zip(CLASS_NAMES, sd)},
        "reference": reference,
    }
    # z-score of the reference's single committed run under the sweep
    # distribution: |ref - mean| / sd per nonzero class
    z = {}
    for c, ref_v in reference.items():
        i = CLASS_NAMES.index(c)
        z[c] = round(abs(ref_v - float(mean[i])) / max(float(sd[i]), 1e-9), 2)
    out["ref_z"] = z
    return out


def main():
    from srnn_tpu.utils.backend import ensure_backend, watchdog

    p = argparse.ArgumentParser()
    p.add_argument("--seeds", type=int, default=10)
    p.add_argument("--rows", nargs="*",
                   default=["soup", "rnn", "rnn_hypotheses"],
                   choices=["soup", "rnn", "rnn_hypotheses"])
    args = p.parse_args()
    watchdog(2400.0, on_fire=lambda: print(json.dumps(
        {"row": "parity_sweep", "error": "watchdog: wedged > 2400s"}),
        flush=True))
    ensure_backend(retries=5, sleep_s=15.0, fallback_cpu=True)
    if "soup" in args.rows:
        print(json.dumps(sweep_soup_trajectorys(args.seeds)))
    if "rnn" in args.rows:
        print(json.dumps(sweep_training_rnn(args.seeds)))
    if "rnn_hypotheses" in args.rows:
        print(json.dumps(sweep_rnn_hypotheses(args.seeds)))


if __name__ == "__main__":
    main()
