"""Perf-regression sentinel: a fresh bench result vs the committed record.

    python benchmarks/regress.py BENCH_r06.json            # self-check
    python bench.py | python benchmarks/regress.py - --json
    python benchmarks/regress.py fresh.json --scale apps_per_chip=0.6
    python benchmarks/regress.py fresh.json --from-archive  # + archived rounds

Compares one fresh ``bench.py`` result (or a ``benchmarks/micro_dispatch``
doc) against the committed ``BENCH_*.json`` trajectory: each comparable
LEG's fresh value is judged against the **median of its history** (the
r0x wrapper files store the parsed result under ``parsed``; bare result
dicts load as-is; rows that never measured a leg — wedged rounds,
different backends — simply don't contribute).  ``BASELINE.json``'s
north-star metric is carried as context.

Tolerance table (why each number — this host's BENCH trail is the
evidence; re-baselining after an INTENTIONAL perf change = commit the new
``BENCH_r0x.json``, which moves the median, and/or adjust ``LEGS`` in
the same PR with the reasoning updated here):

  leg                         direction  tolerance  rationale
  apps_per_chip               down-bad   25%        session drift on the
                                                    shared CPU host spans
                                                    5-12% (PR 5 notes);
                                                    2x that + margin
  scan_apps_per_chip          down-bad   25%        same workload, same
                                                    host noise
  serve_sweeps_speedup_x      down-bad   50%        amortization ratio —
                                                    depends on host load
                                                    during the solo leg
  serve_load_requests_per_sec down-bad   40%        closed-loop; since the
                                                    continuous-batching
                                                    tier the windows adapt
                                                    off the SLO, so only
                                                    host noise remains —
                                                    the fixed-window r0x
                                                    history keeps the
                                                    median conservative
  serve_load_p95_ms           up-bad     50%        latency tail under a
                                                    shared host
  serve_sat_w{1,2,4}_rps      down-bad   40%        fleet closed-loop rps
                                                    (subprocess workers on
                                                    a shared box — same
                                                    drift class as the
                                                    load leg)
  serve_sat_w4_p95_ms         up-bad     50%        the widest fleet's
                                                    tail; same class as
                                                    serve_load_p95_ms
  multihost_process_tax       up-bad     125%       gloo/process overhead
                                                    on a 1-2 core CI box
                                                    is inherently noisy;
                                                    the PR 18 autotuner
                                                    sped up the SOLO
                                                    denominator, shifting
                                                    the ratio ~1.8 → ~3.x
                                                    until tuned rounds
                                                    dominate the median

Backends are compared like-for-like: a fresh CPU(-forced/-fallback)
result is only judged against historical CPU rows — an accelerator
number never masks (or fakes) a CPU regression.

Regressions are emitted as ``soup_bench_regression`` findings (the bench
JSON embeds them under ``result["regression"]``) and the exit code is an
ADVISORY gate: 0 clean / 1 regression(s) / 2 usage error.  bench.py and
run_tests.sh surface the findings without letting perf noise hard-fail a
functional suite.  micro_dispatch docs are judged warning-only (their
overhead rows drift −11..+43% per session on this host — see CHANGES PR 5
— so they inform, never fail).

Pure stdlib on purpose: the bench PARENT calls this and must stay unable
to wedge on a backend import.
"""

import argparse
import glob as _glob
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: leg -> (extractor path, direction, relative tolerance).  direction
#: "down" = a LOWER fresh value regresses; "up" = a HIGHER one does.
LEGS = {
    "apps_per_chip": (("value",), "down", 0.25),
    "scan_apps_per_chip": (("scan_apps_per_chip",), "down", 0.25),
    "serve_sweeps_speedup_x": (("serve", "sweeps_speedup_x"), "down", 0.50),
    "serve_load_requests_per_sec": (("serve", "load", "requests_per_sec"),
                                    "down", 0.40),
    "serve_load_p95_ms": (("serve", "load", "p95_ms"), "up", 0.50),
    "serve_sat_w1_rps": (("serve", "saturation", "w1", "requests_per_sec"),
                         "down", 0.40),
    "serve_sat_w2_rps": (("serve", "saturation", "w2", "requests_per_sec"),
                         "down", 0.40),
    "serve_sat_w4_rps": (("serve", "saturation", "w4", "requests_per_sec"),
                         "down", 0.40),
    "serve_sat_w4_p95_ms": (("serve", "saturation", "w4", "p95_ms"),
                            "up", 0.50),
    # the tax is multi_wall / SOLO_wall: the PR 18 autotuner cut the
    # solo denominator ~10-15% while the 2-process leg stays pinned by
    # single-core time-slicing, so the ratio shifted structurally from
    # ~1.8 to ~3.0-3.6 on this 1-vCPU host — tolerance covers the
    # denominator shift until tuned rounds dominate the median
    "multihost_process_tax": (("multihost", "process_tax"), "up", 1.25),
    # tuned leg (PR 18): apps/chip judged ONLY among rounds that ran
    # with an autotuned lane block (``tuned_block`` present in the
    # result) — the autotuner moves the fused-chain median ~1.9x, so a
    # tuned round must never be excused by an untuned median and an
    # untuned round must never be judged against a tuned one
    "tuned_apps_per_chip": (("value",), "down", 0.25),
}

#: legs whose median is meaningless below this many history rounds: the
#: autotuner moved the fused-chain numbers so much that a 1-round
#: "median" would whipsaw every verdict around whichever single round
#: happened to land first after a re-baseline
MIN_ROUNDS = {"scan_apps_per_chip": 2, "tuned_apps_per_chip": 2}

#: micro_dispatch overhead rows: generous bounds (warning-only — see the
#: module docstring on session drift) on the documented <=5%-class rows
MICRO_BOUND_PCT = 20.0
MICRO_ROWS = ("telemetry", "health", "lineage", "spans", "export",
              "adaptive", "int8", "autotune", "archive")


def _get(doc, path):
    cur = doc
    for key in path:
        if not isinstance(cur, dict):
            return None
        cur = cur.get(key)
    return cur if isinstance(cur, (int, float)) else None


def _backend_family(doc) -> str:
    b = str(doc.get("backend", "") or "")
    return "cpu" if "cpu" in b else (b or "unknown")


def load_result(path_or_dash: str) -> dict:
    text = sys.stdin.read() if path_or_dash == "-" \
        else open(path_or_dash).read()
    doc = json.loads(text)
    # the committed r01-r05 files wrap the result: {n, cmd, rc, tail,
    # parsed} — unwrap; r06+ commit the bare result dict
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    if not isinstance(doc, dict):
        raise ValueError("not a bench result document")
    return doc


def load_history(pattern: str, exclude_path: str = "") -> list:
    out = []
    for path in sorted(_glob.glob(pattern)):
        if exclude_path and os.path.abspath(path) == \
                os.path.abspath(exclude_path):
            continue
        try:
            out.append((os.path.basename(path), load_result(path)))
        except (OSError, ValueError, json.JSONDecodeError):
            continue  # unreadable/foreign file: history degrades, never dies
    return out


#: the bench.py archive hook's sidecar (next to the BENCH_*.json
#: trajectory); the row format is spelled inline in bench.py and here —
#: neither process may import srnn_tpu (telemetry.archive documents the
#: contract and carries the shared name)
ARCHIVE_DEFAULT = os.path.join(REPO_ROOT, "BENCH_archive.jsonl")


def load_archive_rounds(path: str) -> list:
    """``[(label, result), ...]`` oldest-first from a ``BENCH_archive``
    jsonl: ``{"kind": "bench_round", "result": {...}}`` rows, skip-
    unparseable (a torn tail costs one round, never the sentinel)."""
    out = []
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError:
        return out  # no archive yet: history degrades, never dies
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            row = json.loads(line)
        except ValueError:
            continue
        if isinstance(row, dict) and row.get("kind") == "bench_round" \
                and isinstance(row.get("result"), dict):
            out.append((f"archive[{i}]", row["result"]))
    return out


def _median(xs):
    xs = sorted(xs)
    n = len(xs)
    return xs[n // 2] if n % 2 else 0.5 * (xs[n // 2 - 1] + xs[n // 2])


def compare(fresh: dict, history: list) -> dict:
    """The verdict document: one row per leg, ``soup_bench_regression``
    findings for the legs outside tolerance."""
    fam = _backend_family(fresh)
    legs = []
    findings = []
    for leg, (path, direction, tol) in LEGS.items():
        tuned_leg = leg == "tuned_apps_per_chip"
        fresh_v = _get(fresh, path)
        if tuned_leg and not fresh.get("tuned_block"):
            fresh_v = None   # fresh round ran untuned: nothing to judge
        row = {"leg": leg, "fresh": fresh_v, "direction": direction,
               "tolerance": tol}
        if fresh_v is None or fresh_v <= 0:
            row["verdict"] = "no fresh value"
            legs.append(row)
            continue
        # like-for-like: the throughput legs only compare within the same
        # backend family (serve/multihost legs are CPU-pinned by design,
        # so their history is comparable regardless)
        hist = []
        for name, doc in history:
            v = _get(doc, path)
            if v is None or v <= 0:
                continue
            if path[0] in ("value", "scan_apps_per_chip") \
                    and _backend_family(doc) != fam:
                continue
            if tuned_leg and not doc.get("tuned_block"):
                continue
            hist.append((name, v))
        if not hist:
            row["verdict"] = "no comparable history"
            legs.append(row)
            continue
        need = MIN_ROUNDS.get(leg, 1)
        if len(hist) < need:
            # judging against a sub-minimum "median" whipsaws; record the
            # rounds seen so the next committed BENCH_r0x arms the leg
            row["verdict"] = f"insufficient history (<{need} rounds)"
            row["history_rounds"] = [n for n, _v in hist]
            legs.append(row)
            continue
        med = _median([v for _n, v in hist])
        ratio = fresh_v / med
        row.update(history_median=round(med, 4),
                   history_rounds=[n for n, _v in hist],
                   ratio=round(ratio, 4))
        regressed = (ratio < 1.0 - tol) if direction == "down" \
            else (ratio > 1.0 + tol)
        row["verdict"] = "REGRESSION" if regressed else "ok"
        legs.append(row)
        if regressed:
            findings.append({
                "kind": "soup_bench_regression", "leg": leg,
                "fresh": fresh_v, "history_median": round(med, 4),
                "ratio": round(ratio, 4), "tolerance": tol,
                "direction": direction,
                "message": f"{leg}: fresh {fresh_v:.4g} vs history median "
                           f"{med:.4g} ({(ratio - 1) * 100:+.1f}%, "
                           f"tolerance {'-' if direction == 'down' else '+'}"
                           f"{tol * 100:.0f}%)"})
    # tuning-lost sentinel: a fresh fused-chain round that ran UNTUNED
    # while the committed trajectory is tuned means the autotuner
    # regressed (tuning.json unreadable, SRNN_NO_AUTOTUNE left set, or
    # the warmup hook broke) — the apps/chip median would only notice
    # rounds later, after the damage moved it
    tuned_hist = [n for n, doc in history
                  if doc.get("tuned_block")
                  and _backend_family(doc) == fam]
    if fresh.get("impl") and not fresh.get("tuned_block") \
            and len(tuned_hist) >= MIN_ROUNDS["tuned_apps_per_chip"]:
        findings.append({
            "kind": "soup_bench_regression", "leg": "tuned_block",
            "fresh": None, "direction": "down", "tolerance": 0.0,
            "message": "fused-chain leg ran UNTUNED (no tuned_block) but "
                       f"{len(tuned_hist)} tuned history round(s) exist "
                       "— block autotuner regression (tuning.json "
                       "missing/corrupt or SRNN_NO_AUTOTUNE left set)"})
        legs.append({"leg": "tuned_block", "fresh": None,
                     "direction": "down", "tolerance": 0.0,
                     "history_rounds": tuned_hist,
                     "verdict": "REGRESSION"})
    return {"metric": "soup_bench_regression",
            "backend_family": fam,
            "history_files": [n for n, _d in history],
            "legs": legs, "regressions": findings,
            "ok": not findings}


def compare_micro(fresh: dict) -> dict:
    """micro_dispatch doc: warning-only overhead-bound check (the rows
    carry ``overhead_pct`` vs their interleaved baseline)."""
    legs, warnings = [], []
    for row in fresh.get("rows", []):
        name = row.get("row")
        if name not in MICRO_ROWS:
            continue
        pct = row.get("overhead_pct")
        if not isinstance(pct, (int, float)):
            continue
        over = pct > MICRO_BOUND_PCT
        legs.append({"leg": f"micro.{name}", "fresh": pct,
                     "bound_pct": MICRO_BOUND_PCT,
                     "verdict": "WARNING" if over else "ok"})
        if over:
            warnings.append({
                "kind": "soup_bench_regression", "leg": f"micro.{name}",
                "severity": "warning",
                "message": f"micro_dispatch {name} overhead {pct:.1f}% > "
                           f"{MICRO_BOUND_PCT:.0f}% advisory bound "
                           "(session drift makes this warning-only)"})
    return {"metric": "soup_bench_regression", "legs": legs,
            "regressions": [], "warnings": warnings, "ok": True}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("fresh", help="fresh bench/micro_dispatch result JSON "
                                 "('-' = stdin)")
    p.add_argument("--history", default=os.path.join(REPO_ROOT,
                                                     "BENCH_*.json"),
                   metavar="GLOB",
                   help="committed result trajectory to compare against")
    p.add_argument("--from-archive", nargs="?", const=ARCHIVE_DEFAULT,
                   default=None, metavar="PATH",
                   help="ALSO median over the archived rounds in a "
                        "BENCH_archive.jsonl (bench.py's archive hook "
                        "appends every round there; default path is the "
                        "repo-root sidecar) — the committed BENCH_*.json "
                        "glob stays the baseline history either way")
    p.add_argument("--include-self", action="store_true",
                   help="keep the fresh file itself in the history set "
                        "(default: excluded when fresh is a file path, so "
                        "self-comparison cannot dilute the median)")
    p.add_argument("--scale", action="append", default=[],
                   metavar="LEG=FACTOR",
                   help="multiply the fresh doc's leg value before "
                        "comparing (the CI smoke's synthetic-regression "
                        "hook, e.g. apps_per_chip=0.6)")
    p.add_argument("--json", action="store_true",
                   help="emit the machine-readable verdict document")
    args = p.parse_args(argv)
    try:
        fresh = load_result(args.fresh)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"regress: cannot load {args.fresh}: {e}", file=sys.stderr)
        return 2
    for spec in args.scale:
        leg, _, factor = spec.partition("=")
        if leg not in LEGS or not factor:
            print(f"regress: bad --scale {spec!r} (legs: "
                  f"{', '.join(LEGS)})", file=sys.stderr)
            return 2
        path = LEGS[leg][0]
        parent = fresh
        for key in path[:-1]:
            parent = parent.get(key) or {}
        if isinstance(parent.get(path[-1]), (int, float)):
            parent[path[-1]] = parent[path[-1]] * float(factor)
    if fresh.get("bench") == "micro_dispatch":
        verdict = compare_micro(fresh)
    else:
        history = load_history(
            args.history,
            exclude_path="" if (args.include_self or args.fresh == "-")
            else args.fresh)
        if args.from_archive:
            # archived rounds join AFTER the committed files, so the
            # r0x names stay first in history_files for readability;
            # the median is order-independent
            history += load_archive_rounds(args.from_archive)
        verdict = compare(fresh, history)
        try:
            with open(os.path.join(REPO_ROOT, "BASELINE.json")) as f:
                verdict["baseline_metric"] = json.load(f).get("metric")
        except (OSError, json.JSONDecodeError):
            pass
    if args.json:
        print(json.dumps(verdict, indent=1))
    else:
        for leg in verdict["legs"]:
            med = leg.get("history_median")
            print(f"{leg['leg']:<28} {leg['verdict']:<22} "
                  f"fresh={leg.get('fresh')}"
                  + (f" median={med} ratio={leg.get('ratio')}"
                     if med is not None else ""))
        for f in verdict["regressions"] + verdict.get("warnings", []):
            print(f"!! {f['message']}")
        print("verdict: " + ("ok" if verdict["ok"]
                             else f"{len(verdict['regressions'])} "
                                  "regression(s)"))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
