// trajstore: appendable binary store for soup trajectories + event logs.
//
// Role: the host-side IO runtime for srnn_tpu trajectory capture.  The
// reference keeps every per-step weight snapshot of every particle in RAM
// inside ParticleDecorator.save_state (reference network.py:193-198) and
// dill-dumps at exit — impossible at 1M particles x 1000 generations
// (SURVEY §5, §7 hard parts).  This store streams frames to disk from a
// background writer thread so device compute overlaps host IO, with a
// CRC32 per frame for integrity and truncation recovery on read.
//
// File layout (little-endian):
//   header: magic "SRNNTRJ1" | u32 version | u32 reserved
//           | u64 n_particles | u64 n_weights
//   frame:  u64 generation
//           | f32 weights[N*P] | i32 uids[N] | i32 action[N]
//           | i32 counterpart[N] | f32 loss[N] | u32 crc32(payload)
//
// C API (ctypes-friendly): ts_create / ts_open_append / ts_append /
// ts_flush / ts_close on the write side; ts_open_read / ts_meta /
// ts_read_frames / ts_close_read on the read side.  All functions return 0
// on success or a negative TS_E* code.
//
// Resume semantics: ts_create truncates (a NEW run); ts_open_append
// validates the existing header (magic/version/N/P must match), drops a
// torn trailing frame from a crashed writer (ftruncate to the last
// complete frame), and appends after it — a resumed soup run never loses
// previously captured frames.

// Large-file safety: a mega-soup .traj (1M particles ≈ 56 MB/frame) passes
// 2 GiB within ~40 frames, so all offsets go through fseeko/ftello with
// off_t forced to 64 bits — long-based fseek/ftell would overflow on any
// ILP32 build (ADVICE r3).
#ifndef _FILE_OFFSET_BITS
#define _FILE_OFFSET_BITS 64
#endif

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr char kMagic[8] = {'S', 'R', 'N', 'N', 'T', 'R', 'J', '1'};
constexpr uint32_t kVersion = 1;

enum TsError : int {
  TS_OK = 0,
  TS_EIO = -1,
  TS_EFORMAT = -2,
  TS_ECLOSED = -3,
  TS_ERANGE = -4,
};

// CRC32 (IEEE 802.3), small table variant — no zlib dependency.
uint32_t crc32(const uint8_t* data, size_t len, uint32_t crc = 0) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  crc = ~crc;
  for (size_t i = 0; i < len; i++) crc = table[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

struct Header {
  char magic[8];
  uint32_t version;
  uint32_t reserved;
  uint64_t n_particles;
  uint64_t n_weights;
};
static_assert(sizeof(Header) == 32, "header layout");

size_t payload_bytes(uint64_t n, uint64_t p) {
  return sizeof(uint64_t) + n * p * sizeof(float) + 3 * n * sizeof(int32_t) +
         n * sizeof(float);
}

struct Writer {
  FILE* f = nullptr;
  uint64_t n = 0, p = 0;
  std::thread worker;
  std::mutex mu;
  std::condition_variable cv_push, cv_drain;
  std::deque<std::vector<uint8_t>> queue;
  bool closing = false;
  int error = TS_OK;
  size_t max_queue = 8;  // frames in flight before append blocks

  void run() {
    for (;;) {
      std::vector<uint8_t> frame;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_push.wait(lk, [&] { return !queue.empty() || closing; });
        if (queue.empty()) {
          if (closing) return;
          continue;
        }
        frame = std::move(queue.front());
        queue.pop_front();
      }
      if (fwrite(frame.data(), 1, frame.size(), f) != frame.size()) {
        std::lock_guard<std::mutex> lk(mu);
        error = TS_EIO;
      }
      cv_drain.notify_all();
    }
  }
};

struct Reader {
  FILE* f = nullptr;
  uint64_t n = 0, p = 0;
  off_t data_start = 0;
  uint64_t frames = 0;
};

}  // namespace

extern "C" {

// ---- write side -----------------------------------------------------------

void* ts_create(const char* path, uint64_t n_particles, uint64_t n_weights) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  Header h{};
  memcpy(h.magic, kMagic, 8);
  h.version = kVersion;
  h.n_particles = n_particles;
  h.n_weights = n_weights;
  if (fwrite(&h, sizeof h, 1, f) != 1) {
    fclose(f);
    return nullptr;
  }
  Writer* w = new Writer;
  w->f = f;
  w->n = n_particles;
  w->p = n_weights;
  w->worker = std::thread([w] { w->run(); });
  return w;
}

// Open an existing store for appending (or create it if absent).  The
// header must match (n_particles, n_weights) exactly; a torn trailing
// frame is truncated away.  ``existing_frames`` (nullable) receives the
// number of complete frames already on disk.
void* ts_open_append(const char* path, uint64_t n_particles,
                     uint64_t n_weights, uint64_t* existing_frames) {
  if (existing_frames) *existing_frames = 0;
  struct stat st;
  if (stat(path, &st) != 0) return ts_create(path, n_particles, n_weights);
  FILE* f = fopen(path, "r+b");
  if (!f) return nullptr;
  Header h{};
  if (fread(&h, sizeof h, 1, f) != 1 || memcmp(h.magic, kMagic, 8) != 0 ||
      h.version != kVersion || h.n_particles != n_particles ||
      h.n_weights != n_weights) {
    fclose(f);
    return nullptr;
  }
  size_t frame_bytes = payload_bytes(n_particles, n_weights) + sizeof(uint32_t);
  if (fseeko(f, 0, SEEK_END) != 0) {
    fclose(f);
    return nullptr;
  }
  off_t end = ftello(f);
  uint64_t frames =
      static_cast<uint64_t>(end - sizeof(Header)) / frame_bytes;
  off_t valid_end = static_cast<off_t>(sizeof(Header) + frames * frame_bytes);
  if (valid_end != end) {
    // crashed mid-frame: drop the torn tail so appends start clean
    if (ftruncate(fileno(f), valid_end) != 0) {
      fclose(f);
      return nullptr;
    }
  }
  if (fseeko(f, valid_end, SEEK_SET) != 0) {
    fclose(f);
    return nullptr;
  }
  if (existing_frames) *existing_frames = frames;
  Writer* w = new Writer;
  w->f = f;
  w->n = n_particles;
  w->p = n_weights;
  w->worker = std::thread([w] { w->run(); });
  return w;
}

int ts_append(void* handle, uint64_t generation, const float* weights,
              const int32_t* uids, const int32_t* action,
              const int32_t* counterpart, const float* loss) {
  Writer* w = static_cast<Writer*>(handle);
  if (!w || !w->f) return TS_ECLOSED;
  const uint64_t n = w->n, p = w->p;
  std::vector<uint8_t> frame(payload_bytes(n, p) + sizeof(uint32_t));
  uint8_t* dst = frame.data();
  auto put = [&dst](const void* src, size_t len) {
    memcpy(dst, src, len);
    dst += len;
  };
  put(&generation, sizeof generation);
  put(weights, n * p * sizeof(float));
  put(uids, n * sizeof(int32_t));
  put(action, n * sizeof(int32_t));
  put(counterpart, n * sizeof(int32_t));
  put(loss, n * sizeof(float));
  uint32_t crc = crc32(frame.data(), payload_bytes(n, p));
  put(&crc, sizeof crc);
  {
    std::unique_lock<std::mutex> lk(w->mu);
    w->cv_drain.wait(lk, [&] { return w->queue.size() < w->max_queue || w->error; });
    if (w->error) return w->error;
    w->queue.push_back(std::move(frame));
  }
  w->cv_push.notify_one();
  return TS_OK;
}

int ts_flush(void* handle) {
  Writer* w = static_cast<Writer*>(handle);
  if (!w || !w->f) return TS_ECLOSED;
  {
    std::unique_lock<std::mutex> lk(w->mu);
    w->cv_drain.wait(lk, [&] { return w->queue.empty() || w->error; });
    if (w->error) return w->error;
  }
  return fflush(w->f) == 0 ? TS_OK : TS_EIO;
}

int ts_close(void* handle) {
  Writer* w = static_cast<Writer*>(handle);
  if (!w) return TS_ECLOSED;
  {
    std::lock_guard<std::mutex> lk(w->mu);
    w->closing = true;
  }
  w->cv_push.notify_all();
  if (w->worker.joinable()) w->worker.join();
  int rc = w->error;
  if (w->f) {
    if (fflush(w->f) != 0) rc = rc ? rc : TS_EIO;
    if (fclose(w->f) != 0) rc = rc ? rc : TS_EIO;
  }
  delete w;
  return rc;
}

// ---- read side ------------------------------------------------------------

void* ts_open_read(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  Header h{};
  if (fread(&h, sizeof h, 1, f) != 1 || memcmp(h.magic, kMagic, 8) != 0 ||
      h.version != kVersion) {
    fclose(f);
    return nullptr;
  }
  Reader* r = new Reader;
  r->f = f;
  r->n = h.n_particles;
  r->p = h.n_weights;
  r->data_start = static_cast<off_t>(sizeof h);
  fseeko(f, 0, SEEK_END);
  off_t end = ftello(f);
  size_t frame_bytes = payload_bytes(r->n, r->p) + sizeof(uint32_t);
  // a torn trailing frame (crash mid-write) is excluded by integer division
  r->frames = static_cast<uint64_t>(end - r->data_start) / frame_bytes;
  return r;
}

int ts_meta(void* handle, uint64_t* n_particles, uint64_t* n_weights,
            uint64_t* n_frames) {
  Reader* r = static_cast<Reader*>(handle);
  if (!r) return TS_ECLOSED;
  *n_particles = r->n;
  *n_weights = r->p;
  *n_frames = r->frames;
  return TS_OK;
}

// Reads frames [start, start+count) into caller-allocated arrays shaped
// (count, ...). Any frame failing its CRC check aborts with TS_EFORMAT.
int ts_read_frames(void* handle, uint64_t start, uint64_t count,
                   uint64_t* generations, float* weights, int32_t* uids,
                   int32_t* action, int32_t* counterpart, float* loss) {
  Reader* r = static_cast<Reader*>(handle);
  if (!r || !r->f) return TS_ECLOSED;
  if (start + count > r->frames) return TS_ERANGE;
  const uint64_t n = r->n, p = r->p;
  const size_t body = payload_bytes(n, p);
  const size_t frame_bytes = body + sizeof(uint32_t);
  std::vector<uint8_t> buf(frame_bytes);
  if (fseeko(r->f, r->data_start + static_cast<off_t>(start * frame_bytes),
             SEEK_SET) != 0)
    return TS_EIO;
  for (uint64_t i = 0; i < count; i++) {
    if (fread(buf.data(), 1, frame_bytes, r->f) != frame_bytes) return TS_EIO;
    uint32_t stored;
    memcpy(&stored, buf.data() + body, sizeof stored);
    if (crc32(buf.data(), body) != stored) return TS_EFORMAT;
    const uint8_t* src = buf.data();
    auto get = [&src](void* dst, size_t len) {
      memcpy(dst, src, len);
      src += len;
    };
    get(generations + i, sizeof(uint64_t));
    get(weights + i * n * p, n * p * sizeof(float));
    get(uids + i * n, n * sizeof(int32_t));
    get(action + i * n, n * sizeof(int32_t));
    get(counterpart + i * n, n * sizeof(int32_t));
    get(loss + i * n, n * sizeof(float));
  }
  return TS_OK;
}

int ts_close_read(void* handle) {
  Reader* r = static_cast<Reader*>(handle);
  if (!r) return TS_ECLOSED;
  if (r->f) fclose(r->f);
  delete r;
  return TS_OK;
}

}  // extern "C"
