"""Attractor explorations — the ``fixpoint-2.ipynb`` notebook as a script.

The reference notebook (cells 0-24) probes four phenomena around weightwise
self-application; each section below reproduces one, printing its finding
and (optionally) saving a plot.  Run: ``python examples/attractors.py``.

1. Training f(x)=x on a single point: SGD on one sample drives the net to
   reproduce that sample — the simplest "learn to be a fixpoint" picture.
2. Untrained random nets are attractors too: repeated self-application
   almost always converges *somewhere* (zero or infinity), rarely wanders.
3. Chains/cycles of networks: apply net A to net B's weights and vice versa
   — two-element cycles where each rewrites the other.
4. Offset perturbation: nudge an attractor's weights and watch the return
   (or escape) — the notebook-scale version of known-fixpoint-variation.
"""

import jax
import jax.numpy as jnp
import numpy as np

from srnn_tpu import (Topology, init_flat, init_population, is_diverged,
                      is_zero, run_fixpoint)
from srnn_tpu.fixtures import identity_fixpoint_flat, vary
from srnn_tpu.netops import attack, self_attack
from srnn_tpu.train import fit_epoch

TOPO = Topology("weightwise", width=2, depth=2)


def single_point_training(steps: int = 400):
    """Cells ~0-6: regress one fixed (x, y) pair with plain SGD."""
    w = init_flat(TOPO, jax.random.key(0))
    x = jnp.asarray([[0.5, 0.0, 0.5, 0.5]])
    y = jnp.asarray([0.25])
    for _ in range(steps):
        w, loss = fit_epoch(TOPO, w, x, y, lr=0.1, mode="full_batch")
    print(f"1. single-point training: loss after {steps} steps = {float(loss):.2e}")
    return float(loss)


def random_nets_converge(trials: int = 64):
    """Cells ~7-12: classify where untrained nets end up after repeated
    self-application."""
    pop = init_population(TOPO, jax.random.key(1), trials)
    res = run_fixpoint(TOPO, pop, step_limit=100)
    counts = np.asarray(res.counts)
    wandering = counts[4]
    print(f"2. random nets after 100 self-applications: "
          f"{counts[0]} diverged, {counts[1]} at zero, {wandering} still wandering")
    return counts


def two_net_cycle(steps: int = 20):
    """Cells ~13-18: A attacks B, then B attacks A, repeatedly."""
    a = init_flat(TOPO, jax.random.key(2)) * 0.7
    b = init_flat(TOPO, jax.random.key(3)) * 0.7
    for _ in range(steps):
        b = attack(TOPO, a, b)
        a = attack(TOPO, b, a)
    fate = ("diverged" if bool(is_diverged(a) | is_diverged(b)) else
            "zero" if bool(is_zero(a) & is_zero(b)) else "nontrivial")
    print(f"3. two-net cycle after {steps} rounds: {fate}")
    return a, b


def offset_perturbation(scale: float = 1e-4, steps: int = 50):
    """Cells ~19-24: perturb the identity fixpoint, self-apply, measure
    drift from the fixpoint."""
    fp = identity_fixpoint_flat(TOPO)
    w = vary(jax.random.key(4), fp, scale)
    drift0 = float(jnp.abs(w - fp).max())
    w = self_attack(TOPO, w, iterations=steps)
    drift = float(jnp.abs(w - fp).max())
    print(f"4. perturb identity by {scale:g}: initial drift {drift0:.2e} -> "
          f"after {steps} self-applications {drift:.2e}")
    return drift0, drift


def main():
    single_point_training()
    random_nets_converge()
    two_net_cycle()
    offset_perturbation()


if __name__ == "__main__":
    main()
