"""Attractor explorations — the ``fixpoint-2.ipynb`` notebook as a script.

The reference notebook (cells 0-24) probes phenomena around networks as
attractors; each section below reproduces one, printing its finding and
saving figures under ``examples/figures/``.  Run headless:
``python examples/attractors.py``.

1. Training f(x)=x on a single point: SGD on one sample drives the net to
   reproduce that sample — the simplest "learn to be a fixpoint" picture
   (notebook cells 8-13).
2. Untrained random nets are attractors too: repeated self-application
   almost always converges *somewhere* (zero or infinity), rarely wanders
   (cells 16-19).
3. Chains/cycles of networks: apply net A to net B's weights and vice versa
   — two-element cycles where each rewrites the other.
4. Offset perturbation: nudge an attractor's weights and watch the return
   (or escape) — the notebook-scale version of known-fixpoint-variation.
5. Point trajectories through a CYCLE of networks (cells 20-21): feed a
   point x through n nets cyclically, x_{t+1} = f_{t mod n}(x_t); the
   composed map's attractor shows up as a per-dimension trajectory.
6. The same cycle with a constant offset added per application (cells
   22-23) — shifting every net's fixpoint away from zero.
7. Basin of attraction around the identity fixpoint: sweep perturbation
   scales (``fixtures.vary``), measure the fraction of perturbed nets that
   remain/return to a fixpoint vs fall to zero/divergence — the example-
   scale twin of ``setups/known_fixpoint_variation``.

Deviation note for 5/6: the notebook's point-iterated nets are keras
``Dense`` layers WITH biases; this framework's nets are its standard
bias-free MLPs (``Topology`` semantics, reference ``network.py:80``), so
the qualitative picture (spiral/decay to an attractor, offset shifting it)
is the reproduction target, not the exact trajectories — without biases an
un-offset linear cycle's only finite attractor is 0.
"""

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402

from srnn_tpu import (Topology, init_flat, init_population, is_diverged,  # noqa: E402
                      is_zero, run_fixpoint)
from srnn_tpu.fixtures import identity_fixpoint_flat, vary  # noqa: E402
from srnn_tpu.netops import apply_to_weights, attack, self_attack  # noqa: E402
from srnn_tpu.ops.mlp import mlp_forward  # noqa: E402
from srnn_tpu.ops.predicates import is_fixpoint  # noqa: E402
from srnn_tpu.train import fit_epoch  # noqa: E402

TOPO = Topology("weightwise", width=2, depth=2)
FIG_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "figures")


def _savefig(fig, name):
    os.makedirs(FIG_DIR, exist_ok=True)
    path = os.path.join(FIG_DIR, name)
    fig.savefig(path, dpi=110, bbox_inches="tight")
    plt.close(fig)
    return path


def single_point_training(steps: int = 400):
    """Cells ~0-6: regress one fixed (x, y) pair with plain SGD."""
    w = init_flat(TOPO, jax.random.key(0))
    x = jnp.asarray([[0.5, 0.0, 0.5, 0.5]])
    y = jnp.asarray([0.25])
    for _ in range(steps):
        w, loss = fit_epoch(TOPO, w, x, y, lr=0.1, mode="full_batch")
    print(f"1. single-point training: loss after {steps} steps = {float(loss):.2e}")
    return float(loss)


def random_nets_converge(trials: int = 64):
    """Cells ~7-12: classify where untrained nets end up after repeated
    self-application."""
    pop = init_population(TOPO, jax.random.key(1), trials)
    res = run_fixpoint(TOPO, pop, step_limit=100)
    counts = np.asarray(res.counts)
    wandering = counts[4]
    print(f"2. random nets after 100 self-applications: "
          f"{counts[0]} diverged, {counts[1]} at zero, {wandering} still wandering")
    return counts


def two_net_cycle(steps: int = 20):
    """Cells ~13-18: A attacks B, then B attacks A, repeatedly."""
    a = init_flat(TOPO, jax.random.key(2)) * 0.7
    b = init_flat(TOPO, jax.random.key(3)) * 0.7
    for _ in range(steps):
        b = attack(TOPO, a, b)
        a = attack(TOPO, b, a)
    fate = ("diverged" if bool(is_diverged(a) | is_diverged(b)) else
            "zero" if bool(is_zero(a) & is_zero(b)) else "nontrivial")
    print(f"3. two-net cycle after {steps} rounds: {fate}")
    return a, b


def offset_perturbation(scale: float = 1e-4, steps: int = 50):
    """Cells ~19-24: perturb the identity fixpoint, self-apply, measure
    drift from the fixpoint."""
    fp = identity_fixpoint_flat(TOPO)
    w = vary(jax.random.key(4), fp, scale)
    drift0 = float(jnp.abs(w - fp).max())
    w = self_attack(TOPO, w, iterations=steps)
    drift = float(jnp.abs(w - fp).max())
    print(f"4. perturb identity by {scale:g}: initial drift {drift0:.2e} -> "
          f"after {steps} self-applications {drift:.2e}")
    return drift0, drift


def network_cycle_trajectories(n_models: int = 4, steps: int = 100,
                               starts: int = 2, offset: float = 0.0):
    """Cells 20-23: iterate points through a cycle of R^2 -> R^2 nets,
    optionally adding ``offset`` to every prediction."""
    # the framework's 2-in/2-out MLP: the aggregating variant's net shape
    # (reference network.py:324-333) doubles as the notebook's DIM=2 model
    net_topo = Topology("aggregating", width=2, depth=2, aggregates=2)
    keys = jax.random.split(jax.random.key(20), n_models)
    models = [init_flat(net_topo, k) for k in keys]

    fig, axes = plt.subplots(1, starts, figsize=(5 * starts, 3.2),
                             squeeze=False)
    finals = []
    for s in range(starts):
        x = jax.random.uniform(jax.random.key(100 + s), (2,))
        traj = [np.asarray(x)]
        for t in range(steps):
            x = mlp_forward(net_topo, models[t % n_models], x[None, :])[0]
            x = x + offset
            traj.append(np.asarray(x))
        traj = np.stack(traj)
        finals.append(traj[-1])
        ax = axes[0, s]
        ax.plot(traj[:, 0], label="dim 0")
        ax.plot(traj[:, 1], label="dim 1")
        ax.set_xlabel("application t")
        ax.set_title(f"start {s}, offset={offset:g}")
        ax.legend()
    tag = "offset" if offset else "cycle"
    path = _savefig(fig, f"network_{tag}_trajectories.png")
    label = "5. network-cycle" if not offset else "6. offset-cycle"
    print(f"{label} trajectories ({n_models} nets, {steps} applications): "
          f"final points {[np.round(f, 4).tolist() for f in finals]} -> {path}")
    return finals


def basin_of_attraction(scales=tuple(10.0 ** -e for e in range(9, -1, -1)),
                        trials: int = 64, steps: int = 30,
                        epsilon: float = 1e-4):
    """Cells 24 ('is a trained net also an attractor?') meets
    known-fixpoint-variation: perturb the identity fixpoint at each scale
    (``fixtures.vary``), self-apply ``steps`` times, and classify the
    survivors — the basin boundary shows up as the scale where the
    still-a-fixpoint fraction collapses."""
    fp = identity_fixpoint_flat(TOPO)
    rows = []
    for scale in scales:
        keys = jax.random.split(jax.random.fold_in(jax.random.key(7), hash(scale) & 0x7FFFFFFF), trials)
        perturbed = jnp.stack([vary(k, fp, scale) for k in keys])
        res = run_fixpoint(TOPO, perturbed, step_limit=steps, epsilon=epsilon)
        w = res.weights
        still_fix = np.asarray(jax.vmap(
            lambda wi: is_fixpoint(
                functools.partial(apply_to_weights, TOPO, wi), wi,
                epsilon=epsilon))(w))
        diverged = np.asarray(jax.vmap(is_diverged)(w))
        zero = np.asarray(jax.vmap(lambda wi: is_zero(wi, epsilon))(w))
        rows.append((scale, still_fix.mean(), zero.mean(), diverged.mean()))

    rows_a = np.asarray(rows)
    fig, ax = plt.subplots(figsize=(6, 3.6))
    ax.semilogx(rows_a[:, 0], rows_a[:, 1], "o-", label="still a fixpoint")
    ax.semilogx(rows_a[:, 0], rows_a[:, 2], "s--", label="fell to zero")
    ax.semilogx(rows_a[:, 0], rows_a[:, 3], "^:", label="diverged")
    ax.set_xlabel("perturbation scale")
    ax.set_ylabel(f"fraction of {trials} trials after {steps} applications")
    ax.set_title("basin of attraction around the identity fixpoint")
    ax.legend()
    path = _savefig(fig, "basin_of_attraction.png")
    edge = next((s for s, f, _, _ in rows if f < 0.5), None)
    print(f"7. basin of attraction: fixpoint fraction collapses near "
          f"scale {edge:g} -> {path}" if edge is not None else
          f"7. basin of attraction: fixpoint survives every scale -> {path}")
    return rows


def main():
    single_point_training()
    random_nets_converge()
    two_net_cycle()
    offset_perturbation()
    network_cycle_trajectories(offset=0.0)
    network_cycle_trajectories(offset=0.1)
    basin_of_attraction()


if __name__ == "__main__":
    main()
