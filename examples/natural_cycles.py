"""Why natural period-2 cycles exist (at ~1e-5) and natural fixpoints
don't (< 3e-8): the closed-form law behind the 100M-sample density run
(`results_tpu/exp-fixpoint_density-_1785484013.956405-0`, RESULTS.md).

With the linear activation every reference experiment effectively ran
(SURVEY quirk 2.4.11), the weightwise transform is AFFINE in its target:
each output weight is the 4-feature MLP applied to [v_p, coords_p], so

    f_w(v) = a(w) * v + g(w),

where a(w) is the composed linear coefficient of the weight-value input —
for the 4->2->2->1 net, the path sum W1[0, :] @ W2 @ W3 — and g(w)_p is
the affine contribution of the coordinate features.  Iterating,

    f_w(f_w(v)) = a^2 v + (a + 1) g .

Consequences, verified here against the recorded density-run PRNG stream:

  * a(w) = -1  =>  f_w is an involution: EVERY target is a 2-cycle
    (except the single point v* = g/2, which is the fixpoint).  A random
    net is a natural fix_sec exactly when its scalar gain lands within
    the epsilon-tolerance window of -1 — a codimension-1 event, rate
    ~ p_a(-1) x window.  The 100M-run rate (9.5e-6) is reproduced from
    the measured gain density and window below.
  * a(w) = +1 AND w = g/(1 - a) is what a natural degree-1 fixpoint
    would need — a measure-zero intersection of a codim-1 event with a
    codim-P coincidence, hence 0 in 100M.
  * The aggregating variant's transform maps into the rank-k
    replicate(MLP(segment-avg)) subspace, so f^2(w) = w additionally
    requires the net's own 20-dim weight vector to lie in a 4-dim
    subspace — codim 16 on top of the eigenvalue condition; hence
    neither class occurs in 100M samples.

Run headless:  python examples/natural_cycles.py [--samples 5000000]
Writes figures/natural_cycles.png and prints the verification numbers.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from srnn_tpu import Topology, init_population
from srnn_tpu.engine import classify_batch
from srnn_tpu.nets import apply_to_weights

FIG_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "figures")


def input_gain(w: np.ndarray, topo: Topology) -> float:
    """a(w): composed coefficient of the weight-value input feature (path
    sum W1[0, :] @ W2 @ ... through the linear MLP; kernel layout from
    ``ops.flatten.unflatten`` so the layer shapes stay in one place)."""
    from srnn_tpu.ops.flatten import unflatten

    mats = unflatten(topo, jnp.asarray(w))
    acc = np.asarray(mats[0])[0:1]
    for m in mats[1:]:
        acc = acc @ np.asarray(m)
    return float(acc[0, 0])


# The committed 100M density run's batching: its PRNG stream keys each
# batch on the cumulative sample count (`fixpoint_density.py`:
# fold_in(fold_in(key, arch), done) with done stepping by --batch), so
# rescanning the SAME stream requires the SAME batch size — 500,000, per
# the run dir's config.json (this is deliberately NOT a CLI flag here).
RUN_BATCH = 500_000


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--samples", type=int, default=5_000_000,
                    help="how much of the density run's stream to rescan "
                         f"(rounded up to the run's {RUN_BATCH:,} batch)")
    ap.add_argument("--no-figure", action="store_true",
                    help="verification numbers only (smoke tests must not "
                         "overwrite the committed full-sample figure)")
    ap.add_argument("--basin-trials", type=int, default=4000,
                    help="trials for the self-application basin panel")
    args = ap.parse_args(argv)

    from srnn_tpu.ops.predicates import CLS_DIVERGENT, CLS_FIX_SEC

    topo = Topology("weightwise")
    key = jax.random.key(0)  # the committed 100M run's seed stream

    # -- collect natural fix_sec nets from the SAME stream ---------------
    hits, done = [], 0
    while done < args.samples:
        pop = init_population(
            topo, jax.random.fold_in(jax.random.fold_in(key, 0), done),
            RUN_BATCH)
        cls = np.asarray(classify_batch(topo, pop, 1e-4))
        hits += [np.asarray(pop[j])
                 for j in np.nonzero(cls == CLS_FIX_SEC)[0]]
        done += RUN_BATCH
    print(f"natural fix_sec nets: {len(hits)} in {done:,} samples "
          f"(rate {len(hits) / done:.2e})")
    if not hits:
        print(f"no hits at this sample size (expect ~1 per 105k samples); "
              f"re-run with a larger --samples")
        return 0

    gains = np.array([input_gain(w, topo) for w in hits])
    print(f"a(w) over the cycle nets: mean {gains.mean():+.7f}, "
          f"max |a+1| = {np.abs(gains + 1).max():.2e}")

    # -- the gain distribution over ORDINARY random nets -----------------
    ref = np.asarray(init_population(topo, jax.random.key(123), 20_000))
    allg = np.array([input_gain(w, topo) for w in ref])
    h = 0.05
    p_minus1 = (np.abs(allg + 1) < h).sum() / len(allg) / (2 * h)
    window = 2 * np.abs(gains + 1).max()
    print(f"gain density near -1: {p_minus1:.3f}/unit; tolerance window "
          f"~{window:.1e}  =>  predicted rate {p_minus1 * window:.1e} "
          f"(measured {len(hits) / done:.1e})")

    # -- involution check: f_w is period-2 on arbitrary targets ----------
    w = jnp.asarray(hits[0])
    v = jax.random.normal(jax.random.key(7), w.shape)
    v2 = apply_to_weights(topo, w, v)
    v4 = apply_to_weights(topo, w, v2)
    err = float(jnp.max(jnp.abs(v4 - v)))
    print(f"involution on a random target: max |f(f(v)) - v| = {err:.1e}")

    # -- the gain also organizes the SELF-APPLICATION dynamics -----------
    # w_{t+1} = a(w_t) w_t + g(w_t) with a(w) CUBIC in w: growth inflates
    # the gain, so divergence is self-reinforcing — a basin, not a
    # threshold.  |a_0| > 1 is near-sufficient for divergence; below 1
    # the affine offset can still pump |w| across the basin boundary.
    from srnn_tpu.engine import run_fixpoint

    pop_j = init_population(topo, jax.random.key(11), args.basin_trials)
    res = run_fixpoint(topo, pop_j, step_limit=100, epsilon=1e-4)
    cls = np.asarray(res.classes)
    a0 = np.array([input_gain(w, topo) for w in np.asarray(pop_j)])
    div = cls == CLS_DIVERGENT
    print(f"self-application outcomes vs initial gain "
          f"({args.basin_trials} trials: {div.mean():.1%} divergent): "
          f"P(div | |a0|>1) = {div[np.abs(a0) > 1].mean():.2f}, "
          f"P(div | |a0|<1) = {div[np.abs(a0) < 1].mean():.2f}")

    # -- figure ----------------------------------------------------------
    if args.no_figure:
        return len(hits)
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, (ax1, ax2, ax3) = plt.subplots(1, 3, figsize=(16, 4.2))
    ax1.hist(allg, bins=120, range=(-3, 3), color="#888", alpha=0.8)
    ax1.axvline(-1.0, color="tab:red", lw=1.5,
                label="a = -1 (involution)")
    ax1.axvline(1.0, color="tab:blue", lw=1.5, ls="--",
                label="a = +1 (fixpoint gain)")
    ax1.set_xlabel("input gain a(w) over random nets")
    ax1.set_ylabel("count (20k sample)")
    ax1.legend(fontsize=8)
    ax1.grid(alpha=0.3)
    ax2.scatter(range(len(gains)), gains + 1.0, s=12, color="tab:red")
    ax2.axhline(0.0, color="k", lw=0.8)
    ax2.set_xlabel("natural fix_sec net #")
    ax2.set_ylabel("a(w) + 1")
    ax2.set_title(f"all {len(gains)} natural 2-cycles sit on a = -1")
    ax2.grid(alpha=0.3)
    bins = np.linspace(0, 2.5, 26)
    centers = 0.5 * (bins[:-1] + bins[1:])
    p_div = [div[(np.abs(a0) >= lo) & (np.abs(a0) < hi)].mean()
             if ((np.abs(a0) >= lo) & (np.abs(a0) < hi)).any() else np.nan
             for lo, hi in zip(bins[:-1], bins[1:])]
    ax3.plot(centers, p_div, marker="o", ms=3, color="tab:red")
    ax3.axvline(1.0, color="k", lw=0.8, ls="--", label="|a| = 1")
    ax3.set_xlabel("initial gain |a(w0)|")
    ax3.set_ylabel("P(divergent)")
    ax3.set_title("divergence basin of self-application\n"
                  "(gain is cubic in w: runaway is self-reinforcing)")
    ax3.legend(fontsize=8)
    ax3.grid(alpha=0.3)
    os.makedirs(FIG_DIR, exist_ok=True)
    out = os.path.join(FIG_DIR, "natural_cycles.png")
    fig.tight_layout()
    fig.savefig(out, dpi=110)
    print(f"wrote {out}")
    return len(hits)


if __name__ == "__main__":
    main()
