"""Cross-type attack pressure in the TRUE mixed soup.

The reference's mixed-soup experiment sweeps training against attack at a
fixed 0.1 attack rate, and runs a SEPARATE homogeneous soup per
architecture (`mixed-soup.py:66-68`) — its object design cannot put types
in one population.  This framework's multisoup has real any-on-any
cross-type attacks (`ops/popmajor_cross.py`), so a question the reference
could not ask: how does CROSS-TYPE attack pressure reshape each
subpopulation's class structure?

Sweep: attacking_rate in {0, 0.05, 0.1, 0.2, 0.5}, everything else the
committed production run's config (train=10 batch-1, learn_from 0.1/1,
both respawns, popmajor, fused draws; see
results_tpu/exp-mega-multisoup-_1785480462.6968212-0).  N=6,000 (2k per
type), 200 generations per point.

Run headless:  python examples/mixed_attack_sweep.py
Writes figures/mixed_attack_sweep.png and prints one JSON line per point.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

from srnn_tpu import Topology
from srnn_tpu.multisoup import (MultiSoupConfig, count_multi, evolve_multi,
                                seed_multi)

FIG_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "figures")
RATES = (0.0, 0.05, 0.1, 0.2, 0.5)
TYPE_NAMES = ("weightwise", "aggregating", "recurrent")


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--per-type", type=int, default=2000)
    ap.add_argument("--generations", type=int, default=200)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--no-figure", action="store_true",
                    help="counts only (smoke tests must not overwrite the "
                         "committed full-scale figure)")
    args = ap.parse_args(argv)

    results = []
    for rate in RATES:
        cfg = MultiSoupConfig(
            topos=tuple(Topology(v, width=2, depth=2) for v in TYPE_NAMES),
            sizes=(args.per_type,) * 3,
            attacking_rate=rate, learn_from_rate=0.1,
            learn_from_severity=1, train=10,
            remove_divergent=True, remove_zero=True,
            layout="popmajor", respawn_draws="fused")
        st = seed_multi(cfg, jax.random.key(args.seed))
        fin = evolve_multi(cfg, st, generations=args.generations)
        counts = np.asarray(count_multi(cfg, fin))  # (T, 5)
        row = {"attacking_rate": rate,
               "counts": {TYPE_NAMES[t]: counts[t].tolist()
                          for t in range(3)}}
        results.append(row)
        print(json.dumps(row), flush=True)

    # figure: per-type fixpoint fraction (fix_other + fix_sec) vs rate
    if args.no_figure:
        return results
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    from srnn_tpu.ops.predicates import CLS_FIX_OTHER, CLS_FIX_SEC

    fig, ax = plt.subplots(figsize=(7, 4.5))
    for t, name in enumerate(TYPE_NAMES):
        frac = [(r["counts"][name][CLS_FIX_OTHER]
                 + r["counts"][name][CLS_FIX_SEC])
                / args.per_type for r in results]
        ax.plot(RATES, frac, marker="o", label=name)
    ax.set_xlabel("cross-type attacking_rate")
    ax.set_ylabel("fixpoint fraction (fix_other + fix_sec)")
    ax.set_title(f"mixed soup, N={3 * args.per_type}, "
                 f"{args.generations} generations, train=10")
    ax.grid(alpha=0.3)
    ax.legend()
    os.makedirs(FIG_DIR, exist_ok=True)
    out = os.path.join(FIG_DIR, "mixed_attack_sweep.png")
    fig.tight_layout()
    fig.savefig(out, dpi=110)
    print(f"wrote {out}")
    return results


if __name__ == "__main__":
    main()
