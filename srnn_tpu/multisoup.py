"""Heterogeneous soups: mixed-architecture populations with cross-type
attacks.

The reference's mixed-soup experiment (``mixed-soup.py:66-68``) runs
*separate homogeneous* soups per architecture — its object design (victim's
keras layout must match the attacker's expectations) cannot mix types in one
population.  The functional transforms here can (``srnn_tpu.nets.cross``),
so this module implements what SURVEY §2.5 maps to expert-parallel grouping:
one soup whose particles belong to typed subpopulations, where any particle
can attack any other — a weightwise net rewriting an aggregating net's
weights and vice versa.

Semantics per generation mirror ``soup._evolve_parallel`` phase-for-phase
(attack -> learn_from -> train -> respawn, last-action-wins events), with
one typed-population choice: ``learn_from`` counterparts are drawn from the
learner's OWN type — imitation needs the teacher's sample space to match
the learner's input contract, which only same-type pairs guarantee.
"""

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .init import fresh_lanes, init_population
from .nets.cross import cross_apply
from .ops.predicates import DEFAULT_EPSILON, count_classes
from .engine import classify_batch
from .soup import (
    ACT_ATTACK,
    ACT_LEARN,
    ACT_NONE,
    SoupConfig,
    _event_record,
    _learn_epochs,
    _respawn,
    _train_epochs,
)
from .topology import Topology
from .train import DEFAULT_LR


class MultiSoupConfig(NamedTuple):
    topos: Tuple[Topology, ...]
    sizes: Tuple[int, ...]
    attacking_rate: float = 0.1
    learn_from_rate: float = 0.1
    train: int = 0
    learn_from_severity: int = 1
    remove_divergent: bool = False
    remove_zero: bool = False
    epsilon: float = DEFAULT_EPSILON
    lr: float = DEFAULT_LR
    train_mode: str = "sequential"
    # 'popmajor' runs every per-type population as a (P_t, N_t) lane matrix
    # (ops/popmajor*.py) — same dynamics, particle axis on the TPU lanes;
    # requires shuffler='not' on every topo (soup._check_popmajor rationale)
    layout: str = "rowmajor"
    # respawn replacement draws — see SoupConfig.respawn_draws; 'fused'
    # applies per type where the init law allows (the recurrent type always
    # draws per-particle)
    respawn_draws: str = "perparticle"
    # see SoupConfig.train_impl; applies per type where supported
    train_impl: str = "xla"
    # see SoupConfig.apply_impl; routes the cross-type attack transform
    # per ATTACKER type where a kernel exists (recurrent attackers)
    apply_impl: str = "xla"
    # see SoupConfig.generation_impl.  The heterogeneous 'fused' spelling
    # keeps the CROSS-TYPE attack phase in XLA (attacker and victim row
    # counts differ, so it cannot ride one lane-blocked kernel) and fuses
    # each type's learn_from + self-train + respawn into one megakernel
    # launch per type on Mosaic backends; off-envelope types fall back
    # per type silently (the same policy as train_impl='pallas').
    generation_impl: str = "phases"
    # see SoupConfig.population_dtype (per-type populations all share it)
    population_dtype: str = "f32"

    @property
    def total(self) -> int:
        return sum(self.sizes)

    @property
    def offsets(self) -> Tuple[int, ...]:
        offs = [0]
        for s in self.sizes:
            offs.append(offs[-1] + s)
        return tuple(offs)

    def type_config(self, t: int) -> SoupConfig:
        """Per-type view reusing the homogeneous soup helpers."""
        return SoupConfig(
            topo=self.topos[t], size=self.sizes[t],
            attacking_rate=self.attacking_rate,
            learn_from_rate=self.learn_from_rate, train=self.train,
            learn_from_severity=self.learn_from_severity,
            remove_divergent=self.remove_divergent,
            remove_zero=self.remove_zero, epsilon=self.epsilon,
            lr=self.lr, train_mode=self.train_mode,
            respawn_draws=self.respawn_draws,
            train_impl=self.train_impl,
            population_dtype=self.population_dtype)


class MultiSoupState(NamedTuple):
    weights: Tuple[jnp.ndarray, ...]  # per type (N_t, P_t)
    uids: Tuple[jnp.ndarray, ...]     # per type (N_t,)
    next_uid: jnp.ndarray
    time: jnp.ndarray
    key: jax.Array
    # int8 mode only: per-type (N_t,) f32 dequantization scales (see
    # SoupState.scales — None stays an EMPTY subtree for f32/bf16 states)
    scales: Optional[Tuple[jnp.ndarray, ...]] = None


def _type_scales(state: MultiSoupState, t: int) -> Optional[jnp.ndarray]:
    """Type ``t``'s int8 scale vector (None for f32/bf16 states)."""
    return None if state.scales is None else state.scales[t]


class MultiSoupEvents(NamedTuple):
    action: Tuple[jnp.ndarray, ...]
    counterpart: Tuple[jnp.ndarray, ...]
    loss: Tuple[jnp.ndarray, ...]


def seed_multi(config: MultiSoupConfig, key: jax.Array) -> MultiSoupState:
    from .soup import _downcast, _pop_dtype

    keys = jax.random.split(key, len(config.topos) + 1)
    weights, uids, scales = [], [], []
    offs = config.offsets
    for t, topo in enumerate(config.topos):
        w = init_population(topo, keys[t], config.sizes[t])
        if config.population_dtype == "int8":
            w, sc = _downcast(config, w)
            scales.append(sc)
        else:
            w = w.astype(_pop_dtype(config))
        weights.append(w)
        uids.append(jnp.arange(offs[t], offs[t + 1], dtype=jnp.int32))
    return MultiSoupState(
        weights=tuple(weights), uids=tuple(uids),
        next_uid=jnp.int32(config.total), time=jnp.int32(0), key=keys[-1],
        scales=tuple(scales) if scales else None)


def _attack_phase(config: MultiSoupConfig, weights, k_gate, k_tgt):
    """Global attacker/victim draw, then one vmapped cross-apply per
    (attacker-type, victim-type) pair with masking — T^2 fused transforms
    instead of data-dependent control flow."""
    n = config.total
    offs = config.offsets
    gate = jax.random.uniform(k_gate, (n,)) < config.attacking_rate
    tgt = jax.random.randint(k_tgt, (n,), 0, n)
    # last-attacker-wins per victim (same resolution as soup._evolve_parallel)
    att_idx = jax.ops.segment_max(
        jnp.where(gate, jnp.arange(n), -1), tgt, num_segments=n)

    new_weights = []
    for b, victim_topo in enumerate(config.topos):
        w_b = weights[b]
        att_b = jax.lax.dynamic_slice_in_dim(att_idx, offs[b], config.sizes[b])
        out = w_b
        for a, attacker_topo in enumerate(config.topos):
            mask = (att_b >= offs[a]) & (att_b < offs[a + 1])
            rows = weights[a][jnp.clip(att_b - offs[a], 0, config.sizes[a] - 1)]
            attacked = jax.vmap(
                lambda s, v: cross_apply(attacker_topo, s, victim_topo, v)
            )(rows, w_b)
            out = jnp.where(mask[:, None], attacked, out)
        new_weights.append(out)
    return tuple(new_weights), gate, tgt, att_idx


def _record_multi_lineage(lins, win, gen, lin_info, lincfg, axes=None):
    """Post-loop lineage bookkeeping for one mixed generation: per type,
    the fused ``dynamics.record_step`` (attack mints -> learn edges
    against post-attack pids -> respawn mints) with mint bases chained
    type-major through ONE shared global pid counter — the respawn
    uid-block order.  ``lin_info`` is the per-type ``(att_idx slice,
    learn_gate, learn_tgt, dead)`` the phase loop stashed; running AFTER
    all the weights math matters: sharing the phase masks with the weight
    path mid-loop was measured to perturb XLA's fusion of the aggregating
    cross-apply by 1 ulp, breaking the bit-identity contract."""
    from .telemetry.dynamics import record_step

    if axes is None:
        all_pid0 = jnp.concatenate([l.pid for l in lins])
    else:
        all_pid0 = jnp.concatenate([
            jax.lax.all_gather(l.pid, axes, tiled=True) for l in lins])
    running = lins[0].next_pid
    new_lins = []
    for t, (att_b, learn_gate, learn_tgt, dead) in enumerate(lin_info):
        lin_t = lins[t]._replace(next_pid=running)
        lin_t, win = record_step(
            lin_t, win, gen=gen, attacked=att_b >= 0,
            attacker_pid=all_pid0[jnp.clip(att_b, 0)],
            learn_gate=learn_gate, learn_tgt=learn_tgt, dead=dead,
            caps=lincfg[0][t], capacity=lincfg[1], axes=axes)
        running = lin_t.next_pid
        new_lins.append(lin_t)
    # every type's carry ends on the SAME global mint counter
    return tuple(l._replace(next_pid=running) for l in new_lins), win


def _fused_type_route(config: MultiSoupConfig, topo: Topology) -> bool:
    """Does this type's learn+train+respawn block take the fused
    megakernel?  Per-type silent fallback, mirroring
    ``popmajor._use_pallas_sgd`` — ``resolved_generation_impl`` surfaces
    the resolution for run headers.  (Same routing predicate as the
    homogeneous soup: ``ops.pallas_generation.fused_kernel_route``.)"""
    from .ops.pallas_generation import fused_kernel_route

    return fused_kernel_route(topo, config.train_mode)


def fused_supported_multi(config: MultiSoupConfig) -> bool:
    """Would ``generation_impl='fused'`` be a valid spelling of this mixed
    config?  (Per-type kernel eligibility is a SILENT runtime fallback —
    this only checks the config-level constraints, mirroring
    ``soup.fused_supported`` for the AOT warmup.)"""
    if config.layout != "popmajor":
        return False
    try:
        _check_popmajor_multi(config._replace(generation_impl="fused"))
    except ValueError:
        return False
    return True


def check_tenant_stackable_multi(config: MultiSoupConfig) -> None:
    """Validate that ``config`` may ride the serve tenant axis (see
    ``soup.check_tenant_stackable`` — same contract, heterogeneous twin):
    parallel row-major only, bitwise-equal per tenant to the solo run."""
    if config.layout != "rowmajor":
        raise ValueError(
            "tenant stacking requires layout='rowmajor': the popmajor "
            "lane layout's reductions reassociate under the tenant vmap "
            "axis, breaking the bitwise-equal-to-solo contract")


def tenant_stackable_multi(config: MultiSoupConfig) -> bool:
    """Would this mixed config's evolve ride the serve tenant axis?"""
    try:
        check_tenant_stackable_multi(config)
    except ValueError:
        return False
    return True


def resolved_generation_impl(config: MultiSoupConfig,
                             topo: Topology) -> str:
    """The generation impl this type will ACTUALLY run: 'fused' only
    where the megakernel applies on this backend, else 'phases'."""
    return "fused" if (config.generation_impl == "fused"
                       and _fused_type_route(config, topo)) else "phases"


def _check_popmajor_multi(config: MultiSoupConfig) -> None:
    if config.apply_impl not in ("xla", "pallas"):
        raise ValueError(f"unknown apply_impl {config.apply_impl!r}")
    if config.train_impl not in ("xla", "pallas"):
        raise ValueError(f"unknown train_impl {config.train_impl!r}")
    if config.generation_impl not in ("phases", "fused"):
        raise ValueError(
            f"unknown generation_impl {config.generation_impl!r}")
    if config.generation_impl == "fused" and (
            config.train_impl == "pallas" or config.apply_impl == "pallas"):
        raise ValueError(
            "generation_impl='fused' already fuses the per-type SGD "
            "chains; use train_impl='xla' and apply_impl='xla' (the "
            "per-phase pallas legs are subsumed)")
    for topo in config.topos:
        if topo.shuffler == "random":
            raise ValueError(
                "layout='popmajor' requires shuffler='not' on every topo "
                "(per-lane permutation — use layout='rowmajor')")


def _evolve_multi_popmajor(config: MultiSoupConfig, state: MultiSoupState,
                           wTs: Tuple[jnp.ndarray, ...], lins=None, win=None,
                           lincfg=None):
    """Population-major twin of ``evolve_multi_step``: every per-type
    population is a (P_t, N_t) lane matrix, cross-type attacks ride
    ``cross_apply_popmajor``, and the train/learn phases use the per-variant
    lane kernels.  Same PRNG draws, same phase order, same event record as
    the row-major path (parity-tested).

    ``lins``/``win``/``lincfg`` (per-type caps + window capacity) thread
    the replication-dynamics carry: per-type ``LineageState`` tuples with
    mint bases chained type-major through ONE shared global pid counter
    (the same sequencing the respawn uid blocks use) and one shared
    event-edge window for the whole mixed population."""
    from .ops.popmajor import learn_epochs_popmajor, train_epochs_popmajor
    from .ops.popmajor_cross import cross_apply_popmajor
    from .ops.predicates import is_diverged, is_zero
    from .soup import ACT_DIV_DEAD, ACT_ZERO_DEAD, _downcast, _upcast

    fused = config.generation_impl == "fused"
    apply_impl = "xla" if fused else config.apply_impl

    n = config.total
    offs = config.offsets
    key, k_ag, k_at, k_lg, k_lt, k_re = jax.random.split(state.key, 6)
    att_idx = jnp.full(n, -1, jnp.int32)
    wTs = tuple(_upcast(config, wT, _type_scales(state, t), paxis=-1)
                for t, wT in enumerate(wTs))

    # --- attack (cross-type, last-attacker-wins) ------------------------
    with jax.named_scope("multisoup.attack"):
        if config.attacking_rate > 0:
            attack_gate = jax.random.uniform(k_ag, (n,)) < config.attacking_rate
            attack_tgt = jax.random.randint(k_at, (n,), 0, n)
            att_idx = jax.ops.segment_max(
                jnp.where(attack_gate, jnp.arange(n), -1), attack_tgt,
                num_segments=n)
            new_wTs = []
            for b, vic in enumerate(config.topos):
                att_b = jax.lax.dynamic_slice_in_dim(att_idx, offs[b],
                                                     config.sizes[b])
                out = wTs[b]
                for a, atk in enumerate(config.topos):
                    mask = (att_b >= offs[a]) & (att_b < offs[a + 1])
                    selfT = wTs[a][:, jnp.clip(att_b - offs[a], 0,
                                               config.sizes[a] - 1)]
                    attacked = cross_apply_popmajor(atk, selfT, vic, wTs[b],
                                                    impl=apply_impl)
                    out = jnp.where(mask[None, :], attacked, out)
                new_wTs.append(out)
            wTs = tuple(new_wTs)
        else:
            attack_gate = jnp.zeros(n, bool)
            attack_tgt = jnp.zeros(n, jnp.int32)

    all_uids = jnp.concatenate(state.uids)
    lin_info = []

    out_wTs, out_scales, new_uids = [], [], []
    actions, counterparts, losses = [], [], []
    total_deaths = jnp.int32(0)
    re_keys = jax.random.split(k_re, len(config.topos))
    for t, topo in enumerate(config.topos):
        wT_t = wTs[t]
        n_t = config.sizes[t]
        sl = lambda arr: jax.lax.dynamic_slice_in_dim(arr, offs[t], n_t)

        # learn draws are shared by both routes (the event record needs
        # them even when severity is 0); same key stream either way
        if config.learn_from_rate > 0:
            learn_gate = sl(jax.random.uniform(k_lg, (n,))) < config.learn_from_rate
            learn_tgt = jax.random.randint(
                jax.random.fold_in(k_lt, t), (n_t,), 0, n_t)
            learn_cp = state.uids[t][learn_tgt]
        else:
            learn_gate = jnp.zeros(n_t, bool)
            learn_tgt = jnp.zeros(n_t, jnp.int32)
            learn_cp = jnp.zeros(n_t, jnp.int32)
        sgd_learn = config.learn_from_rate > 0 \
            and config.learn_from_severity > 0

        if fused and _fused_type_route(config, topo):
            # --- fused learn+train+respawn: one launch for this type ----
            # (the cross-type attack above already ran, so the imitation
            # columns gather post-attack directly — no in-kernel recompute)
            from .ops.pallas_generation import generation_popmajor

            with jax.named_scope("multisoup.fused_generation"):
                fresh = fresh_lanes(topo, re_keys[t], n_t,
                                    config.respawn_draws)
                wT_t, loss_t, dead_div, dead_zero = generation_popmajor(
                    topo, wT_t, fresh,
                    otherT=wT_t[:, learn_tgt] if sgd_learn else None,
                    learn_gate=learn_gate if sgd_learn else None,
                    severity=config.learn_from_severity if sgd_learn else 0,
                    train=config.train, lr=config.lr,
                    remove_divergent=config.remove_divergent,
                    remove_zero=config.remove_zero, epsilon=config.epsilon)
        else:
            # --- learn_from (same-type teachers, post-attack weights) ---
            with jax.named_scope("multisoup.learn_from"):
                if sgd_learn:
                    learned, _ = learn_epochs_popmajor(
                        topo, wT_t, wT_t[:, learn_tgt],
                        config.learn_from_severity, config.lr,
                        config.train_mode, config.train_impl)
                    wT_t = jnp.where(learn_gate[None, :], learned, wT_t)

            # --- train --------------------------------------------------
            with jax.named_scope("multisoup.train"):
                if config.train > 0:
                    wT_t, loss_t = train_epochs_popmajor(
                        topo, wT_t, config.train, config.lr,
                        config.train_mode, config.train_impl)
                else:
                    loss_t = jnp.zeros(n_t, wT_t.dtype)

            # --- respawn predicates + replacement select ----------------
            with jax.named_scope("multisoup.respawn"):
                dead_div = is_diverged(wT_t, axis=0) \
                    if config.remove_divergent else jnp.zeros(n_t, bool)
                dead_zero = (is_zero(wT_t, config.epsilon, axis=0)
                             & ~dead_div) \
                    if config.remove_zero else jnp.zeros(n_t, bool)
                fresh = fresh_lanes(topo, re_keys[t], n_t,
                                    config.respawn_draws)
                wT_t = jnp.where((dead_div | dead_zero)[None, :], fresh,
                                 wT_t)

        # --- shared respawn bookkeeping (same uid blocks as row-major) --
        dead = dead_div | dead_zero
        rank = jnp.cumsum(dead) - 1
        base = state.next_uid + total_deaths
        uids_t = jnp.where(dead, base + rank.astype(jnp.int32),
                           state.uids[t])
        total_deaths = total_deaths + dead.sum(dtype=jnp.int32)
        death_action = jnp.full(n_t, ACT_NONE, jnp.int32)
        death_action = jnp.where(dead_div, ACT_DIV_DEAD, death_action)
        death_action = jnp.where(dead_zero, ACT_ZERO_DEAD, death_action)
        death_cp = jnp.where(dead, uids_t, -1)
        if lins is not None:
            lin_info.append((sl(att_idx), learn_gate, learn_tgt, dead))

        action, counterpart = _event_record(
            n_t, sl(attack_gate), all_uids[sl(attack_tgt)],
            learn_gate, learn_cp, config.train > 0, death_action, death_cp)

        stored_t, scales_t = _downcast(config, wT_t, paxis=-1)
        out_wTs.append(stored_t)
        out_scales.append(scales_t)
        new_uids.append(uids_t)
        actions.append(action)
        counterparts.append(counterpart)
        losses.append(loss_t)

    new_state = MultiSoupState(
        weights=state.weights, uids=tuple(new_uids),
        next_uid=state.next_uid + total_deaths, time=state.time + 1, key=key,
        scales=tuple(out_scales)
        if config.population_dtype == "int8" else None)
    events = MultiSoupEvents(tuple(actions), tuple(counterparts),
                             tuple(losses))
    if lins is not None:
        new_lins, win = _record_multi_lineage(lins, win, state.time,
                                              lin_info, lincfg)
        return new_state, events, tuple(out_wTs), new_lins, win
    return new_state, events, tuple(out_wTs)


def _evolve_multi_step(config: MultiSoupConfig, state: MultiSoupState,
                       lins=None, win=None, lincfg=None):
    """One mixed-soup generation (phase order of ``soup.py:51-87``).  With
    a lineage carry (``lins``/``win``/``lincfg``) additionally returns the
    advanced per-type ``LineageState`` tuple and the shared edge window."""
    from .soup import _pop_dtype

    _pop_dtype(config)  # validates population_dtype
    if config.layout == "popmajor":
        _check_popmajor_multi(config)
        out = _evolve_multi_popmajor(
            config, state, tuple(w.T for w in state.weights), lins, win,
            lincfg)
        new_state, events, wTs = out[:3]
        new_state = new_state._replace(weights=tuple(wT.T for wT in wTs))
        return (new_state, events) + out[3:]
    if config.layout != "rowmajor":
        raise ValueError(f"unknown multisoup layout {config.layout!r}")
    if config.train_impl == "pallas":
        raise ValueError(
            "train_impl='pallas' is the popmajor lane kernel; the "
            "row-major multisoup needs train_impl='xla'")
    if config.apply_impl == "pallas":
        raise ValueError(
            "apply_impl='pallas' is the popmajor lane kernel; the "
            "row-major multisoup needs apply_impl='xla'")
    if config.generation_impl != "phases":
        if config.generation_impl != "fused":
            raise ValueError(
                f"unknown generation_impl {config.generation_impl!r}")
        raise ValueError(
            "generation_impl='fused' is the popmajor lane megakernel; the "
            "row-major multisoup needs generation_impl='phases'")
    from .soup import _downcast, _upcast

    n = config.total
    offs = config.offsets
    key, k_ag, k_at, k_lg, k_lt, k_re = jax.random.split(state.key, 6)
    weights = tuple(_upcast(config, w, _type_scales(state, t))
                    for t, w in enumerate(state.weights))
    att_idx = jnp.full(n, -1, jnp.int32)

    # --- attack (cross-type) -------------------------------------------
    with jax.named_scope("multisoup.attack"):
        if config.attacking_rate > 0:
            weights, attack_gate, attack_tgt, att_idx = _attack_phase(
                config, weights, k_ag, k_at)
        else:
            attack_gate = jnp.zeros(n, bool)
            attack_tgt = jnp.zeros(n, jnp.int32)

    # global uid lookup for counterpart logging
    all_uids = jnp.concatenate(state.uids)
    lin_info = []

    new_weights, new_scales, new_uids = [], [], []
    actions, counterparts, losses = [], [], []
    total_deaths = jnp.int32(0)
    re_keys = jax.random.split(k_re, len(config.topos))
    for t, topo in enumerate(config.topos):
        tc = config.type_config(t)
        w_t = weights[t]
        n_t = config.sizes[t]
        sl = lambda arr: jax.lax.dynamic_slice_in_dim(arr, offs[t], n_t)

        # --- learn_from (same-type teachers) ---------------------------
        with jax.named_scope("multisoup.learn_from"):
            if config.learn_from_rate > 0:
                learn_gate = sl(jax.random.uniform(k_lg, (n,))) < config.learn_from_rate
                learn_tgt = jax.random.randint(
                    jax.random.fold_in(k_lt, t), (n_t,), 0, n_t)
                if config.learn_from_severity > 0:
                    learned, _ = jax.vmap(
                        lambda wi, ow: _learn_epochs(tc, wi, ow))(w_t, w_t[learn_tgt])
                    w_t = jnp.where(learn_gate[:, None], learned, w_t)
                learn_cp = state.uids[t][learn_tgt]
            else:
                learn_gate = jnp.zeros(n_t, bool)
                learn_tgt = jnp.zeros(n_t, jnp.int32)
                learn_cp = jnp.zeros(n_t, jnp.int32)

        # --- train ------------------------------------------------------
        with jax.named_scope("multisoup.train"):
            if config.train > 0:
                w_t, loss_t = jax.vmap(lambda wi: _train_epochs(tc, wi))(w_t)
            else:
                loss_t = jnp.zeros(n_t, w_t.dtype)

        # --- respawn with per-type uid blocks ---------------------------
        with jax.named_scope("multisoup.respawn"):
            w_t, uids_t, deaths, death_action, death_cp = _respawn(
                tc, w_t, state.uids[t], state.next_uid + total_deaths,
                re_keys[t])
            total_deaths = total_deaths + deaths
        if lins is not None:
            lin_info.append((sl(att_idx), learn_gate, learn_tgt,
                             death_action != ACT_NONE))

        action, counterpart = _event_record(
            n_t, sl(attack_gate), all_uids[sl(attack_tgt)],
            learn_gate, learn_cp, config.train > 0, death_action, death_cp)

        stored_t, scales_t = _downcast(config, w_t)
        new_weights.append(stored_t)
        new_scales.append(scales_t)
        new_uids.append(uids_t)
        actions.append(action)
        counterparts.append(counterpart)
        losses.append(loss_t)

    new_state = MultiSoupState(
        weights=tuple(new_weights), uids=tuple(new_uids),
        next_uid=state.next_uid + total_deaths, time=state.time + 1, key=key,
        scales=tuple(new_scales)
        if config.population_dtype == "int8" else None)
    events = MultiSoupEvents(tuple(actions), tuple(counterparts),
                             tuple(losses))
    if lins is not None:
        new_lins, win = _record_multi_lineage(lins, win, state.time,
                                              lin_info, lincfg)
        return new_state, events, new_lins, win
    return new_state, events


#: jitted single-generation mixed-soup step; the ``_donated`` twin donates
#: the state pytree so every per-type population is rewritten in place
#: (see ``soup.evolve_step_donated`` — same contract: input dead after the
#: call, rebinding callers only).
evolve_multi_step = jax.jit(_evolve_multi_step, static_argnames=("config",))
evolve_multi_step_donated = jax.jit(_evolve_multi_step,
                                    static_argnames=("config",),
                                    donate_argnums=(1,))


def _evolve_multi(config: MultiSoupConfig, state: MultiSoupState,
                  generations: int = 1, metrics: bool = False,
                  health: bool = False, lineage: bool = False,
                  lineage_state=None, lineage_capacity: int = 4096):
    """Evolve ``generations`` mixed-soup steps as one scan.

    ``metrics=True`` additionally returns one
    ``telemetry.device.SoupMetrics`` carry PER TYPE, accumulated inside
    the scan from the per-type event records (zero extra host syncs; the
    evolved state is bit-identical to the unmetered program).

    ``health=True`` additionally returns one
    ``telemetry.device.HealthStats`` carry PER TYPE — the flight
    recorder's population-health sentinels, folded from each type's
    post-step weights with the same guarantees.

    ``lineage=True`` (``lineage_state`` = per-type tuple of
    ``telemetry.dynamics.LineageState``, one shared pid space) returns
    the replication-dynamics window ``(new_lineage_states, LineageWindow,
    per-type FixpointStats)`` — see ``soup._evolve``.  Return order:
    ``final``, metrics carries, health carries, lineage triple."""
    if metrics:
        from .telemetry.device import (accumulate_soup_metrics,
                                       zero_soup_metrics)

        def acc(ms, ev):
            return tuple(accumulate_soup_metrics(m, a, l) for m, a, l
                         in zip(ms, ev.action, ev.loss))

        m0 = tuple(zero_soup_metrics() for _ in config.topos)
    else:
        m0 = None
    if health:
        from .telemetry.device import accumulate_health, zero_health

        def acc_h(hs, ws, axis):
            return tuple(accumulate_health(h, w, axis, config.epsilon)
                         for h, w in zip(hs, ws))

        h0 = tuple(zero_health() for _ in config.topos)
    else:
        h0 = None
    l0 = w0 = lincfg = None
    if lineage:
        if lineage_state is None or len(lineage_state) != len(config.topos):
            raise ValueError(
                "lineage=True needs lineage_state= (one "
                "telemetry.dynamics.LineageState per type — seed with "
                "seed_lineage over each type's uid block)")
        from .soup import _lineage_caps
        from .telemetry.dynamics import close_window, zero_window

        l0 = tuple(lineage_state)
        w0 = zero_window(lineage_capacity)
        lincfg = (tuple(_lineage_caps(n_t, config, lineage_capacity)
                        for n_t in config.sizes), lineage_capacity)

    def pack(final, ms, hs, ltriple=None):
        out = (final,)
        if metrics:
            out += (ms,)
        if health:
            out += (hs,)
        if lineage:
            out += (ltriple,)
        return out if len(out) > 1 else final

    def close(lins, ws, axis, scales=None):
        """End-of-window per-type fixpoint census (ws = per-type weights
        in the layout's orientation; ``scales`` = the final state's int8
        scale tuple, None otherwise)."""
        from .nets import apply_to_weights
        from .ops.popmajor import apply_popmajor

        from .soup import _upcast

        new_lins, stats = [], []
        for t, (lin_t, w_t) in enumerate(zip(lins, ws)):
            topo = config.topos[t]
            w_t = _upcast(config, w_t,
                          None if scales is None else scales[t],
                          paxis=-1 if axis == 0 else 0)
            if axis == 0:
                fw = apply_popmajor(topo, w_t, w_t)
            else:
                fw = jax.vmap(
                    lambda wi, topo=topo: apply_to_weights(topo, wi, wi))(w_t)
            lin_t, s = close_window(lin_t, w_t, fw, axis, config.epsilon)
            new_lins.append(lin_t)
            stats.append(s)
        return tuple(new_lins), tuple(stats)

    if config.layout == "popmajor":
        # keep every per-type carry transposed across the whole run: one
        # transpose per type at entry/exit instead of two per generation
        _check_popmajor_multi(config)

        def body_t(carry, _):
            s, wTs, ms, hs, lins, win = carry
            if lineage:
                new_s, ev, new_wTs, lins, win = _evolve_multi_popmajor(
                    config, s, wTs, lins, win, lincfg)
            else:
                new_s, ev, new_wTs = _evolve_multi_popmajor(config, s, wTs)
            if metrics:
                ms = acc(ms, ev)
            if health:
                from .soup import _stored_view

                hs = acc_h(hs, tuple(
                    _stored_view(config, wT, _type_scales(new_s, t),
                                 paxis=-1)
                    for t, wT in enumerate(new_wTs)), 0)
            return (new_s, new_wTs, ms, hs, lins, win), None

        light = state._replace(weights=tuple(
            jnp.zeros((0,), w.dtype) for w in state.weights))
        (final, wTs, ms, hs, lins, win), _ = jax.lax.scan(
            body_t, (light, tuple(w.T for w in state.weights), m0, h0, l0,
                     w0), None, length=generations)
        final = final._replace(weights=tuple(wT.T for wT in wTs))
        ltriple = None
        if lineage:
            lins, stats = close(lins, wTs, 0, final.scales)
            ltriple = (lins, win, stats)
        return pack(final, ms, hs, ltriple)

    def body(carry, _):
        s, ms, hs, lins, win = carry
        if lineage:
            new_s, ev, lins, win = _evolve_multi_step(config, s, lins, win,
                                                      lincfg)
        else:
            new_s, ev = evolve_multi_step(config, s)
        if metrics:
            ms = acc(ms, ev)
        if health:
            from .soup import _stored_view

            hs = acc_h(hs, tuple(
                _stored_view(config, w, _type_scales(new_s, t))
                for t, w in enumerate(new_s.weights)), -1)
        return (new_s, ms, hs, lins, win), None

    (final, ms, hs, lins, win), _ = jax.lax.scan(
        body, (state, m0, h0, l0, w0), None, length=generations)
    ltriple = None
    if lineage:
        lins, stats = close(lins, final.weights, -1, final.scales)
        ltriple = (lins, win, stats)
    return pack(final, ms, hs, ltriple)


#: jitted multi-generation mixed-soup run + its buffer-donating twin
#: (mega-run hot loops; state rebound chunk over chunk).
evolve_multi = jax.jit(_evolve_multi,
                       static_argnames=("config", "generations", "metrics",
                                        "health", "lineage",
                                        "lineage_capacity"))
evolve_multi_donated = jax.jit(_evolve_multi,
                               static_argnames=("config", "generations",
                                                "metrics", "health",
                                                "lineage",
                                                "lineage_capacity"),
                               donate_argnums=(1,))


@functools.partial(jax.jit, static_argnames=("config",))
def count_multi(config: MultiSoupConfig, state: MultiSoupState) -> jnp.ndarray:
    """(T, 5) per-type class histograms (types keep their own science)."""
    from .soup import _stored_view

    rows = [count_classes(classify_batch(
                config.topos[t],
                _stored_view(config, state.weights[t], _type_scales(state, t)),
                config.epsilon))
            for t in range(len(config.topos))]
    return jnp.stack(rows)
