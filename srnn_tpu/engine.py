"""Vectorized experiment engines.

The reference drives each net through a Python while-loop, one at a time
(``FixpointExperiment.run_net``, ``experiment.py:70-77``;
``MixedFixpointExperiment.run_net``, ``experiment.py:94-109``;
``known-fixpoint-variation.py:66-87``).  Here a whole population of trials
runs as ONE ``lax.scan`` with per-trial active masks — the while-loop's
early-exit becomes a mask update, so every trial retires at exactly the
same step it would have in the reference while the batch stays static-shaped
for XLA.

All engines return plain pytrees of arrays; persistence/logging lives in
``srnn_tpu.experiment`` (the runtime layer), not here.
"""

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from .nets import apply_to_weights
from .ops.predicates import DEFAULT_EPSILON, classify, count_classes, is_diverged, is_fixpoint, is_zero
from .topology import Topology
from .train import DEFAULT_LR, train_step


class FixpointRunResult(NamedTuple):
    weights: jnp.ndarray      # (N, P) final weights
    steps: jnp.ndarray        # (N,) self-attacks actually executed per trial
    classes: jnp.ndarray      # (N,) 5-way class ids
    counts: jnp.ndarray       # (5,) class histogram
    trajectory: Optional[jnp.ndarray]  # (steps+1, N, P) weight history or None


def _apply_self_batch(topo: Topology, w: jnp.ndarray) -> jnp.ndarray:
    """vmapped self-application: each row applied to itself."""
    return jax.vmap(lambda wi: apply_to_weights(topo, wi, wi))(w)


def _is_fixpoint_batch(topo: Topology, w: jnp.ndarray, epsilon: float) -> jnp.ndarray:
    return jax.vmap(
        lambda wi: is_fixpoint(functools.partial(apply_to_weights, topo, wi), wi, 1, epsilon)
    )(w)


def classify_batch(topo: Topology, w: jnp.ndarray, epsilon: float = DEFAULT_EPSILON) -> jnp.ndarray:
    """(N, P) -> (N,) class ids (the reference's ``count``, ``experiment.py:79-91``)."""
    return jax.vmap(
        lambda wi: classify(functools.partial(apply_to_weights, topo, wi), wi, epsilon)
    )(w)


def _run_fixpoint(
    topo: Topology,
    pop: jnp.ndarray,
    step_limit: int = 100,
    epsilon: float = DEFAULT_EPSILON,
    record: bool = False,
) -> FixpointRunResult:
    """Pure self-application to fixpoint, vectorized over trials.

    Per reference ``run_net`` (``experiment.py:70-77``): while under the step
    limit and neither diverged nor a (degree-1) fixpoint, self-attack.  The
    predicates are evaluated at the top of every iteration, exactly as the
    reference does.
    """

    def step(carry, _):
        w, steps = carry
        with jax.named_scope("engine.classify"):
            active = ~is_diverged(w) & ~_is_fixpoint_batch(topo, w, epsilon)
        with jax.named_scope("engine.self_apply"):
            new_w = jnp.where(active[:, None], _apply_self_batch(topo, w), w)
        out = new_w if record else None
        return (new_w, steps + active), out

    (w, steps), traj = jax.lax.scan(step, (pop, jnp.zeros(pop.shape[0], jnp.int32)),
                                    None, length=step_limit)
    classes = classify_batch(topo, w, epsilon)
    trajectory = jnp.concatenate([pop[None], traj], axis=0) if record else None
    return FixpointRunResult(w, steps, classes, count_classes(classes), trajectory)


#: jitted fixpoint engine; the ``_donated`` twin donates ``pop`` so the
#: final weights reuse the trial population's buffer in place (input dead
#: after the call — see ``soup.evolve_step_donated`` for the contract).
run_fixpoint = jax.jit(_run_fixpoint,
                       static_argnames=("topo", "step_limit", "record"))
run_fixpoint_donated = jax.jit(
    _run_fixpoint, static_argnames=("topo", "step_limit", "record"),
    donate_argnums=(1,))


def _run_mixed_fixpoint(
    topo: Topology,
    pop: jnp.ndarray,
    trains_per_application: int = 100,
    step_limit: int = 100,
    epsilon: float = DEFAULT_EPSILON,
    lr: float = DEFAULT_LR,
    train_mode: str = "sequential",
    record: bool = False,
) -> FixpointRunResult:
    """Interleaved self-attack + self-training
    (``MixedFixpointExperiment.run_net``, ``experiment.py:94-109``):
    each outer step is one self-attack followed by ``trains_per_application``
    train epochs, gated by the same diverged/fixpoint mask."""

    def train_n(w):
        def one(wi):
            def body(x, _):
                new_x, loss = train_step(topo, x, lr, train_mode)
                return new_x, loss
            out, losses = jax.lax.scan(body, wi, None, length=trains_per_application)
            return out, losses[-1] if trains_per_application else jnp.float32(0)
        return jax.vmap(one)(w)

    def step(carry, _):
        w, steps, loss = carry
        with jax.named_scope("engine.classify"):
            active = ~is_diverged(w) & ~_is_fixpoint_batch(topo, w, epsilon)
        with jax.named_scope("engine.self_apply"):
            attacked = _apply_self_batch(topo, w)
        with jax.named_scope("engine.train"):
            trained, new_loss = train_n(attacked) if trains_per_application \
                else (attacked, loss)
        new_w = jnp.where(active[:, None], trained, w)
        out = new_w if record else None
        return (new_w, steps + active, jnp.where(active, new_loss, loss)), out

    n = pop.shape[0]
    init = (pop, jnp.zeros(n, jnp.int32), jnp.zeros(n, pop.dtype))
    (w, steps, _), traj = jax.lax.scan(step, init, None, length=step_limit)
    classes = classify_batch(topo, w, epsilon)
    trajectory = jnp.concatenate([pop[None], traj], axis=0) if record else None
    return FixpointRunResult(w, steps, classes, count_classes(classes), trajectory)


_MIXED_STATICS = ("topo", "trains_per_application", "step_limit",
                  "train_mode", "record")
run_mixed_fixpoint = jax.jit(_run_mixed_fixpoint, static_argnames=_MIXED_STATICS)
run_mixed_fixpoint_donated = jax.jit(
    _run_mixed_fixpoint, static_argnames=_MIXED_STATICS, donate_argnums=(1,))


class TrainingRunResult(NamedTuple):
    weights: jnp.ndarray      # (N, P) final weights
    losses: jnp.ndarray       # (E, N) per-epoch training loss
    classes: jnp.ndarray      # (N,) 5-way class ids
    counts: jnp.ndarray       # (5,) class histogram
    trajectory: Optional[jnp.ndarray]  # (E+1, N, P) weight history or None


def _run_training(
    topo: Topology,
    pop: jnp.ndarray,
    epochs: int = 1000,
    epsilon: float = DEFAULT_EPSILON,
    lr: float = DEFAULT_LR,
    train_mode: str = "sequential",
    record: bool = False,
    shuffle_key: Optional[jax.Array] = None,
) -> TrainingRunResult:
    """Pure self-training, vectorized over trials
    (``training-fixpoints.py:52-56``: N trials x ``epochs`` train calls, no
    self-attacks, then classify).  Each epoch recomputes the samples from
    the current weights — the reference's moving-target regression toward
    being a fixpoint (``network.py:613-618``).

    ``shuffle_key`` emulates keras ``fit``'s default per-epoch sample-order
    shuffle, which the golden replay of the 2019 artifacts proved the
    reference runs actually used (RESULTS.md round-5): each epoch each
    particle takes its sequential batch-1 steps in an independent random
    order.  Only the weightwise variant has multi-sample epochs, so this
    is a bitwise no-op for aggregating/recurrent (asserted in tests);
    ``None`` keeps the deterministic enumeration order."""

    @jax.named_scope("engine.train_epoch")
    def epoch(w, e_idx):
        if shuffle_key is None:
            new_w, loss = jax.vmap(
                lambda wi: train_step(topo, wi, lr, train_mode))(w)
        else:
            ks = jax.random.split(jax.random.fold_in(shuffle_key, e_idx),
                                  w.shape[0])
            new_w, loss = jax.vmap(
                lambda wi, ki: train_step(topo, wi, lr, train_mode, key=ki)
            )(w, ks)
        out = (loss, new_w if record else None)
        return new_w, out

    w, (losses, traj) = jax.lax.scan(epoch, pop, jnp.arange(epochs))
    classes = classify_batch(topo, w, epsilon)
    trajectory = jnp.concatenate([pop[None], traj], axis=0) if record else None
    return TrainingRunResult(w, losses, classes, count_classes(classes), trajectory)


run_training = jax.jit(_run_training,
                       static_argnames=("topo", "epochs", "train_mode", "record"))
run_training_donated = jax.jit(
    _run_training, static_argnames=("topo", "epochs", "train_mode", "record"),
    donate_argnums=(1,))


class VariationResult(NamedTuple):
    time_to_vergence: jnp.ndarray   # (N,) steps until zero/divergence (or max)
    time_as_fixpoint: jnp.ndarray   # (N,) steps still classified as the initial fixpoint


@functools.partial(jax.jit, static_argnames=("topo", "max_steps"))
def run_known_fixpoint_variation(
    topo: Topology,
    pop: jnp.ndarray,
    max_steps: int = 100,
    epsilon: float = DEFAULT_EPSILON,
) -> VariationResult:
    """Perturbed-fixpoint decay measurement (``known-fixpoint-variation.py:66-87``).

    Per trial: self-attack up to ``max_steps``; break on zero/divergence;
    count ``time_to_something`` (steps before vergence) and
    ``time_as_fixpoint`` (steps counted only while the ``still_fixpoint``
    flag holds, with the reference's silent re-entry behavior preserved).
    """

    def step(carry, _):
        w, alive, still_fix, t_some, t_fix = carry
        new_w = jnp.where(alive[:, None], _apply_self_batch(topo, w), w)
        verged = is_zero(new_w, epsilon) | is_diverged(new_w)
        # predicates evaluated on the post-attack net, as in the reference
        fix_now = _is_fixpoint_batch(topo, new_w, epsilon)
        counted = alive & ~verged
        t_fix = t_fix + (counted & fix_now & still_fix)
        # reference flag algebra collapses to: after a counted step the flag
        # equals fix_now (re-entry sets it True without counting, loss of
        # fixpointness clears it; 'remarkable' logging is handled upstream)
        still_fix = jnp.where(counted, fix_now, still_fix)
        t_some = t_some + counted
        alive = alive & ~verged
        return (new_w, alive, still_fix, t_some, t_fix), None

    n = pop.shape[0]
    init = (
        pop,
        jnp.ones(n, bool),
        jnp.ones(n, bool),  # starts True: the unperturbed net is the known fixpoint
        jnp.zeros(n, jnp.int32),
        jnp.zeros(n, jnp.int32),
    )
    (w, alive, still_fix, t_some, t_fix), _ = jax.lax.scan(step, init, None, length=max_steps)
    return VariationResult(t_some, t_fix)


def _fixpoint_density(topo: Topology, pop: jnp.ndarray,
                      epsilon: float = DEFAULT_EPSILON) -> jnp.ndarray:
    """Immediate classification of freshly-initialized nets, no dynamics
    (``fixpoint-density.py``). Returns the (5,) class histogram."""
    return count_classes(classify_batch(topo, pop, epsilon))


fixpoint_density = jax.jit(_fixpoint_density, static_argnames=("topo",))


# ---------------------------------------------------------------------------
# tenant-stacked twins (srnn_tpu.serve): K independent experiment configs
# dispatched as ONE (K, N, ...) program.  epsilon is a traced (K,) vector —
# tenants may differ in it without selecting a new program — and every
# tenant's row is BITWISE-equal to its solo dispatch (the per-row lane
# programs are unchanged under the leading vmap axis; tests assert it).
# ---------------------------------------------------------------------------


def _fixpoint_density_stacked(topo: Topology, pops: jnp.ndarray,
                              epsilons: jnp.ndarray) -> jnp.ndarray:
    """(K, N, P) populations + (K,) epsilons -> (K, 5) class histograms,
    one vmapped dispatch for K tenants' ``fixpoint_density`` sweeps."""
    return jax.vmap(lambda p, e: _fixpoint_density(topo, p, e))(
        pops, epsilons)


fixpoint_density_stacked = jax.jit(_fixpoint_density_stacked,
                                   static_argnames=("topo",))


def _run_fixpoint_stacked(topo: Topology, pops: jnp.ndarray,
                          step_limit: int = 100,
                          epsilons: jnp.ndarray = None,
                          record: bool = False):
    """Tenant-stacked ``run_fixpoint``: (K, N, P) populations, per-tenant
    traced epsilons (a (K,) vector — REQUIRED; the stacked spelling has
    no scalar fallback), one dispatch; each tenant's
    ``FixpointRunResult`` rides a leading K axis."""
    if epsilons is None:
        raise TypeError(
            "run_fixpoint_stacked needs epsilons= (a (K,) per-tenant "
            "vector; vmap over None would fail deep inside jit)")
    return jax.vmap(
        lambda p, e: _run_fixpoint(topo, p, step_limit, e, record))(
            pops, epsilons)


_STACKED_FIX_STATICS = ("topo", "step_limit", "record")
run_fixpoint_stacked = jax.jit(_run_fixpoint_stacked,
                               static_argnames=_STACKED_FIX_STATICS)
run_fixpoint_stacked_donated = jax.jit(_run_fixpoint_stacked,
                                       static_argnames=_STACKED_FIX_STATICS,
                                       donate_argnums=(1,))
