"""Experiment runtime: run directories, logging, persistence, resume.

Reference layer L1 (``experiment.py:8-59``): a context manager that creates
``experiments/exp-{name}-{id}-{iteration}/``, collects log messages in RAM
(flushed to ``log.txt`` on exit), and dill-dumps arbitrary keyword objects.
The reference has **no mid-run resume** — ``next_iteration`` exists
(``experiment.py:18,33``) but every run restarts from scratch.

TPU-native redesign:

  * Artifacts are **safe, inspectable formats** instead of dill pickles:
    arrays/pytrees of arrays -> ``.npz`` (flattened path keys), plain
    JSON-able python -> ``.json``.  ``load_artifact`` round-trips both.
  * Logging is dual: human ``log.txt`` lines (reference parity — the
    committed ``results/*/log.txt`` files are the baseline artifacts,
    SURVEY §6) plus structured ``events.jsonl`` records for tooling.
  * **True checkpoint/resume** via orbax: the whole ``SoupState`` pytree
    (weights, uids, PRNG key, generation counter) round-trips, so a soup can
    continue exactly where it stopped — the capability gap called out in
    SURVEY §5 (checkpoint/resume row).
  * Counters are jnp (5,) histograms; ``format_counters`` renders them as
    the reference's dict repr so log lines stay diffable against the
    committed baselines.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional

import jax
import numpy as np

from .ops.predicates import CLASS_NAMES
from .soup import SoupState

_SEP = "/"  # path separator for flattened pytree keys inside npz files
_VALUE_KEY = "__value__"  # reserved npz key for a bare (non-pytree) array


# ---------------------------------------------------------------------------
# artifact persistence (npz / json instead of dill)
# ---------------------------------------------------------------------------


def _is_arraylike(x) -> bool:
    return isinstance(x, (np.ndarray, jax.Array))


def save_artifact(path: str, value: Any) -> str:
    """Persist one artifact; returns the full filename written.

    Pytrees whose leaves are all arrays (or scalars) go to ``{path}.npz``
    with flattened key paths; everything JSON-serializable goes to
    ``{path}.json``.  The reference dill-dumps arbitrary objects
    (``experiment.py:56-59``); restricting to data formats keeps artifacts
    loadable without the producing code and safe to share.
    """
    # typed PRNG keys can't cross into numpy; store their raw key data.
    # (Exact resume should go through save_checkpoint, which keeps the impl.)
    value = jax.tree.map(
        lambda v: jax.random.key_data(v)
        if isinstance(v, jax.Array) and jax.dtypes.issubdtype(v.dtype, jax.dtypes.prng_key)
        else v,
        value)
    # tree_util spelling: ``jax.tree.flatten_with_path`` only exists on
    # newer jax (same version-compat story as ``parallel.compat.shard_map``)
    leaves, treedef = jax.tree_util.tree_flatten_with_path(value)
    # npz only when every leaf is an actual array: plain-python structures
    # (sweep dicts of lists, name lists) keep their shape better as JSON
    if leaves and all(_is_arraylike(v) for _, v in leaves):
        flat = {}
        for keypath, leaf in leaves:
            key = _SEP.join(_key_str(k) for k in keypath) or _VALUE_KEY
            if key in flat:
                raise ValueError(
                    f"flattened key collision at {key!r} (a dict key containing "
                    f"{_SEP!r} collides with nesting); rename the offending key")
            flat[key] = np.asarray(leaf)
        fname = path + ".npz"
        np.savez_compressed(fname, **flat)
        return fname
    fname = path + ".json"
    with open(fname, "w") as f:
        json.dump(_jsonify(value), f, indent=1, default=str)
    return fname


def _key_str(k) -> str:
    for attr in ("key", "idx", "name"):
        if hasattr(k, attr):
            return str(getattr(k, attr))
    return str(k)


def _jsonify(v):
    if _is_arraylike(v):
        return np.asarray(v).tolist()
    if isinstance(v, dict):
        return {str(k): _jsonify(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonify(x) for x in v]
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    return v


def load_artifact(path: str) -> Any:
    """Load an artifact written by :func:`save_artifact`.

    ``.npz`` artifacts come back as a flat ``{path_key: np.ndarray}`` dict
    (or a bare array when it was saved as a single value); ``.json`` as
    parsed JSON.  Accepts the basename or the full filename.
    """
    if os.path.exists(path + ".npz"):
        path = path + ".npz"
    elif os.path.exists(path + ".json"):
        path = path + ".json"
    if path.endswith(".npz"):
        with np.load(path) as z:
            out = {k: z[k] for k in z.files}
        if set(out) == {_VALUE_KEY}:
            return out[_VALUE_KEY]
        return out
    with open(path) as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# counters
# ---------------------------------------------------------------------------


def counters_dict(counts) -> Dict[str, int]:
    """(5,) histogram -> the reference's counter dict
    (``experiment.py:67``: keys divergent/fix_zero/fix_other/fix_sec/other)."""
    arr = np.asarray(counts)
    return {name: int(arr[i]) for i, name in enumerate(CLASS_NAMES)}


def format_counters(counts) -> str:
    """Render a histogram exactly like the reference's logged dict repr, so
    log lines stay textually comparable to ``results/*/log.txt``."""
    return str(counters_dict(counts))


# ---------------------------------------------------------------------------
# the Experiment run-directory context
# ---------------------------------------------------------------------------


class Experiment:
    """Run-directory + log manager (reference ``Experiment``,
    ``experiment.py:8-59``).

    >>> with Experiment('applying_fixpoint', root='experiments') as exp:
    ...     exp.log('counters: ...')
    ...     exp.save(all_counters=counts)        # -> all_counters.npz

    On exit, ``log.txt`` (one line per ``log()`` call) and ``meta.json``
    are written.  ``next_iteration`` increments per ``with`` entry, giving
    ``-0``, ``-1``, ... suffixed sibling dirs like the reference.
    """

    def __init__(self, name: Optional[str] = None, ident: Optional[str] = None,
                 root: str = "experiments", seed: Optional[int] = None):
        self.experiment_name = name or "unnamed_experiment"
        self.experiment_id = f"{ident or ''}_{time.time()}"
        self.root = root
        self.next_iteration = 0
        self.seed = seed
        self.log_messages: list = []
        self.dir: Optional[str] = None
        self._t0: Optional[float] = None
        self._prior_wall = 0.0  # accumulated runtime of earlier attach()ed runs
        # events.jsonl is written from the run loop AND from the async
        # pipeline's background writer (heartbeat rows, metrics flushes run
        # as queued jobs) — serialize the write+flush(+fsync) per record
        self._events_lock = threading.Lock()

    @classmethod
    def attach(cls, run_dir: str) -> "Experiment":
        """Re-attach to an existing run directory (resume support — no
        reference equivalent; its runs always restart, SURVEY §5).

        Returns an entered Experiment whose ``log``/``event``/``save`` append
        to the existing ``log.txt``/``events.jsonl``/artifacts.  Exit it (or
        use it as a context manager) to flush the log as usual.
        """
        run_dir = os.path.normpath(run_dir)
        if not os.path.isdir(run_dir):
            raise FileNotFoundError(run_dir)
        base = os.path.basename(run_dir)
        self = cls(name=base, root=os.path.dirname(run_dir) or ".")
        meta_path = os.path.join(run_dir, "meta.json")
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
            self.experiment_name = meta.get("name", base)
            self.experiment_id = meta.get("id", self.experiment_id)
            self.next_iteration = meta.get("iteration", 0)
            self.seed = meta.get("seed")
            # carry runtime forward so a resumed run's meta.json reports the
            # CUMULATIVE wall time across all sessions, not just the last one
            self._prior_wall = float(meta.get("wall_seconds") or 0.0)
        self.dir = run_dir
        self._t0 = time.time()
        log_path = os.path.join(run_dir, "log.txt")
        if os.path.exists(log_path):
            with open(log_path) as f:
                self.log_messages = [line.rstrip("\n") for line in f]
        self._events = open(os.path.join(run_dir, "events.jsonl"), "a")
        return self

    # -- context ---------------------------------------------------------

    def __enter__(self) -> "Experiment":
        self.dir = os.path.join(
            self.root,
            f"exp-{self.experiment_name}-{self.experiment_id}-{self.next_iteration}")
        os.makedirs(self.dir)
        self.log_messages = []
        self._t0 = time.time()
        self._events = open(os.path.join(self.dir, "events.jsonl"), "w")
        print(f"** created {self.dir} **")
        return self

    def __exit__(self, exc_type, exc_value, traceback):
        self.save_log()
        meta = {
            "name": self.experiment_name,
            "id": self.experiment_id,
            "iteration": self.next_iteration,
            "seed": self.seed,
            "wall_seconds": self._prior_wall + (time.time() - self._t0),
            "error": repr(exc_value) if exc_value is not None else None,
        }
        with open(os.path.join(self.dir, "meta.json"), "w") as f:
            json.dump(meta, f, indent=1)
        self._events.close()
        self.next_iteration += 1
        return False

    # -- logging ---------------------------------------------------------

    def log(self, message, **event_fields):
        """Print + record a log line (``experiment.py:35-37``); any keyword
        fields additionally emit a structured jsonl event."""
        self.log_messages.append(message)
        print(message)
        if event_fields:
            self.event(message=str(message), **event_fields)

    def event(self, _fsync: bool = False, **fields):
        """Append one structured record to ``events.jsonl``.

        Every write is flushed so a killed run keeps its structured tail;
        ``_fsync=True`` (heartbeats — telemetry liveness rows) additionally
        forces the record to disk past the OS cache."""
        fields.setdefault("t", time.time() - self._t0)
        with self._events_lock:
            self._events.write(json.dumps(_jsonify(fields), default=str) + "\n")
            self._events.flush()
            if _fsync:
                os.fsync(self._events.fileno())

    def save_log(self, log_name: str = "log"):
        with open(os.path.join(self.dir, f"{log_name}.txt"), "w") as f:
            for message in self.log_messages:
                print(str(message), file=f)

    # -- artifacts -------------------------------------------------------

    def save(self, **kwargs) -> Dict[str, str]:
        """Persist each keyword artifact into the run dir
        (``experiment.py:56-59``); returns {name: filename}."""
        out = {}
        for name, value in kwargs.items():
            out[name] = save_artifact(os.path.join(self.dir, name), value)
        return out

    def load(self, name: str) -> Any:
        return load_artifact(os.path.join(self.dir, name))


# ---------------------------------------------------------------------------
# checkpoint / resume (orbax) — capability the reference lacks (SURVEY §5)
# ---------------------------------------------------------------------------


def _soup_state_to_pytree(state: SoupState) -> Dict[str, Any]:
    """Typed PRNG keys don't serialize; split into raw key data + impl tag.

    int8 populations add a ``scales`` entry (the per-particle dequant
    vector — codes are meaningless without it); f32/bf16 trees keep the
    exact pre-int8 schema so old checkpoints restore unchanged."""
    tree = {
        "weights": state.weights,
        "uids": state.uids,
        "next_uid": state.next_uid,
        "time": state.time,
        "key_data": jax.random.key_data(state.key),
        "key_impl": str(jax.random.key_impl(state.key)),
    }
    if state.scales is not None:
        tree["scales"] = state.scales
    return tree


def _soup_state_from_pytree(tree: Dict[str, Any]) -> SoupState:
    import jax.numpy as jnp

    key = jax.random.wrap_key_data(
        jnp.asarray(tree["key_data"]), impl=str(tree["key_impl"]))
    return SoupState(
        weights=jnp.asarray(tree["weights"]),
        uids=jnp.asarray(tree["uids"]),
        next_uid=jnp.asarray(tree["next_uid"]),
        time=jnp.asarray(tree["time"]),
        key=key,
        scales=jnp.asarray(tree["scales"]) if "scales" in tree else None,
    )


#: completion marker published (tmp + fsync + atomic rename) inside a
#: checkpoint dir AFTER orbax finishes — its presence is the positive
#: proof ``setups.common.checkpoint_intact`` wants before a resume trusts
#: the dir (orbax's own tmp-dir rename guards against a kill mid-save,
#: but not against a torn file from a dying disk or a partial copy)
CKPT_OK_MARKER = "SRNN_CKPT_OK"


def _finalize_checkpoint(path: str, time_value) -> None:
    from .utils.atomicio import atomic_write_text

    atomic_write_text(os.path.join(path, CKPT_OK_MARKER),
                      json.dumps({"time": int(time_value)}) + "\n")


def save_checkpoint(path: str, state: SoupState, primary: bool = True) -> str:
    """Write a resumable checkpoint of a soup (weights + uids + PRNG key +
    generation counter) at ``path`` (a directory, created fresh), then
    publish its completion marker (write-tmp + fsync + atomic rename).

    In a multi-process run EVERY process must call this with the same
    (host-gathered) state and at the same point of its loop: orbax's
    multihost machinery barriers across processes and writes each array
    once.  ``primary=False`` marks the non-0 processes, which then skip
    the completion marker (one marker, written by the process that owns
    run-dir I/O)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, _soup_state_to_pytree(state), force=True)
    if primary:
        _finalize_checkpoint(path, state.time)
    return path


def restore_checkpoint(path: str) -> SoupState:
    """Load a :func:`save_checkpoint` checkpoint back into a live
    ``SoupState``; evolution continues bit-exactly (same PRNG stream)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        tree = ckptr.restore(path)
    return _soup_state_from_pytree(tree)


def save_multi_checkpoint(path: str, state, primary: bool = True) -> str:
    """Resumable checkpoint of a heterogeneous (``MultiSoupState``) soup:
    per-type weights/uids lists + scalars + raw PRNG key data.  The
    multi-process contract matches :func:`save_checkpoint`."""
    import orbax.checkpoint as ocp

    tree = {
        "weights": list(state.weights),
        "uids": list(state.uids),
        "next_uid": state.next_uid,
        "time": state.time,
        "key_data": jax.random.key_data(state.key),
        "key_impl": str(jax.random.key_impl(state.key)),
    }
    if state.scales is not None:
        tree["scales"] = list(state.scales)
    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, tree, force=True)
    if primary:
        _finalize_checkpoint(path, state.time)
    return path


def restore_multi_checkpoint(path: str):
    """Load a :func:`save_multi_checkpoint` back into a ``MultiSoupState``
    (bit-exact continuation, same PRNG stream)."""
    import orbax.checkpoint as ocp

    import jax.numpy as jnp

    from .multisoup import MultiSoupState

    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        tree = ckptr.restore(path)
    key = jax.random.wrap_key_data(
        jnp.asarray(tree["key_data"]), impl=str(tree["key_impl"]))
    return MultiSoupState(
        weights=tuple(jnp.asarray(w) for w in tree["weights"]),
        uids=tuple(jnp.asarray(u) for u in tree["uids"]),
        next_uid=jnp.asarray(tree["next_uid"]),
        time=jnp.asarray(tree["time"]),
        key=key,
        scales=tuple(jnp.asarray(s) for s in tree["scales"])
        if "scales" in tree else None,
    )
