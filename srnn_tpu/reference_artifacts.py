"""Loader + golden-replay helpers for the reference's committed 2019 dill
artifacts.

The reference repo ships 35 ``.dill`` files under ``code/results/`` and
``code/setups/experiments/`` (dated 2019-03).  Several of them contain
*actual recorded weight trajectories* computed by the 2019 tf.keras code —
per-step flat-weight snapshots in ``ParticleDecorator.make_state`` format
(``/root/reference/code/network.py:185-198``):

    {'class': <variant name>, 'weights': np.ndarray (P,),
     'time': int, 'action': str|absent, 'counterpart': uid|None|absent}

Those recorded ``w_t -> w_{t+1}`` pairs are the strongest parity evidence
available anywhere: replaying them through this repo's transforms checks our
math against the *reference's own 2019 TF numerics*, step by step, rather
than against distributions.  ``tests/test_golden_replay.py`` does exactly
that; RESULTS.md carries the error statistics.

Loading needs no keras/TF: the pickles only reference the reference's class
*names* (``experiment.Experiment``, ``network.ParticleDecorator``, ...) plus
numpy.  We inject stub modules with attribute-bag shim classes before
``dill.load``.  Two wrinkles:

* The soup artifacts (``soup.dill``) embed the soup's ``generator`` closure,
  pickled by 2019 dill as a raw Python-3.6/3.7 **code object** (15
  constructor args; modern CPython wants 18).  We patch
  ``dill._dill._create_code`` during the load to rebuild those legacy tuples
  into inert modern code objects — the closure is never *called* during
  analysis, it only has to unpickle.
* ``Experiment.historical_particles`` values are either shim
  ``ParticleDecorator`` instances (attr ``states``) or plain state lists,
  depending on whether ``without_particles()`` ran; ``particle_states``
  normalizes both.

Public surface:
  load_artifact(path)          -> shim object tree (no keras required)
  particle_states(obj)         -> {uid: [state dict, ...]} normalized
  trajectory_artifact(obj)     -> {"weights": (T, N, P), "uids": (T, N)}
                                  NaN-padded, viz.particle_trajectories-ready
  scan(root)                   -> inventory of every .dill under root
  step_pairs(states)           -> consecutive (state_t, state_{t+1}) pairs
"""

from __future__ import annotations

import contextlib
import glob
import os
import sys
import types
from typing import Any, Dict, Iterator, List, Tuple

import numpy as np

# Class names the 2019 pickles may reference, per reference module
# (``experiment.py``, ``network.py``, ``soup.py``, ``util.py``).
_SHIM_CLASSES = {
    "experiment": (
        "Experiment", "FixpointExperiment", "MixedFixpointExperiment",
        "SoupExperiment", "IdentLearningExperiment",
    ),
    "network": (
        "NeuralNetwork", "WeightwiseNeuralNetwork", "AggregatingNeuralNetwork",
        "FFTNeuralNetwork", "RecurrentNeuralNetwork", "ParticleDecorator",
        "TrainingNeuralNetworkDecorator", "SaveStateCallback",
    ),
    "soup": ("Soup",),
    "util": ("PrintingObject",),
}


class _Shim:
    """Attribute bag standing in for any reference class during unpickle."""

    def __init__(self, *args, **kwargs):
        pass

    def __setstate__(self, state):
        if isinstance(state, dict):
            self.__dict__.update(state)
        else:  # pragma: no cover - no reference class uses non-dict state
            self.__dict__["_state"] = state

    def __repr__(self):
        keys = ", ".join(sorted(self.__dict__)[:6])
        return f"<ref {type(self).__name__} {keys}>"


def _adapted_code(*args):
    """Build an inert modern code object from a 2019-era (py3.6/3.7, 15-arg)
    ``CodeType`` call recorded in the pickle stream.

    The 2019 dill pickled code objects as ``_load_type('CodeType')(*args)``
    with the 3.7 constructor order: (argcount, kwonlyargcount, nlocals,
    stacksize, flags, code, consts, names, varnames, filename, name,
    firstlineno, lnotab, freevars, cellvars).  Modern CPython inserts
    posonlyargcount (3.8) and qualname/exceptiontable (3.11), so the raw
    call raises.  The bytecode itself is stale — these closures (e.g. the
    Soup ``generator`` lambda, ``soup.py:37-40``) are never executed by
    analysis code, they only have to unpickle.  ``co_freevars`` must
    survive so ``_create_function`` can attach the pickled closure cells.
    """
    try:
        return types.CodeType(*args)
    except TypeError:
        pass
    if len(args) == 15:  # py3.6/3.7 layout
        (argcount, kwonly, nlocals, stacksize, flags, code, consts, names,
         varnames, filename, name, firstlineno, lnotab, freevars,
         cellvars) = args
        try:
            return types.CodeType(
                argcount, 0, kwonly, nlocals, stacksize, flags, code,
                consts, names, varnames, filename, name, name,
                firstlineno, lnotab, b"", freevars, cellvars)
        except Exception:
            # last resort: placeholder preserving the closure arity
            placeholder = (lambda: None).__code__
            try:
                return placeholder.replace(co_freevars=tuple(freevars))
            except Exception:
                return placeholder
    raise TypeError(f"unadaptable legacy code tuple of len {len(args)}")


def _legacy_load_type(orig_load_type):
    """Wrap dill's ``_load_type`` so lookups of ``CodeType`` hand back the
    adapting constructor above instead of the raw type."""

    def load_type(name, *args, **kwargs):
        if name == "CodeType":
            return _adapted_code
        return orig_load_type(name, *args, **kwargs)

    return load_type


@contextlib.contextmanager
def _shimmed_modules():
    """Temporarily install the reference's module/class namespace (plus the
    legacy-code dill patch), restoring any real modules afterwards."""
    import dill
    import dill._dill as dill_impl

    saved = {}
    for mod_name, class_names in _SHIM_CLASSES.items():
        saved[mod_name] = sys.modules.get(mod_name)
        mod = types.ModuleType(mod_name)
        for cls_name in class_names:
            setattr(mod, cls_name, type(cls_name, (_Shim,), {}))
        sys.modules[mod_name] = mod
    orig_load_type = dill_impl._load_type
    dill_impl._load_type = _legacy_load_type(orig_load_type)
    try:
        yield dill
    finally:
        dill_impl._load_type = orig_load_type
        for mod_name, prev in saved.items():
            if prev is None:
                sys.modules.pop(mod_name, None)
            else:
                sys.modules[mod_name] = prev


def load_artifact(path: str) -> Any:
    """dill-load one reference artifact with the class shims installed."""
    with _shimmed_modules() as dill:
        with open(path, "rb") as fh:
            return dill.load(fh)


def particle_states(obj: Any) -> Dict[Any, List[dict]]:
    """Normalize ``historical_particles`` to {uid: [state, ...]}.

    Values are state lists already when the artifact went through
    ``without_particles()`` (``experiment.py:50-54``); live
    ``ParticleDecorator`` shims keep them under ``.states``
    (``network.py:193-198``).  Particles with no recorded states are
    dropped.
    """
    hp = getattr(obj, "historical_particles", None)
    if hp is None and isinstance(obj, dict):
        hp = obj
    if hp is None:
        raise TypeError(f"no historical_particles on {type(obj).__name__}")
    out = {}
    for uid, particle in hp.items():
        states = particle if isinstance(particle, list) else \
            getattr(particle, "states", None)
        if states:
            out[uid] = states
    return out


def step_pairs(states: List[dict]) -> Iterator[Tuple[dict, dict]]:
    """Consecutive recorded (state_t, state_{t+1}) pairs."""
    return zip(states, states[1:])


def trajectory_artifact(obj: Any) -> Dict[str, np.ndarray]:
    """Reference experiment/soup object -> the repo's rectangular trajectory
    artifact ``{"weights": (T, N, P), "uids": (T, N)}``.

    Histories are ragged two ways: runs stop early (divergence), and mixed
    experiments can hold particles of different weight counts.  *Missing
    time steps* pad with NaN rows — ``viz.particle_trajectories`` drops
    non-finite rows per particle, so that padding (like the reference's own
    NaN-state skip, ``network.py:186-188``) never renders.  *Missing weight
    dims* of a smaller-than-max particle pad with 0.0 instead: a NaN
    anywhere in a row would make the finite filter erase the whole
    particle, while a constant 0 merely embeds its trajectory in a
    lower-dimensional slice of the PCA space.
    """
    by_uid = particle_states(obj)
    if not by_uid:
        raise ValueError("artifact has no recorded particle states")
    uids = sorted(by_uid, key=lambda u: (str(type(u)), u))
    p = max(len(np.ravel(s["weights"]))
            for states in by_uid.values() for s in states)
    t_len = max(len(states) for states in by_uid.values())
    weights = np.full((t_len, len(uids), p), np.nan, dtype=np.float32)
    uid_grid = np.zeros((t_len, len(uids)), dtype=np.int64)
    for col, uid in enumerate(uids):
        uid_grid[:, col] = col if not isinstance(uid, (int, np.integer)) else uid
        for row, state in enumerate(by_uid[uid]):
            w = np.ravel(np.asarray(state["weights"], dtype=np.float32))
            weights[row, col, :len(w)] = w
            weights[row, col, len(w):] = 0.0
    return {"weights": weights, "uids": uid_grid}


def scan(root: str) -> List[dict]:
    """Inventory every ``.dill`` under ``root``: loadability, type, particle
    counts, per-class state statistics.  Used by the golden-replay tests to
    prove claims like "no RNN trajectories exist anywhere in the reference
    artifacts" against the full artifact set rather than one file."""
    rows = []
    for path in sorted(glob.glob(os.path.join(root, "**", "*.dill"),
                                 recursive=True)):
        row = {"path": path, "size": os.path.getsize(path), "loads": False,
               "type": None, "particles": 0, "classes": {}, "step_pairs": 0}
        try:
            obj = load_artifact(path)
        except Exception as e:  # noqa: BLE001 - inventory must not die
            row["error"] = f"{type(e).__name__}: {e}"
            rows.append(row)
            continue
        row["loads"] = True
        row["type"] = type(obj).__name__
        try:
            by_uid = particle_states(obj)
        except (TypeError, ValueError):
            by_uid = {}
        row["particles"] = len(by_uid)
        for states in by_uid.values():
            cls = states[0].get("class", "?")
            row["classes"][cls] = row["classes"].get(cls, 0) + 1
            row["step_pairs"] += max(0, len(states) - 1)
        rows.append(row)
    return rows


REFERENCE_ROOT = os.environ.get("SRNN_REFERENCE_ROOT", "/root/reference/code")

# The artifacts with real recorded trajectories (verified by ``scan``; the
# rest are sweep-curve dicts, name lists, or ``without_particles()`` shells
# whose ``historical_particles`` is empty).
WW_SELF_APPLICATION = (
    "setups/experiments/"
    "exp-weightwise_self_application-_1552664922.4501734-0/trajectorys.dill")
AGG_SELF_APPLICATION = (
    "results/self_application_aggregation_network/trajectorys.dill")
WW_SELF_TRAINING = (
    "results/self_training_weightwise_network/trajectorys.dill")
SOUP_RUNS = (
    "results/Soup/soup.dill",
    "results/exp-learn-from-soup-_1552658566.5572753-0/soup.dill",
)


def reference_path(rel: str) -> str:
    return os.path.join(REFERENCE_ROOT, rel)
