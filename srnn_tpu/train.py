"""Self-training and imitation ("learn_from") as pure SGD steps.

Reference semantics (``TrainingNeuralNetworkDecorator``, ``network.py:577-626``):

  * ``train()`` = one keras ``fit`` epoch on ``compute_samples()`` with
    ``loss='mse'``, plain SGD (keras default lr=0.01) and **batch_size=1**
    (``network.py:613-618``): one sequential gradient step per sample, with
    x/y computed ONCE from the current weights at call time (a moving
    target across calls, frozen within a call).
  * ``learn_from(other)`` = the same single epoch but on *other's* samples
    (imitation, ``network.py:620-626``).
  * the reported loss is the mean of per-batch losses over the epoch, each
    evaluated at the weights *before* that batch's update (keras history
    semantics).

Modes:
  * ``'sequential'`` (default) — ``lax.scan`` of per-sample SGD updates in
    enumeration order, the faithful batch_size=1 analog (SURVEY §2.4.10).
    keras ``fit`` actually shuffles by default with an unseeded numpy RNG,
    so exact order parity with any particular reference run is impossible;
    pass ``key`` to shuffle functionally, or leave None for deterministic
    enumeration order.
  * ``'full_batch'`` — a single gradient step on the mean loss over all
    samples; changes semantics (documented deviation) but runs as one fused
    matmul — the fast path for mega-soups.
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .nets import compute_samples
from .nets.dispatch import _MODULES
from .topology import Topology

DEFAULT_LR = 0.01  # keras SGD default learning rate


def predict(topo: Topology, flat: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """Batched forward pass on training samples, per variant.

    weightwise: x (B, 4) -> (B, 1); aggregating/fft: x (B, k) -> (B, k);
    recurrent: x (B, T, 1) -> (B, T, 1).
    """
    mod = _MODULES[topo.variant]
    if topo.variant == "recurrent":
        return jax.vmap(lambda seq: mod.forward(topo, flat, seq))(x)
    return mod.forward(topo, flat, x)


def _mse(topo: Topology, flat: jnp.ndarray, xb: jnp.ndarray, yb: jnp.ndarray) -> jnp.ndarray:
    pred = predict(topo, flat, xb)
    return jnp.mean((pred - yb.reshape(pred.shape)) ** 2)


def fit_epoch(
    topo: Topology,
    flat: jnp.ndarray,
    x: jnp.ndarray,
    y: jnp.ndarray,
    lr: float = DEFAULT_LR,
    mode: str = "sequential",
    key: Optional[jax.Array] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One epoch of mse-SGD on fixed (x, y). Returns (new_flat, epoch_loss)."""
    x = jax.lax.stop_gradient(x)
    y = jax.lax.stop_gradient(y)
    if mode == "full_batch":
        loss, grad = jax.value_and_grad(_mse, argnums=1)(topo, flat, x, y)
        return flat - lr * grad, loss
    if mode != "sequential":
        raise ValueError(f"unknown train mode {mode!r}")
    n = x.shape[0]
    order = jnp.arange(n) if key is None else jax.random.permutation(key, n)

    def step(w, i):
        loss, grad = jax.value_and_grad(_mse, argnums=1)(topo, w, x[i][None], y[i][None])
        return w - lr * grad, loss

    flat, losses = jax.lax.scan(step, flat, order)
    return flat, losses.mean()


def fit_epochs_flat(
    topo: Topology,
    flat: jnp.ndarray,
    epochs: int,
    lr: float = DEFAULT_LR,
    mode: str = "sequential",
    xy: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``epochs`` repeated ``train()``/``learn_from()`` calls as ONE
    compile-bounded program.

    ``xy=None`` is self-training: the sample set is re-snapshotted from the
    CURRENT weights whenever the flattened sample index wraps to 0 —
    "samples recomputed before every epoch" (``network.py:613-618``).
    Otherwise ``xy`` is a fixed imitation sample set (``learn_from``,
    ``network.py:620-626``).

    Why flat: the naive scan(epochs){scan(samples){grad}} nest, once wrapped
    in the soup's scan(generations) (and worse, shard_map), compiles
    unboundedly long on the remote TPU compile service.  Sequential mode
    here is a SINGLE scan of length ``epochs * n_samples`` with one grad in
    the body — per-step math identical to ``fit_epoch('sequential')``, same
    update order, same pre-update keras-history loss.  Returns
    (new_flat, last epoch's mean pre-update loss).
    """
    if epochs <= 0:
        return flat, jnp.zeros((), flat.dtype)
    if mode == "full_batch":
        def body(w, _):
            x, y = compute_samples(topo, w) if xy is None else xy
            new_w, loss = fit_epoch(topo, w, x, y, lr, "full_batch")
            return new_w, loss

        new_flat, losses = jax.lax.scan(body, flat, None, length=epochs)
        return new_flat, losses[-1]
    if mode != "sequential":
        raise ValueError(f"unknown train mode {mode!r}")

    x0, y0 = compute_samples(topo, flat) if xy is None else xy
    x0 = jax.lax.stop_gradient(x0)
    y0 = jax.lax.stop_gradient(y0)
    s = x0.shape[0]
    idx = jnp.tile(jnp.arange(s), epochs)
    zero = jnp.zeros((), flat.dtype)

    def step(carry, s_idx):
        w, sx, sy, accum, last = carry
        if xy is None:  # refresh the sample snapshot at each epoch top
            # cond, not where: the snapshot forward pass (a full RNN run for
            # the recurrent variant) must only execute on epoch boundaries,
            # not on every flattened sample step.  (Under vmap XLA lowers
            # cond to select-with-both-branches — same cost as before; the
            # win is the unvmapped single-net path, e.g. run_training.)
            sx, sy = jax.lax.cond(
                s_idx == 0,
                lambda w, sx, sy: compute_samples(topo, w),
                lambda w, sx, sy: (sx, sy),
                w, sx, sy)
        loss, grad = jax.value_and_grad(_mse, argnums=1)(
            topo, w, sx[s_idx][None], sy[s_idx][None])
        w = w - lr * grad
        accum = accum + loss
        done = s_idx == s - 1
        last = jnp.where(done, accum / s, last)
        accum = jnp.where(done, zero, accum)
        return (w, sx, sy, accum, last), None

    (new_flat, _, _, _, last), _ = jax.lax.scan(
        step, (flat, x0, y0, zero, zero), idx)
    return new_flat, last


def train_step(
    topo: Topology,
    flat: jnp.ndarray,
    lr: float = DEFAULT_LR,
    mode: str = "sequential",
    key: Optional[jax.Array] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One ``train()`` call: fit one epoch on the net's own samples
    (self-training toward being a fixpoint)."""
    x, y = compute_samples(topo, flat)
    return fit_epoch(topo, flat, x, y, lr, mode, key)


def learn_from(
    topo: Topology,
    flat: jnp.ndarray,
    other_flat: jnp.ndarray,
    lr: float = DEFAULT_LR,
    mode: str = "sequential",
    key: Optional[jax.Array] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One ``learn_from(other)`` call: fit one epoch on *other's* samples."""
    x, y = compute_samples(topo, other_flat)
    return fit_epoch(topo, flat, x, y, lr, mode, key)
