"""Self-training and imitation ("learn_from") as pure SGD steps.

Reference semantics (``TrainingNeuralNetworkDecorator``, ``network.py:577-626``):

  * ``train()`` = one keras ``fit`` epoch on ``compute_samples()`` with
    ``loss='mse'``, plain SGD (keras default lr=0.01) and **batch_size=1**
    (``network.py:613-618``): one sequential gradient step per sample, with
    x/y computed ONCE from the current weights at call time (a moving
    target across calls, frozen within a call).
  * ``learn_from(other)`` = the same single epoch but on *other's* samples
    (imitation, ``network.py:620-626``).
  * the reported loss is the mean of per-batch losses over the epoch, each
    evaluated at the weights *before* that batch's update (keras history
    semantics).

Modes:
  * ``'sequential'`` (default) — ``lax.scan`` of per-sample SGD updates in
    enumeration order, the faithful batch_size=1 analog (SURVEY §2.4.10).
    keras ``fit`` actually shuffles by default with an unseeded numpy RNG,
    so exact order parity with any particular reference run is impossible;
    pass ``key`` to shuffle functionally, or leave None for deterministic
    enumeration order.
  * ``'full_batch'`` — a single gradient step on the mean loss over all
    samples; changes semantics (documented deviation) but runs as one fused
    matmul — the fast path for mega-soups.
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .nets import compute_samples
from .nets.dispatch import _MODULES
from .topology import Topology

DEFAULT_LR = 0.01  # keras SGD default learning rate


def predict(topo: Topology, flat: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """Batched forward pass on training samples, per variant.

    weightwise: x (B, 4) -> (B, 1); aggregating/fft: x (B, k) -> (B, k);
    recurrent: x (B, T, 1) -> (B, T, 1).
    """
    mod = _MODULES[topo.variant]
    if topo.variant == "recurrent":
        return jax.vmap(lambda seq: mod.forward(topo, flat, seq))(x)
    return mod.forward(topo, flat, x)


def _mse(topo: Topology, flat: jnp.ndarray, xb: jnp.ndarray, yb: jnp.ndarray) -> jnp.ndarray:
    pred = predict(topo, flat, xb)
    return jnp.mean((pred - yb.reshape(pred.shape)) ** 2)


def fit_epoch(
    topo: Topology,
    flat: jnp.ndarray,
    x: jnp.ndarray,
    y: jnp.ndarray,
    lr: float = DEFAULT_LR,
    mode: str = "sequential",
    key: Optional[jax.Array] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One epoch of mse-SGD on fixed (x, y). Returns (new_flat, epoch_loss)."""
    x = jax.lax.stop_gradient(x)
    y = jax.lax.stop_gradient(y)
    if mode == "full_batch":
        loss, grad = jax.value_and_grad(_mse, argnums=1)(topo, flat, x, y)
        return flat - lr * grad, loss
    if mode != "sequential":
        raise ValueError(f"unknown train mode {mode!r}")
    n = x.shape[0]
    order = jnp.arange(n) if key is None else jax.random.permutation(key, n)

    def step(w, i):
        loss, grad = jax.value_and_grad(_mse, argnums=1)(topo, w, x[i][None], y[i][None])
        return w - lr * grad, loss

    flat, losses = jax.lax.scan(step, flat, order)
    return flat, losses.mean()


def train_step(
    topo: Topology,
    flat: jnp.ndarray,
    lr: float = DEFAULT_LR,
    mode: str = "sequential",
    key: Optional[jax.Array] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One ``train()`` call: fit one epoch on the net's own samples
    (self-training toward being a fixpoint)."""
    x, y = compute_samples(topo, flat)
    return fit_epoch(topo, flat, x, y, lr, mode, key)


def learn_from(
    topo: Topology,
    flat: jnp.ndarray,
    other_flat: jnp.ndarray,
    lr: float = DEFAULT_LR,
    mode: str = "sequential",
    key: Optional[jax.Array] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One ``learn_from(other)`` call: fit one epoch on *other's* samples."""
    x, y = compute_samples(topo, other_flat)
    return fit_epoch(topo, flat, x, y, lr, mode, key)
