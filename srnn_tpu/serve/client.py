"""Client for the experiment service (``serve.server`` transport).

One connection per op — the ops are tiny JSON lines and the service is
local (Unix socket), so connection reuse buys nothing and per-op sockets
keep the client trivially thread-safe (the bench's load generators run
many client threads).
"""

import json
import socket
import time
from typing import Optional


class ServiceError(RuntimeError):
    """The service answered ``ok: false`` (bad request, failed dispatch)."""


class ServiceClient:
    def __init__(self, socket_path: str, timeout_s: float = 600.0):
        self.socket_path = socket_path
        self.timeout_s = timeout_s

    def _op(self, msg: dict, timeout_s: Optional[float] = None) -> dict:
        with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
            s.settimeout(timeout_s or self.timeout_s)
            s.connect(self.socket_path)
            s.sendall((json.dumps(msg) + "\n").encode())
            line = s.makefile("rb").readline()
        if not line:
            raise ServiceError("service closed the connection mid-op")
        resp = json.loads(line.decode("utf-8", "replace"))
        if not resp.get("ok"):
            raise ServiceError(resp.get("error")
                               or f"request failed: {resp}")
        return resp

    def ping(self, timeout_s: float = 5.0) -> bool:
        try:
            self._op({"op": "ping"}, timeout_s=timeout_s)
            return True
        except (OSError, ServiceError):
            return False

    def wait_until_up(self, timeout_s: float = 30.0) -> None:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.ping(timeout_s=2.0):
                return
            time.sleep(0.1)
        raise TimeoutError(
            f"no experiment service answering on {self.socket_path} "
            f"after {timeout_s}s")

    def submit(self, kind: str, params: dict,
               tenant: Optional[str] = None) -> str:
        return self._op({"op": "submit", "kind": kind, "params": params,
                         "tenant": tenant})["ticket"]

    def wait(self, ticket: str, timeout_s: Optional[float] = None) -> dict:
        t = timeout_s if timeout_s is not None else self.timeout_s
        # socket deadline sits OUTSIDE the service-side wait timeout so the
        # service's own TimeoutError (a clean ok:false) arrives first
        return self._op({"op": "wait", "ticket": ticket, "timeout_s": t},
                        timeout_s=t + 10.0)["result"]

    def request(self, kind: str, params: dict,
                tenant: Optional[str] = None,
                timeout_s: Optional[float] = None) -> dict:
        """Submit + wait in one op (the setups' submit mode)."""
        t = timeout_s if timeout_s is not None else self.timeout_s
        return self._op({"op": "request", "kind": kind, "params": params,
                         "tenant": tenant, "timeout_s": t},
                        timeout_s=t + 10.0)["result"]

    def stats(self) -> dict:
        return self._op({"op": "stats"}, timeout_s=10.0)["stats"]

    def shutdown(self) -> None:
        self._op({"op": "shutdown"}, timeout_s=10.0)
