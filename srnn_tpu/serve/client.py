"""Client for the experiment service (``serve.server`` transport).

One connection per op — the ops are tiny JSON lines and the service is
local (Unix socket), so connection reuse buys nothing and per-op sockets
keep the client trivially thread-safe (the bench's load generators run
many client threads).

Self-healing etiquette (PR 13): with ``retries > 0`` the client rides out
a service restart — connection failures (``ECONNREFUSED`` / missing
socket / timeout / a connection the dying service closed mid-op) retry
with DETERMINISTIC seeded exponential backoff, and a typed ``overloaded``
rejection (admission control pushing back) backs off the same way instead
of hammering a saturated queue.  Pair that with ``idempotency_key``:
a resubmit after a restart dedupes against the service's durable journal
and returns the ORIGINAL ticket instead of double-running the work.
"""

import json
import socket
import time
import uuid
from typing import Optional

from ..resilience.supervisor import BackoffPolicy


def mint_trace_id() -> str:
    """A fresh 16-hex-char trace id (fleet tracing).  Host-only entropy:
    the id labels telemetry rows and never reaches a dispatch path, so
    minting cannot perturb results (the ``--no-spans`` bitwise oracle
    covers the whole propagation chain)."""
    return uuid.uuid4().hex[:16]


class ServiceError(RuntimeError):
    """The service answered ``ok: false`` (bad request, failed dispatch)."""


class ServiceOverloaded(ServiceError):
    """Typed admission rejection (``overloaded: true``): the queue is at
    ``--max-queue``.  Back off and resubmit — the request was never
    admitted, so resubmitting cannot double-run."""


#: failures where the op can never have REACHED the service (the connect
#: itself failed) — always safe to retry.  BlockingIOError is Linux's
#: EAGAIN from connect(2) on an AF_UNIX socket whose listen backlog is
#: full (a burst of per-op connects against a momentarily stalled accept
#: loop): nothing was delivered
_RETRY_SAFE_EXC = (ConnectionRefusedError, FileNotFoundError,
                   BlockingIOError)
#: failures where the op may have been DELIVERED before the connection
#: died — retried only for idempotent messages (reads, or admissions
#: carrying an ``idempotency_key`` the service dedupes on); a keyless
#: submit retried here could double-run work that was already admitted
_RETRY_DELIVERED_EXC = (ConnectionResetError, BrokenPipeError,
                        TimeoutError, socket.timeout)

#: ops that are idempotent regardless of payload (pure reads)
_IDEMPOTENT_OPS = frozenset({"ping", "stats", "wait"})


def _retry_is_safe(msg: dict) -> bool:
    return msg.get("op") in _IDEMPOTENT_OPS \
        or bool(msg.get("idempotency_key"))


class ServiceClient:
    def __init__(self, socket_path: str, timeout_s: float = 600.0,
                 retries: int = 0, backoff_base_s: float = 0.2,
                 backoff_max_s: float = 5.0, seed: int = 0):
        self.socket_path = socket_path
        self.timeout_s = timeout_s
        self.retries = max(0, int(retries))
        # the supervisor's deterministic-backoff policy, reused verbatim:
        # the same seed yields the same delay sequence, so a
        # chaos-harness run replays end to end; real fleets seed per
        # client and decorrelate
        self._policy = BackoffPolicy(max_restarts=self.retries,
                                     base_s=backoff_base_s,
                                     max_s=backoff_max_s, jitter=0.25,
                                     seed=int(seed) ^ 0xC11E)

    def _op_once(self, msg: dict, timeout_s: Optional[float] = None) -> dict:
        with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
            s.settimeout(timeout_s or self.timeout_s)
            s.connect(self.socket_path)
            s.sendall((json.dumps(msg) + "\n").encode())
            line = s.makefile("rb").readline()
        if not line:
            # a dying service closes mid-op; retryable like a refused
            # connect (the op may not have been admitted — idempotency
            # keys make the retry safe either way)
            raise ConnectionResetError("service closed the connection "
                                       "mid-op")
        resp = json.loads(line.decode("utf-8", "replace"))
        if not resp.get("ok"):
            err = resp.get("error") or f"request failed: {resp}"
            if resp.get("overloaded"):
                raise ServiceOverloaded(err)
            raise ServiceError(err)
        return resp

    def _op(self, msg: dict, timeout_s: Optional[float] = None,
            retry_overload: bool = False) -> dict:
        attempt = 0
        while True:
            try:
                return self._op_once(msg, timeout_s=timeout_s)
            except ServiceOverloaded:
                # never admitted: always safe to resubmit
                if not retry_overload or attempt >= self.retries:
                    raise
            except _RETRY_SAFE_EXC:
                if attempt >= self.retries:
                    raise
            except _RETRY_DELIVERED_EXC:
                # the op may have landed before the connection died —
                # only idempotent messages may go again
                if attempt >= self.retries or not _retry_is_safe(msg):
                    raise
            time.sleep(self._policy.delay(attempt))
            attempt += 1

    def ping(self, timeout_s: float = 5.0) -> bool:
        try:
            self._op_once({"op": "ping"}, timeout_s=timeout_s)
            return True
        except (OSError, ServiceError):
            return False

    def wait_until_up(self, timeout_s: float = 30.0) -> None:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.ping(timeout_s=2.0):
                return
            time.sleep(0.1)
        raise TimeoutError(
            f"no experiment service answering on {self.socket_path} "
            f"after {timeout_s}s")

    def _submit_msg(self, op: str, kind: str, params: dict,
                    tenant: Optional[str],
                    deadline_s: Optional[float],
                    idempotency_key: Optional[str],
                    trace_id: Optional[str] = None,
                    parent_span: Optional[int] = None) -> dict:
        msg = {"op": op, "kind": kind, "params": params, "tenant": tenant}
        if deadline_s is not None:
            msg["deadline_s"] = deadline_s
        if idempotency_key is not None:
            msg["idempotency_key"] = idempotency_key
        # trace context rides as optional header fields: a traceless
        # submit is byte-identical to the pre-tracing protocol
        if trace_id is not None:
            msg["trace_id"] = trace_id
        if parent_span is not None:
            msg["parent_span"] = parent_span
        return msg

    def submit(self, kind: str, params: dict,
               tenant: Optional[str] = None,
               deadline_s: Optional[float] = None,
               idempotency_key: Optional[str] = None,
               trace_id: Optional[str] = None,
               parent_span: Optional[int] = None) -> str:
        return self._op(self._submit_msg("submit", kind, params, tenant,
                                         deadline_s, idempotency_key,
                                         trace_id or mint_trace_id(),
                                         parent_span),
                        retry_overload=True)["ticket"]

    def wait(self, ticket: str, timeout_s: Optional[float] = None) -> dict:
        t = timeout_s if timeout_s is not None else self.timeout_s
        # socket deadline sits OUTSIDE the service-side wait timeout so the
        # service's own TimeoutError (a clean ok:false) arrives first
        return self._op({"op": "wait", "ticket": ticket, "timeout_s": t},
                        timeout_s=t + 10.0)["result"]

    def request(self, kind: str, params: dict,
                tenant: Optional[str] = None,
                timeout_s: Optional[float] = None,
                deadline_s: Optional[float] = None,
                idempotency_key: Optional[str] = None,
                trace_id: Optional[str] = None,
                parent_span: Optional[int] = None) -> dict:
        """Submit + wait in one op (the setups' submit mode)."""
        t = timeout_s if timeout_s is not None else self.timeout_s
        msg = self._submit_msg("request", kind, params, tenant,
                               deadline_s, idempotency_key,
                               trace_id or mint_trace_id(), parent_span)
        msg["timeout_s"] = t
        return self._op(msg, timeout_s=t + 10.0,
                        retry_overload=True)["result"]

    def stats(self) -> dict:
        return self._op({"op": "stats"}, timeout_s=10.0)["stats"]

    def drain(self) -> None:
        """Graceful drain (the socket spelling of SIGTERM): in-flight
        dispatches finish, the queued rest stays journaled for a restart
        to replay."""
        self._op_once({"op": "drain"}, timeout_s=10.0)

    def shutdown(self) -> None:
        self._op_once({"op": "shutdown"}, timeout_s=10.0)
