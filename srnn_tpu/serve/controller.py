"""Continuous-batching window controller (the adaptive dispatch tier).

PR 10's dispatcher slept a FIXED ``--batch-window-s`` (250ms) before
every drain, so at light load p50 was window-bound (~310ms against a
~20ms dispatch) and throughput froze at ~13 req/s no matter how fast the
executors got.  This module closes ROADMAP item 2's control loop: the
signals already exist — the per-ticket ``serve_ticket_{queue,window,
dispatch}_seconds`` breakdown (PR 12) and the SLO counter the PR 15
``serve_slo_burn`` alert rate-watches — and the controller turns them
into the one knob the dispatcher owns, the batching window.

Control law (per scheduler group — the static spelling IS the batching
domain, so each spelling earns its own window):

  * a group's window STARTS at the floor: the first tickets of a
    spelling dispatch near-immediately (continuous batching — first-
    ticket latency is dispatch-bound, not window-bound);
  * every retired dispatch reports its SLO-violation count (the same
    per-ticket ``latency > --slo-p95-ms`` predicate that feeds
    ``serve_slo_violations_total``, i.e. the PR 15 burn rule's
    numerator).  A burning round SHRINKS the window multiplicatively
    (halve, clamp at the floor): under SLO pressure, stop waiting for
    stackmates and ship;
  * a clean round GROWS the window multiplicatively toward the
    ``--batch-window-s`` CEILING: headroom against the SLO is spent on
    wider stacks (amortization), and a service idling back to quiet
    recovers its full stacking window one clean round at a time.

Determinism contract: the controller's state is a pure fold over the
observed ``(group, violations)`` dispatch-retire sequence — no clocks,
no randomness — so the same ticket arrival trace (same admissions, same
measured outcomes) yields the same window sequence, replayable by the
chaos harness like every other recovery ladder.

The fixed-window dispatcher remains available as the A/B oracle
(``--no-adaptive``): with the controller off, the serve tier runs
exactly the PR 10 code path and reproduces its results bitwise — the
``--no-spans``/``--no-costs``/``--no-export`` discipline, asserted in
``tests/test_serve_scale.py``.
"""

import threading
from typing import Dict, Hashable, Optional, Sequence

#: multiplicative shrink on a burning round: halving reaches the floor
#: from any ceiling in <10 rounds, fast enough that a burst's tail does
#: not keep paying the window that made its head violate
SHRINK = 0.5

#: multiplicative growth on a clean round: gentler than the shrink
#: (MIMD-style — back off hard, recover gently) so one clean round
#: cannot bounce the window straight back into burn territory
GROW = 1.5

#: the smallest window the controller will ask the dispatcher to sleep:
#: below ~1ms the sleep syscall itself is the wait, and 0 would turn the
#: dispatch loop into a spin between back-to-back singleton dispatches
DEFAULT_FLOOR_S = 1e-3

#: per-group state cap: group keys are static spellings, so a long-lived
#: service fed ever-fresh configs could otherwise grow without bound;
#: past the cap the OLDEST group's state evicts (deterministic — and a
#: re-seen group simply restarts at the floor, the cold-start behavior)
MAX_GROUPS = 256


class AdaptiveWindowController:
    """Per-group adaptive batching windows for one dispatch loop.

    Thread-safety: ``window_s`` / ``observe_dispatch`` run on the
    dispatch thread; ``snapshot`` is read from stats/watch handler
    threads — the lock keeps the state dict consistent, not the law
    (which only ever folds on the dispatch thread)."""

    def __init__(self, ceiling_s: float, slo_p95_ms: float = 0.0,
                 floor_s: float = DEFAULT_FLOOR_S,
                 shrink: float = SHRINK, grow: float = GROW):
        self.ceiling_s = max(0.0, float(ceiling_s))
        self.slo_p95_ms = max(0.0, float(slo_p95_ms))
        self.floor_s = min(max(0.0, float(floor_s)), self.ceiling_s) \
            if self.ceiling_s > 0 else 0.0
        self.shrink = float(shrink)
        self.grow = float(grow)
        self._lock = threading.Lock()
        self._windows: Dict[Hashable, float] = {}

    def _get(self, group: Hashable) -> float:
        if group not in self._windows:
            while len(self._windows) >= MAX_GROUPS:
                self._windows.pop(next(iter(self._windows)))
            self._windows[group] = self.floor_s
        return self._windows[group]

    def window_s(self, groups: Sequence[Hashable]) -> float:
        """The wait the dispatcher performs before the next drain: the
        MINIMUM over the pending groups' windows — one group under SLO
        pressure must not sit out a calmer group's stacking window (the
        drain dispatches every group either way; the wait only bounds
        how long the tightest group's tickets age before it)."""
        with self._lock:
            if not groups:
                return self.floor_s
            return min(self._get(g) for g in groups)

    def observe_dispatch(self, group: Hashable, violations: int,
                         completed: int) -> float:
        """Fold one retired dispatch into the group's window and return
        the new value.  ``violations`` is the dispatch's share of the
        SLO counter (the burn rule's numerator); with no SLO target it
        is always 0 and the window simply grows to the ceiling — the
        fixed-window behavior, reached instead of configured."""
        with self._lock:
            w = self._get(group)
            if violations > 0:
                w = max(self.floor_s, w * self.shrink)
            elif completed > 0:
                w = min(self.ceiling_s, max(self.floor_s, w * self.grow))
            self._windows[group] = w
            return w

    def snapshot(self) -> dict:
        """Stats/watch view: group count plus the min/max live windows
        (the per-group keys are config objects — summarized, not
        serialized)."""
        with self._lock:
            ws = list(self._windows.values())
        return {"adaptive": True,
                "ceiling_s": self.ceiling_s,
                "floor_s": self.floor_s,
                "slo_p95_ms": self.slo_p95_ms or None,
                "groups": len(ws),
                "window_min_s": round(min(ws), 6) if ws else None,
                "window_max_s": round(max(ws), 6) if ws else None}


def make_controller(batch_window_s: float, slo_p95_ms: float,
                    adaptive: bool = True) \
        -> Optional[AdaptiveWindowController]:
    """The ``__main__``/bench construction helper: ``adaptive=False``
    (the ``--no-adaptive`` oracle) returns None and the dispatcher runs
    the PR 10 fixed-window path verbatim."""
    if not adaptive:
        return None
    return AdaptiveWindowController(ceiling_s=batch_window_s,
                                    slo_p95_ms=slo_p95_ms)
