"""Unix-socket JSON-lines transport around :class:`ExperimentService`.

One request per line, one response per line:

  {"op": "ping"}                               -> {"ok": true}
  {"op": "submit", "kind": K, "params": {...},
   "tenant": "name"}                           -> {"ok": true, "ticket": id}
  {"op": "wait", "ticket": id, "timeout_s": S} -> {"ok": true, "status":
                                                   "done", "result": {...}}
  {"op": "request", ...submit fields...}       -> submit + wait in one line
  {"op": "stats"}                              -> {"ok": true, "stats": ...}
  {"op": "shutdown"}                           -> {"ok": true} and the
                                                  server stops

Connection handling rides ``socketserver.ThreadingMixIn`` (per-connection
threads, joined on ``server_close``); the DISPATCH loop is one dedicated
thread (``pipeline.spawn_thread``) draining the service queue with a
batching window, so jax dispatch stays single-threaded no matter how many
clients connect.  The batching window is the stacking knob: requests
arriving within the window of each other are scheduled together and stack
when their static spellings match.

Continuous batching (PR 16): the dispatcher BLOCKS on the service's
admission condition variable while idle (no poll-sleep — an idle worker
burns no CPU, and the first ticket after quiet wakes it immediately), and
with a ``controller`` attached the wait window is the ADAPTIVE per-group
value (``serve.controller``) instead of the fixed ``batch_window_s`` —
tickets admit into the very next dispatch as soon as the current one
retires.  ``controller=None`` keeps the PR 10 fixed-window dispatch
byte-exact (the ``--no-adaptive`` A/B oracle).
"""

import json
import os
import socket
import socketserver
import threading
import time
from typing import Optional

from ..utils.pipeline import spawn_thread
from .service import DeadlineExpired, ExperimentService, OverloadedError


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        server: "ServiceServer" = self.server  # type: ignore[assignment]
        for raw in self.rfile:
            line = raw.decode("utf-8", "replace").strip()
            if not line:
                continue
            try:
                resp = server.handle_op(json.loads(line))
            except Exception as e:
                resp = {"ok": False, "error": f"{type(e).__name__}: {e}"}
            self.wfile.write((json.dumps(resp) + "\n").encode())
            self.wfile.flush()
            if resp.get("bye"):
                break


class ServiceServer(socketserver.ThreadingMixIn,
                    socketserver.UnixStreamServer):
    """The long-lived server: socket accept loop + one dispatch thread."""

    daemon_threads = False   # joined on server_close: no stranded handlers
    allow_reuse_address = True
    # connection-per-op clients connect in bursts; the socketserver
    # default backlog of 5 turns any accept-loop stall into EAGAIN
    # connect failures under concurrent load
    request_queue_size = 128

    def __init__(self, service: ExperimentService, socket_path: str,
                 batch_window_s: float = 0.25, controller=None,
                 idle_tick_s: float = 1.0):
        if os.path.exists(socket_path):
            # only a STALE socket (killed server) may be reclaimed — a
            # live server answering ping must not have its socket stolen
            # out from under its clients by a second instance
            if wait_for_socket(socket_path, timeout_s=0.0):
                raise RuntimeError(
                    f"a live experiment service already answers on "
                    f"{socket_path}; refusing to steal its socket")
            os.unlink(socket_path)
        super().__init__(socket_path, _Handler)
        self.service = service
        self.socket_path = socket_path
        self.batch_window_s = batch_window_s
        #: adaptive window controller (None = fixed window, the PR 10
        #: oracle); attaching it also flips the service's fairness plan
        self.controller = controller
        if controller is not None:
            service.attach_controller(controller)
        #: idle heartbeat: how often a blocked dispatcher wakes to slide
        #: the rate-alert windows (throttled inside the service) and
        #: re-check its stop flag
        self._idle_tick_s = max(0.05, float(idle_tick_s))
        self._stop = threading.Event()
        #: graceful-drain flag (the SIGTERM path): finish the in-flight
        #: dispatch, do NOT dispatch the remaining queue — those tickets
        #: stay journaled-unfinished and a restarted service replays them
        self._drain = threading.Event()
        self._dispatcher = None

    # -- ops -------------------------------------------------------------

    def handle_op(self, msg: dict) -> dict:
        op = msg.get("op")
        if op == "ping":
            return {"ok": True}
        if op == "submit":
            if self._stop.is_set():
                return {"ok": False, "error": "service shutting down"}
            return self._admit(msg)
        if op in ("wait", "request"):
            if op == "request":
                if self._stop.is_set():
                    return {"ok": False, "error": "service shutting down"}
                admitted = self._admit(msg)
                if not admitted["ok"]:
                    return admitted
                ticket = admitted["ticket"]
            else:
                ticket = msg["ticket"]
            entry = self.service.wait(ticket,
                                      timeout_s=float(msg.get("timeout_s",
                                                              600.0)))
            out = {"ok": entry["status"] == "done", "ticket": ticket}
            out.update(entry)
            return out
        if op == "stats":
            return {"ok": True, "stats": self.service.stats()}
        if op == "drain":
            # socket spelling of the SIGTERM drain (tests, orchestrators)
            self.stop(drain=True)
            return {"ok": True, "bye": True, "draining": True}
        if op == "shutdown":
            self._stop.set()
            self.service.wake()   # a condvar-blocked dispatcher re-checks
            # unblock serve_forever from a handler thread without joining
            # ourselves: shutdown() must run off the serve_forever thread
            spawn_thread(self.shutdown, name="serve-shutdown")
            return {"ok": True, "bye": True}
        return {"ok": False, "error": f"unknown op {op!r}"}

    def _admit(self, msg: dict) -> dict:
        """Submit with the typed admission responses: ``overloaded`` and
        ``deadline_expired`` flags let the client pick the right reaction
        (back off and resubmit vs give up) without string-matching."""
        try:
            ticket = self.service.submit(
                msg["kind"], msg.get("params", {}),
                tenant=msg.get("tenant"),
                deadline_s=msg.get("deadline_s"),
                idempotency_key=msg.get("idempotency_key"),
                trace_id=msg.get("trace_id"),
                parent_span=msg.get("parent_span"))
        except OverloadedError as e:
            return {"ok": False, "error": str(e), "overloaded": True}
        except DeadlineExpired as e:
            return {"ok": False, "error": str(e), "deadline_expired": True}
        return {"ok": True, "ticket": ticket}

    # -- lifecycle -------------------------------------------------------

    def _dispatch_loop(self) -> None:
        """Single-threaded jax dispatch, continuous batching: block on
        the admission condvar while idle, give the (adaptive) batching
        window a chance to aggregate, drain, loop straight into the next
        round — tickets admit into the next dispatch the moment the
        current one retires."""
        while not self._stop.is_set():
            if not self.service.wait_for_work(timeout_s=self._idle_tick_s):
                # rate-alert windows keep sliding while idle (throttled
                # inside — a fired SLO-burn alert must clear on quiet)
                self.service.idle_sample_live()
                continue
            if self.controller is None:
                window = self.batch_window_s
            else:
                window = self.controller.window_s(
                    self.service.pending_groups())
            if window > 0:
                time.sleep(window)
            if self._drain.is_set():
                # SIGTERM landed during the window: the queued tickets
                # stay journaled-unfinished for the restart to replay —
                # dispatching them now is exactly what drain forbids
                return
            # window_s = the sleep just performed: the service splits each
            # ticket's pre-dispatch wait into queue vs window spans with it
            self.service.run_pending(window_s=window)
        if self._drain.is_set():
            return
        # drain whatever raced the stop (handle_op rejects new traffic
        # once _stop is set, so this converges; no window sleep here)
        while self.service.queue_depth() > 0:
            self.service.run_pending()

    def serve_until_shutdown(self) -> None:
        """Run the accept loop on THIS thread and the dispatch loop on a
        spawned one; returns after a ``shutdown`` op (or ``stop()``)."""
        self._dispatcher = spawn_thread(self._dispatch_loop,
                                        name="serve-dispatch")
        try:
            self.serve_forever(poll_interval=0.1)
        finally:
            self._stop.set()
            self.service.wake()
            self._dispatcher.join()
            # a submit that slipped between the stop-check and the
            # dispatcher's final drain must not leave its handler thread
            # blocked in wait() — server_close() JOINS handler threads,
            # so a stranded waiter would hang shutdown for its timeout.
            # Either way the stranded tickets stay journaled-unfinished;
            # the drain spelling says so in the typed response.
            if self._drain.is_set():
                self.service.fail_pending(
                    "service draining; ticket journaled for replay "
                    "after restart", resumable=True)
            else:
                self.service.fail_pending(
                    "service shut down before dispatch")
            self.server_close()
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass

    def stop(self, drain: bool = False) -> None:
        """Signal-safe stop (the SIGTERM path): ``shutdown()`` blocks
        until ``serve_forever`` exits, and a signal handler runs ON the
        thread inside ``serve_forever`` — calling it synchronously there
        deadlocks, so it moves to a helper thread like the shutdown op.
        ``drain=True`` is the graceful-preemption contract: finish the
        in-flight dispatch, journal (keep) the rest, exit clean so a
        restart resumes them."""
        if drain:
            self._drain.set()
        self._stop.set()
        self.service.wake()
        spawn_thread(self.shutdown, name="serve-stop")


def wait_for_socket(path: str, timeout_s: float = 30.0) -> bool:
    """Readiness probe: can we connect and ping?  Always probes at least
    once, so ``timeout_s=0`` is a one-shot liveness check."""
    deadline = time.monotonic() + timeout_s
    while True:
        try:
            with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
                s.settimeout(2.0)
                s.connect(path)
                s.sendall(b'{"op": "ping"}\n')
                if b'"ok": true' in s.makefile("rb").readline():
                    return True
        except OSError:
            pass
        if time.monotonic() >= deadline:
            return False
        time.sleep(0.1)
