"""Batching scheduler: group compatible requests into stacked dispatches.

The grouping key is the request's STATIC spelling — everything that
selects a compiled program (experiment kind, topology, the full
``SoupConfig`` statics, generation count, dispatch shapes).  Requests
whose keys match are interchangeable up to traced values (seeds,
epsilons), so K of them stack into one ``(K, ...)`` tenant-axis dispatch
(``serve.tenant``); an odd-one-out key falls back to SOLO dispatch — the
exact per-tenant program, so fallback never changes results, only
amortization.

Pure host logic, no jax: the service owns execution; this module only
decides who rides together.
"""

from typing import Dict, Hashable, List, NamedTuple, Optional, Sequence

#: default cap on tenants per stacked dispatch (K): past this the stacked
#: program's own compile becomes a new spelling per K — the service warms
#: a fixed K and chunks bigger groups into full stacks + a remainder
DEFAULT_MAX_STACK = 8


class Request(NamedTuple):
    """One queued experiment request."""
    ticket: str           # unique id, assigned by the service
    kind: str             # executor name ('fixpoint_density', 'soup', ...)
    params: dict          # kind-specific payload (seeds, shapes, knobs)
    tenant: str           # tenant label for telemetry/lineage rows
    submitted_s: float    # monotonic submit stamp (latency accounting)
    #: absolute MONOTONIC deadline stamped at admission (None = no
    #: deadline); expired tickets fail fast and never occupy a stack slot
    deadline_mono: Optional[float] = None
    #: client idempotency key — a resubmit with the same key dedupes
    #: against the live table / the durable journal instead of re-running
    idem_key: Optional[str] = None


class Dispatch(NamedTuple):
    """One planned dispatch: ``requests`` ride together iff ``stacked``."""
    kind: str
    key: Hashable
    requests: List[Request]

    @property
    def stacked(self) -> bool:
        return len(self.requests) > 1


def plan_dispatches(requests: Sequence[Request], group_keys: Dict[str, "callable"],
                    max_stack: int = DEFAULT_MAX_STACK) -> List[Dispatch]:
    """Group ``requests`` into stacked/solo dispatches.

    ``group_keys`` maps kind -> key function over params; a kind without
    one (or a key function returning ``None``) never stacks.  Groups keep
    submission order, chunk at ``max_stack``, and a chunk of one is a
    solo dispatch by construction.  The returned plan preserves
    first-submission order across groups (fairness: an early solo request
    is not starved behind later stackable traffic).
    """
    groups: Dict = {}
    order: List = []
    for i, req in enumerate(requests):
        keyfn = group_keys.get(req.kind)
        try:
            key = keyfn(req.params) if keyfn is not None else None
        except Exception:
            # malformed params must not take down the scheduling round:
            # route the request solo so its executor raises inside the
            # per-dispatch error wall and fails ONLY this request
            key = None
        if key is None:
            gid = ("solo", i)      # unstackable: its own group
            full_key = None
        else:
            gid = (req.kind, key)
            full_key = key
        if gid not in groups:
            groups[gid] = (full_key, [])
            order.append(gid)
        groups[gid][1].append(req)
    plan: List[Dispatch] = []
    for gid in order:
        key, members = groups[gid]
        for lo in range(0, len(members), max(1, max_stack)):
            plan.append(Dispatch(kind=members[0].kind, key=key,
                                 requests=members[lo:lo + max_stack]))
    return plan
