"""Batching scheduler: group compatible requests into stacked dispatches.

The grouping key is the request's STATIC spelling — everything that
selects a compiled program (experiment kind, topology, the full
``SoupConfig`` statics, generation count, dispatch shapes).  Requests
whose keys match are interchangeable up to traced values (seeds,
epsilons), so K of them stack into one ``(K, ...)`` tenant-axis dispatch
(``serve.tenant``); an odd-one-out key falls back to SOLO dispatch — the
exact per-tenant program, so fallback never changes results, only
amortization.

Fairness (the continuous-batching tier's policy, ``fair=True``): a hog
tenant flooding one spelling must not starve other tenants' tickets for
whole drain cycles.  Two stable transforms, both deterministic given the
same queue snapshot: :func:`interleave_tenants` reorders the batch
round-robin by tenant (round r takes each tenant's r-th request, tenants
in first-appearance order) BEFORE grouping, and the planner then emits
chunks round-robin ACROSS groups (chunk 0 of every group, then chunk 1,
...) so one group's long chunk train cannot push another group's first
chunk to the end of the drain.

Pure host logic, no jax: the service owns execution; this module only
decides who rides together.
"""

from typing import Dict, Hashable, List, NamedTuple, Optional, Sequence

#: default cap on tenants per stacked dispatch (K): past this the stacked
#: program's own compile becomes a new spelling per K — the service warms
#: a fixed K and chunks bigger groups into full stacks + a remainder
DEFAULT_MAX_STACK = 8


class Request(NamedTuple):
    """One queued experiment request."""
    ticket: str           # unique id, assigned by the service
    kind: str             # executor name ('fixpoint_density', 'soup', ...)
    params: dict          # kind-specific payload (seeds, shapes, knobs)
    tenant: str           # tenant label for telemetry/lineage rows
    submitted_s: float    # monotonic submit stamp (latency accounting)
    #: absolute MONOTONIC deadline stamped at admission (None = no
    #: deadline); expired tickets fail fast and never occupy a stack slot
    deadline_mono: Optional[float] = None
    #: client idempotency key — a resubmit with the same key dedupes
    #: against the live table / the durable journal instead of re-running
    idem_key: Optional[str] = None
    #: propagated trace context (fleet tracing).  ``trace_id`` is the
    #: distributed trace this ticket belongs to (the service falls back
    #: to the ticket id when absent); ``parent_span`` is the span id of
    #: the far side of the hop (e.g. the pool front's relay span), kept
    #: as a REMOTE link because span ids are only unique per process.
    #: Telemetry labels only — scheduling/grouping never reads them.
    trace_id: Optional[str] = None
    parent_span: Optional[int] = None


class Dispatch(NamedTuple):
    """One planned dispatch: ``requests`` ride together iff ``stacked``."""
    kind: str
    key: Hashable
    requests: List[Request]

    @property
    def stacked(self) -> bool:
        return len(self.requests) > 1


def interleave_tenants(requests: Sequence[Request]) -> List[Request]:
    """Stable per-tenant round-robin: round r takes the r-th request of
    each tenant, tenants ordered by first appearance.  Within a tenant,
    submission order is preserved; across tenants, a hog submitting 50
    tickets ahead of a second tenant's one no longer owns the first 50
    stack slots of the drain."""
    by_tenant: Dict[str, List[Request]] = {}
    tenant_order: List[str] = []
    for req in requests:
        if req.tenant not in by_tenant:
            by_tenant[req.tenant] = []
            tenant_order.append(req.tenant)
        by_tenant[req.tenant].append(req)
    out: List[Request] = []
    r = 0
    while len(out) < len(requests):
        for tenant in tenant_order:
            queue = by_tenant[tenant]
            if r < len(queue):
                out.append(queue[r])
        r += 1
    return out


def plan_dispatches(requests: Sequence[Request], group_keys: Dict[str, "callable"],
                    max_stack: int = DEFAULT_MAX_STACK,
                    fair: bool = False) -> List[Dispatch]:
    """Group ``requests`` into stacked/solo dispatches.

    ``group_keys`` maps kind -> key function over params; a kind without
    one (or a key function returning ``None``) never stacks.  Groups keep
    submission order, chunk at ``max_stack``, and a chunk of one is a
    solo dispatch by construction.  The returned plan preserves
    first-submission order across groups (fairness: an early solo request
    is not starved behind later stackable traffic).

    ``fair=True`` (the adaptive tier) layers the tenant policy on top:
    requests are tenant-interleaved before grouping, and chunks are
    emitted round-robin across groups rather than group-by-group — see
    the module docstring.  Stacking itself is unchanged (same spellings
    ride together either way), so fairness reorders WHO dispatches when,
    never WHAT a dispatch computes.
    """
    if fair:
        requests = interleave_tenants(requests)
    groups: Dict = {}
    order: List = []
    for i, req in enumerate(requests):
        keyfn = group_keys.get(req.kind)
        try:
            key = keyfn(req.params) if keyfn is not None else None
        except Exception:
            # malformed params must not take down the scheduling round:
            # route the request solo so its executor raises inside the
            # per-dispatch error wall and fails ONLY this request
            key = None
        if key is None:
            gid = ("solo", i)      # unstackable: its own group
            full_key = None
        else:
            gid = (req.kind, key)
            full_key = key
        if gid not in groups:
            groups[gid] = (full_key, [])
            order.append(gid)
        groups[gid][1].append(req)
    chunked: Dict = {}
    for gid in order:
        key, members = groups[gid]
        chunked[gid] = [
            Dispatch(kind=members[0].kind, key=key,
                     requests=members[lo:lo + max_stack])
            for lo in range(0, len(members), max(1, max_stack))]
    plan: List[Dispatch] = []
    if fair:
        # round-robin across groups: chunk 0 of every group in order,
        # then chunk 1 of every group, ... — no group's chunk train
        # monopolizes the head of the drain
        r = 0
        while len(plan) < sum(len(c) for c in chunked.values()):
            for gid in order:
                if r < len(chunked[gid]):
                    plan.append(chunked[gid][r])
            r += 1
    else:
        for gid in order:
            plan.extend(chunked[gid])
    return plan
