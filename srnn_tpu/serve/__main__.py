"""``python -m srnn_tpu.serve`` — run (or talk to) the experiment service.

Server mode (default): bind the Unix socket, warm any requested
spellings, and serve until a ``shutdown`` op or SIGTERM.  Client mode
(``--shutdown`` / ``--stats`` / ``--ping``) talks to a RUNNING service on
the same socket — the smoke scripts use it for clean teardown.
"""

import argparse
import json
import os
import signal
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--root", default="serve_root",
                   help="service directory (events.jsonl, lineage.jsonl, "
                        "metrics.prom; default socket lives here too)")
    p.add_argument("--socket", default=None,
                   help="Unix socket path (default <root>/serve.sock)")
    p.add_argument("--max-stack", type=int, default=8, metavar="K",
                   help="most tenants per stacked dispatch")
    p.add_argument("--batch-window-s", type=float, default=0.25, metavar="S",
                   help="requests arriving within S seconds of each other "
                        "are scheduled together (the stacking window)")
    p.add_argument("--slo-p95-ms", type=float, default=0.0, metavar="MS",
                   help="latency target: each request slower than MS "
                        "counts into serve_slo_violations_total and the "
                        "stats/watch SLO view (0 = no target)")
    p.add_argument("--warm-fixpoint-density", default=None,
                   metavar="TRIALS,BATCH",
                   help="pre-dispatch the fixpoint-density executor at "
                        "these shapes (stacked at --max-stack AND solo) "
                        "before accepting traffic")
    p.add_argument("--ping", action="store_true",
                   help="client mode: exit 0 iff a service answers")
    p.add_argument("--stats", action="store_true",
                   help="client mode: print a running service's stats JSON")
    p.add_argument("--shutdown", action="store_true",
                   help="client mode: ask a running service to exit")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    sock = args.socket or os.path.join(args.root, "serve.sock")

    if args.ping or args.stats or args.shutdown:
        from .client import ServiceClient, ServiceError

        client = ServiceClient(sock)
        try:
            if args.ping:
                return 0 if client.ping() else 1
            if args.stats:
                print(json.dumps(client.stats(), indent=1, default=str))
                return 0
            client.shutdown()
            return 0
        except (OSError, ServiceError) as e:
            print(f"serve client: {e}", file=sys.stderr)
            return 1

    if os.environ.get("SRNN_SETUPS_PLATFORM") == "cpu":
        # config-level CPU pin for subprocess callers (tests, CI) — the
        # same escape hatch as setups/__main__
        from ..utils.backend import force_cpu

        force_cpu()
    from ..utils.aot import ensure_compilation_cache
    from .server import ServiceServer
    from .service import ExperimentService

    ensure_compilation_cache()
    os.makedirs(args.root, exist_ok=True)
    service = ExperimentService(args.root, max_stack=args.max_stack,
                                slo_p95_ms=args.slo_p95_ms)
    if args.warm_fixpoint_density:
        trials, batch = (int(x) for x in
                         args.warm_fixpoint_density.split(","))
        service.warm("fixpoint_density", {"trials": trials, "batch": batch})
    server = ServiceServer(service, sock,
                           batch_window_s=args.batch_window_s)
    prev = signal.signal(signal.SIGTERM, lambda *_: server.stop())
    print(f"serve: listening on {sock} (root={args.root}, "
          f"max_stack={args.max_stack}, "
          f"batch_window_s={args.batch_window_s})", flush=True)
    try:
        server.serve_until_shutdown()
    finally:
        signal.signal(signal.SIGTERM, prev)
        service.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
