"""``python -m srnn_tpu.serve`` — run (or talk to) the experiment service.

Server mode (default): replay any journaled-unfinished tickets from a
previous (possibly killed) service on the same ``--root``, bind the Unix
socket, warm any requested spellings, and serve until a ``shutdown`` op
or SIGTERM.  SIGTERM drains gracefully: the in-flight dispatch finishes,
the queued rest stays journaled, and the process exits 0 so a restart
resumes exactly where it stopped.  Client mode (``--shutdown`` /
``--drain`` / ``--stats`` / ``--ping``) talks to a RUNNING service on
the same socket — the smoke scripts use it for clean teardown.
"""

import argparse
import json
import os
import signal
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--root", default="serve_root",
                   help="service directory (events.jsonl, lineage.jsonl, "
                        "metrics.prom; default socket lives here too)")
    p.add_argument("--socket", default=None,
                   help="Unix socket path (default <root>/serve.sock)")
    p.add_argument("--max-stack", type=int, default=8, metavar="K",
                   help="most tenants per stacked dispatch")
    p.add_argument("--batch-window-s", type=float, default=0.25, metavar="S",
                   help="the stacking window CEILING: the adaptive "
                        "controller grows each scheduler group's dispatch "
                        "window toward S when the SLO has headroom and "
                        "shrinks it under burn; with --no-adaptive, the "
                        "fixed per-cycle window (the PR 10 behavior)")
    p.add_argument("--no-adaptive", action="store_true",
                   help="disable the continuous-batching controller (and "
                        "the tenant-fairness plan that ships with it): "
                        "the dispatcher sleeps the fixed --batch-window-s "
                        "every cycle — the A/B oracle that reproduces the "
                        "fixed-window results bitwise")
    p.add_argument("--workers", type=int, default=1, metavar="N",
                   help="dispatch processes: N>1 runs a worker fleet "
                        "behind this socket (shared AOT cache, per-tenant "
                        "sticky round-robin, journal-backed replay when a "
                        "worker dies; see serve/pool.py)")
    p.add_argument("--slo-p95-ms", type=float, default=0.0, metavar="MS",
                   help="latency target: each request slower than MS "
                        "counts into serve_slo_violations_total and the "
                        "stats/watch SLO view (0 = no target)")
    p.add_argument("--warm-fixpoint-density", default=None,
                   metavar="TRIALS,BATCH",
                   help="pre-dispatch the fixpoint-density executor at "
                        "these shapes (stacked at --max-stack AND solo) "
                        "before accepting traffic")
    p.add_argument("--max-queue", type=int, default=0, metavar="N",
                   help="admission control: reject submits with a typed "
                        "'overloaded' response once N tickets are queued "
                        "(0 = unbounded)")
    p.add_argument("--results-ttl-s", type=float, default=3600.0,
                   metavar="S",
                   help="evict completed-but-never-collected results "
                        "after S seconds (0 = keep until the retention "
                        "cap)")
    p.add_argument("--dispatch-retries", type=int, default=2, metavar="N",
                   help="bounded retries for transient classified "
                        "dispatch faults (device_loss/io/stall) before "
                        "bisection/failure")
    p.add_argument("--retry-backoff-s", type=float, default=0.05,
                   metavar="S",
                   help="base of the deterministic dispatch-retry "
                        "backoff")
    p.add_argument("--metrics-port", type=int, default=0, metavar="PORT",
                   help="live telemetry: serve the service registry at "
                        "http://127.0.0.1:PORT/metrics (+/healthz with "
                        "queue depth and the active-alerts panel); 0 = "
                        "off.  The history rings + metrics_history.jsonl "
                        "+ alert rules run either way (host-side only)")
    p.add_argument("--no-profile", action="store_true",
                   help="drop the continuous profiling plane (host stack "
                        "sampler, profile.folded, utilization gauges, "
                        "anomaly capture); host-side only, so serve "
                        "results are identical either way")
    p.add_argument("--profile-hz", type=float, default=50.0, metavar="HZ",
                   help="host stack-sampling rate of the continuous "
                        "profiler (see telemetry.profiler)")
    p.add_argument("--profile-ring-s", type=float, default=30.0,
                   metavar="S",
                   help="seconds of raw profiler samples kept for "
                        "anomaly bundles (samples.jsonl)")
    p.add_argument("--anomaly-captures", type=int, default=4, metavar="N",
                   help="FIFO retention bound on anomaly/<rule>-<seq>/ "
                        "bundles in the service root")
    p.add_argument("--chaos", default=None, metavar="SPEC",
                   help="serve-layer fault injection, e.g. "
                        "'serve_kill@1,serve_dispatch_fault@2:io,"
                        "serve_poison_tenant@3' (resilience.chaos "
                        "schedule syntax; drills the recovery ladders on "
                        "CPU CI)")
    p.add_argument("--ping", action="store_true",
                   help="client mode: exit 0 iff a service answers")
    p.add_argument("--stats", action="store_true",
                   help="client mode: print a running service's stats JSON")
    p.add_argument("--drain", action="store_true",
                   help="client mode: graceful drain — finish in-flight "
                        "dispatches, keep the queued rest journaled for "
                        "a restart to replay")
    p.add_argument("--shutdown", action="store_true",
                   help="client mode: ask a running service to exit")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    sock = args.socket or os.path.join(args.root, "serve.sock")

    if args.ping or args.stats or args.shutdown or args.drain:
        from .client import ServiceClient, ServiceError

        client = ServiceClient(sock)
        try:
            if args.ping:
                return 0 if client.ping() else 1
            if args.stats:
                print(json.dumps(client.stats(), indent=1, default=str))
                return 0
            if args.drain:
                client.drain()
                return 0
            client.shutdown()
            return 0
        except (OSError, ServiceError) as e:
            print(f"serve client: {e}", file=sys.stderr)
            return 1

    if args.workers > 1:
        # fleet mode: the front process stays jax-free (the launcher
        # tier's discipline) — each worker is a full solo service on its
        # own sub-root, admission/replay live in serve/pool.py
        from .pool import run_pool

        worker_args = ["--max-stack", str(args.max_stack),
                       "--batch-window-s", str(args.batch_window_s),
                       "--slo-p95-ms", str(args.slo_p95_ms),
                       "--results-ttl-s", str(args.results_ttl_s),
                       "--dispatch-retries", str(args.dispatch_retries),
                       "--retry-backoff-s", str(args.retry_backoff_s)]
        worker_args += ["--profile-hz", str(args.profile_hz),
                        "--profile-ring-s", str(args.profile_ring_s),
                        "--anomaly-captures", str(args.anomaly_captures)]
        if args.no_profile:
            worker_args.append("--no-profile")
        if args.no_adaptive:
            worker_args.append("--no-adaptive")
        if args.warm_fixpoint_density:
            worker_args += ["--warm-fixpoint-density",
                            args.warm_fixpoint_density]
        if args.chaos:
            worker_args += ["--chaos", args.chaos]
        return run_pool(args, worker_args)

    if os.environ.get("SRNN_SETUPS_PLATFORM") == "cpu":
        # config-level CPU pin for subprocess callers (tests, CI) — the
        # same escape hatch as setups/__main__
        from ..utils.backend import force_cpu

        force_cpu()
    from ..utils.aot import ensure_compilation_cache
    from .server import ServiceServer
    from .service import ExperimentService

    ensure_compilation_cache()
    os.makedirs(args.root, exist_ok=True)
    chaos = None
    if args.chaos:
        from ..resilience.chaos import ChaosMonkey, parse_schedule

        try:
            chaos = ChaosMonkey(parse_schedule(args.chaos))
        except ValueError as e:
            raise SystemExit(f"--chaos: {e}")
    service = ExperimentService(args.root, max_stack=args.max_stack,
                                slo_p95_ms=args.slo_p95_ms,
                                max_queue=args.max_queue,
                                results_ttl_s=args.results_ttl_s,
                                dispatch_retries=args.dispatch_retries,
                                retry_backoff_s=args.retry_backoff_s,
                                chaos=chaos)
    # live telemetry plane: history ring + metrics_history.jsonl in the
    # service root, the serve alert rules (queue depth at the admission
    # bound, SLO burn, overload pushback), and — with --metrics-port —
    # the /metrics + /healthz endpoint over the SAME registry that
    # writes metrics.prom
    from ..telemetry.alerts import AlertEngine, default_serve_rules
    from ..telemetry.timeseries import MetricHistory

    history = MetricHistory(
        service.registry,
        path=os.path.join(args.root, "metrics_history.jsonl"))
    engine = AlertEngine(default_serve_rules(max_queue=args.max_queue),
                         service.registry, history)
    # continuous profiling plane: the stack sampler watches this
    # process's dispatcher/writer/exporter threads; anomaly bundles land
    # in the service root on each serve-rule firing edge
    prof = capture = None
    if not args.no_profile:
        from ..telemetry.profiler import AnomalyCapture, SamplingProfiler

        prof = SamplingProfiler(hz=args.profile_hz,
                                ring_s=args.profile_ring_s).start()
        capture = AnomalyCapture(args.root, profiler=prof,
                                 registry=service.registry,
                                 max_bundles=args.anomaly_captures,
                                 ring_s=args.profile_ring_s)
    service.attach_live(history, engine, capture=capture, profiler=prof)
    exporter = None
    if args.metrics_port:
        from ..telemetry.exporter import MetricsExporter, healthz_metrics

        def healthz():
            return {"ok": True, "queue_depth": service.queue_depth(),
                    "active_alerts": engine.active(),
                    "metrics": healthz_metrics(service.registry)}

        # bind failures are non-fatal (same contract as the mega loops'
        # make_live_plane): observability must never take down the
        # service — the journaled tickets still need their replay
        try:
            exporter = MetricsExporter(service.registry,
                                       port=args.metrics_port,
                                       healthz=healthz)
            print(f"serve: /metrics + /healthz live on {exporter.url}",
                  flush=True)
        except OSError as e:
            print(f"serve: metrics exporter bind failed on "
                  f":{args.metrics_port} ({e}); continuing without the "
                  "live endpoint", flush=True)
    replayed = service.recover()
    # replayed tickets restored a (possibly at-the-bound) queue before
    # the dispatch loop exists — sample now so the depth alert's firing
    # edge is on the record even if the first drain resolves it
    service._sample_live()
    if replayed:
        print(f"serve: replayed {replayed} journaled ticket(s) from a "
              "previous run", flush=True)
    if args.warm_fixpoint_density:
        trials, batch = (int(x) for x in
                         args.warm_fixpoint_density.split(","))
        service.warm("fixpoint_density", {"trials": trials, "batch": batch})
    from .controller import make_controller

    controller = make_controller(args.batch_window_s, args.slo_p95_ms,
                                 adaptive=not args.no_adaptive)
    server = ServiceServer(service, sock,
                           batch_window_s=args.batch_window_s,
                           controller=controller)
    # SIGTERM is the preemption signal (the supervisor tier's contract):
    # drain gracefully — finish in flight, journal the rest, exit clean
    prev = signal.signal(signal.SIGTERM, lambda *_: server.stop(drain=True))
    print(f"serve: listening on {sock} (root={args.root}, "
          f"max_stack={args.max_stack}, "
          f"batch_window_s={args.batch_window_s}, "
          f"dispatch={'adaptive' if controller else 'fixed'}"
          + (f", max_queue={args.max_queue}" if args.max_queue else "")
          + (f", chaos={args.chaos}" if args.chaos else "") + ")",
          flush=True)
    try:
        server.serve_until_shutdown()
    finally:
        signal.signal(signal.SIGTERM, prev)
        if exporter is not None:
            exporter.close()
        # halt sampling before close — service.close() writes the final
        # profile.folded/.jsonl from the frozen tables
        if prof is not None:
            prof.stop()
        service.close()
    unfinished = service._self_healing_stats()["journal_unfinished"]
    if unfinished:
        print(f"serve: exiting with {unfinished} ticket(s) journaled for "
              "replay on restart", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
