"""Multi-tenant batched experiment service ("soup of soups").

The paper's experiment suite is dozens of tiny-population runs that each
paid their own process startup, compile, and per-batch dispatch.  This
package serves them instead (ROADMAP item 4):

  * ``serve.tenant`` — the TENANT AXIS: K independent experiment configs
    (same statics, different seeds) stacked into one ``(K, N, ...)``
    vmapped dispatch, every tenant bitwise-equal to its solo run.
  * ``serve.scheduler`` — group requests by static spelling; stacked
    dispatch for matching groups, solo fallback for odd configs.
  * ``serve.service`` — the long-lived core: warmed AOT executables held
    across requests, ``srnn_serve_*`` queue/latency/throughput metrics,
    tenant-labeled telemetry and lineage rows on the BackgroundWriter.
  * ``serve.server`` / ``serve.client`` — Unix-socket JSON-lines
    transport; ``python -m srnn_tpu.serve`` runs the server, the setups'
    ``--service`` flag makes them clients.
  * ``serve.journal`` — the durable ticket journal (PR 13's self-healing
    spine): admits are fsynced before acknowledgment, completions are
    journaled, and a restarted service replays the unfinished rest
    bitwise-equal to an uninterrupted run.  ``serve.service`` adds the
    supervised dispatch (classified-fault retries, poison-quarantine
    bisection), admission control (``max_queue`` ->
    :class:`OverloadedError`), per-ticket deadlines, and graceful
    SIGTERM drain around it.
"""

from .client import ServiceClient, ServiceError, ServiceOverloaded
from .journal import TicketJournal, read_journal
from .scheduler import DEFAULT_MAX_STACK, Request, plan_dispatches
from .service import (DeadlineExpired, ExperimentService, OverloadedError)
from .tenant import (evolve_multi_stacked, evolve_multi_stacked_donated,
                     evolve_stacked, evolve_stacked_captured,
                     evolve_stacked_donated, evolve_stacked_step,
                     evolve_stacked_step_donated, init_population_stacked,
                     seed_stacked, stack_tenants, unstack_tenants)

__all__ = [
    "DEFAULT_MAX_STACK",
    "DeadlineExpired",
    "ExperimentService",
    "OverloadedError",
    "Request",
    "ServiceClient",
    "ServiceError",
    "ServiceOverloaded",
    "TicketJournal",
    "read_journal",
    "evolve_multi_stacked",
    "evolve_multi_stacked_donated",
    "evolve_stacked",
    "evolve_stacked_captured",
    "evolve_stacked_donated",
    "evolve_stacked_step",
    "evolve_stacked_step_donated",
    "init_population_stacked",
    "plan_dispatches",
    "seed_stacked",
    "stack_tenants",
    "unstack_tenants",
]
