"""Multi-tenant batched experiment service ("soup of soups").

The paper's experiment suite is dozens of tiny-population runs that each
paid their own process startup, compile, and per-batch dispatch.  This
package serves them instead (ROADMAP item 4):

  * ``serve.tenant`` — the TENANT AXIS: K independent experiment configs
    (same statics, different seeds) stacked into one ``(K, N, ...)``
    vmapped dispatch, every tenant bitwise-equal to its solo run.
  * ``serve.scheduler`` — group requests by static spelling; stacked
    dispatch for matching groups, solo fallback for odd configs.
  * ``serve.service`` — the long-lived core: warmed AOT executables held
    across requests, ``srnn_serve_*`` queue/latency/throughput metrics,
    tenant-labeled telemetry and lineage rows on the BackgroundWriter.
  * ``serve.server`` / ``serve.client`` — Unix-socket JSON-lines
    transport; ``python -m srnn_tpu.serve`` runs the server, the setups'
    ``--service`` flag makes them clients.
  * ``serve.journal`` — the durable ticket journal (PR 13's self-healing
    spine): admits are fsynced before acknowledgment, completions are
    journaled, and a restarted service replays the unfinished rest
    bitwise-equal to an uninterrupted run.  ``serve.service`` adds the
    supervised dispatch (classified-fault retries, poison-quarantine
    bisection), admission control (``max_queue`` ->
    :class:`OverloadedError`), per-ticket deadlines, and graceful
    SIGTERM drain around it.
  * ``serve.controller`` — the continuous-batching tier (PR 16): the
    dispatcher blocks on admission instead of poll-sleeping, and the
    :class:`AdaptiveWindowController` adapts each scheduler group's
    batching window against ``--slo-p95-ms`` (shrink on SLO burn, grow
    toward the ``--batch-window-s`` ceiling when clean) — deterministic
    given the same arrival trace; ``--no-adaptive`` is the fixed-window
    A/B oracle.
  * ``serve.pool`` — multi-worker scale-out (``--workers N``): N
    dispatch processes behind one front socket, sharing the persistent
    AOT cache, with sticky per-tenant round-robin assignment and the
    journal as the shared-nothing recovery substrate — any worker can
    replay any admitted ticket, so a worker killed mid-load heals
    without losing acknowledged work.
"""

from .client import ServiceClient, ServiceError, ServiceOverloaded
from .controller import AdaptiveWindowController, make_controller
from .journal import TicketJournal, read_journal
from .pool import ServicePool, WorkerHandle
from .scheduler import (DEFAULT_MAX_STACK, Request, interleave_tenants,
                        plan_dispatches)
from .service import (DeadlineExpired, ExperimentService, OverloadedError)
from .tenant import (evolve_multi_stacked, evolve_multi_stacked_donated,
                     evolve_stacked, evolve_stacked_captured,
                     evolve_stacked_donated, evolve_stacked_step,
                     evolve_stacked_step_donated, init_population_stacked,
                     seed_stacked, stack_tenants, unstack_tenants)

__all__ = [
    "AdaptiveWindowController",
    "DEFAULT_MAX_STACK",
    "DeadlineExpired",
    "ExperimentService",
    "OverloadedError",
    "Request",
    "ServiceClient",
    "ServiceError",
    "ServiceOverloaded",
    "ServicePool",
    "TicketJournal",
    "WorkerHandle",
    "read_journal",
    "evolve_multi_stacked",
    "evolve_multi_stacked_donated",
    "evolve_stacked",
    "evolve_stacked_captured",
    "evolve_stacked_donated",
    "evolve_stacked_step",
    "evolve_stacked_step_donated",
    "init_population_stacked",
    "interleave_tenants",
    "make_controller",
    "plan_dispatches",
    "seed_stacked",
    "stack_tenants",
    "unstack_tenants",
]
