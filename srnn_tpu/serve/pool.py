"""Multi-worker serve fleet: N dispatch processes behind one front socket.

``python -m srnn_tpu.serve --workers N`` turns the single-process
experiment service into a fleet: the FRONT process (this module — pure
host logic, no jax, the launcher tier's discipline) binds the public
socket, owns admission, and forwards tickets to N WORKER processes, each
a full ``python -m srnn_tpu.serve`` service on its own sub-root with its
own journal, dispatch thread, adaptive window controller, and a SHARED
persistent AOT cache (``utils.aot.ensure_compilation_cache`` — the env
is inherited, so worker 2's first soup dispatch deserializes the
executable worker 1 compiled).

Recovery topology (the PR 13 journal as the shared-nothing substrate):

  * the front journals every admission (append+fsync BEFORE the ticket
    id is acknowledged — the same contract the solo service keeps), so
    an acknowledged ticket survives even a ``kill -9`` of the front; a
    restarted front replays its journal and re-forwards.
  * each forward carries ``idempotency_key="pool:<front-ticket>"``, so
    worker journals speak front ticket ids.  Any worker can therefore
    replay any admitted ticket: when a worker DIES mid-load (SIGKILL,
    OOM, chaos), the front reads the dead worker's journal suffix
    (``journal.read_journal`` on its sub-root — the dead process needs
    no cooperation), maps the unfinished entries back to front tickets,
    and resubmits them to the survivors.  Acknowledged work is never
    lost; the executors are deterministic functions of the journaled
    params, so replayed results are bitwise-equal.
  * ``/healthz`` tells the story live: ``ok`` is false while any
    admitted ticket is stranded on a dead worker and true again once the
    survivors have absorbed the replays (the loss, then the heal); the
    per-worker ``workers`` map keeps showing the corpse.

Fairness: tenants are assigned to workers STICKY round-robin by first
appearance (a tenant's tickets land on one worker while it lives, so
same-spelling tickets still stack; tenants spread across the fleet), and
each worker runs the service-level fair plan (``scheduler.plan_dispatches
(fair=True)``) within its own queue.

Process discipline is the PR 11 launcher's: workers spawn with relayed
``[w<i>]`` output prefixes, reap with terminate-then-kill
(``distributed.launch._reap``), and the front's exit code never reports
success over a worker it had to kill.
"""

import itertools
import json
import os
import signal
import socket
import socketserver
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from ..distributed.launch import _reap
from ..telemetry.exemplars import EXEMPLARS_NAME, ExemplarRing
from ..utils.pipeline import spawn_thread
from .client import ServiceClient
from .journal import TicketJournal, read_journal
from .server import _Handler

#: connection-class failures that mean "this worker is gone" when talking
#: to a worker socket — the trigger for the death/replay ladder.  The
#: fault-taxonomy srnnlint pass (T010) checks every member is a
#: connection-class exception: a value error must never be read as a
#: worker death, or the replay ladder would double-run real work.
WORKER_DEATH_EXC = (ConnectionRefusedError, FileNotFoundError,
                    ConnectionResetError, BrokenPipeError, TimeoutError)

#: monitor cadence: how often worker processes are polled for death
POLL_S = 0.25
#: fleet gauge / history-sample refresh cadence (the live plane's turn)
SAMPLE_S = 5.0


class WorkerHandle:
    """One spawned worker process + its client-side state."""

    def __init__(self, index: int, root: str, socket_path: str,
                 proc: subprocess.Popen):
        self.index = index
        self.root = root
        self.socket_path = socket_path
        self.proc = proc
        self.alive = True
        self.client = ServiceClient(socket_path)


def spawn_worker(index: int, root: str, worker_args: List[str],
                 module: str = "srnn_tpu.serve") -> WorkerHandle:
    """Spawn worker ``index`` on ``<root>/workers/w<i>`` with a relayed
    ``[w<i>]`` output prefix (the launcher's ``[p<i>]`` idiom)."""
    wroot = os.path.join(root, "workers", f"w{index}")
    wsock = os.path.join(root, "workers", f"w{index}.sock")
    os.makedirs(os.path.dirname(wroot), exist_ok=True)
    proc = subprocess.Popen(
        [sys.executable, "-u", "-m", module, "--root", wroot,
         "--socket", wsock, *worker_args],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)

    def relay():
        for line in proc.stdout:
            print(f"[w{index}] {line.rstrip()}", flush=True)

    spawn_thread(relay, name=f"pool-relay-w{index}")
    return WorkerHandle(index, wroot, wsock, proc)


class ServicePool:
    """The front: admission + forwarding + death/replay over N workers."""

    def __init__(self, root: str, workers: List[WorkerHandle],
                 registry=None, max_queue: int = 0, history=None,
                 engine=None, capture=None, profiler=None):
        from ..telemetry.metrics import MetricsRegistry

        os.makedirs(root, exist_ok=True)
        self.root = root
        self.workers = list(workers)
        self.max_queue = max(0, int(max_queue))
        self.registry = registry or MetricsRegistry()
        self.journal = TicketJournal(root)
        self._history = history
        self._engine = engine
        # continuous profiling plane, the front's half: the sampler
        # watches the monitor/relay threads, anomaly bundles land in the
        # FRONT root on pool-rule firing edges (worker bundles land in
        # their own sub-roots)
        self._capture = capture
        self._profiler = profiler
        self._lock = threading.Lock()
        self._done_cv = threading.Condition(self._lock)
        #: front ticket -> {"kind","params","tenant","worker","key",
        #: "deadline_s","replays"} for every admitted-not-yet-collected
        self._tickets: Dict[str, dict] = {}
        self._idem: Dict[str, str] = {}
        self._tenant_worker: Dict[str, int] = {}
        self._rr = 0
        self._counter = itertools.count(1)
        #: front-side span ids (front.admit/assign/relay/replay) — only
        #: unique per process, which is why cross-hop links travel as
        #: ``remote_parent``/``parent_span``, never as ``parent``
        self._span_ids = itertools.count(1)
        #: tail-kept exemplar ring, the front's half: replayed / failed
        #: tickets keep their full front-span family, the rest keep only
        #: their admit root
        self._exemplars = ExemplarRing(os.path.join(root, EXEMPLARS_NAME))
        self._admitted = 0
        self._completed = 0
        self._replayed = 0
        self._deaths = 0
        self._draining = False
        self._stop = threading.Event()
        self._events = open(os.path.join(root, "events.jsonl"), "a")
        self._events_lock = threading.Lock()
        self._t0 = time.monotonic()
        # eager zeros, the serve counters' discipline: a clean fleet
        # scrapes 0 deaths/replays, not missing series
        self.registry.counter("serve_worker_deaths_total",
                              help="worker processes lost (crash/kill)")
        self.registry.counter(
            "serve_worker_replays_total",
            help="admitted tickets resubmitted to surviving workers "
                 "after a worker death")
        self._set_worker_gauge()
        self._monitor = spawn_thread(self._monitor_loop,
                                     name="pool-monitor")

    # -- admission / results ---------------------------------------------

    def submit(self, kind: str, params: dict,
               tenant: Optional[str] = None,
               deadline_s: Optional[float] = None,
               idempotency_key: Optional[str] = None,
               trace_id: Optional[str] = None,
               parent_span: Optional[int] = None) -> str:
        """Admit one ticket at the front (durable-before-acknowledged,
        the solo service's contract) and forward it to its tenant's
        worker.  The front is the fleet's admission authority: workers
        run unbounded queues; ``max_queue`` bounds the ADMITTED-not-
        collected set here.

        ``trace_id``/``parent_span`` are the client's trace context;
        the front journals them, adopts the id for its own
        ``front.*`` spans, and propagates it across the worker hop —
        one trace per ticket, fleet-wide."""
        from .service import OverloadedError

        with self._lock:
            if self._draining:
                raise RuntimeError("service shutting down")
            if idempotency_key:
                known = self._idem.get(idempotency_key)
                if known is not None:
                    return known
            depth = len(self._tickets)
            if self.max_queue and depth >= self.max_queue:
                self.registry.counter(
                    "serve_overload_rejections_total",
                    help="submits rejected at admission "
                         "(--max-queue)").inc(1, kind=kind)
                raise OverloadedError(
                    f"queue full ({depth} >= max_queue={self.max_queue}); "
                    "back off and resubmit")
            ticket = f"t{next(self._counter):06d}"
            tenant = tenant or ticket
            admit_start = time.monotonic() - self._t0
            self.journal.record_submit(
                ticket=ticket, kind=kind, params=dict(params),
                tenant=tenant, key=idempotency_key,
                deadline_wall=(time.time() + float(deadline_s)
                               if deadline_s is not None else None),
                wall=time.time(), trace_id=trace_id or None,
                parent_span=parent_span)
            admit_span = next(self._span_ids)
            self._tickets[ticket] = {
                "kind": kind, "params": dict(params), "tenant": tenant,
                "worker": None, "worker_ticket": None,
                "deadline_s": deadline_s, "replays": 0,
                "key": idempotency_key,
                "trace_id": trace_id or ticket,
                "admit_span": admit_span, "spans": []}
            if idempotency_key:
                self._idem[idempotency_key] = ticket
            self._admitted += 1
        # the pool hop's root marker: duration = the durable journal
        # append the ack waited on; remote_parent points back at the
        # CLIENT's span when it sent one
        self._span_row(ticket, "front.admit", admit_span,
                       start_s=admit_start,
                       seconds=time.monotonic() - self._t0 - admit_start,
                       remote_parent=parent_span)
        self.registry.counter("serve_requests_total",
                              help="experiment requests accepted").inc(
                                  1, kind=kind)
        self.registry.gauge(
            "serve_queue_depth",
            help="requests queued, not yet dispatched").set(
                self.queue_depth())
        self._forward(ticket)
        return ticket

    def _span_row(self, ticket: str, name: str, span_id: int, *,
                  start_s: float, seconds: float,
                  parent: Optional[int] = None, **labels) -> None:
        """One front-side span: appended to the front's events.jsonl
        (the merged fleet timeline's process-0 lane) AND retained on the
        ticket entry so resolution can capture the family into the
        exemplar ring."""
        with self._lock:
            ent = self._tickets.get(ticket)
            trace_id = ent["trace_id"] if ent else ticket
            tenant = ent["tenant"] if ent else None
            req_kind = ent["kind"] if ent else None
        row = dict(kind="span", span=name, span_id=span_id,
                   trace_id=trace_id, ticket=ticket, process=0,
                   tenant=tenant, request_kind=req_kind,
                   start_s=round(start_s, 6), seconds=round(seconds, 6),
                   **labels)
        if parent is not None:
            row["parent"] = parent
        row = {k: v for k, v in row.items() if v is not None}
        self._event_row(**row)
        with self._lock:
            ent = self._tickets.get(ticket)
            if ent is not None and len(ent.get("spans", ())) < 64:
                ent["spans"].append(row)

    def _pick_worker(self, tenant: str) -> Optional[WorkerHandle]:
        """Sticky per-tenant round-robin over the LIVE workers."""
        with self._lock:
            alive = [w for w in self.workers if w.alive]
            if not alive:
                return None
            idx = self._tenant_worker.get(tenant)
            w = next((x for x in alive if x.index == idx), None)
            if w is None:
                w = alive[self._rr % len(alive)]
                self._rr += 1
                self._tenant_worker[tenant] = w.index
            return w

    def _forward(self, ticket: str) -> None:
        """Send ``ticket`` to its tenant's worker; a worker dying under
        the forward routes through the death ladder and the next
        survivor takes the ticket (bounded by the fleet size)."""
        from .client import ServiceError

        for _ in range(len(self.workers) + 1):
            with self._lock:
                ent = self._tickets.get(ticket)
            if ent is None:
                return   # collected (a racing wait) — nothing to do
            assign_start = time.monotonic() - self._t0
            w = self._pick_worker(ent["tenant"])
            if w is None:
                self._resolve_failed(ticket, "no live workers")
                return
            self._span_row(ticket, "front.assign", next(self._span_ids),
                           start_s=assign_start,
                           seconds=(time.monotonic() - self._t0
                                    - assign_start),
                           parent=ent.get("admit_span"), worker=w.index)
            # relay span id minted BEFORE the worker submit: it crosses
            # the hop as the worker ticket's parent_span, so the far
            # side links back without a second round trip.  A replay
            # (post-worker-death re-forward) gets its own span name —
            # the kill -9 story stays legible in the merged timeline.
            relay_span = next(self._span_ids)
            relay_name = ("front.replay" if ent.get("replays")
                          else "front.relay")
            relay_start = time.monotonic() - self._t0
            try:
                wt = w.client.submit(ent["kind"], ent["params"],
                                     tenant=ent["tenant"],
                                     deadline_s=ent["deadline_s"],
                                     idempotency_key=f"pool:{ticket}",
                                     trace_id=ent["trace_id"],
                                     parent_span=relay_span)
                with self._done_cv:
                    if ticket in self._tickets:
                        self._tickets[ticket]["worker"] = w.index
                        self._tickets[ticket]["worker_ticket"] = wt
                    self._done_cv.notify_all()
                self._span_row(ticket, relay_name, relay_span,
                               start_s=relay_start,
                               seconds=(time.monotonic() - self._t0
                                        - relay_start),
                               parent=ent.get("admit_span"),
                               worker=w.index, worker_ticket=wt,
                               replays=ent.get("replays") or None)
                return
            except WORKER_DEATH_EXC as e:
                self._span_row(ticket, relay_name, relay_span,
                               start_s=relay_start,
                               seconds=(time.monotonic() - self._t0
                                        - relay_start),
                               parent=ent.get("admit_span"),
                               worker=w.index, error=type(e).__name__)
                self._note_death(w.index)
            except ServiceError as e:
                self._resolve_failed(ticket, str(e))
                return
        self._resolve_failed(ticket, "no live workers")

    def _resolve_failed(self, ticket: str, error: str) -> None:
        with self._lock:
            ent = self._tickets.get(ticket)
            if ent is None:
                return
            ent["worker"] = None
            ent["failed"] = {"status": "failed", "error": error,
                             "mode": "none"}
            self._done_cv.notify_all()

    def wait(self, ticket: str, timeout_s: float = 600.0) -> dict:
        """Block until ``ticket`` completes; CONSUMES the entry (the solo
        service's contract).  Rides out worker deaths: a connection that
        dies mid-wait triggers the replay ladder and the wait re-targets
        wherever the ticket landed."""
        deadline = time.monotonic() + timeout_s
        while True:
            left = deadline - time.monotonic()
            if left <= 0:
                raise TimeoutError(f"request {ticket} still pending "
                                   f"after {timeout_s}s")
            with self._lock:
                ent = self._tickets.get(ticket)
                if ent is None:
                    raise KeyError(f"unknown ticket {ticket!r}")
                if "failed" in ent:
                    entry = dict(ent["failed"])
                    self._finish_locked(ticket, "failed")
                    return entry
                widx, wticket = ent["worker"], ent["worker_ticket"]
            if widx is None or wticket is None:
                # forward still in flight (or mid-replay): wait for it
                with self._done_cv:
                    self._done_cv.wait(timeout=min(0.2, left))
                continue
            w = self.workers[widx]
            try:
                resp = _raw_op(w.socket_path,
                               {"op": "wait", "ticket": wticket,
                                "timeout_s": min(left, 60.0)},
                               timeout_s=min(left, 60.0) + 10.0)
            except WORKER_DEATH_EXC:
                self._note_death(widx)
                continue
            if resp.get("status") in ("done", "failed"):
                with self._lock:
                    self._finish_locked(ticket, resp["status"])
                entry = {k: v for k, v in resp.items()
                         if k not in ("ok", "ticket")}
                return entry
            # service-side timeout (clean ok:false, still pending) or a
            # transient error string: loop and re-check the deadline

    def _finish_locked(self, ticket: str, status: str) -> None:
        ent = self._tickets.pop(ticket, None)
        if ent is None:
            return
        self._completed += 1
        self.journal.record_done([ticket], status)
        if ent.get("key"):
            self._idem.pop(ent["key"], None)
        # tail-based retention, the front's half: a ticket that was
        # REPLAYED across a worker death (or failed) keeps its whole
        # front-span family — admit, assigns, the dead relay and the
        # replay — everything else keeps only its admit root
        reasons = [r for r, on in (("replayed", bool(ent.get("replays"))),
                                   ("failed", status != "done")) if on]
        spans = ent.get("spans") or []
        self._exemplars.add(
            {"ticket": ticket, "trace_id": ent.get("trace_id", ticket),
             "reason": ",".join(reasons) or "root",
             "kind": ent.get("kind"), "tenant": ent.get("tenant"),
             "replays": ent.get("replays", 0),
             "spans": spans if reasons else spans[:1]})
        self.registry.gauge(
            "serve_queue_depth",
            help="requests queued, not yet dispatched").set(
                len(self._tickets))

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._tickets)

    def recover(self) -> int:
        """Replay the FRONT journal after a front restart: unfinished
        admissions re-enter the tickets table under their original ids
        and re-forward.  (Workers recover their own journals themselves
        at startup — this is the front's half of the topology.)"""
        entries, torn, next_ticket = self.journal.recover()
        replayed = []
        with self._lock:
            self._counter = itertools.count(next_ticket)
            now_s = time.monotonic() - self._t0
            for e in entries:
                deadline_s = None
                if e.deadline_wall is not None:
                    deadline_s = float(e.deadline_wall) - time.time()
                self._tickets[e.ticket] = {
                    "kind": e.kind, "params": dict(e.params),
                    "tenant": e.tenant, "worker": None,
                    "worker_ticket": None, "deadline_s": deadline_s,
                    "replays": 0, "key": e.key,
                    "trace_id": e.trace_id or e.ticket,
                    "admit_span": next(self._span_ids), "spans": []}
                if e.key:
                    self._idem[e.key] = e.ticket
                replayed.append(e.ticket)
            self._admitted += len(replayed)
        for t in replayed:
            # a re-admission marker under the ORIGINAL trace id: the
            # restarted front's lane joins the trace the dead front left
            with self._lock:
                ent = self._tickets.get(t)
            if ent is not None:
                self._span_row(t, "front.admit", ent["admit_span"],
                               start_s=now_s, seconds=0.0, replayed=True)
        for t in replayed:
            self._forward(t)
        if replayed:
            self._event_row(kind="pool_replay", source="front_journal",
                            tickets=replayed, torn_tail=torn or None)
        return len(replayed)

    # -- death / replay ladder -------------------------------------------

    def _note_death(self, index: int) -> None:
        """The fleet's heal: mark worker ``index`` dead (idempotent),
        reap its process, read its journal's unfinished suffix, and
        resubmit every stranded admitted ticket to the survivors."""
        with self._lock:
            w = self.workers[index]
            if not w.alive:
                return
            w.alive = False
            self._deaths += 1
            stranded = [t for t, ent in self._tickets.items()
                        if ent["worker"] == index]
            for t in stranded:
                self._tickets[t]["worker"] = None
                self._tickets[t]["worker_ticket"] = None
                self._tickets[t]["replays"] += 1
        self.registry.counter(
            "serve_worker_deaths_total",
            help="worker processes lost (crash/kill)").inc(1)
        self._set_worker_gauge()
        _reap([w.proc], set())
        # the shared-nothing story: the DEAD worker's journal names every
        # ticket it had admitted but not finished — read it without any
        # cooperation from the corpse, map keys back to front tickets
        from_journal: List[str] = []
        try:
            unfinished, _torn, _next = read_journal(
                os.path.join(w.root, "journal.jsonl"))
            from_journal = [e.key[len("pool:"):] for e in unfinished
                            if e.key and e.key.startswith("pool:")]
        except OSError:
            pass
        replay = sorted(set(stranded) | set(from_journal))
        replay = [t for t in replay if t in self._tickets]
        self._event_row(kind="pool_worker_death", worker=index,
                        pid=w.proc.pid,
                        journal_unfinished=len(from_journal),
                        replaying=len(replay))
        print(f"serve pool: worker w{index} died; replaying "
              f"{len(replay)} ticket(s) onto the survivors", flush=True)
        if replay:
            with self._lock:
                self._replayed += len(replay)
            self.registry.counter(
                "serve_worker_replays_total",
                help="admitted tickets resubmitted to surviving workers "
                     "after a worker death").inc(len(replay))
        for t in replay:
            self._forward(t)
        with self._done_cv:
            self._done_cv.notify_all()

    def _set_worker_gauge(self) -> None:
        with self._lock:
            alive = sum(1 for w in self.workers if w.alive)
        self.registry.gauge("serve_workers",
                            help="live worker processes").set(alive)

    def _monitor_loop(self) -> None:
        """Poll worker liveness (the death ladder's detector for workers
        nobody is talking to) and refresh the fleet gauges + the live
        telemetry plane on the sample cadence."""
        last_sample = float("-inf")
        while not self._stop.is_set():
            for w in list(self.workers):
                if w.alive and w.proc.poll() is not None:
                    self._note_death(w.index)
            now = time.monotonic()
            if now - last_sample >= SAMPLE_S:
                last_sample = now
                self._refresh_fleet_gauges()
                if self._history is not None:
                    try:
                        self._history.sample()
                        transitions = []
                        if self._engine is not None:
                            for tr in self._engine.evaluate():
                                self._event_row(kind="alert", **tr)
                                transitions.append(tr)
                        if self._capture is not None:
                            self._capture.on_transitions(transitions)
                        if self._profiler is not None:
                            # monitor cadence doubles as the profile
                            # flush cadence (inline — the front has no
                            # background writer, and this IS its own
                            # housekeeping thread)
                            self._profiler.update_gauges(self.registry)
                            self._profiler.write_files(self.root)
                    except Exception as e:  # pragma: no cover - defensive
                        print(f"serve pool: live telemetry sample failed:"
                              f" {type(e).__name__}: {e}",
                              file=sys.stderr, flush=True)
            self._stop.wait(POLL_S)

    def _refresh_fleet_gauges(self) -> None:
        g = self.registry.gauge(
            "serve_worker_queue_depth",
            help="per-worker dispatch queue depth")
        for w in list(self.workers):
            if not w.alive:
                continue
            try:
                st = w.client.stats()
            except Exception:
                continue
            g.set(st.get("queue_depth", 0), worker=f"w{w.index}")

    # -- views -----------------------------------------------------------

    def stats(self) -> dict:
        """Fleet snapshot: front admission state + one row per worker
        (queue depth, in-flight slots, adaptive window, replay counts) —
        the shape ``watch --service`` renders."""
        with self._lock:
            depth = len(self._tickets)
            front = {"admitted": self._admitted,
                     "completed": self._completed,
                     "pending": depth, "replayed": self._replayed,
                     "deaths": self._deaths,
                     "workers": sum(1 for w in self.workers if w.alive),
                     "max_queue": self.max_queue or None}
        fleet = {}
        slowest: List[dict] = []
        for w in list(self.workers):
            row = {"alive": w.alive, "pid": w.proc.pid}
            if w.alive:
                try:
                    st = w.client.stats()
                    row.update(
                        queue_depth=st.get("queue_depth"),
                        completed=st.get("completed"),
                        inflight=_metric_sum(st, "serve_inflight_requests"),
                        window_s=(st.get("dispatch") or {}).get(
                            "window_min_s"),
                        adaptive=(st.get("dispatch") or {}).get(
                            "adaptive"),
                        replayed=(st.get("self_healing") or {}).get(
                            "replayed"))
                    slowest.extend(dict(e, worker=f"w{w.index}")
                                   for e in st.get("slowest") or ())
                except Exception as e:
                    row["error"] = f"{type(e).__name__}: {e}"
            fleet[f"w{w.index}"] = row
        # fleet-wide slowest-traces panel: the workers' per-service
        # top-K lists merged and re-capped (same depth as the solo
        # service's SLOWEST_KEPT)
        slowest.sort(key=lambda e: -e.get("seconds", 0.0))
        del slowest[8:]
        alerts = None
        if self._engine is not None:
            alerts = {"active": self._engine.active()}
        return {"completed": front["completed"], "queue_depth": depth,
                "uptime_s": round(time.monotonic() - self._t0, 2),
                "front": front, "fleet": fleet, "slowest": slowest,
                "alerts": alerts,
                "metrics": self.registry.rows()}

    def healthz(self) -> dict:
        """The loss-then-heal contract: ``ok`` is false while any
        admitted ticket is stranded on a dead worker (between the death
        and the survivors absorbing its replays) and true again after
        the heal; dead workers stay visible in ``workers``."""
        with self._lock:
            stranded = sum(
                1 for ent in self._tickets.values()
                if ent["worker"] is not None
                and not self.workers[ent["worker"]].alive)
            unassigned = sum(1 for ent in self._tickets.values()
                             if ent["worker"] is None
                             and "failed" not in ent)
            workers = {}
            for w in self.workers:
                # event-lane heartbeat age, the PR 15 worker_liveness
                # idiom: seconds since the worker's events.jsonl moved
                # (pure mtime read — callable under the lock)
                try:
                    age = round(time.time() - os.path.getmtime(
                        os.path.join(w.root, "events.jsonl")), 1)
                except OSError:
                    age = None
                workers[str(w.index)] = {"ok": w.alive,
                                         "pid": w.proc.pid,
                                         "age_s": age}
            any_alive = any(w.alive for w in self.workers)
        return {"ok": bool(any_alive and not stranded and not unassigned),
                "workers": workers, "stranded": stranded + unassigned,
                "deaths": self._deaths, "replayed": self._replayed,
                "queue_depth": self.queue_depth()}

    # -- lifecycle -------------------------------------------------------

    def _event_row(self, **fields) -> None:
        fields.setdefault("t", round(time.monotonic() - self._t0, 4))
        fields = {k: v for k, v in fields.items() if v is not None}
        with self._events_lock:
            self._events.write(json.dumps(fields) + "\n")
            self._events.flush()

    def close(self, drain: bool = False) -> None:
        """Stop the fleet: drain (or shut down) every live worker, reap
        stragglers with the launcher's terminate-then-kill, publish the
        front's metrics.prom."""
        with self._lock:
            if self._draining:
                return
            self._draining = True
        self._stop.set()
        self._monitor.join(timeout=10)
        for w in self.workers:
            if not w.alive:
                continue
            try:
                if drain:
                    w.client.drain()
                else:
                    w.client.shutdown()
            except (OSError, RuntimeError):
                pass
        _reap([w.proc for w in self.workers], set())
        if self._profiler is not None:
            try:
                self._profiler.update_gauges(self.registry)
                self._profiler.write_files(self.root)
            except OSError:
                pass
        if self._capture is not None:
            self._capture.close()
        self.registry.write_textfile(os.path.join(self.root,
                                                  "metrics.prom"))
        self.journal.close()
        with self._events_lock:
            self._events.close()


def _metric_sum(stats: dict, name: str):
    """Sum a metric's label sets out of a stats() ``metrics`` rows dict
    (rows are keyed ``name{labels}`` flat strings)."""
    rows = stats.get("metrics") or {}
    vals = [v for k, v in rows.items()
            if k == name or k.startswith(name + "{")]
    return sum(vals) if vals else None


def _raw_op(socket_path: str, msg: dict, timeout_s: float = 60.0) -> dict:
    """One worker op returning the parsed response REGARDLESS of ``ok``
    (the front's proxied wait needs failed entries verbatim, where
    ``ServiceClient`` would raise them away)."""
    with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
        s.settimeout(timeout_s)
        s.connect(socket_path)
        s.sendall((json.dumps(msg) + "\n").encode())
        line = s.makefile("rb").readline()
    if not line:
        raise ConnectionResetError("worker closed the connection mid-op")
    return json.loads(line.decode("utf-8", "replace"))


class PoolServer(socketserver.ThreadingMixIn,
                 socketserver.UnixStreamServer):
    """The front transport: the SAME one-JSON-line-per-op protocol as
    ``ServiceServer`` (clients cannot tell a fleet from a solo service),
    delegating to a :class:`ServicePool`."""

    daemon_threads = False
    allow_reuse_address = True
    # the client opens one connection PER OP, so a burst of concurrent
    # clients is a burst of simultaneous connects; socketserver's default
    # backlog of 5 overflows whenever the accept loop stalls (e.g. a
    # worker-death replay) and Linux fails the connect with EAGAIN
    request_queue_size = 128

    def __init__(self, pool: ServicePool, socket_path: str):
        from .server import wait_for_socket

        if os.path.exists(socket_path):
            if wait_for_socket(socket_path, timeout_s=0.0):
                raise RuntimeError(
                    f"a live experiment service already answers on "
                    f"{socket_path}; refusing to steal its socket")
            os.unlink(socket_path)
        super().__init__(socket_path, _Handler)
        self.pool = pool
        self.socket_path = socket_path
        self._stop = threading.Event()
        self._drain = threading.Event()

    def handle_op(self, msg: dict) -> dict:
        from .service import DeadlineExpired, OverloadedError

        op = msg.get("op")
        if op == "ping":
            return {"ok": True}
        if op in ("submit", "request"):
            if self._stop.is_set():
                return {"ok": False, "error": "service shutting down"}
            try:
                ticket = self.pool.submit(
                    msg["kind"], msg.get("params", {}),
                    tenant=msg.get("tenant"),
                    deadline_s=msg.get("deadline_s"),
                    idempotency_key=msg.get("idempotency_key"),
                    trace_id=msg.get("trace_id"),
                    parent_span=msg.get("parent_span"))
            except OverloadedError as e:
                return {"ok": False, "error": str(e), "overloaded": True}
            except DeadlineExpired as e:
                return {"ok": False, "error": str(e),
                        "deadline_expired": True}
            if op == "submit":
                return {"ok": True, "ticket": ticket}
            msg = dict(msg, ticket=ticket)
        if op in ("wait", "request"):
            ticket = msg["ticket"]
            try:
                entry = self.pool.wait(
                    ticket, timeout_s=float(msg.get("timeout_s", 600.0)))
            except (KeyError, TimeoutError) as e:
                return {"ok": False, "ticket": ticket, "error": str(e)}
            out = {"ok": entry.get("status") == "done", "ticket": ticket}
            out.update(entry)
            return out
        if op == "stats":
            return {"ok": True, "stats": self.pool.stats()}
        if op == "drain":
            self.stop(drain=True)
            return {"ok": True, "bye": True, "draining": True}
        if op == "shutdown":
            self.stop()
            return {"ok": True, "bye": True}
        return {"ok": False, "error": f"unknown op {op!r}"}

    def stop(self, drain: bool = False) -> None:
        if drain:
            self._drain.set()
        self._stop.set()
        spawn_thread(self.shutdown, name="pool-stop")

    def serve_until_shutdown(self) -> None:
        try:
            self.serve_forever(poll_interval=0.1)
        finally:
            self._stop.set()
            self.pool.close(drain=self._drain.is_set())
            self.server_close()
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass


def run_pool(args, worker_args: List[str]) -> int:
    """``python -m srnn_tpu.serve --workers N`` server mode: spawn the
    fleet, bind the front socket, serve until shutdown/SIGTERM (SIGTERM
    drains: workers journal their queues and a restart resumes)."""
    os.makedirs(args.root, exist_ok=True)
    sock = args.socket or os.path.join(args.root, "serve.sock")
    workers = []
    for i in range(args.workers):
        wargs = list(worker_args)
        if args.metrics_port:
            # port layout mirrors the PR 15 mega fleet: the front (the
            # fleet's process 0) owns PORT below; worker i — process
            # i+1 of the merged timeline — exports on PORT+1+i
            wargs += ["--metrics-port", str(args.metrics_port + 1 + i)]
        workers.append(spawn_worker(i, args.root, wargs))
    try:
        for w in workers:
            w.client.wait_until_up(timeout_s=180.0)
    except TimeoutError as e:
        _reap([w.proc for w in workers], set())
        raise SystemExit(f"serve pool: {e}")
    from ..telemetry.alerts import (AlertEngine, default_pool_rules,
                                    default_serve_rules)
    from ..telemetry.metrics import MetricsRegistry
    from ..telemetry.timeseries import MetricHistory

    registry = MetricsRegistry()
    history = MetricHistory(
        registry, path=os.path.join(args.root, "metrics_history.jsonl"))
    engine = AlertEngine(
        default_serve_rules(max_queue=args.max_queue)
        + default_pool_rules(workers=args.workers),
        registry, history)
    prof = capture = None
    if not getattr(args, "no_profile", False):
        from ..telemetry.profiler import AnomalyCapture, SamplingProfiler

        prof = SamplingProfiler(
            hz=getattr(args, "profile_hz", 50.0),
            ring_s=getattr(args, "profile_ring_s", 30.0)).start()
        capture = AnomalyCapture(
            args.root, profiler=prof, registry=registry,
            max_bundles=getattr(args, "anomaly_captures", 4),
            ring_s=getattr(args, "profile_ring_s", 30.0))
    pool = ServicePool(args.root, workers, registry=registry,
                       max_queue=args.max_queue, history=history,
                       engine=engine, capture=capture, profiler=prof)
    exporter = None
    if args.metrics_port:
        from ..telemetry.exporter import MetricsExporter

        try:
            exporter = MetricsExporter(registry, port=args.metrics_port,
                                       healthz=pool.healthz)
            print(f"serve pool: /metrics + /healthz live on "
                  f"{exporter.url}", flush=True)
        except OSError as e:
            print(f"serve pool: metrics exporter bind failed on "
                  f":{args.metrics_port} ({e}); continuing without the "
                  "live endpoint", flush=True)
    replayed = pool.recover()
    if replayed:
        print(f"serve pool: replayed {replayed} journaled ticket(s) "
              "from a previous front", flush=True)
    server = PoolServer(pool, sock)
    prev = signal.signal(signal.SIGTERM,
                         lambda *_: server.stop(drain=True))
    print(f"serve pool: listening on {sock} (root={args.root}, "
          f"workers={args.workers}"
          + (f", max_queue={args.max_queue}" if args.max_queue else "")
          + ")", flush=True)
    try:
        server.serve_until_shutdown()
    finally:
        signal.signal(signal.SIGTERM, prev)
        if exporter is not None:
            exporter.close()
        if prof is not None:
            prof.stop()
        history.close()
    pending = pool.queue_depth()
    if pending:
        print(f"serve pool: exiting with {pending} ticket(s) journaled "
              "for replay on restart", flush=True)
    return 0
