"""The tenant axis: K independent experiment configs as ONE dispatch.

The paper's experiment suite is dozens of tiny-population runs; under the
multi-tenant service each request would still pay its own per-dispatch
overhead one level up.  This module removes that: K tenants whose configs
share the same STATIC spelling (same topology, same ``SoupConfig`` —
tenants differ in seeds and traced values only) stack their states on a
leading tenant axis and evolve as one ``(K, N, P)`` vmapped program.

The load-bearing contract is **bitwise equality to solo**: every tenant's
slice of a stacked dispatch carries exactly the bits its solo run would
have produced — weights, uids, PRNG keys, event records, the
metrics/health carries and the lineage pids/edges (tests recount all of
them).  That holds on the parallel ROW-MAJOR path only (the per-row lane
programs are unchanged under a leading vmap axis); the popmajor lane
layout's reductions reassociate under vmap, so ``soup.tenant_stackable``
gates stacking and the scheduler falls back to solo dispatch for
everything else.

Entries mirror the soup/multisoup surfaces flag-for-flag (the srnnlint
``flag-parity`` pass holds them to the same contract as the four evolve
surfaces) and ship ``_donated`` twins for the service hot loop.
"""

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..init import init_population
from ..multisoup import (MultiSoupConfig, _evolve_multi,
                         check_tenant_stackable_multi)
from ..soup import (SoupConfig, SoupState, _evolve, _evolve_step,
                    check_tenant_stackable, seed)
from ..topology import Topology


# ---------------------------------------------------------------------------
# stacking / unstacking pytrees of per-tenant states
# ---------------------------------------------------------------------------


def stack_tenants(items: Sequence):
    """Stack K same-shaped pytrees (states, lineage carries, ...) on a new
    leading tenant axis.  Typed PRNG-key leaves stack like any array."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *items)


def unstack_tenants(tree, k: int) -> List:
    """Split a stacked result back into K per-tenant pytrees (slices — no
    copy; callers that outlive the stacked buffer should device_get)."""
    return [jax.tree.map(lambda x: x[i], tree) for i in range(k)]


def _seed_stacked(config: SoupConfig, keys: jax.Array) -> SoupState:
    return jax.vmap(lambda k: seed(config, k))(keys)


#: seed K tenant soups from a (K,) key vector — tenant i's state is
#: bitwise ``seed(config, keys[i])``
seed_stacked = jax.jit(_seed_stacked, static_argnames=("config",))


def _init_population_stacked(topo: Topology, keys: jax.Array,
                             n: int) -> jnp.ndarray:
    return jax.vmap(lambda k: init_population(topo, k, n))(keys)


#: (K,) keys -> (K, n, P) fresh populations, tenant i bitwise
#: ``init_population(topo, keys[i], n)`` (the fixpoint-density executor's
#: per-batch draw)
init_population_stacked = jax.jit(_init_population_stacked,
                                  static_argnames=("topo", "n"))


# ---------------------------------------------------------------------------
# the stacked evolve surfaces
# ---------------------------------------------------------------------------


def _evolve_stacked(
    config: SoupConfig,
    states: SoupState,
    generations: int = 1,
    record: bool = False,
    metrics: bool = False,
    health: bool = False,
    lineage: bool = False,
    lineage_state=None,
    lineage_capacity: int = 4096,
):
    """Tenant-stacked ``soup.evolve``: ``states`` carries a leading K axis
    on every leaf; returns ``soup._evolve``'s result pytree with the same
    leading axis (final state, then recs/metrics/health/lineage per the
    flags).  ``lineage_state`` is a stacked ``LineageState`` carry."""
    check_tenant_stackable(config)
    if lineage:
        return jax.vmap(
            lambda s, l: _evolve(config, s, generations=generations,
                                 record=record, metrics=metrics,
                                 health=health, lineage=True,
                                 lineage_state=l,
                                 lineage_capacity=lineage_capacity)
        )(states, lineage_state)
    return jax.vmap(
        lambda s: _evolve(config, s, generations=generations, record=record,
                          metrics=metrics, health=health))(states)


#: jitted stacked run + the buffer-donating twin (the service's hot loop
#: always rebinds, so generation N+1 rewrites the stacked population in
#: place exactly like the solo mega loops).  static_argnames stay inline
#: literals: the srnnlint flag-parity pass reads them off the AST.
evolve_stacked = jax.jit(_evolve_stacked,
                         static_argnames=("config", "generations", "record",
                                          "metrics", "health", "lineage",
                                          "lineage_capacity"))
evolve_stacked_donated = jax.jit(_evolve_stacked,
                                 static_argnames=("config", "generations",
                                                  "record", "metrics",
                                                  "health", "lineage",
                                                  "lineage_capacity"),
                                 donate_argnums=(1,))


def _evolve_stacked_step(config: SoupConfig, states: SoupState):
    """Tenant-stacked single generation (``soup.evolve_step``'s twin) —
    the stacked capture loop's frame step, so a stacked ``.traj`` stream
    is built from the same per-generation program as the solo one."""
    check_tenant_stackable(config)
    return jax.vmap(lambda s: _evolve_step(config, s))(states)


evolve_stacked_step = jax.jit(_evolve_stacked_step,
                              static_argnames=("config",))
evolve_stacked_step_donated = jax.jit(_evolve_stacked_step,
                                      static_argnames=("config",),
                                      donate_argnums=(1,))


def _evolve_multi_stacked(
    config: MultiSoupConfig,
    states,
    generations: int = 1,
    metrics: bool = False,
    health: bool = False,
    lineage: bool = False,
    lineage_state=None,
    lineage_capacity: int = 4096,
):
    """Tenant-stacked ``multisoup.evolve_multi`` (``lineage_state`` = the
    per-type tuple of stacked ``LineageState`` carries)."""
    check_tenant_stackable_multi(config)
    if lineage:
        return jax.vmap(
            lambda s, l: _evolve_multi(config, s, generations=generations,
                                       metrics=metrics, health=health,
                                       lineage=True, lineage_state=l,
                                       lineage_capacity=lineage_capacity)
        )(states, lineage_state)
    return jax.vmap(
        lambda s: _evolve_multi(config, s, generations=generations,
                                metrics=metrics, health=health))(states)


evolve_multi_stacked = jax.jit(_evolve_multi_stacked,
                               static_argnames=("config", "generations",
                                                "metrics", "health",
                                                "lineage",
                                                "lineage_capacity"))
evolve_multi_stacked_donated = jax.jit(_evolve_multi_stacked,
                                       static_argnames=("config",
                                                        "generations",
                                                        "metrics", "health",
                                                        "lineage",
                                                        "lineage_capacity"),
                                       donate_argnums=(1,))


# ---------------------------------------------------------------------------
# stacked trajectory capture
# ---------------------------------------------------------------------------


def evolve_stacked_captured(
    config: SoupConfig,
    states: SoupState,
    generations: int,
    stores: Sequence,
    every: int = 1,
    owned: bool = False,
    writer: Optional[object] = None,
) -> SoupState:
    """Stacked twin of ``utils.capture.evolve_captured``: evolve K stacked
    tenants in device-resident chunks of ``every`` generations and append
    each tenant's captured frame to ITS OWN ``TrajStore`` in ``stores``.

    The internal stream is all-donated (chunk run + frame step), mirroring
    the solo capture loop dispatch-for-dispatch, so every tenant's
    ``.traj`` stream is BITWISE-equal to its solo
    ``evolve_captured(..., every=every)`` stream (tested).  With
    ``writer`` (a ``pipeline.BackgroundWriter``) the frame pulls are
    snapshot-resolved off-thread like the solo pipelined path; without
    one the loop blocks per frame.
    """
    from ..utils.aot import own_pytree
    from ..utils.pipeline import resolve, snapshot

    if generations % every != 0:
        raise ValueError(
            f"generations={generations} not divisible by every={every}")
    if not owned:
        states = own_pytree(states)

    def append_frames(frame):
        t, w, uids, action, counterpart, loss = \
            resolve(frame) if writer is not None else frame
        for i, store in enumerate(stores):
            store.append(int(t[i]), w[i], uids[i], action[i],
                         counterpart[i], loss[i])

    for _ in range(generations // every):
        if every > 1:
            states = evolve_stacked_donated(config, states,
                                            generations=every - 1)
        states, events = evolve_stacked_step_donated(config, states)
        frame = (states.time, states.weights, states.uids, events.action,
                 events.counterpart, events.loss)
        if writer is not None:
            # snapshot BEFORE the next iteration donates the buffers; the
            # append job resolves the in-flight transfer off-thread
            writer.submit(append_frames, snapshot(frame))
        else:
            append_frames(jax.device_get(frame))
    flush_jobs = [store.flush for store in stores]
    if writer is not None:
        for job in flush_jobs:
            writer.submit(job)
    else:
        for job in flush_jobs:
            job()
    return states
