"""The multi-tenant experiment service core.

A long-lived :class:`ExperimentService` owns the warmed AOT executables,
a request queue, and the batching scheduler: submitted experiment
requests are grouped by static spelling (``serve.scheduler``), compatible
groups dispatch STACKED on the tenant axis (``serve.tenant``), odd
configs fall back to solo dispatch — per-tenant results are bitwise-equal
either way, so batching is purely an amortization decision.

Telemetry: queue-depth / latency / throughput ride the PR 2 registry as
``srnn_serve_*`` metrics (``telemetry/names.py``), every dispatch and
every per-tenant completion appends a labeled row to the service's
``events.jsonl`` through the existing ``BackgroundWriter``, and soup
requests with ``lineage: true`` stream per-tenant replication-dynamics
window rows (tenant-labeled) into ``lineage.jsonl`` — one I/O thread, the
same submission-order guarantees as the mega loops.

Ticket tracing (the fleet observatory's request-level half): every
completed ticket emits a structured span family into ``events.jsonl`` —
a ``serve.ticket`` root whose duration IS the measured request latency,
with ``queue``/``window``/``dispatch``/``publish`` children that sum to
it exactly (queue = pre-window backlog wait, window = the share of the
batching window the ticket actually sat out, dispatch = its group's
execution wall with the per-tenant amortized cost and stack width K as
labels, publish = the result-delivery residual).  The breakdown also
feeds the ``serve_ticket_*_seconds`` histograms, and a request whose
latency exceeds the ``slo_p95_ms`` target counts into
``serve_slo_violations_total`` — the signal a future SLO-driven adaptive
batch window optimizes against (ROADMAP item 3).

Transport lives elsewhere (``serve.server`` wraps this in a Unix-socket
JSON-lines server; in-process callers — tests, the bench load leg — drive
it directly).
"""

import itertools
import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..telemetry.metrics import MetricsRegistry
from .scheduler import (DEFAULT_MAX_STACK, Dispatch, Request,
                        plan_dispatches)

#: request latency / dispatch wall buckets: 1ms .. 2 min
_LATENCY_BUCKETS = (1e-3, 5e-3, 2e-2, 0.1, 0.5, 2.0, 8.0, 30.0, 120.0)


def _soup_config_from_params(params: dict):
    """Build the STATIC ``SoupConfig`` a soup request selects (the group
    key: tenants stack iff this — plus the generation count and lineage
    flag — matches exactly)."""
    from ..soup import SoupConfig
    from ..topology import Topology

    topo_kw = {"width": int(params.get("width", 2)),
               "depth": int(params.get("depth", 2))}
    if params.get("aggregates") is not None:
        # only when stated: Topology has its own default, and overriding
        # it with None would select a different static config (and jit
        # cache entry) than the solo process builds
        topo_kw["aggregates"] = int(params["aggregates"])
    topo = Topology(params.get("variant", "weightwise"), **topo_kw)
    base = SoupConfig(topo=topo, size=int(params["size"]))
    # unstated knobs take SoupConfig's OWN defaults (DEFAULT_LR etc.):
    # a drifted default here would silently run tenants with different
    # dynamics than the solo process they must stay bitwise-equal to
    return base._replace(
        attacking_rate=float(params.get("attacking_rate",
                                        base.attacking_rate)),
        learn_from_rate=float(params.get("learn_from_rate",
                                         base.learn_from_rate)),
        train=int(params.get("train", base.train)),
        learn_from_severity=int(params.get("learn_from_severity",
                                           base.learn_from_severity)),
        remove_divergent=bool(params.get("remove_divergent",
                                         base.remove_divergent)),
        remove_zero=bool(params.get("remove_zero", base.remove_zero)),
        epsilon=float(params.get("epsilon", base.epsilon)),
        lr=float(params.get("lr", base.lr)),
        train_mode=params.get("train_mode", base.train_mode),
        mode=params.get("mode", base.mode),
        layout=params.get("layout", base.layout),
        respawn_draws=params.get("respawn_draws", base.respawn_draws))


def _fixpoint_density_key(params: dict):
    """Tenants stack iff the dispatch SHAPES match; seed and epsilon are
    traced per tenant."""
    return (int(params["trials"]), int(params["batch"]))


def _soup_key(params: dict):
    """Full static spelling: config + generations (+ lineage, which picks
    a different program).  Non-stackable configs return None -> solo."""
    from ..soup import tenant_stackable

    cfg = _soup_config_from_params(params)
    if not tenant_stackable(cfg):
        return None
    return (cfg, int(params.get("generations", 10)),
            bool(params.get("lineage", False)))


GROUP_KEYS = {
    "fixpoint_density": _fixpoint_density_key,
    "soup": _soup_key,
}


#: completed results kept for ``poll`` readers; ``wait`` CONSUMES its
#: entry, so this bound only matters for fire-and-forget submitters —
#: past it the oldest un-waited results evict (a long-lived service must
#: not grow without bound; soup results can embed whole final states)
RESULT_RETENTION = 4096


class ExperimentService:
    """Queue + scheduler + executors + telemetry; one instance per
    service process.  Thread-safe: any thread may ``submit``/``wait``;
    execution happens on whichever thread calls ``run_pending`` (the
    socket server runs one dispatch thread)."""

    def __init__(self, root: str, max_stack: int = DEFAULT_MAX_STACK,
                 registry: Optional[MetricsRegistry] = None,
                 writer=None, slo_p95_ms: float = 0.0):
        from ..utils.pipeline import BackgroundWriter

        os.makedirs(root, exist_ok=True)
        self.root = root
        self.max_stack = max_stack
        self.slo_p95_ms = float(slo_p95_ms)
        self.registry = registry or MetricsRegistry()
        # registered eagerly so metrics.prom always exposes the SLO
        # counter (a clean service shows the 0, not a missing series)
        self.registry.counter(
            "serve_slo_violations_total",
            help="requests whose latency exceeded the --slo-p95-ms "
                 "target")
        self._own_writer = writer is None
        self.writer = writer or BackgroundWriter(name="serve-io")
        self._events = open(os.path.join(root, "events.jsonl"), "a")
        self._lineage = None  # opened lazily on the first lineage row
        self._lock = threading.Lock()
        self._done = threading.Condition(self._lock)
        self._pending: List[Request] = []
        self._results: Dict[str, dict] = {}
        self._completed = 0   # monotone; _results is consume-on-wait
        self._draining = False   # set by fail_pending: no more submits
        self._warming = False    # warm() dispatches skip telemetry rows
        self._tickets = itertools.count(1)
        self._span_ids = itertools.count(1)   # ticket-span ids
        self._programs = set()   # distinct (kind, key, shape) signatures
        self._closed = False
        self._t0 = time.monotonic()

    # -- submission / results -------------------------------------------

    def submit(self, kind: str, params: dict,
               tenant: Optional[str] = None) -> str:
        """Queue one request; returns its ticket id."""
        if kind not in GROUP_KEYS:
            raise ValueError(f"unknown request kind {kind!r}; "
                             f"expected one of {sorted(GROUP_KEYS)}")
        with self._lock:
            if self._draining:
                # closes the shutdown race for good: fail_pending flips
                # this under the SAME lock, so a submit that slipped past
                # the transport's stop check cannot strand its waiter
                raise RuntimeError("service shutting down")
            ticket = f"t{next(self._tickets):06d}"
            req = Request(ticket=ticket, kind=kind, params=dict(params),
                          tenant=tenant or ticket,
                          submitted_s=time.monotonic())
            self._pending.append(req)
            depth = len(self._pending)
        self.registry.counter("serve_requests_total",
                              help="experiment requests accepted").inc(
                                  1, kind=kind)
        self.registry.gauge("serve_queue_depth",
                            help="requests queued, not yet dispatched").set(
                                depth)
        return ticket

    def poll(self, ticket: str) -> Optional[dict]:
        """Completed entry for ``ticket`` ({'status', 'result'|'error'}),
        or None while pending."""
        with self._lock:
            return self._results.get(ticket)

    def wait(self, ticket: str, timeout_s: float = 600.0) -> dict:
        """Block until ``ticket`` completes (or fail after ``timeout_s``).
        CONSUMES the entry — each result is delivered to exactly one
        waiter, and the results table stays bounded under load."""
        deadline = time.monotonic() + timeout_s
        with self._done:
            while ticket not in self._results:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(f"request {ticket} still pending "
                                       f"after {timeout_s}s")
                self._done.wait(timeout=left)
            return self._results.pop(ticket)

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._pending)

    # -- execution -------------------------------------------------------

    def run_pending(self, window_s: float = 0.0) -> int:
        """Drain the queue through the scheduler: plan stacked/solo
        dispatches, execute them, publish results.  Returns the number of
        requests completed.  ``window_s`` is the batching-window sleep
        the transport just performed before this drain (the stacking
        knob) — it attributes each ticket's pre-dispatch wait between
        queue backlog and window in the ticket-span breakdown."""
        with self._lock:
            batch, self._pending = self._pending, []
        if not batch:
            return 0
        self.registry.gauge("serve_queue_depth",
                            help="requests queued, not yet dispatched").set(
                                self.queue_depth())
        plan = plan_dispatches(batch, GROUP_KEYS, self.max_stack)
        for dispatch in plan:
            self._run_dispatch(dispatch, window_s=window_s)
        self.write_metrics()
        return len(batch)

    def _run_dispatch(self, dispatch: Dispatch,
                      window_s: float = 0.0) -> None:
        mode = "stacked" if dispatch.stacked else "solo"
        t0 = time.monotonic()
        try:
            if dispatch.kind == "fixpoint_density":
                results = self._exec_fixpoint_density(dispatch)
            elif dispatch.kind == "soup":
                results = self._exec_soup(dispatch)
            else:  # pragma: no cover - submit() already validates
                raise ValueError(f"unknown kind {dispatch.kind!r}")
            error = None
        except Exception as e:  # a bad request must not kill the service
            results, error = None, f"{type(e).__name__}: {e}"
        wall = time.monotonic() - t0
        self.registry.counter(
            "serve_dispatches_total",
            help="scheduler dispatch groups executed").inc(
                1, kind=dispatch.kind, mode=mode)
        self.registry.counter(
            "serve_dispatch_tenants_total",
            help="tenant slots executed across all dispatches").inc(
                len(dispatch.requests), mode=mode)
        self.registry.histogram(
            "serve_dispatch_seconds", help="dispatch group wall seconds",
            unit="seconds", buckets=_LATENCY_BUCKETS).observe(
                wall, kind=dispatch.kind, mode=mode)
        self._event_row(kind="serve_dispatch", request_kind=dispatch.kind,
                        mode=mode, tenants=[r.tenant for r in
                                            dispatch.requests],
                        wall_s=round(wall, 4),
                        error=error)
        now = time.monotonic()
        with self._done:
            for i, req in enumerate(dispatch.requests):
                if error is None:
                    entry = {"status": "done", "result": results[i],
                             "mode": mode}
                else:
                    entry = {"status": "failed", "error": error,
                             "mode": mode}
                self._results[req.ticket] = entry
                self._completed += 1
                self.registry.histogram(
                    "serve_request_seconds",
                    help="submit-to-completion latency", unit="seconds",
                    buckets=_LATENCY_BUCKETS).observe(
                        now - req.submitted_s, kind=req.kind)
                if error is not None:
                    self.registry.counter(
                        "serve_requests_failed_total",
                        help="requests whose dispatch raised").inc(
                            1, kind=req.kind)
                self._ticket_spans(req, mode=mode,
                                   stack_k=len(dispatch.requests),
                                   dispatch_start=t0, wall=wall, now=now,
                                   window_s=window_s, error=error)
                self._event_row(kind="serve_tenant", ticket=req.ticket,
                                tenant=req.tenant, request_kind=req.kind,
                                mode=mode,
                                latency_s=round(now - req.submitted_s, 4),
                                error=error)
            # bound the table for fire-and-forget submitters (waiters
            # consume their own entries): evict oldest-first
            while len(self._results) > RESULT_RETENTION:
                self._results.pop(next(iter(self._results)))
            self._done.notify_all()

    def _ticket_spans(self, req: Request, *, mode: str, stack_k: int,
                      dispatch_start: float, wall: float, now: float,
                      window_s: float, error) -> None:
        """One completed ticket's structured span family + the
        ``serve_ticket_*`` histograms + the SLO counter.

        Breakdown contract (asserted in ``tests/test_fleet.py``): the
        root ``serve.ticket`` span's duration is EXACTLY the latency the
        ``serve_request_seconds`` histogram observed, and the four child
        durations sum to it — queue (backlog wait before the batching
        window's share), window (``min(pre-dispatch wait, window_s)`` —
        a ticket that arrived mid-window only sat out the remainder),
        dispatch (its group's execution wall), publish (result-delivery
        residual)."""
        latency = now - req.submitted_s
        pre_dispatch = max(0.0, dispatch_start - req.submitted_s)
        window_wait = min(max(0.0, float(window_s)), pre_dispatch)
        queue_wait = pre_dispatch - window_wait
        publish = max(0.0, latency - pre_dispatch - wall)
        start = req.submitted_s - self._t0
        root = next(self._span_ids)
        common = dict(trace_id=req.ticket, process=0, tenant=req.tenant,
                      request_kind=req.kind)
        self._event_row(kind="span", span="serve.ticket", span_id=root,
                        start_s=round(start, 6),
                        seconds=round(latency, 6), mode=mode,
                        stack_k=stack_k, error=error, **common)
        for name, child_start, dur, extra in (
                ("serve.ticket.queue", start, queue_wait, {}),
                ("serve.ticket.window", start + queue_wait, window_wait,
                 {}),
                ("serve.ticket.dispatch", dispatch_start - self._t0, wall,
                 {"stack_k": stack_k,
                  "per_tenant_s": round(wall / max(1, stack_k), 6)}),
                ("serve.ticket.publish", now - self._t0 - publish, publish,
                 {})):
            self._event_row(kind="span", span=name,
                            span_id=next(self._span_ids), parent=root,
                            start_s=round(child_start, 6),
                            seconds=round(dur, 6), **common, **extra)
        h = self.registry.histogram
        h("serve_ticket_queue_seconds",
          help="per-ticket backlog wait before the batching window",
          unit="seconds", buckets=_LATENCY_BUCKETS).observe(
            queue_wait, kind=req.kind)
        h("serve_ticket_window_seconds",
          help="per-ticket share of the batching window sat out",
          unit="seconds", buckets=_LATENCY_BUCKETS).observe(
            window_wait, kind=req.kind)
        h("serve_ticket_dispatch_seconds",
          help="per-ticket dispatch-group execution wall",
          unit="seconds", buckets=_LATENCY_BUCKETS).observe(
            wall, kind=req.kind)
        if self.slo_p95_ms > 0 and latency * 1000.0 > self.slo_p95_ms:
            self.registry.counter(
                "serve_slo_violations_total",
                help="requests whose latency exceeded the --slo-p95-ms "
                     "target").inc(1, kind=req.kind)

    # -- executors -------------------------------------------------------

    def _note_program(self, kind: str, signature) -> None:
        self._programs.add((kind,) + tuple(signature))

    def _exec_fixpoint_density(self, dispatch: Dispatch) -> List[dict]:
        """The fixpoint-density sweep (``setups/fixpoint_density.py``'s
        compute) for 1..K tenants: same per-batch PRNG keying as the solo
        script, stacked across tenants on the leading axis."""
        import jax
        import jax.numpy as jnp

        from ..engine import fixpoint_density, fixpoint_density_stacked
        from ..init import init_population
        from ..setups.common import STANDARD_VARIANTS
        from .tenant import init_population_stacked

        reqs = dispatch.requests
        k = len(reqs)
        trials = int(reqs[0].params["trials"])
        batch = int(reqs[0].params["batch"])
        keys = [jax.random.key(int(r.params.get("seed", 0))) for r in reqs]
        eps = jnp.asarray([float(r.params.get("epsilon", 1e-4))
                           for r in reqs], jnp.float32)
        variants = STANDARD_VARIANTS[:2]  # WW + Agg, like the reference
        per_variant = []
        for i, (_name, topo) in enumerate(variants):
            totals = jnp.zeros((k, 5), jnp.int32)
            done = 0
            while done < trials:
                n = min(batch, trials - done)
                bkeys = [jax.random.fold_in(jax.random.fold_in(kk, i), done)
                         for kk in keys]
                if k > 1:
                    pops = init_population_stacked(topo, jnp.stack(bkeys), n)
                    totals = totals + fixpoint_density_stacked(topo, pops,
                                                               eps)
                else:
                    # the python-float epsilon keeps the solo fallback on
                    # the EXACT program the setups dispatch (a weak-typed
                    # scalar), so it shares their warm cache entries
                    pop = init_population(topo, bkeys[0], n)
                    totals = totals + fixpoint_density(
                        topo, pop,
                        float(reqs[0].params.get("epsilon", 1e-4)))[None]
                self._note_program(dispatch.kind, (str(topo), k, n))
                done += n
            per_variant.append(np.asarray(totals))
        names = [name for name, _ in variants]
        return [{"variant_names": names,
                 "counters": [v[t].tolist() for v in per_variant]}
                for t in range(k)]

    def _exec_soup(self, dispatch: Dispatch) -> List[dict]:
        """A homogeneous soup run (seed -> evolve -> count) for 1..K
        tenants; the stacked spelling dispatches ``serve.tenant``'s
        vmapped twins and streams per-tenant telemetry/lineage rows."""
        import jax

        from ..soup import count, evolve, seed
        from ..telemetry.dynamics import DEFAULT_EDGE_CAPACITY, seed_lineage
        from .tenant import (evolve_stacked_donated, seed_stacked,
                             stack_tenants, unstack_tenants)

        reqs = dispatch.requests
        k = len(reqs)
        params0 = reqs[0].params
        cfg = _soup_config_from_params(params0)
        gens = int(params0.get("generations", 10))
        lineage = bool(params0.get("lineage", False))
        keys = [jax.random.key(int(r.params.get("seed", 0))) for r in reqs]
        if k > 1:
            import jax.numpy as jnp

            states = seed_stacked(cfg, jnp.stack(keys))
            kw = {"generations": gens, "metrics": True}
            if lineage:
                kw["lineage"] = True
                kw["lineage_state"] = stack_tenants(
                    [seed_lineage(cfg.size) for _ in range(k)])
                kw["lineage_capacity"] = DEFAULT_EDGE_CAPACITY
            out = evolve_stacked_donated(cfg, states, **kw)
            finals = unstack_tenants(out[0], k)
            metrics = unstack_tenants(out[1], k)
            ltriples = (unstack_tenants(out[2], k) if lineage else
                        [None] * k)
        else:
            kw = {"generations": gens, "metrics": True}
            if lineage:
                kw["lineage"] = True
                kw["lineage_state"] = seed_lineage(cfg.size)
                kw["lineage_capacity"] = DEFAULT_EDGE_CAPACITY
            out = evolve(cfg, seed(cfg, keys[0]), **kw)
            finals, metrics = [out[0]], [out[1]]
            ltriples = [out[2]] if lineage else [None]
        self._note_program(dispatch.kind,
                           (repr(cfg), gens, lineage, k))
        results = []
        for t, req in enumerate(reqs):
            counts = np.asarray(count(cfg, finals[t]))
            m = metrics[t]
            row = {"counters": counts.tolist(),
                   "final_time": int(np.asarray(finals[t].time)),
                   "next_uid": int(np.asarray(finals[t].next_uid)),
                   "metrics": {
                       "generations": int(np.asarray(m.generations)),
                       "actions": np.asarray(m.actions).tolist(),
                       "loss_sum": float(np.asarray(m.loss_sum))}}
            if bool(req.params.get("return_state", True)) \
                    and cfg.size * cfg.topo.num_weights <= 262144:
                row["weights"] = np.asarray(finals[t].weights).tolist()
                row["uids"] = np.asarray(finals[t].uids).tolist()
            if lineage:
                self._lineage_row(req, cfg, gens, ltriples[t])
            results.append(row)
        return results

    def _lineage_row(self, req: Request, cfg, gens: int, ltriple) -> None:
        """Per-tenant replication-dynamics window row, tenant-labeled,
        appended to the service's lineage.jsonl through the writer."""
        if self._warming:
            return   # throwaway warm tenants must not pollute the stream
        from ..telemetry.dynamics import DEFAULT_EDGE_CAPACITY, window_record

        lin, win, stats = ltriple
        row = window_record(0, gens, jax_device_get(win),
                            jax_device_get(stats), DEFAULT_EDGE_CAPACITY,
                            next_pid=int(np.asarray(lin.next_pid)))
        row["tenant"] = req.tenant
        row["ticket"] = req.ticket

        def append():
            if self._lineage is None:
                self._lineage = open(os.path.join(self.root,
                                                  "lineage.jsonl"), "a")
            self._lineage.write(json.dumps(row) + "\n")
            self._lineage.flush()

        self.writer.submit(append)

    def warm(self, kind: str, params: dict,
             widths: Optional[Sequence[int]] = None) -> None:
        """Pre-dispatch (compile or cache-deserialize) the executor for
        ``(kind, params)`` at each stack width in ``widths`` (default: the
        service's ``max_stack`` and solo) with throwaway seeds, so the
        first real tenants of that spelling only execute.  Warm dispatches
        do not touch the serve metrics; they DO count into
        ``distinct_programs`` (the load bench snapshots around its serving
        phase)."""
        widths = sorted(set(widths or (self.max_stack, 1)))
        self._warming = True   # no lineage/event rows for warm tenants
        try:
            for k in widths:
                reqs = [Request(ticket=f"warm{i:03d}", kind=kind,
                                params=dict(params), tenant=f"warm{i:03d}",
                                submitted_s=time.monotonic())
                        for i in range(k)]
                d = Dispatch(kind=kind, key=("warm",), requests=reqs)
                if kind == "fixpoint_density":
                    self._exec_fixpoint_density(d)
                elif kind == "soup":
                    self._exec_soup(d)
                else:
                    raise ValueError(f"unknown request kind {kind!r}")
        finally:
            self._warming = False

    # -- telemetry sinks -------------------------------------------------

    def _event_row(self, **fields) -> None:
        fields.setdefault("t", round(time.monotonic() - self._t0, 4))
        fields = {k: v for k, v in fields.items() if v is not None}

        def append():
            self._events.write(json.dumps(fields) + "\n")
            self._events.flush()

        self.writer.submit(append)

    def write_metrics(self) -> str:
        """Atomically publish metrics.prom in the service root (riding the
        writer so it lands after the rows it summarizes)."""
        path = os.path.join(self.root, "metrics.prom")
        self.writer.submit(self.registry.write_textfile, path)
        return path

    def stats(self) -> dict:
        """Host-side snapshot for the ``stats`` op / load bench / watch
        console; ``slo`` carries the target, the violation count, and a
        conservative measured p95 (histogram-bucket upper bound)."""
        with self._lock:
            done = self._completed
            depth = len(self._pending)
            programs = len(self._programs)
        violations = sum(
            v for _suffix, v in self.registry.counter(
                "serve_slo_violations_total").samples())
        p95 = self.registry.histogram(
            "serve_request_seconds",
            help="submit-to-completion latency", unit="seconds",
            buckets=_LATENCY_BUCKETS).quantile(0.95)
        return {"completed": done, "queue_depth": depth,
                "distinct_programs": programs,
                "uptime_s": round(time.monotonic() - self._t0, 2),
                "slo": {
                    "target_p95_ms": self.slo_p95_ms or None,
                    "violations": int(violations),
                    "p95_ms": round(p95 * 1000.0, 3)
                    if p95 is not None else None,
                },
                "metrics": self.registry.rows()}

    def fail_pending(self, reason: str) -> int:
        """Resolve every still-queued request as failed (shutdown path:
        a submit that raced the dispatcher's final drain must not leave
        its waiter blocked until timeout).  Returns how many."""
        with self._done:
            self._draining = True   # submit() refuses from here on
            stranded, self._pending = self._pending, []
            for req in stranded:
                self._results[req.ticket] = {"status": "failed",
                                             "error": reason,
                                             "mode": "none"}
                self.registry.counter(
                    "serve_requests_failed_total",
                    help="requests whose dispatch raised").inc(
                        1, kind=req.kind)
            self._done.notify_all()
            return len(stranded)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.registry.write_textfile(os.path.join(self.root,
                                                  "metrics.prom"))
        if self._own_writer:
            self.writer.close()
        else:
            # a SHARED writer stays open for its other producers, but any
            # queued row jobs reference the files closed below — drain
            # them first or they would latch a WriterError on everyone
            self.writer.flush()
        self._events.close()
        if self._lineage is not None:
            self._lineage.close()

    def __enter__(self) -> "ExperimentService":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


def jax_device_get(tree):
    """Small alias so executor rows pull device values exactly once."""
    import jax

    return jax.device_get(tree)
