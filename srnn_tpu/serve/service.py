"""The multi-tenant experiment service core.

A long-lived :class:`ExperimentService` owns the warmed AOT executables,
a request queue, and the batching scheduler: submitted experiment
requests are grouped by static spelling (``serve.scheduler``), compatible
groups dispatch STACKED on the tenant axis (``serve.tenant``), odd
configs fall back to solo dispatch — per-tenant results are bitwise-equal
either way, so batching is purely an amortization decision.

Telemetry: queue-depth / latency / throughput ride the PR 2 registry as
``srnn_serve_*`` metrics (``telemetry/names.py``), every dispatch and
every per-tenant completion appends a labeled row to the service's
``events.jsonl`` through the existing ``BackgroundWriter``, and soup
requests with ``lineage: true`` stream per-tenant replication-dynamics
window rows (tenant-labeled) into ``lineage.jsonl`` — one I/O thread, the
same submission-order guarantees as the mega loops.

Ticket tracing (the fleet observatory's request-level half): every
completed ticket emits a structured span family into ``events.jsonl`` —
a ``serve.ticket`` root whose duration IS the measured request latency,
with ``queue``/``window``/``dispatch``/``publish`` children that sum to
it exactly (queue = pre-window backlog wait, window = the share of the
batching window the ticket actually sat out, dispatch = its group's
execution wall with the per-tenant amortized cost and stack width K as
labels, publish = the result-delivery residual).  The breakdown also
feeds the ``serve_ticket_*_seconds`` histograms, and a request whose
latency exceeds the ``slo_p95_ms`` target counts into
``serve_slo_violations_total`` — the signal a future SLO-driven adaptive
batch window optimizes against (ROADMAP item 3).

Self-healing (the serve tier's PR-7 moment): every admitted submit is
journaled durably (``serve.journal``, append+fsync) BEFORE its ticket id
is acknowledged, so a ``kill -9`` loses no admitted work — a restarted
service calls :meth:`ExperimentService.recover` and replays every
unfinished ticket with results bitwise-equal to an uninterrupted run.
Dispatch is SUPERVISED: failures route through the resilience tier's
``classify_fault`` taxonomy — retryable kinds (:data:`DISPATCH_RETRYABLE`)
get bounded deterministic-backoff retries, and a persisting stacked-group
failure BISECTS the group to isolate the poisoned tenant(s), quarantining
them (failed, with the real error) while the innocent groupmates complete
solo.  Admission is bounded (``max_queue`` -> typed
:class:`OverloadedError` the client backs off on), per-ticket deadlines
are enforced at admission and at dispatch (expired tickets fail fast,
never occupying a stack slot), and completed-but-never-collected results
evict on a TTL so a long-lived service cannot leak its results table.

Transport lives elsewhere (``serve.server`` wraps this in a Unix-socket
JSON-lines server; in-process callers — tests, the bench load leg — drive
it directly).
"""

import itertools
import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..resilience.supervisor import (DEVICE_LOSS, IO, STALL, BackoffPolicy,
                                     classify_fault)
from ..telemetry.exemplars import EXEMPLARS_NAME, ExemplarRing
from ..telemetry.metrics import MetricsRegistry
from .journal import TicketJournal
from .scheduler import (DEFAULT_MAX_STACK, Dispatch, Request,
                        plan_dispatches)

#: request latency / dispatch wall buckets: 1ms .. 2 min
_LATENCY_BUCKETS = (1e-3, 5e-3, 2e-2, 0.1, 0.5, 2.0, 8.0, 30.0, 120.0)

#: dispatch-thread fault kinds the service retries in place (bounded,
#: deterministic backoff) instead of failing the group: transient by the
#: supervisor's taxonomy.  Everything else — including the deterministic
#: config errors a poisoned tenant raises — goes straight to bisection
#: (stacked) or a failed ticket (solo).  The fault-taxonomy srnnlint pass
#: checks each member is one of the supervisor's RETRYABLE kinds (T008).
DISPATCH_RETRYABLE = (DEVICE_LOSS, IO, STALL)


class OverloadedError(RuntimeError):
    """Typed admission rejection: the queue is at ``max_queue``.  The
    transport maps it to an ``overloaded: true`` response the client
    backs off on — load past saturation degrades into explicit pushback,
    never an unbounded queue."""


class DeadlineExpired(RuntimeError):
    """The ticket's ``deadline_s`` was already spent at admission (the
    dispatch-time expiry path resolves the ticket as failed instead,
    since it was admitted and journaled)."""


def _soup_config_from_params(params: dict):
    """Build the STATIC ``SoupConfig`` a soup request selects (the group
    key: tenants stack iff this — plus the generation count and lineage
    flag — matches exactly)."""
    from ..soup import SoupConfig
    from ..topology import Topology

    topo_kw = {"width": int(params.get("width", 2)),
               "depth": int(params.get("depth", 2))}
    if params.get("aggregates") is not None:
        # only when stated: Topology has its own default, and overriding
        # it with None would select a different static config (and jit
        # cache entry) than the solo process builds
        topo_kw["aggregates"] = int(params["aggregates"])
    topo = Topology(params.get("variant", "weightwise"), **topo_kw)
    base = SoupConfig(topo=topo, size=int(params["size"]))
    # unstated knobs take SoupConfig's OWN defaults (DEFAULT_LR etc.):
    # a drifted default here would silently run tenants with different
    # dynamics than the solo process they must stay bitwise-equal to
    return base._replace(
        attacking_rate=float(params.get("attacking_rate",
                                        base.attacking_rate)),
        learn_from_rate=float(params.get("learn_from_rate",
                                         base.learn_from_rate)),
        train=int(params.get("train", base.train)),
        learn_from_severity=int(params.get("learn_from_severity",
                                           base.learn_from_severity)),
        remove_divergent=bool(params.get("remove_divergent",
                                         base.remove_divergent)),
        remove_zero=bool(params.get("remove_zero", base.remove_zero)),
        epsilon=float(params.get("epsilon", base.epsilon)),
        lr=float(params.get("lr", base.lr)),
        train_mode=params.get("train_mode", base.train_mode),
        mode=params.get("mode", base.mode),
        layout=params.get("layout", base.layout),
        respawn_draws=params.get("respawn_draws", base.respawn_draws))


def _fixpoint_density_key(params: dict):
    """Tenants stack iff the dispatch SHAPES match; seed and epsilon are
    traced per tenant."""
    return (int(params["trials"]), int(params["batch"]))


def _soup_key(params: dict):
    """Full static spelling: config + generations (+ lineage, which picks
    a different program).  Non-stackable configs return None -> solo."""
    from ..soup import tenant_stackable

    cfg = _soup_config_from_params(params)
    if not tenant_stackable(cfg):
        return None
    return (cfg, int(params.get("generations", 10)),
            bool(params.get("lineage", False)))


GROUP_KEYS = {
    "fixpoint_density": _fixpoint_density_key,
    "soup": _soup_key,
}


#: completed results kept for ``poll`` readers; ``wait`` CONSUMES its
#: entry, so this bound only matters for fire-and-forget submitters —
#: past it the oldest un-waited results evict (a long-lived service must
#: not grow without bound; soup results can embed whole final states)
RESULT_RETENTION = 4096

#: slowest-traces panel depth (``stats()['slowest']``, rendered by
#: ``watch --service``): the in-memory top-K by latency, flag-labeled
SLOWEST_KEPT = 8


class ExperimentService:
    """Queue + scheduler + executors + telemetry; one instance per
    service process.  Thread-safe: any thread may ``submit``/``wait``;
    execution happens on whichever thread calls ``run_pending`` (the
    socket server runs one dispatch thread)."""

    def __init__(self, root: str, max_stack: int = DEFAULT_MAX_STACK,
                 registry: Optional[MetricsRegistry] = None,
                 writer=None, slo_p95_ms: float = 0.0,
                 max_queue: int = 0, results_ttl_s: float = 0.0,
                 dispatch_retries: int = 2, retry_backoff_s: float = 0.05,
                 chaos=None, fair_tenants: bool = False):
        from ..utils.pipeline import BackgroundWriter

        os.makedirs(root, exist_ok=True)
        self.root = root
        self.max_stack = max_stack
        self.slo_p95_ms = float(slo_p95_ms)
        self.max_queue = max(0, int(max_queue))       # 0 = unbounded
        self.results_ttl_s = max(0.0, float(results_ttl_s))  # 0 = no TTL
        self.dispatch_retries = max(0, int(dispatch_retries))
        #: deterministic retry backoff (seeded like the supervisor's, so
        #: a chaos-harness run replays the same delay sequence); the base
        #: is service-scale — a dispatch retry must not stall the queue
        #: the way a mega-run restart may
        self._retry_policy = BackoffPolicy(
            max_restarts=self.dispatch_retries,
            base_s=max(0.0, float(retry_backoff_s)), max_s=2.0, seed=0)
        self.chaos = chaos
        self.registry = registry or MetricsRegistry()
        # registered eagerly so metrics.prom always exposes the SLO
        # counter (a clean service shows the 0, not a missing series)
        self.registry.counter(
            "serve_slo_violations_total",
            help="requests whose latency exceeded the --slo-p95-ms "
                 "target")
        # ... and the self-healing ladder counters, for the same reason:
        # the watch console / chaos smoke read zeros, not missing series
        self.registry.counter(
            "serve_journal_replays_total",
            help="journaled tickets replayed after a restart")
        self.registry.counter(
            "serve_quarantined_tenants_total",
            help="poisoned tenants isolated by group bisection")
        self.registry.counter(
            "serve_overload_rejections_total",
            help="submits rejected at admission (--max-queue)")
        self.registry.counter(
            "serve_deadline_expirations_total",
            help="tickets expired by their deadline_s (admission or "
                 "dispatch)")
        self.registry.counter(
            "serve_dispatch_retries_total",
            help="dispatch attempts retried on a transient classified "
                 "fault")
        self.registry.counter(
            "serve_results_evicted_total",
            help="uncollected results evicted (TTL or retention cap)")
        self._own_writer = writer is None
        self.writer = writer or BackgroundWriter(name="serve-io")
        self._events = open(os.path.join(root, "events.jsonl"), "a")
        self._lineage = None  # opened lazily on the first lineage row
        self.journal = TicketJournal(root)
        #: the continuous-batching tier's fairness flag (on whenever the
        #: adaptive controller is attached): tenant-interleave + cross-
        #: group round-robin chunk emission in every drain's plan
        self.fair_tenants = bool(fair_tenants)
        self._lock = threading.Lock()
        self._done = threading.Condition(self._lock)
        #: signaled at admission (submit/recover) — the dispatcher blocks
        #: here instead of poll-sleeping, so an idle service burns no CPU
        #: and the first ticket after quiet starts its window immediately
        self._work = threading.Condition(self._lock)
        #: the adaptive window controller (attach_controller); None = the
        #: fixed-window dispatcher, byte-exact PR 10 telemetry included
        self._controller = None
        self._pending: List[Request] = []
        self._results: Dict[str, dict] = {}
        self._idem: Dict[str, str] = {}          # idempotency key -> ticket
        self._idem_by_ticket: Dict[str, str] = {}  # reverse (for cleanup)
        self._unfinished = set()  # admitted, not yet journaled done
        self._replayed = 0        # tickets re-admitted by recover()
        self._completed = 0   # monotone; _results is consume-on-wait
        self._draining = False   # set by fail_pending: no more submits
        self._warming = False    # warm() dispatches skip telemetry rows
        self._tickets = itertools.count(1)
        self._span_ids = itertools.count(1)   # ticket-span ids
        #: tail-kept exemplar traces (full family for SLO-violating /
        #: failed / quarantined tickets, root-only otherwise) + the
        #: in-memory slowest-traces panel the stats op exposes
        self._exemplars = ExemplarRing(os.path.join(root, EXEMPLARS_NAME))
        self._slowest: List[dict] = []
        self._programs = set()   # distinct (kind, key, shape) signatures
        self._dispatch_flops = 0.0   # HLO flops of the dispatch in flight
        self._closed = False
        # live telemetry plane (attach_live): the history ring sampled at
        # the top of every drain + the declarative alert engine
        self._live_history = None
        self._live_engine = None
        self._live_capture = None
        self._live_profiler = None
        self._live_last_profile_flush = float("-inf")
        self._t0 = time.monotonic()

    # -- submission / results -------------------------------------------

    def submit(self, kind: str, params: dict,
               tenant: Optional[str] = None,
               deadline_s: Optional[float] = None,
               idempotency_key: Optional[str] = None,
               trace_id: Optional[str] = None,
               parent_span: Optional[int] = None) -> str:
        """Admit one request; returns its ticket id.

        The returned id is DURABLE: the journal append (with fsync)
        happens under the admission lock, before the id escapes — an
        acknowledged ticket survives ``kill -9`` and replays on restart.
        ``idempotency_key`` dedupes: a resubmit with a known key (live
        table or journal-recovered) returns the existing ticket instead
        of double-running.  Raises :class:`OverloadedError` past
        ``max_queue`` and :class:`DeadlineExpired` for a ``deadline_s``
        that is already spent.

        ``trace_id``/``parent_span`` are the propagated trace context
        (fleet tracing): journaled with the submit and adopted by the
        ticket's span family, so a pool-forwarded ticket keeps ONE trace
        across the hop.  Telemetry-only — dispatch never reads them.
        """
        if kind not in GROUP_KEYS:
            raise ValueError(f"unknown request kind {kind!r}; "
                             f"expected one of {sorted(GROUP_KEYS)}")
        if deadline_s is not None and float(deadline_s) <= 0:
            self.registry.counter(
                "serve_deadline_expirations_total",
                help="tickets expired by their deadline_s (admission or "
                     "dispatch)").inc(1, kind=kind)
            raise DeadlineExpired(
                f"deadline_s={deadline_s} is already spent at admission")
        with self._lock:
            if self._draining:
                # closes the shutdown race for good: fail_pending flips
                # this under the SAME lock, so a submit that slipped past
                # the transport's stop check cannot strand its waiter
                raise RuntimeError("service shutting down")
            if idempotency_key:
                known = self._idem.get(idempotency_key)
                if known is not None:
                    return known   # admitted once per key; no re-run
            if self.max_queue and len(self._pending) >= self.max_queue:
                depth = len(self._pending)
                self.registry.counter(
                    "serve_overload_rejections_total",
                    help="submits rejected at admission "
                         "(--max-queue)").inc(1, kind=kind)
                self.registry.gauge(
                    "serve_queue_rejected_depth",
                    help="queue depth observed at the last overload "
                         "rejection").set(depth)
                raise OverloadedError(
                    f"queue full ({depth} >= max_queue={self.max_queue}); "
                    "back off and resubmit")
            now = time.monotonic()
            ticket = f"t{next(self._tickets):06d}"
            req = Request(ticket=ticket, kind=kind, params=dict(params),
                          tenant=tenant or ticket, submitted_s=now,
                          deadline_mono=(now + float(deadline_s)
                                         if deadline_s is not None
                                         else None),
                          idem_key=idempotency_key or None,
                          trace_id=trace_id or None,
                          parent_span=parent_span)
            # durable BEFORE acknowledged: fsync under the admission lock,
            # so the ticket id never outruns its journal record
            self.journal.record_submit(
                ticket=ticket, kind=kind, params=req.params,
                tenant=req.tenant, key=idempotency_key,
                deadline_wall=(time.time() + float(deadline_s)
                               if deadline_s is not None else None),
                wall=time.time(), trace_id=req.trace_id,
                parent_span=req.parent_span)
            admit_done = time.monotonic()
            admit_span = next(self._span_ids)
            self._pending.append(req)
            self._unfinished.add(ticket)
            if idempotency_key:
                self._idem[idempotency_key] = ticket
                self._idem_by_ticket[ticket] = idempotency_key
            depth = len(self._pending)
            self._work.notify_all()   # wake the blocked dispatcher
        # admission span: emitted NOW, not at completion like the ticket
        # family — it is the corpse's only lane marker for a ticket whose
        # worker dies mid-flight, which is exactly the trace the fleet
        # merge must still render end to end.  Duration = the durable
        # journal append the ack waited on.
        self._event_row(kind="span", span="serve.admit",
                        span_id=admit_span,
                        trace_id=req.trace_id or ticket,
                        remote_parent=req.parent_span, ticket=ticket,
                        process=0, tenant=req.tenant, request_kind=kind,
                        start_s=round(now - self._t0, 6),
                        seconds=round(admit_done - now, 6))
        if self.chaos is not None:
            self.chaos.note_submit(ticket)
        self.registry.counter("serve_requests_total",
                              help="experiment requests accepted").inc(
                                  1, kind=kind)
        self.registry.gauge("serve_queue_depth",
                            help="requests queued, not yet dispatched").set(
                                depth)
        return ticket

    def recover(self) -> int:
        """Replay the journal's unfinished tickets after a restart: each
        is re-admitted under its ORIGINAL ticket id (clients reconnect
        and ``wait`` the ids they already hold; idempotent resubmits
        dedupe onto them), the ticket counter resumes past every id the
        journal ever issued, and the journal itself is compacted to the
        unfinished suffix.  Returns the number of replayed tickets."""
        entries, torn, next_ticket = self.journal.recover()
        bad = []
        now = time.monotonic()
        wall_now = time.time()
        with self._lock:
            self._tickets = itertools.count(next_ticket)
            for e in entries:
                if e.kind not in GROUP_KEYS:
                    bad.append(e)     # foreign/forward-version record
                    continue
                deadline_mono = None
                if e.deadline_wall is not None:
                    # wall-clock deadline re-derived: downtime counts
                    # against the budget, like any other queueing delay
                    deadline_mono = now + (float(e.deadline_wall)
                                           - wall_now)
                req = Request(ticket=e.ticket, kind=e.kind,
                              params=dict(e.params), tenant=e.tenant,
                              submitted_s=now, deadline_mono=deadline_mono,
                              idem_key=e.key, trace_id=e.trace_id,
                              parent_span=e.parent_span)
                self._pending.append(req)
                self._unfinished.add(e.ticket)
                if e.key:
                    self._idem[e.key] = e.ticket
                    self._idem_by_ticket[e.ticket] = e.key
            replayed = [e for e in entries if e.kind in GROUP_KEYS]
            self._replayed += len(replayed)
            replay_spans = [next(self._span_ids) for _ in replayed]
            depth = len(self._pending)
            if depth:
                self._work.notify_all()
        for e, span_id in zip(replayed, replay_spans):
            # the survivor's re-admission marker, under the ORIGINAL
            # trace id: the merged fleet timeline shows the corpse's
            # serve.admit and this replay admit in one trace
            self._event_row(kind="span", span="serve.admit",
                            span_id=span_id,
                            trace_id=e.trace_id or e.ticket,
                            remote_parent=e.parent_span, ticket=e.ticket,
                            process=0, tenant=e.tenant,
                            request_kind=e.kind, replayed=True,
                            start_s=round(now - self._t0, 6),
                            seconds=0.0)
        for e in replayed:
            if self.chaos is not None:
                self.chaos.note_submit(e.ticket)
        for e in bad:
            req = Request(ticket=e.ticket, kind=e.kind, params=e.params,
                          tenant=e.tenant, submitted_s=now)
            self._resolve_failed(
                [req], f"unknown request kind {e.kind!r} in journal")
        if replayed:
            self.registry.counter(
                "serve_journal_replays_total",
                help="journaled tickets replayed after a restart").inc(
                    len(replayed))
            self.registry.gauge(
                "serve_queue_depth",
                help="requests queued, not yet dispatched").set(depth)
            self._event_row(kind="serve_replay",
                            tickets=[e.ticket for e in replayed],
                            torn_tail=torn or None)
        return len(replayed)

    def poll(self, ticket: str) -> Optional[dict]:
        """Completed entry for ``ticket`` ({'status', 'result'|'error'}),
        or None while pending."""
        with self._lock:
            return self._results.get(ticket)

    def wait(self, ticket: str, timeout_s: float = 600.0) -> dict:
        """Block until ``ticket`` completes (or fail after ``timeout_s``).
        CONSUMES the entry — each result is delivered to exactly one
        waiter, and the results table stays bounded under load."""
        deadline = time.monotonic() + timeout_s
        with self._done:
            while ticket not in self._results:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(f"request {ticket} still pending "
                                       f"after {timeout_s}s")
                self._done.wait(timeout=left)
            self._drop_idem_locked(ticket)
            return self._results.pop(ticket)

    def _drop_idem_locked(self, ticket: str) -> None:
        """A consumed (or evicted) result ends its idempotency window: a
        later resubmit with the same key is a fresh run, not a dangling
        pointer at a ticket whose result is gone."""
        key = self._idem_by_ticket.pop(ticket, None)
        if key is not None:
            self._idem.pop(key, None)

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._pending)

    def wait_for_work(self, timeout_s: float = 1.0) -> bool:
        """Block until at least one request is pending (or ``timeout_s``
        elapses); returns whether work is pending.  The dispatcher's
        idle wait: admission (``submit``/``recover``) signals it, so an
        idle service burns no CPU and first-ticket latency is bounded by
        the adaptive window, not a poll interval.  Spurious returns are
        fine — the caller loops."""
        with self._work:
            if self._pending:
                return True
            self._work.wait(timeout=timeout_s)
            return bool(self._pending)

    def wake(self) -> None:
        """Wake a dispatcher blocked in :meth:`wait_for_work` (the
        transport's stop/drain path — the dispatcher re-checks its stop
        flag on every wake)."""
        with self._work:
            self._work.notify_all()

    def pending_groups(self) -> List:
        """Ordered-unique scheduler group ids ``(kind, key)`` of the
        pending queue — the adaptive controller's lookup domain for the
        next wait window.  A request whose key function raises (or
        returns None) reports ``(kind, None)``: the solo pool, which the
        controller treats as one group per kind."""
        with self._lock:
            snapshot = list(self._pending)
        out, seen = [], set()
        for req in snapshot:
            keyfn = GROUP_KEYS.get(req.kind)
            try:
                key = keyfn(req.params) if keyfn is not None else None
            except Exception:
                key = None
            gid = (req.kind, key)
            if gid not in seen:
                seen.add(gid)
                out.append(gid)
        return out

    # -- execution -------------------------------------------------------

    def attach_controller(self, controller, fair: bool = True) -> None:
        """Arm the continuous-batching tier: ``controller`` (an
        ``serve.controller.AdaptiveWindowController``) observes every
        retired dispatch and owns the per-group wait windows; ``fair``
        turns on the tenant-fairness plan (the two ship together — the
        ``--no-adaptive`` oracle disables both so the fixed-window path
        is byte-exact PR 10, metrics.prom included)."""
        self._controller = controller
        self.fair_tenants = bool(fair)

    def attach_live(self, history, engine=None, capture=None,
                    profiler=None) -> None:
        """Arm the live telemetry plane: ``history`` (a
        ``telemetry.timeseries.MetricHistory`` over this service's
        registry, its jsonl stream in the service root) is sampled at
        the TOP of every drain — before the queue pops, so the
        queue-depth gauge still holds its pre-drain peak and a
        queue-at-the-bound condition is observable — and ``engine`` (a
        ``telemetry.alerts.AlertEngine``) evaluates on the same cadence,
        each transition riding events.jsonl as a ``{"kind": "alert"}``
        row.  ``capture`` (a ``telemetry.profiler.AnomalyCapture``)
        publishes its black-box bundle on each firing edge, inline after
        the alert rows that cite it; ``profiler`` (the owning process's
        ``SamplingProfiler``) folds its gauges on the same cadence and
        rides a throttled ``profile.folded``/``profile.jsonl`` rewrite
        on the service writer.  All close with the service (the caller
        keeps ownership of the profiler's ``stop()``)."""
        self._live_history = history
        self._live_engine = engine
        self._live_capture = capture
        self._live_profiler = profiler
        self._live_last_sample = float("-inf")
        self._live_last_profile_flush = float("-inf")

    def _sample_live(self) -> None:
        """One live-plane turn, inline on the dispatch thread (the
        sample is a registry snapshot + a jsonl append — microseconds
        against a dispatch).  Fail-soft: a telemetry error must never
        take down the dispatch loop."""
        if self._live_history is None:
            return
        try:
            self._live_last_sample = time.monotonic()
            self._live_history.sample()
            transitions = []
            if self._live_engine is not None:
                for transition in self._live_engine.evaluate():
                    self._event_row(kind="alert", **transition)
                    transitions.append(transition)
            if self._live_capture is not None:
                self._live_capture.on_transitions(transitions)
            if self._live_profiler is not None:
                self._live_profiler.update_gauges(self.registry)
                now = time.monotonic()
                if now - self._live_last_profile_flush >= 10.0:
                    # throttled cumulative rewrite: profile files need
                    # not track every dispatch, just stay fresh
                    self._live_last_profile_flush = now
                    self.writer.submit(self._live_profiler.write_files,
                                       self.root)
        except Exception as e:  # pragma: no cover - defensive
            import sys

            print(f"serve: live telemetry sample failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr, flush=True)

    def idle_sample_live(self, min_interval_s: float = 5.0) -> None:
        """Throttled live-plane turn for the dispatcher's IDLE ticks.
        Rate windows must keep sliding while no traffic arrives — a
        fired SLO-burn alert clears only once a quiet window passes,
        and with sampling confined to ``run_pending`` an idle service
        would report it firing until the next request.  Throttled so a
        50ms idle poll doesn't grow metrics_history.jsonl one row per
        tick."""
        if self._live_history is None:
            return
        if time.monotonic() - self._live_last_sample < min_interval_s:
            return
        self._sample_live()

    def run_pending(self, window_s: float = 0.0) -> int:
        """Drain the queue through the scheduler: plan stacked/solo
        dispatches, execute them, publish results.  Returns the number of
        requests completed.  ``window_s`` is the batching-window sleep
        the transport just performed before this drain (the stacking
        knob) — it attributes each ticket's pre-dispatch wait between
        queue backlog and window in the ticket-span breakdown."""
        # live plane first: the queue-depth gauge still holds the
        # pre-drain peak, so a saturated queue fires its alert even
        # though this very drain is about to empty it
        self._sample_live()
        with self._lock:
            batch, self._pending = self._pending, []
        if not batch:
            return 0
        self.registry.gauge("serve_queue_depth",
                            help="requests queued, not yet dispatched").set(
                                self.queue_depth())
        batch = self._expire_overdue(batch)
        plan = plan_dispatches(batch, GROUP_KEYS, self.max_stack,
                               fair=self.fair_tenants)
        inflight = sum(len(d.requests) for d in plan)
        if self._controller is not None:
            # fleet-view gauges, adaptive tier only: the fixed-window
            # oracle's metrics.prom must stay byte-exact PR 10
            g = self.registry.gauge(
                "serve_inflight_requests",
                help="tenant slots in the dispatch round in flight")
            g.set(inflight)
            self.registry.gauge(
                "serve_window_seconds",
                help="the adaptive batching window just applied "
                     "(min over pending groups)").set(
                    max(0.0, float(window_s)))
        for dispatch in plan:
            self._run_dispatch(dispatch, window_s=window_s)
            if self._controller is not None:
                inflight -= len(dispatch.requests)
                g.set(inflight)
        self.write_metrics()
        # post-drain turn: conditions this drain resolved (the queue is
        # empty again) emit their "cleared" edge now rather than at the
        # next burst
        self._sample_live()
        return len(batch)

    def _expire_overdue(self, reqs: Sequence[Request]) -> List[Request]:
        """Fail every request whose deadline has passed (they never
        occupy a stack slot) and return the live remainder."""
        now = time.monotonic()
        overdue = [r for r in reqs
                   if r.deadline_mono is not None and now > r.deadline_mono]
        if overdue:
            for r in overdue:
                self.registry.counter(
                    "serve_deadline_expirations_total",
                    help="tickets expired by their deadline_s (admission "
                         "or dispatch)").inc(1, kind=r.kind)
            self._resolve_failed(overdue,
                                 "deadline_s expired before dispatch")
        return [r for r in reqs
                if r.deadline_mono is None or now <= r.deadline_mono]

    def _execute(self, dispatch: Dispatch) -> List[dict]:
        """One dispatch execution attempt through the production path
        (the chaos injector's serve hooks fire here, so every recovery
        ladder drills the code real traffic runs)."""
        if self.chaos is not None:
            self.chaos.serve_dispatch(dispatch.requests)
        self._dispatch_flops = 0.0   # executors accumulate per attempt
        if dispatch.kind == "fixpoint_density":
            return self._exec_fixpoint_density(dispatch)
        if dispatch.kind == "soup":
            return self._exec_soup(dispatch)
        # pragma: no cover - submit() already validates
        raise ValueError(f"unknown kind {dispatch.kind!r}")

    def _run_dispatch(self, dispatch: Dispatch,
                      window_s: float = 0.0, _depth: int = 0) -> None:
        """Supervised dispatch: execute with bounded deterministic-backoff
        retries for transient classified faults; on a persisting STACKED
        failure, bisect the group to isolate the poisoned tenant(s) — the
        innocents complete solo, the poisoned quarantine (failed with the
        real error).  ``_depth`` marks bisection recursion: a solo failure
        under bisection is a quarantine, a top-level solo failure is an
        ordinary failed request."""
        # a ticket whose deadline burned away in the queue/backoff must
        # not occupy a stack slot — re-check at every (sub)dispatch
        live = self._expire_overdue(dispatch.requests)
        if not live:
            return
        if len(live) != len(dispatch.requests):
            dispatch = Dispatch(kind=dispatch.kind, key=dispatch.key,
                                requests=live)
        mode = "stacked" if dispatch.stacked else "solo"
        t0 = time.monotonic()
        attempt = 0
        while True:
            try:
                results = self._execute(dispatch)
                error = fault = None
                break
            except Exception as e:  # a bad request must not kill the service
                fault = classify_fault(e)
                if fault in DISPATCH_RETRYABLE \
                        and attempt < self.dispatch_retries:
                    delay = self._retry_policy.delay(attempt)
                    attempt += 1
                    self.registry.counter(
                        "serve_dispatch_retries_total",
                        help="dispatch attempts retried on a transient "
                             "classified fault").inc(
                            1, kind=dispatch.kind, fault=fault)
                    self._event_row(kind="serve_retry",
                                    request_kind=dispatch.kind, fault=fault,
                                    attempt=attempt,
                                    backoff_s=round(delay, 4),
                                    error=f"{type(e).__name__}: {e}")
                    if delay > 0:
                        time.sleep(delay)
                    continue
                if dispatch.stacked:
                    # persisting group failure: bisect — one poisoned
                    # tenant must not take its stacked groupmates down
                    self._event_row(
                        kind="serve_bisect", request_kind=dispatch.kind,
                        tenants=[r.tenant for r in dispatch.requests],
                        fault=fault, error=f"{type(e).__name__}: {e}")
                    mid = len(dispatch.requests) // 2
                    for half in (dispatch.requests[:mid],
                                 dispatch.requests[mid:]):
                        self._run_dispatch(
                            Dispatch(kind=dispatch.kind, key=dispatch.key,
                                     requests=list(half)),
                            window_s=window_s, _depth=_depth + 1)
                    return
                results, error = None, f"{type(e).__name__}: {e}"
                break
        wall = time.monotonic() - t0
        quarantined = error is not None and _depth > 0
        if quarantined:
            self.registry.counter(
                "serve_quarantined_tenants_total",
                help="poisoned tenants isolated by group bisection").inc(
                    len(dispatch.requests), kind=dispatch.kind)
        self.registry.counter(
            "serve_dispatches_total",
            help="scheduler dispatch groups executed").inc(
                1, kind=dispatch.kind, mode=mode)
        self.registry.counter(
            "serve_dispatch_tenants_total",
            help="tenant slots executed across all dispatches").inc(
                len(dispatch.requests), mode=mode)
        self.registry.histogram(
            "serve_dispatch_seconds", help="dispatch group wall seconds",
            unit="seconds", buckets=_LATENCY_BUCKETS).observe(
                wall, kind=dispatch.kind, mode=mode)
        self._event_row(kind="serve_dispatch", request_kind=dispatch.kind,
                        mode=mode, tenants=[r.tenant for r in
                                            dispatch.requests],
                        wall_s=round(wall, 4),
                        error=error)
        if error is None:
            # per-tenant cost attribution (telemetry.costs): the
            # dispatched program's HLO flops split across its slots
            self._attribute_tenant_flops(dispatch.requests, mode)
        now = time.monotonic()
        # journal the completions BEFORE any waiter can observe them: a
        # kill between delivery and the done-record would otherwise
        # replay tickets whose results were already collected
        self._mark_done(dispatch.requests,
                        "done" if error is None else "failed")
        violations = 0
        with self._done:
            for i, req in enumerate(dispatch.requests):
                if error is None:
                    entry = {"status": "done", "result": results[i],
                             "mode": mode}
                else:
                    entry = {"status": "failed", "error": error,
                             "mode": mode}
                if quarantined:
                    entry["quarantined"] = True
                entry["done_s"] = round(now, 4)   # TTL-eviction stamp
                self._results[req.ticket] = entry
                self._completed += 1
                self.registry.histogram(
                    "serve_request_seconds",
                    help="submit-to-completion latency", unit="seconds",
                    buckets=_LATENCY_BUCKETS).observe(
                        now - req.submitted_s, kind=req.kind)
                if error is not None:
                    self.registry.counter(
                        "serve_requests_failed_total",
                        help="requests whose dispatch raised").inc(
                            1, kind=req.kind)
                if self._ticket_spans(req, mode=mode,
                                      stack_k=len(dispatch.requests),
                                      dispatch_start=t0, wall=wall,
                                      now=now, window_s=window_s,
                                      error=error,
                                      quarantined=quarantined):
                    violations += 1
                self._event_row(kind="serve_tenant", ticket=req.ticket,
                                tenant=req.tenant, request_kind=req.kind,
                                mode=mode, quarantined=quarantined or None,
                                latency_s=round(now - req.submitted_s, 4),
                                error=error)
            self._evict_results_locked(now)
            self._done.notify_all()
        if self._controller is not None:
            # the controller's error signal: this dispatch's share of
            # the SLO counter (the PR 15 burn rule's numerator) folds
            # into its group's window — shrink on burn, grow on clean
            self._controller.observe_dispatch(
                (dispatch.kind, dispatch.key), violations,
                len(dispatch.requests))

    def _mark_done(self, reqs: Sequence[Request], status: str) -> None:
        """Journal the completions (one fsync for the group) so a restart
        never re-runs a resolved ticket."""
        tickets = [r.ticket for r in reqs]
        self.journal.record_done(tickets, status)
        with self._lock:
            self._unfinished.difference_update(tickets)

    def _evict_results_locked(self, now: float) -> None:
        """Collected-or-TTL retention (caller holds ``self._done``):
        ``wait`` consumes its own entry; what nobody collects leaves by
        TTL (``results_ttl_s``) or, as the backstop, by the retention
        cap — counted, and with the idempotency window closed, so a
        long-lived service cannot leak its results table."""
        evicted = 0
        if self.results_ttl_s > 0:
            expired = [t for t, e in self._results.items()
                       if now - e.get("done_s", now) > self.results_ttl_s]
            for t in expired:
                self._results.pop(t)
                self._drop_idem_locked(t)
                evicted += 1
        # bound the table for fire-and-forget submitters (waiters
        # consume their own entries): evict oldest-first
        while len(self._results) > RESULT_RETENTION:
            t = next(iter(self._results))
            self._results.pop(t)
            self._drop_idem_locked(t)
            evicted += 1
        if evicted:
            self.registry.counter(
                "serve_results_evicted_total",
                help="uncollected results evicted (TTL or retention "
                     "cap)").inc(evicted)

    def _resolve_failed(self, reqs: Sequence[Request], error: str,
                        journal_done: bool = True,
                        resumable: bool = False) -> None:
        """Resolve ``reqs`` as failed WITHOUT executing (deadline expiry,
        drain, shutdown races).  ``journal_done=False`` leaves the
        tickets unfinished in the journal — the drain path's contract:
        the waiter gets a typed resumable failure now, and a restarted
        service replays the ticket."""
        now = time.monotonic()
        if journal_done:
            # journaled before any waiter observes it, like _run_dispatch
            self._mark_done(reqs, "failed")
        with self._done:
            for req in reqs:
                entry = {"status": "failed", "error": error, "mode": "none",
                         "done_s": round(now, 4)}
                if resumable:
                    entry["resumable"] = True
                self._results[req.ticket] = entry
                self._completed += 1
                self.registry.counter(
                    "serve_requests_failed_total",
                    help="requests whose dispatch raised").inc(
                        1, kind=req.kind)
                self._event_row(kind="serve_tenant", ticket=req.ticket,
                                tenant=req.tenant, request_kind=req.kind,
                                mode="none", error=error,
                                resumable=resumable or None)
            self._evict_results_locked(now)
            self._done.notify_all()

    def _ticket_spans(self, req: Request, *, mode: str, stack_k: int,
                      dispatch_start: float, wall: float, now: float,
                      window_s: float, error,
                      quarantined: bool = False) -> bool:
        """One completed ticket's structured span family + the
        ``serve_ticket_*`` histograms + the SLO counter; returns whether
        the ticket violated the SLO (the adaptive controller's per-
        dispatch error signal).

        Breakdown contract (asserted in ``tests/test_fleet.py``): the
        root ``serve.ticket`` span's duration is EXACTLY the latency the
        ``serve_request_seconds`` histogram observed, and the four child
        durations sum to it — queue (backlog wait before the batching
        window's share), window (``min(pre-dispatch wait, window_s)`` —
        a ticket that arrived mid-window only sat out the remainder),
        dispatch (its group's execution wall), publish (result-delivery
        residual).

        Trace adoption (fleet tracing): the family's ``trace_id`` is the
        PROPAGATED id when the submit carried one (a pool-forwarded
        ticket), the ticket id otherwise — and the root records the far
        side of the hop as ``remote_parent`` (the front's relay span id;
        a remote link, not ``parent``, because span ids are only unique
        per process).  The resolved family also feeds tail-based
        exemplar retention and the slowest-traces panel."""
        latency = now - req.submitted_s
        pre_dispatch = max(0.0, dispatch_start - req.submitted_s)
        window_wait = min(max(0.0, float(window_s)), pre_dispatch)
        queue_wait = pre_dispatch - window_wait
        publish = max(0.0, latency - pre_dispatch - wall)
        start = req.submitted_s - self._t0
        root = next(self._span_ids)
        common = dict(trace_id=req.trace_id or req.ticket, process=0,
                      tenant=req.tenant, request_kind=req.kind)
        rows = [dict(kind="span", span="serve.ticket", span_id=root,
                     start_s=round(start, 6),
                     seconds=round(latency, 6), mode=mode,
                     stack_k=stack_k, error=error, ticket=req.ticket,
                     remote_parent=req.parent_span, **common)]
        for name, child_start, dur, extra in (
                ("serve.ticket.queue", start, queue_wait, {}),
                ("serve.ticket.window", start + queue_wait, window_wait,
                 {}),
                ("serve.ticket.dispatch", dispatch_start - self._t0, wall,
                 {"stack_k": stack_k,
                  "per_tenant_s": round(wall / max(1, stack_k), 6)}),
                ("serve.ticket.publish", now - self._t0 - publish, publish,
                 {})):
            rows.append(dict(kind="span", span=name,
                             span_id=next(self._span_ids), parent=root,
                             start_s=round(child_start, 6),
                             seconds=round(dur, 6), **common, **extra))
        for row in rows:
            self._event_row(**row)
        h = self.registry.histogram
        h("serve_ticket_queue_seconds",
          help="per-ticket backlog wait before the batching window",
          unit="seconds", buckets=_LATENCY_BUCKETS).observe(
            queue_wait, kind=req.kind)
        h("serve_ticket_window_seconds",
          help="per-ticket share of the batching window sat out",
          unit="seconds", buckets=_LATENCY_BUCKETS).observe(
            window_wait, kind=req.kind)
        h("serve_ticket_dispatch_seconds",
          help="per-ticket dispatch-group execution wall",
          unit="seconds", buckets=_LATENCY_BUCKETS).observe(
            wall, kind=req.kind)
        violated = (self.slo_p95_ms > 0
                    and latency * 1000.0 > self.slo_p95_ms)
        if violated:
            self.registry.counter(
                "serve_slo_violations_total",
                help="requests whose latency exceeded the --slo-p95-ms "
                     "target").inc(1, kind=req.kind)
        self._retain_exemplar(req, rows, latency=latency, mode=mode,
                              violated=violated, quarantined=quarantined,
                              error=error)
        return violated   # a violating ticket burns: controller signal

    def _retain_exemplar(self, req: Request, rows: List[dict], *,
                         latency: float, mode: str, violated: bool,
                         quarantined: bool, error) -> None:
        """Tail-based retention: a ticket that violated the SLO, failed,
        or was quarantined keeps its FULL span family in the bounded
        exemplars ring; every other ticket keeps only its root span.
        Also maintains the slowest-traces panel (stats ``slowest``) —
        caller holds the service lock, so the list update is safe."""
        reasons = [r for r, on in (("slo", violated),
                                   ("quarantined", quarantined),
                                   ("failed", error is not None)) if on]
        spans = rows if reasons else rows[:1]
        record = {"ticket": req.ticket,
                  "trace_id": req.trace_id or req.ticket,
                  "reason": ",".join(reasons) or "root",
                  "seconds": round(latency, 6), "kind": req.kind,
                  "tenant": req.tenant,
                  "spans": [{k: v for k, v in row.items()
                             if v is not None} for row in spans]}
        # rides the writer like the span rows themselves: retention is
        # one appended line off the dispatch thread, never an fsync
        self.writer.submit(self._exemplars.add, record)
        self._slowest.append(
            {"ticket": req.ticket, "trace_id": req.trace_id or req.ticket,
             "seconds": round(latency, 6), "kind": req.kind,
             "tenant": req.tenant, "mode": mode,
             "slo_violation": violated, "failed": error is not None,
             "quarantined": quarantined})
        self._slowest.sort(key=lambda e: -e["seconds"])
        del self._slowest[SLOWEST_KEPT:]

    # -- executors -------------------------------------------------------

    def _note_program(self, kind: str, signature) -> None:
        self._programs.add((kind,) + tuple(signature))

    def _probe_flops(self, name: str, jitted, args, kwargs=None) -> float:
        """HLO flops of one dispatched program (``telemetry.costs`` via
        the AOT memo: the first probe per program lowers against abstract
        shapes — served by the persistent cache the real dispatch just
        filled — later probes are memo hits).  Returns 0.0 when the cost
        plane is off or the backend reports no flops; fail-soft — cost
        attribution must never fail a dispatch."""
        try:
            from ..telemetry import costs

            if not costs.enabled():
                return 0.0
            from ..utils.aot import _abstract, aot_compile

            kwargs = {k: _abstract(v) for k, v in (kwargs or {}).items()}
            aot_compile(name, jitted, args, kwargs)
            return costs.entry_flops(name) or 0.0
        except Exception:
            return 0.0

    def _attribute_tenant_flops(self, reqs: Sequence["Request"],
                                mode: str) -> None:
        """Split the completed dispatch's program flops evenly across its
        tenant slots (``serve_tenant_flops_total`` — the per-tenant cost
        view the stats/billing story reads).  A stacked dispatch amortizes
        ONE program across K tenants, which is exactly the counter's
        point."""
        flops, self._dispatch_flops = self._dispatch_flops, 0.0
        if not flops or self._warming:
            return
        per_tenant = flops / max(1, len(reqs))
        c = self.registry.counter(
            "serve_tenant_flops_total",
            help="HLO flops attributed to each tenant (stacked dispatch "
                 "flops split across its K slots)")
        for req in reqs:
            c.inc(per_tenant, tenant=req.tenant, kind=req.kind, mode=mode)

    def _exec_fixpoint_density(self, dispatch: Dispatch) -> List[dict]:
        """The fixpoint-density sweep (``setups/fixpoint_density.py``'s
        compute) for 1..K tenants: same per-batch PRNG keying as the solo
        script, stacked across tenants on the leading axis."""
        import jax
        import jax.numpy as jnp

        from ..engine import fixpoint_density, fixpoint_density_stacked
        from ..init import init_population
        from ..setups.common import STANDARD_VARIANTS
        from .tenant import init_population_stacked

        reqs = dispatch.requests
        k = len(reqs)
        trials = int(reqs[0].params["trials"])
        batch = int(reqs[0].params["batch"])
        keys = [jax.random.key(int(r.params.get("seed", 0))) for r in reqs]
        eps = jnp.asarray([float(r.params.get("epsilon", 1e-4))
                           for r in reqs], jnp.float32)
        variants = STANDARD_VARIANTS[:2]  # WW + Agg, like the reference
        per_variant = []
        for i, (_name, topo) in enumerate(variants):
            totals = jnp.zeros((k, 5), jnp.int32)
            done = 0
            while done < trials:
                n = min(batch, trials - done)
                bkeys = [jax.random.fold_in(jax.random.fold_in(kk, i), done)
                         for kk in keys]
                if k > 1:
                    pops = init_population_stacked(topo, jnp.stack(bkeys), n)
                    totals = totals + fixpoint_density_stacked(topo, pops,
                                                               eps)
                    self._dispatch_flops += self._probe_flops(
                        f"serve.cost.fixpoint_density.{topo.variant}"
                        f".k{k}.n{n}",
                        fixpoint_density_stacked, (topo, pops, eps))
                else:
                    # the python-float epsilon keeps the solo fallback on
                    # the EXACT program the setups dispatch (a weak-typed
                    # scalar), so it shares their warm cache entries
                    pop = init_population(topo, bkeys[0], n)
                    eps_solo = float(reqs[0].params.get("epsilon", 1e-4))
                    totals = totals + fixpoint_density(
                        topo, pop, eps_solo)[None]
                    self._dispatch_flops += self._probe_flops(
                        f"serve.cost.fixpoint_density.{topo.variant}"
                        f".solo.n{n}",
                        fixpoint_density, (topo, pop, eps_solo))
                self._note_program(dispatch.kind, (str(topo), k, n))
                done += n
            per_variant.append(np.asarray(totals))
        names = [name for name, _ in variants]
        return [{"variant_names": names,
                 "counters": [v[t].tolist() for v in per_variant]}
                for t in range(k)]

    def _exec_soup(self, dispatch: Dispatch) -> List[dict]:
        """A homogeneous soup run (seed -> evolve -> count) for 1..K
        tenants; the stacked spelling dispatches ``serve.tenant``'s
        vmapped twins and streams per-tenant telemetry/lineage rows."""
        import jax

        from ..soup import count, evolve, seed
        from ..telemetry.dynamics import DEFAULT_EDGE_CAPACITY, seed_lineage
        from .tenant import (evolve_stacked_donated, seed_stacked,
                             stack_tenants, unstack_tenants)

        reqs = dispatch.requests
        k = len(reqs)
        params0 = reqs[0].params
        cfg = _soup_config_from_params(params0)
        gens = int(params0.get("generations", 10))
        lineage = bool(params0.get("lineage", False))
        keys = [jax.random.key(int(r.params.get("seed", 0))) for r in reqs]
        if k > 1:
            import jax.numpy as jnp

            states = seed_stacked(cfg, jnp.stack(keys))
            kw = {"generations": gens, "metrics": True}
            if lineage:
                kw["lineage"] = True
                kw["lineage_state"] = stack_tenants(
                    [seed_lineage(cfg.size) for _ in range(k)])
                kw["lineage_capacity"] = DEFAULT_EDGE_CAPACITY
            out = evolve_stacked_donated(cfg, states, **kw)
            finals = unstack_tenants(out[0], k)
            metrics = unstack_tenants(out[1], k)
            ltriples = (unstack_tenants(out[2], k) if lineage else
                        [None] * k)
            from ..utils.aot import abstract_stacked_soup_state

            self._dispatch_flops += self._probe_flops(
                f"serve.cost.soup.k{k}.n{cfg.size}.g{gens}"
                + (".lineage" if lineage else ""),
                evolve_stacked_donated,
                (cfg, abstract_stacked_soup_state(cfg, k)), kw)
        else:
            kw = {"generations": gens, "metrics": True}
            if lineage:
                kw["lineage"] = True
                kw["lineage_state"] = seed_lineage(cfg.size)
                kw["lineage_capacity"] = DEFAULT_EDGE_CAPACITY
            out = evolve(cfg, seed(cfg, keys[0]), **kw)
            finals, metrics = [out[0]], [out[1]]
            ltriples = [out[2]] if lineage else [None]
            from ..utils.aot import abstract_soup_state

            self._dispatch_flops += self._probe_flops(
                f"serve.cost.soup.solo.n{cfg.size}.g{gens}"
                + (".lineage" if lineage else ""),
                evolve, (cfg, abstract_soup_state(cfg)), kw)
        self._note_program(dispatch.kind,
                           (repr(cfg), gens, lineage, k))
        results = []
        for t, req in enumerate(reqs):
            counts = np.asarray(count(cfg, finals[t]))
            m = metrics[t]
            row = {"counters": counts.tolist(),
                   "final_time": int(np.asarray(finals[t].time)),
                   "next_uid": int(np.asarray(finals[t].next_uid)),
                   "metrics": {
                       "generations": int(np.asarray(m.generations)),
                       "actions": np.asarray(m.actions).tolist(),
                       "loss_sum": float(np.asarray(m.loss_sum))}}
            if bool(req.params.get("return_state", True)) \
                    and cfg.size * cfg.topo.num_weights <= 262144:
                row["weights"] = np.asarray(finals[t].weights).tolist()
                row["uids"] = np.asarray(finals[t].uids).tolist()
            if lineage:
                self._lineage_row(req, cfg, gens, ltriples[t])
            results.append(row)
        return results

    def _lineage_row(self, req: Request, cfg, gens: int, ltriple) -> None:
        """Per-tenant replication-dynamics window row, tenant-labeled,
        appended to the service's lineage.jsonl through the writer."""
        if self._warming:
            return   # throwaway warm tenants must not pollute the stream
        from ..telemetry.dynamics import DEFAULT_EDGE_CAPACITY, window_record

        lin, win, stats = ltriple
        row = window_record(0, gens, jax_device_get(win),
                            jax_device_get(stats), DEFAULT_EDGE_CAPACITY,
                            next_pid=int(np.asarray(lin.next_pid)))
        row["tenant"] = req.tenant
        row["ticket"] = req.ticket

        def append():
            if self._lineage is None:
                self._lineage = open(os.path.join(self.root,
                                                  "lineage.jsonl"), "a")
            self._lineage.write(json.dumps(row) + "\n")
            self._lineage.flush()

        self.writer.submit(append)

    def warm(self, kind: str, params: dict,
             widths: Optional[Sequence[int]] = None) -> None:
        """Pre-dispatch (compile or cache-deserialize) the executor for
        ``(kind, params)`` at each stack width in ``widths`` (default: the
        service's ``max_stack`` and solo) with throwaway seeds, so the
        first real tenants of that spelling only execute.  Warm dispatches
        do not touch the serve metrics; they DO count into
        ``distinct_programs`` (the load bench snapshots around its serving
        phase)."""
        widths = sorted(set(widths or (self.max_stack, 1)))
        self._warming = True   # no lineage/event rows for warm tenants
        try:
            # block autotuner: tune the spelling's lane blocks BEFORE the
            # warm dispatch compiles, so the cached executables are the
            # tuned programs (memo-hit from tuning.json on restart;
            # SRNN_NO_AUTOTUNE=1 is the A/B oracle).  Fail-soft host-side.
            if kind == "soup":
                try:
                    from .. import autotune

                    autotune.autotune_for_run(
                        _soup_config_from_params(params))
                except Exception:
                    pass
            for k in widths:
                reqs = [Request(ticket=f"warm{i:03d}", kind=kind,
                                params=dict(params), tenant=f"warm{i:03d}",
                                submitted_s=time.monotonic())
                        for i in range(k)]
                d = Dispatch(kind=kind, key=("warm",), requests=reqs)
                if kind == "fixpoint_density":
                    self._exec_fixpoint_density(d)
                elif kind == "soup":
                    self._exec_soup(d)
                else:
                    raise ValueError(f"unknown request kind {kind!r}")
        finally:
            self._warming = False

    # -- telemetry sinks -------------------------------------------------

    def _event_row(self, **fields) -> None:
        fields.setdefault("t", round(time.monotonic() - self._t0, 4))
        fields = {k: v for k, v in fields.items() if v is not None}

        def append():
            self._events.write(json.dumps(fields) + "\n")
            self._events.flush()

        self.writer.submit(append)

    def write_metrics(self) -> str:
        """Atomically publish metrics.prom in the service root (riding the
        writer so it lands after the rows it summarizes)."""
        path = os.path.join(self.root, "metrics.prom")
        self.writer.submit(self.registry.write_textfile, path)
        return path

    def stats(self) -> dict:
        """Host-side snapshot for the ``stats`` op / load bench / watch
        console; ``slo`` carries the target, the violation count, and a
        conservative measured p95 (histogram-bucket upper bound)."""
        with self._lock:
            done = self._completed
            depth = len(self._pending)
            programs = len(self._programs)
            slowest = [dict(e) for e in self._slowest]
        violations = sum(
            v for _suffix, v in self.registry.counter(
                "serve_slo_violations_total").samples())
        p95 = self.registry.histogram(
            "serve_request_seconds",
            help="submit-to-completion latency", unit="seconds",
            buckets=_LATENCY_BUCKETS).quantile(0.95)
        alerts = None
        if self._live_engine is not None:
            alerts = {"active": self._live_engine.active(),
                      "fired": self._counter_total("soup_alerts_total")}
        if self._controller is not None:
            dispatch = self._controller.snapshot()
            dispatch["fair_tenants"] = self.fair_tenants
        else:
            dispatch = {"adaptive": False}
        return {"completed": done, "queue_depth": depth,
                "dispatch": dispatch,
                "distinct_programs": programs,
                "uptime_s": round(time.monotonic() - self._t0, 2),
                "slo": {
                    "target_p95_ms": self.slo_p95_ms or None,
                    "violations": int(violations),
                    "p95_ms": round(p95 * 1000.0, 3)
                    if p95 is not None else None,
                },
                "self_healing": self._self_healing_stats(),
                "slowest": slowest,
                "alerts": alerts,
                "metrics": self.registry.rows()}

    def _counter_total(self, name: str) -> int:
        return int(sum(v for _suffix, v in
                       self.registry.counter(name).samples()))

    def _self_healing_stats(self) -> dict:
        """The recovery-ladder snapshot the watch console's ``--service``
        view renders: journal depth, replay/quarantine/admission
        counters."""
        with self._lock:
            unfinished = len(self._unfinished)
            replayed = self._replayed
        return {"journal_unfinished": unfinished,
                "replayed": replayed,
                "quarantined": self._counter_total(
                    "serve_quarantined_tenants_total"),
                "dispatch_retries": self._counter_total(
                    "serve_dispatch_retries_total"),
                "overload_rejections": self._counter_total(
                    "serve_overload_rejections_total"),
                "deadline_expirations": self._counter_total(
                    "serve_deadline_expirations_total"),
                "results_evicted": self._counter_total(
                    "serve_results_evicted_total"),
                "max_queue": self.max_queue or None}

    def fail_pending(self, reason: str, resumable: bool = False) -> int:
        """Resolve every still-queued request as failed (shutdown/drain
        path: a submit that raced the dispatcher's final drain must not
        leave its waiter blocked until timeout).  The tickets stay
        UNFINISHED in the journal either way — a restarted service
        replays them; ``resumable=True`` (the SIGTERM drain) says so in
        the typed response, so the client resubmits-or-waits after the
        restart instead of treating the failure as final.  Returns how
        many."""
        with self._done:
            self._draining = True   # submit() refuses from here on
            stranded, self._pending = self._pending, []
            self._work.notify_all()   # unblock an idle dispatcher
        if stranded:
            self._resolve_failed(stranded, reason, journal_done=False,
                                 resumable=resumable)
        return len(stranded)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._live_profiler is not None:
            # final cumulative profile rewrite from the (frozen) tables
            try:
                self._live_profiler.update_gauges(self.registry)
                self._live_profiler.write_files(self.root)
            except OSError:
                pass
        self.registry.write_textfile(os.path.join(self.root,
                                                  "metrics.prom"))
        if self._own_writer:
            self.writer.close()
        else:
            # a SHARED writer stays open for its other producers, but any
            # queued row jobs reference the files closed below — drain
            # them first or they would latch a WriterError on everyone
            self.writer.flush()
        self._events.close()
        self.journal.close()
        if self._lineage is not None:
            self._lineage.close()
        if self._live_history is not None:
            self._live_history.close()
        if self._live_capture is not None:
            self._live_capture.close()

    def __enter__(self) -> "ExperimentService":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


def jax_device_get(tree):
    """Small alias so executor rows pull device values exactly once."""
    import jax

    return jax.device_get(tree)
