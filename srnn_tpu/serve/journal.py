"""Durable ticket journal: the service's crash-recovery log.

The soup inside the service is self-healing by construction (divergent
and collapsed particles respawn every generation); this module makes the
service *around* it hold the same contract — Chang & Lipson's quine
framing: does the system reproduce its own state after perturbation?
Concretely: every admitted submit is APPENDED AND FSYNCED here before
its ticket id is acknowledged to the client, every completion appends a
matching ``done`` record, and a restarted service REPLAYS every submit
without a matching done.  A ``kill -9`` mid-load therefore loses no
admitted work, and because the executors are deterministic functions of
the journaled params, the replayed results are bitwise-equal to an
uninterrupted run (asserted end-to-end in
``tests/test_serve_resilience.py`` and the ``serve_chaos_smoke`` CI
group).

Format: JSON-lines, one record per line::

  {"e": "submit", "ticket": "t000001", "kind": "soup", "params": {...},
   "tenant": "a", "key": "idem-1", "deadline_wall": null, "wall": ...}
  {"e": "done", "ticket": "t000001", "status": "done"}
  {"e": "mark", "next_ticket": 9}

``mark`` is the ticket-counter watermark the recovery compaction writes:
without it, compacting a fully-finished journal would discard every
issued id and a later restart would hand out ``t000001`` again —
colliding with earlier runs' telemetry rows and with stale clients
still holding old tickets.

Durability discipline: a log APPENDS with per-record fsync (the
tmp+fsync+rename sequence of ``utils.atomicio`` is for whole-file
publish, not appends); the atomic-publish half lives in the recovery
compaction, which rewrites the journal down to its unfinished suffix via
:func:`~srnn_tpu.utils.atomicio.atomic_write_text` — a crash
mid-compaction leaves the complete old journal, never a torn new one.
A torn TAIL (the one partial line a kill -9 mid-append can leave) is
skipped on read and counted; its record was by definition never
acknowledged, so skipping it is exactly the admission contract.

``deadline_wall`` is the wall-clock absolute deadline (submit wall time
plus the client's ``deadline_s``): monotonic stamps do not survive a
process, so replay re-derives the remaining budget from the wall clock —
a ticket whose deadline elapsed while the service was down expires at
replay instead of occupying a stack slot.
"""

import json
import os
import threading
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

from ..utils.atomicio import atomic_write_text

JOURNAL_NAME = "journal.jsonl"


class JournalEntry(NamedTuple):
    """One journaled admission (the replayable half of a ticket)."""
    ticket: str
    kind: str
    params: dict
    tenant: str
    key: Optional[str]            # client idempotency key, if any
    deadline_wall: Optional[float]  # absolute wall-clock deadline
    wall: float                   # wall-clock admission stamp
    #: propagated trace context (fleet tracing): the distributed trace
    #: this admission belongs to and the parent span on the far side of
    #: the hop.  None on pre-tracing journals — replay behavior is
    #: identical either way (the fields only label telemetry rows).
    trace_id: Optional[str] = None
    parent_span: Optional[int] = None
    #: record fields THIS reader does not know (a journal written by a
    #: newer version) — preserved verbatim through recovery compaction,
    #: so downgrade-then-upgrade never strips them
    extra: Optional[dict] = None


#: the submit-record keys this reader interprets; anything else rides in
#: ``JournalEntry.extra`` and survives compaction untouched
_KNOWN_SUBMIT_KEYS = frozenset({
    "e", "ticket", "kind", "params", "tenant", "key", "deadline_wall",
    "wall", "trace_id", "parent_span"})


def _submit_row(e: JournalEntry) -> dict:
    """One entry back to its wire form (compaction): the fixed fields,
    the trace context only when present (pre-tracing journals compact
    byte-identically), and every unknown field merged back in."""
    row = {"e": "submit", "ticket": e.ticket, "kind": e.kind,
           "params": e.params, "tenant": e.tenant, "key": e.key,
           "deadline_wall": e.deadline_wall, "wall": e.wall}
    if e.trace_id is not None:
        row["trace_id"] = e.trace_id
    if e.parent_span is not None:
        row["parent_span"] = e.parent_span
    if e.extra:
        row.update(e.extra)
    return row


def _ticket_number(ticket: str) -> int:
    """The numeric part of a ``t%06d`` ticket id (0 for foreign ids)."""
    if ticket.startswith("t") and ticket[1:].isdigit():
        return int(ticket[1:])
    return 0


def read_journal(path: str) -> Tuple[List[JournalEntry], int, int]:
    """Read ``path`` -> (unfinished entries in admission order,
    torn/corrupt line count, next free ticket number).

    A line that fails to parse is skipped and counted — the torn tail a
    kill -9 mid-append leaves is the expected case; a torn line anywhere
    else still only loses that one record.  ``done`` records without a
    surviving submit (compacted away earlier) are ignored.
    """
    entries: Dict[str, JournalEntry] = {}
    done: Dict[str, str] = {}
    order: List[str] = []
    torn = 0
    max_ticket = 0
    if not os.path.exists(path):
        return [], 0, 1
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
                event = row["e"]
                if event == "mark":
                    # counter watermark: ids up to next_ticket-1 were
                    # issued before the last compaction
                    max_ticket = max(max_ticket,
                                     int(row.get("next_ticket", 1)) - 1)
                    continue
                ticket = row["ticket"]
            except (ValueError, KeyError, TypeError):
                torn += 1
                continue
            max_ticket = max(max_ticket, _ticket_number(str(ticket)))
            if event == "submit":
                try:
                    unknown = {k: v for k, v in row.items()
                               if k not in _KNOWN_SUBMIT_KEYS}
                    trace_id = row.get("trace_id")
                    parent_span = row.get("parent_span")
                    entry = JournalEntry(
                        ticket=str(ticket), kind=str(row["kind"]),
                        params=dict(row.get("params") or {}),
                        tenant=str(row.get("tenant") or ticket),
                        key=row.get("key"),
                        deadline_wall=row.get("deadline_wall"),
                        wall=float(row.get("wall", 0.0)),
                        trace_id=(None if trace_id is None
                                  else str(trace_id)),
                        parent_span=(None if parent_span is None
                                     else int(parent_span)),
                        extra=unknown or None)
                except (ValueError, KeyError, TypeError):
                    torn += 1
                    continue
                if ticket not in entries:
                    order.append(str(ticket))
                entries[str(ticket)] = entry
            elif event == "done":
                done[str(ticket)] = str(row.get("status", "done"))
    unfinished = [entries[t] for t in order if t not in done]
    return unfinished, torn, max_ticket + 1


class TicketJournal:
    """Append-only fsynced journal handle for one service root.

    Thread-safe: admissions append from handler threads (under the
    service's admission lock) while completions append from the dispatch
    thread — every append takes the journal's own lock and fsyncs before
    returning, so a record that has been acknowledged is durable."""

    def __init__(self, root: str):
        self.path = os.path.join(root, JOURNAL_NAME)
        self._lock = threading.Lock()
        self._f = open(self.path, "a", encoding="utf-8")

    # -- appends (durable before return) --------------------------------

    def _append(self, rows: Sequence[dict]) -> None:
        payload = "".join(json.dumps(r) + "\n" for r in rows)
        with self._lock:
            self._f.write(payload)
            self._f.flush()
            os.fsync(self._f.fileno())

    def record_submit(self, *, ticket: str, kind: str, params: dict,
                      tenant: str, key: Optional[str] = None,
                      deadline_wall: Optional[float] = None,
                      wall: float, trace_id: Optional[str] = None,
                      parent_span: Optional[int] = None) -> None:
        row = {"e": "submit", "ticket": ticket, "kind": kind,
               "params": params, "tenant": tenant, "key": key,
               "deadline_wall": deadline_wall, "wall": wall}
        # trace context only when propagated: traceless submits journal
        # byte-identically to pre-tracing builds
        if trace_id is not None:
            row["trace_id"] = trace_id
        if parent_span is not None:
            row["parent_span"] = parent_span
        self._append([row])

    def record_done(self, tickets: Sequence[str], status: str) -> None:
        """One fsync for a whole dispatch group's completions."""
        if tickets:
            self._append([{"e": "done", "ticket": t, "status": status}
                          for t in tickets])

    # -- recovery --------------------------------------------------------

    def recover(self) -> Tuple[List[JournalEntry], int, int]:
        """Read the journal, COMPACT it down to its unfinished suffix
        (atomic publish — a crash mid-compaction keeps the old file),
        and return ``(unfinished, torn, next_ticket_number)``.  The
        compaction keeps the journal bounded across restarts: finished
        submit/done pairs do not accumulate forever."""
        with self._lock:
            unfinished, torn, next_ticket = read_journal(self.path)
            self._f.close()
            # the watermark leads the compacted file: an idle restart
            # cycle must never reset the counter into reused ids
            atomic_write_text(
                self.path,
                json.dumps({"e": "mark", "next_ticket": next_ticket})
                + "\n"
                + "".join(json.dumps(_submit_row(e)) + "\n"
                          for e in unfinished))
            self._f = open(self.path, "a", encoding="utf-8")
        return unfinished, torn, next_ticket

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()
