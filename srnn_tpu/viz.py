"""Analysis & visualization of run-dir artifacts (reference layer L5).

Reference tools (``visualization.py``, ``line_plots.py``, ``bar_plot.py``,
``box_plots.py``) dill-load experiment artifacts and emit offline plotly
HTML.  This module renders the same views from the npz/json artifacts the
TPU runtime writes, using matplotlib (plotly is not in the image):

  * :func:`plot_latent_trajectories_3d` — per-particle weight trajectories
    embedded by PCA(2) fit on ALL trajectories stacked
    (``visualization.py:109-115``), drawn as 3-D lines with x/y = PCA
    components, z = time, red start / black end markers
    (``visualization.py:119-154``).
  * :func:`plot_latent_trajectories` — 2-D t-SNE scatter of trajectory
    points (``visualization.py:43-93``).
  * :func:`line_plot` — fixpoint-rate-vs-sweep curves from
    ``all_data``/``all_names`` (``line_plots.py:27-81``).
  * :func:`plot_bars` — stacked class-distribution bars from
    ``all_counters`` (``bar_plot.py:28-59``).
  * :func:`plot_box` — time-to-vergence / time-as-fixpoint boxes per
    perturbation scale (``box_plots.py:28-94``).
  * :func:`search_and_apply` — recursive walker that renders every known
    artifact that doesn't have an output image yet
    (``visualization.py:255-275``), CLI ``python -m srnn_tpu.viz -i <dir>``.

Trajectory views are emitted twice per artifact: a static PNG and an
interactive, dependency-free HTML (``viz_html.py``) — the stand-in for the
reference's offline plotly HTML output.

Soup trajectories are split at uid changes, so each respawned particle gets
its own line — the equivalent of the reference's per-uid
``historical_particles`` registry (``soup.py:37-43``).
"""

import argparse
import os
from typing import Dict, List, Optional, Sequence

import matplotlib

matplotlib.use("Agg")

import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402

from .experiment import load_artifact  # noqa: E402
from .ops.predicates import CLASS_NAMES  # noqa: E402

CLASS_COLORS = ("#d62728", "#1f77b4", "#2ca02c", "#9467bd", "#7f7f7f")


# ---------------------------------------------------------------------------
# trajectory extraction
# ---------------------------------------------------------------------------


MAX_RENDER_PARTICLES = 2048


def render_columns(n: int, max_particles: int = MAX_RENDER_PARTICLES
                   ) -> np.ndarray:
    """THE deterministic even-stride column subset used everywhere a
    mega-scale population is sampled (renders, packaged trajectory
    samples) — one definition so plots and committed samples can never
    silently diverge."""
    if n <= max_particles:
        return np.arange(n)
    return np.unique(np.linspace(0, n - 1, max_particles).astype(int))


def particle_trajectories(artifact: Dict[str, np.ndarray],
                          max_particles: Optional[int] = None,
                          ) -> List[Dict[str, np.ndarray]]:
    """Artifact -> list of {'trajectory': (T, P), 'time': (T,), 'uid': int}.

    Accepts both artifact shapes the setups write:
      * experiment trajectories: ``{"weights": (T, N, P)}`` — one particle
        per trial column, uid = column index;
      * soup histories: ``{"weights": (G, N, P), "uids": (G, N)}`` — slots
        are split wherever the uid changes (respawn), mirroring
        ``build_from_soup_or_exp`` (``visualization.py:27-40``).

    ``max_particles`` caps the rendered slots by a deterministic even
    stride over the columns — a mega-soup capture holds 1M slots, and a
    plot of 1M lines is neither readable nor computable; ``None`` keeps
    every column (the paper-scale artifacts).
    """
    w = np.asarray(artifact["weights"])
    if w.ndim != 3:
        raise ValueError(f"expected (T, N, P) weights, got {w.shape}")
    t_len, n, _ = w.shape
    uids = np.asarray(artifact["uids"]) if "uids" in artifact else \
        np.broadcast_to(np.arange(n, dtype=np.int64), (t_len, n))
    cols = range(n) if max_particles is None else \
        render_columns(n, max_particles)
    out = []
    for col in cols:
        col_uids = uids[:, col]
        # contiguous segments of constant uid = one particle lifetime
        breaks = np.flatnonzero(np.diff(col_uids) != 0) + 1
        for seg in np.split(np.arange(t_len), breaks):
            traj = w[seg, col]
            finite = np.isfinite(traj).all(axis=-1)
            traj = traj[finite]
            if len(traj) < 1:
                continue
            out.append({
                "trajectory": traj,
                "time": seg[finite].astype(np.int64),
                "uid": int(col_uids[seg[0]]),
            })
    return out


def pca2_fit(stacked: np.ndarray):
    """PCA to 2 components via SVD (replaces the reference's
    ``sklearn.manifold.t_sne.PCA`` import from a private pre-0.22 path,
    ``visualization.py:17``). Returns (mean, (P, 2) components)."""
    mean = stacked.mean(axis=0)
    centered = stacked - mean
    _, _, vt = np.linalg.svd(centered, full_matrices=False)
    return mean, vt[:2].T


# ---------------------------------------------------------------------------
# plots
# ---------------------------------------------------------------------------


def extract_pca(artifact, max_particles: Optional[int] = MAX_RENDER_PARTICLES):
    """Shared per-artifact preprocessing for the 3-D trajectory views:
    -> (trajs, mean, (P, 2) components).  Compute once, render many.
    Renders cap at ``MAX_RENDER_PARTICLES`` deterministically-strided
    slots so mega-scale captures stay plottable."""
    trajs = particle_trajectories(artifact, max_particles=max_particles)
    if not trajs:
        raise ValueError("no finite trajectories to plot")
    mean, comps = pca2_fit(np.vstack([t["trajectory"] for t in trajs]))
    return trajs, mean, comps


def plot_latent_trajectories_3d(artifact, out_path: str, title: str = "",
                                extracted=None) -> str:
    """3-D PCA trajectory plot (``plot_latent_trajectories_3D``,
    ``visualization.py:109-154``): PCA fit on all trajectories stacked,
    per-particle lines, red start / black end markers."""
    trajs, mean, comps = extracted if extracted is not None else extract_pca(artifact)
    fig = plt.figure(figsize=(9, 8))
    ax = fig.add_subplot(projection="3d")
    cmap = plt.get_cmap("tab20")
    for i, t in enumerate(trajs):
        xy = (t["trajectory"] - mean) @ comps
        z = t["time"]
        ax.plot(xy[:, 0], xy[:, 1], z, lw=1.0, color=cmap(i % 20), alpha=0.8)
        ax.scatter(*xy[0], z[0], color="red", s=14)      # start marker
        ax.scatter(*xy[-1], z[-1], color="black", s=14)  # end marker
    ax.set_xlabel("PCA 1")
    ax.set_ylabel("PCA 2")
    ax.set_zlabel("time")
    ax.set_title(title or "weight-space trajectories (PCA)")
    fig.savefig(out_path, dpi=110, bbox_inches="tight")
    plt.close(fig)
    return out_path


def plot_latent_trajectories(artifact, out_path: str, title: str = "",
                             perplexity: float = 12.0) -> str:
    """2-D t-SNE scatter of all trajectory points, colored per particle
    (``plot_latent_trajectories``, ``visualization.py:43-93``)."""
    from sklearn.manifold import TSNE

    # t-SNE is ~quadratic in POINTS (= particles x frames), so cap the
    # particle count so the stacked rows stay bounded — a mega-scale
    # capture would otherwise hang the embedding even after the generic
    # MAX_RENDER_PARTICLES cap
    t_len = np.asarray(artifact["weights"]).shape[0]
    cap = min(MAX_RENDER_PARTICLES, max(8, 20_000 // max(1, t_len)))
    trajs = particle_trajectories(artifact, max_particles=cap)
    stacked = np.vstack([t["trajectory"] for t in trajs])
    perplexity = min(perplexity, max(2.0, (len(stacked) - 1) / 3))
    emb = TSNE(n_components=2, perplexity=perplexity,
               init="pca", random_state=0).fit_transform(stacked)
    fig, ax = plt.subplots(figsize=(8, 7))
    cmap = plt.get_cmap("tab20")
    pos = 0
    for i, t in enumerate(trajs):
        n = len(t["trajectory"])
        seg = emb[pos:pos + n]
        ax.plot(seg[:, 0], seg[:, 1], lw=0.8, color=cmap(i % 20), alpha=0.7)
        pos += n
    ax.set_title(title or "weight-space trajectories (t-SNE)")
    fig.savefig(out_path, dpi=110, bbox_inches="tight")
    plt.close(fig)
    return out_path


def line_plot(all_data: Sequence[dict], all_names: Sequence[str],
              out_path: str, xlabel: str = "trains per self-attack",
              ylabel: str = "fixpoint rate") -> str:
    """Sweep curves (``line_plots.line_plot``, ``line_plots.py:27-81``).
    Each entry contributes its 'ys' (and 'zs' dashed, when present)."""
    fig, ax = plt.subplots(figsize=(8, 5))
    for i, (data, name) in enumerate(zip(all_data, all_names)):
        color = plt.get_cmap("tab10")(i % 10)
        ax.plot(data["xs"], data["ys"], "-o", color=color, label=str(name))
        if "zs" in data:
            ax.plot(data["xs"], data["zs"], "--s", color=color,
                    label=f"{name} (non-zero)")
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    ax.legend(fontsize=7)
    ax.grid(alpha=0.3)
    fig.savefig(out_path, dpi=110, bbox_inches="tight")
    plt.close(fig)
    return out_path


def plot_bars(all_counters: np.ndarray, all_names: Sequence[str],
              out_path: str) -> str:
    """Stacked class-distribution bars (``bar_plot.plot_bars``,
    ``bar_plot.py:28-59``): one bar per experiment, stacked by the 5
    classes."""
    counters = np.atleast_2d(np.asarray(all_counters))
    fig, ax = plt.subplots(figsize=(1.8 + 1.1 * len(counters), 5))
    x = np.arange(len(counters))
    bottom = np.zeros(len(counters), dtype=float)
    for cls in range(counters.shape[1]):
        vals = counters[:, cls].astype(float)
        ax.bar(x, vals, bottom=bottom, color=CLASS_COLORS[cls],
               label=CLASS_NAMES[cls])
        bottom += vals
    ax.set_xticks(x)
    ax.set_xticklabels([str(n)[:28] for n in all_names], rotation=20,
                       ha="right", fontsize=7)
    ax.set_ylabel("count")
    ax.legend(fontsize=7)
    fig.savefig(out_path, dpi=110, bbox_inches="tight")
    plt.close(fig)
    return out_path


def plot_box(data: Dict[str, np.ndarray], out_path: str,
             trials: Optional[int] = None) -> str:
    """Perturbation-robustness boxes (``box_plots.plot_box``,
    ``box_plots.py:28-94``): per scale level, boxplots of time-to-vergence
    and time-as-fixpoint."""
    xs, ys, zs = (np.asarray(data[k]) for k in ("xs", "ys", "zs"))
    scales = sorted(set(xs.tolist()), reverse=True)
    by_scale_y = [ys[xs == s] for s in scales]
    by_scale_z = [zs[xs == s] for s in scales]
    fig, axes = plt.subplots(1, 2, figsize=(12, 5), sharey=True)
    for ax, series, name in zip(axes, (by_scale_y, by_scale_z),
                                ("time to vergence", "time as fixpoint")):
        ax.boxplot(series, tick_labels=[f"{s:.0e}" for s in scales])
        ax.set_xlabel("perturbation scale")
        ax.set_title(name)
        ax.tick_params(axis="x", rotation=45)
    axes[0].set_ylabel("steps")
    fig.savefig(out_path, dpi=110, bbox_inches="tight")
    plt.close(fig)
    return out_path


def plot_histogram(bars_dict_list: Sequence[dict], out_path: str,
                   title: str = "") -> str:
    """Generic categorical count histogram (``visualization.plot_histogram``,
    ``visualization.py:183-206``): one series per dict, counted over its
    'name' categories."""
    fig, ax = plt.subplots(figsize=(6, 4.5))
    cmap = plt.get_cmap("RdYlBu")
    names = []
    for d in bars_dict_list:
        xs = d.get("name", "unnamed")
        names += list(np.atleast_1d(xs))
    cats = sorted(set(names))
    for i, d in enumerate(bars_dict_list):
        xs = np.atleast_1d(d.get("name", "unnamed"))
        counts = [int(np.sum(xs == c)) for c in cats]
        offset = (i - (len(bars_dict_list) - 1) / 2) * 0.8 / max(len(bars_dict_list), 1)
        ax.bar(np.arange(len(cats)) + offset,
               counts, width=0.8 / max(len(bars_dict_list), 1),
               color=cmap(i / max(len(bars_dict_list) - 1, 1)))
    ax.set_xticks(np.arange(len(cats)))
    ax.set_xticklabels(cats, rotation=20, ha="right", fontsize=8)
    ax.set_ylabel("count")
    if title:
        ax.set_title(title)
    fig.savefig(out_path, dpi=110, bbox_inches="tight")
    plt.close(fig)
    return out_path


def line_plot_with_bands(line_dict_list: Sequence[dict], out_path: str,
                         title: str = "") -> str:
    """Generic mean curves with shaded upper/lower bands
    (``visualization.line_plot``, ``visualization.py:209-252``): each dict
    carries 'x', 'main_y', 'upper_y', 'lower_y', and optionally 'name'."""
    fig, ax = plt.subplots(figsize=(8, 5))
    for i, d in enumerate(line_dict_list):
        color = plt.get_cmap("RdYlGn")(i / max(len(line_dict_list) - 1, 1))
        x = np.asarray(d["x"])
        ax.fill_between(x, np.asarray(d["lower_y"]), np.asarray(d["upper_y"]),
                        color=color, alpha=0.4, lw=0)
        ax.plot(x, np.asarray(d["main_y"]), color=color,
                label=str(d.get("name", f"series {i}")))
    ax.legend(fontsize=8)
    ax.grid(alpha=0.3)
    if title:
        ax.set_title(title)
    fig.savefig(out_path, dpi=110, bbox_inches="tight")
    plt.close(fig)
    return out_path


# ---------------------------------------------------------------------------
# run-dir walker
# ---------------------------------------------------------------------------

def _render_traj_views(artifact, run_dir: str, stem: str, title: str = "") -> List[str]:
    """Static PNG + interactive HTML (the reference emits offline plotly
    HTML per artifact, ``visualization.py:119-179``).  Trajectory extraction
    and the PCA fit run once, shared by both renderers."""
    from .viz_html import write_html_trajectories_3d

    extracted = extract_pca(artifact)
    return [
        plot_latent_trajectories_3d(
            artifact, os.path.join(run_dir, stem + ".png"), title=title,
            extracted=extracted),
        write_html_trajectories_3d(
            artifact, os.path.join(run_dir, stem + ".html"), title=title,
            extracted=extracted),
    ]


def _render_trajectories(run_dir: str, path: str) -> List[str]:
    art = load_artifact(path)
    outs = []
    if "weights" in art:  # soup-style single artifact
        outs += _render_traj_views(art, run_dir, "trajectories_3d")
    else:  # per-variant dict of (T, N, P) histories
        for variant in sorted({k.split("/")[0] for k in art}):
            sub = {"weights": art[f"{variant}/__value__"]} \
                if f"{variant}/__value__" in art else {"weights": art[variant]}
            outs += _render_traj_views(
                sub, run_dir, f"trajectories_3d_{variant}", title=variant)
    return outs


def _render_soup(run_dir: str, path: str) -> List[str]:
    return _render_traj_views(load_artifact(path), run_dir, "soup_trajectories_3d")


def _render_sweep(run_dir: str, path: str) -> List[str]:
    data = load_artifact(path)
    names_path = os.path.join(os.path.dirname(path), "all_names")
    names = load_artifact(names_path) if os.path.exists(names_path + ".json") \
        else [f"series {i}" for i in range(len(data))]
    return [line_plot(data, names, os.path.join(run_dir, "sweep.png"))]


def _render_counters(run_dir: str, path: str) -> List[str]:
    counters = load_artifact(path)
    names_path = os.path.join(os.path.dirname(path), "all_names")
    names = load_artifact(names_path) if os.path.exists(names_path + ".json") \
        else [f"exp {i}" for i in range(np.atleast_2d(counters).shape[0])]
    return [plot_bars(counters, names, os.path.join(run_dir, "counters.png"))]


def _render_variation(run_dir: str, path: str) -> List[str]:
    return [plot_box(load_artifact(path), os.path.join(run_dir, "variation_box.png"))]


def _render_mega_curve(run_dir: str, path: str) -> List[str]:
    """Class-count trajectory of a mega run, from the structured event log
    (``config.json`` marks a mega run dir; events carry per-chunk
    ``generation`` + ``counts``).  Homogeneous ``mega_soup`` events hold one
    name->count dict; heterogeneous ``mega_multisoup`` events hold a list of
    per-type 5-class count arrays (ww/agg/rnn — the entry point's fixed
    blend), rendered one panel per type."""
    import json as _json

    events_path = os.path.join(os.path.dirname(path), "events.jsonl")
    if not os.path.exists(events_path):
        return []
    gens, rows = [], []
    with open(events_path) as f:
        for line in f:
            try:
                ev = _json.loads(line)
            except ValueError:
                continue
            if "generation" not in ev or "counts" not in ev:
                continue
            gens.append(ev["generation"])
            rows.append(ev["counts"])
    multi = bool(rows) and isinstance(rows[0], list)
    # always write the marker PNG — even with no counts yet — so the walk
    # stays idempotent; staleness vs the growing events.jsonl is handled by
    # the mtime rule in search_and_apply
    if multi:
        n_types = len(rows[0])
        # per-type panel titles come from the run's own config.json when the
        # entry point recorded them (mega_multisoup writes "type_names");
        # legacy run dirs fall back to the historical fixed blend
        type_names = ("weightwise", "aggregating", "recurrent")
        try:
            recorded = load_artifact(path).get("type_names")
            if recorded:
                type_names = tuple(str(n) for n in recorded)
        except Exception:
            pass
        fig, axes = plt.subplots(1, n_types, figsize=(6 * n_types, 5),
                                 sharex=True)
        axes = [axes] if n_types == 1 else list(axes)
        for t, ax in enumerate(axes):
            for i, name in enumerate(CLASS_NAMES):
                ax.plot(gens, [r[t][i] for r in rows],
                        color=CLASS_COLORS[i], label=name)
            ax.set_title(type_names[t] if t < len(type_names)
                         else f"type {t}")
            ax.set_xlabel("generation")
            ax.grid(alpha=0.3)
        axes[0].set_ylabel("particles")
        axes[0].legend(fontsize=8)
    else:
        fig, ax = plt.subplots(figsize=(9, 5))
        for i, name in enumerate(CLASS_NAMES):
            ax.plot(gens, [r.get(name, 0) for r in rows],
                    color=CLASS_COLORS[i], label=name)
        ax.set_xlabel("generation")
        ax.set_ylabel("particles")
        if gens:
            ax.legend(fontsize=8)
        else:
            ax.set_title("no generation counts logged yet")
        ax.grid(alpha=0.3)
    out = os.path.join(run_dir, "mega_curve.png")
    fig.savefig(out, dpi=110, bbox_inches="tight")
    plt.close(fig)
    return [out]


#: basin label colors for the replication-dynamics panels
#: (telemetry.dynamics.BASIN_NAMES order: fixpoint/drifting/divergent/zero)
BASIN_COLORS = ("tab:green", "tab:blue", "tab:red", "tab:gray")


def _render_dynamics(run_dir: str, path: str) -> List[str]:
    """Replication-dynamics panels of a ``--lineage`` run, from the
    ``lineage.jsonl`` window stream (``telemetry.dynamics``): the fixpoint
    census trajectory (per-type subplots for a multisoup run) and the
    per-window event-edge/birth rates.  Renders the CURRENT (last) epoch,
    like ``report --dynamics``."""
    from .telemetry.dynamics import BASIN_NAMES
    from .telemetry.genealogy import census_trajectory, load_lineage

    epoch = load_lineage(path + ".jsonl")[-1]
    windows = epoch["windows"]
    traj = census_trajectory(windows)
    multi = bool(traj) and any(
        isinstance(v, dict) for row in traj for v in row.values())
    type_names = sorted({k for row in traj for k, v in row.items()
                         if isinstance(v, dict)}) if multi else [None]

    n_panels = len(type_names)
    fig, axes = plt.subplots(1, n_panels + 1,
                             figsize=(6 * (n_panels + 1), 5))
    axes = list(np.atleast_1d(axes))
    gens = [row.get("gen") for row in traj]
    for t, tname in enumerate(type_names):
        ax = axes[t]
        for i, basin in enumerate(BASIN_NAMES):
            if multi:
                ys = [(row.get(tname) or {}).get(basin, 0) for row in traj]
            else:
                ys = [row.get(basin, 0) for row in traj]
            ax.plot(gens, ys, color=BASIN_COLORS[i], label=basin)
        ax.set_title(f"fixpoint census — {tname}" if tname
                     else "fixpoint census")
        ax.set_xlabel("generation")
        ax.set_ylabel("particles")
        ax.grid(alpha=0.3)
        if gens:
            ax.legend(fontsize=8)
        else:
            ax.set_title("no dynamics windows logged yet")

    # event-rate panel: births + recorded/dropped edges per window
    ax = axes[-1]
    wrows = [w for w in windows if w.get("kind") == "window"]
    wg = [w.get("gen_end") for w in wrows]
    for key, label, color in (
            ("births_attack", "attack births", "tab:purple"),
            ("births_respawn", "respawn births", "tab:orange"),
            ("edges_dropped", "edges dropped", "tab:red")):
        ax.plot(wg, [int(w.get(key, 0)) for w in wrows], label=label,
                color=color)
    ax.plot(wg, [len(w.get("edges", ())) for w in wrows],
            label="edges recorded", color="tab:blue")
    ax.set_title("replication events per window")
    ax.set_xlabel("generation")
    ax.set_ylabel("count")
    ax.grid(alpha=0.3)
    if wg:
        ax.legend(fontsize=8)
    out = os.path.join(run_dir, "dynamics.png")
    fig.savefig(out, dpi=110, bbox_inches="tight")
    plt.close(fig)
    return [out]


#: artifact basename -> (renderer(run_dir, artifact_path) -> [outputs],
#:                        output-file marker prefix)
RENDERERS = {
    "trajectorys": (_render_trajectories, "trajectories_3d"),
    "soup": (_render_soup, "soup_trajectories_3d"),
    "all_data": (_render_sweep, "sweep"),
    "all_counters": (_render_counters, "counters"),
    "data": (_render_variation, "variation_box"),
    "config": (_render_mega_curve, "mega_curve"),
    "lineage": (_render_dynamics, "dynamics"),
}


def search_and_apply(directory: str, redo: bool = False,
                     out_dir: Optional[str] = None) -> List[str]:
    """Walk ``directory`` recursively; for every known artifact whose run
    dir has no rendered .png yet (unless ``redo``), render all applicable
    views (``search_and_apply``, ``visualization.py:255-275``).

    Reference-format dill artifacts (``trajectorys.dill`` / ``soup.dill``,
    the exact filenames the reference CLI targets at
    ``visualization.py:255-275``) render too, via the 2019-artifact shim
    loader — a migration path for existing reference result trees.  Because
    such trees may be read-only, ``out_dir`` mirrors the directory
    structure somewhere writable for EVERY render in the walk (renderers
    keep reading their inputs from the source tree); ``None`` renders next
    to each artifact like the reference CLI does.
    """
    import re

    outputs = []
    directory = os.path.normpath(directory)
    for root, _dirs, files in os.walk(directory):
        render_dir = root if out_dir is None else os.path.join(
            out_dir, os.path.relpath(root, directory))
        # done-detection must look where the renders actually go
        rendered = files if render_dir == root else (
            sorted(os.listdir(render_dir)) if os.path.isdir(render_dir)
            else [])
        for f in sorted(files):
            if f not in ("trajectorys.dill", "soup.dill"):
                continue
            stem = f[:-5] + "_ref_trajectories_3d"
            done = all(stem + ext in rendered for ext in (".png", ".html"))
            if done and not redo:
                continue
            from . import reference_artifacts as ref
            try:
                art = ref.trajectory_artifact(
                    ref.load_artifact(os.path.join(root, f)))
                os.makedirs(render_dir, exist_ok=True)
                outputs += _render_traj_views(art, render_dir, stem)
            except Exception as e:  # empty without_particles() shells etc.
                print(f"viz: skipping {f} in {root}: {e!r}")
        # native trajectory stores render like soup artifacts; a multihost
        # capture leaves only per-process shards (soup.traj.pNNNNofMMMM) —
        # collapse those to their base name so the merged store renders once
        bases = set()
        for f in files:
            if f.endswith(".traj"):
                bases.add(f)
            else:
                m = re.match(r"(.+\.traj)\.p\d+of\d+$", f)
                if m:
                    bases.add(m.group(1))
        for f in sorted(bases):
            stem = f[:-5] + "_trajectories_3d"
            done = all(stem + ext in rendered for ext in (".png", ".html"))
            if done and not redo:
                continue
            from .utils import read_store_artifact
            from .utils.trajstore import store_shape
            try:
                os.makedirs(render_dir, exist_ok=True)
                # sample columns at READ time: a mega store's full frames
                # would exhaust host RAM long before the render cap runs
                n_slots = store_shape(os.path.join(root, f))[0]
                cols = render_columns(n_slots) \
                    if n_slots > MAX_RENDER_PARTICLES else None
                outputs += _render_traj_views(
                    read_store_artifact(os.path.join(root, f),
                                        columns=cols), render_dir, stem)
            except Exception as e:
                print(f"viz: skipping {f} in {root}: {e!r}")
        basenames = {f.rsplit(".", 1)[0] for f in files
                     if f.endswith((".npz", ".json", ".jsonl"))}
        for base, (renderer, marker) in RENDERERS.items():
            if base not in basenames:
                continue
            done_marker = any(f.endswith(".png") and f.startswith(marker)
                              for f in rendered)
            if base in ("trajectorys", "soup"):
                # trajectory renderers also emit the interactive HTML twin;
                # any PNG without its own .html sibling (pre-HTML run dirs,
                # partial multi-variant failure) must be revisited so the
                # walker backfills the missing HTML
                pngs = [f for f in rendered
                        if f.endswith(".png") and f.startswith(marker)]
                done_marker = bool(pngs) and all(
                    f[:-4] + ".html" in rendered for f in pngs)
            if base == "config" and done_marker:
                # events.jsonl is append-only (resumed runs grow it): the
                # curve is only done if at least as new as the event log
                png = os.path.join(render_dir, marker + ".png")
                ev = os.path.join(root, "events.jsonl")
                done_marker = not os.path.exists(ev) or \
                    os.path.getmtime(png) >= os.path.getmtime(ev)
            if base == "lineage" and done_marker:
                # lineage.jsonl is append-only too (resumes extend it)
                png = os.path.join(render_dir, marker + ".png")
                src = os.path.join(root, "lineage.jsonl")
                done_marker = not os.path.exists(src) or \
                    os.path.getmtime(png) >= os.path.getmtime(src)
            if done_marker and not redo:
                continue
            try:
                os.makedirs(render_dir, exist_ok=True)
                outputs += renderer(render_dir, os.path.join(root, base))
            except Exception as e:  # keep walking like the reference CLI
                print(f"viz: skipping {base} in {root}: {e!r}")
    return outputs


def main(argv=None):
    p = argparse.ArgumentParser(description="render plots for run-dir artifacts")
    p.add_argument("-i", "--in-dir", dest="in_dir", default="experiments",
                   help="directory tree to scan (visualization.py:20-24)")
    p.add_argument("--redo", action="store_true", help="re-render existing plots")
    p.add_argument("-o", "--out-dir", dest="out_dir", default=None,
                   help="mirror renders of reference .dill artifacts here "
                        "(for read-only result trees)")
    args = p.parse_args(argv)
    outs = search_and_apply(args.in_dir, redo=args.redo, out_dir=args.out_dir)
    for o in outs:
        print(o)
    return 0


if __name__ == "__main__":
    main()
