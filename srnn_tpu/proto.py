"""Second-generation prototype networks ("methods.py" family).

Reference: ``code/methods.py`` — a later, experiment-unused redesign where
"fit" is **repeated self-application with a delta loss and no gradients**:
per epoch, predict the flat weights through the net, write the outputs back
positionally, and record loss = MSE(f(w_t), w_t) *before* the update
(``RecurrentNetwork.fit``, ``methods.py:106-129``;
``FeedForwardNetwork.fit``, ``methods.py:141-174``).

Semantics kept bit-faithful:

  * the feed-forward positional feature is ``index / cells`` — divided by
    the cell count, NOT normalized by the parameter count
    (``methods.py:154``; quirk noted in SURVEY §2 methods row);
  * the topology builder's parameter-count formula over-counts the
    feed-forward head (it assumes a ``features×cells`` output layer while
    the model ends in Dense(1), ``methods.py:36,50``) — the reference
    comments out the consistency assert for FF (``methods.py:139``).
    :meth:`ProtoTopology.builder_parameter_count` reproduces that formula;
    :meth:`ProtoTopology.num_weights` is the true count.

TPU-native form: one fused forward per epoch (the reference re-enters
``model.predict`` per epoch from Python), epochs as ``lax.scan``.
"""

import functools
from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .ops.linalg import matmul
from .topology import Topology


@dataclass(frozen=True)
class ProtoTopology:
    """Mirror of the ``Network`` builder (``methods.py:17-54``):
    ``features`` inputs, ``cells`` wide, ``layers`` deep, Dense or
    SimpleRNN body, no biases, linear activations."""

    features: int = 2
    cells: int = 2
    layers: int = 2
    recurrent: bool = False
    precision: str = "highest"

    @property
    def layer_shapes(self) -> Tuple[Tuple[int, int], ...]:
        f, c, l = self.features, self.cells, self.layers
        if self.recurrent:
            shapes = [(f, c), (c, c)]                    # RNN 1: input + recurrent
            shapes += [(c, c), (c, c)] * (l - 1)         # further RNN layers
            shapes += [(c, f)]                           # Dense(features) head
            return tuple(shapes)
        return ((f, c),) + ((c, c),) * (l - 1) + ((c, 1),)

    @property
    def num_weights(self) -> int:
        return int(sum(a * b for a, b in self.layer_shapes))

    @property
    def builder_parameter_count(self) -> int:
        """The reference's printed/announced count (``methods.py:27-37``) —
        equals :attr:`num_weights` for recurrent nets (asserted there), but
        over-counts feed-forward heads (assert commented out)."""
        f, c, l = self.features, self.cells, self.layers
        if self.recurrent:
            p1 = f * c + c * c
            pn = (c * c + c * c) * (l - 1)
        else:
            p1 = f * c
            pn = (c * c) * (l - 1)
        return p1 + pn + f * c

    @property
    def seq_len(self) -> int:
        """RNN input sequence length (``methods.py:40``: parameters //
        features, on the true count for recurrent nets)."""
        assert self.recurrent
        return self.num_weights // self.features

    def offsets(self):
        offs = [0]
        for a, b in self.layer_shapes:
            offs.append(offs[-1] + a * b)
        return offs

    def _as_linalg_topo(self) -> Topology:
        """Precision carrier for ops.linalg.matmul."""
        return Topology("weightwise", precision=self.precision)


def _kernels(pt: ProtoTopology, flat: jnp.ndarray):
    offs = pt.offsets()
    return [flat[offs[i]:offs[i + 1]].reshape(shape)
            for i, shape in enumerate(pt.layer_shapes)]


def forward_ff(pt: ProtoTopology, flat: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """(B, features) -> (B, 1): linear Dense chain (``methods.py:43-50``)."""
    topo = pt._as_linalg_topo()
    h = x
    for k in _kernels(pt, flat):
        h = matmul(topo, h, k)
    return h


def forward_rnn(pt: ProtoTopology, flat: jnp.ndarray, seq: jnp.ndarray) -> jnp.ndarray:
    """(T, features) -> (T, features): linear SimpleRNN stack +
    Dense(features) head over the sequence (``methods.py:43-50``)."""
    topo = pt._as_linalg_topo()
    ks = _kernels(pt, flat)
    h = seq
    for layer in range(pt.layers):
        wx, wh = ks[2 * layer], ks[2 * layer + 1]

        def cell(hprev, xt, wx=wx, wh=wh):
            ht = matmul(topo, xt[None, :], wx)[0] + matmul(topo, hprev[None, :], wh)[0]
            return ht, ht

        _, h = jax.lax.scan(cell, jnp.zeros(wh.shape[0], flat.dtype), h)
    return matmul(topo, h, ks[-1])


def apply_self(pt: ProtoTopology, flat: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One prototype self-application: (new_flat, loss) with
    loss = MSE(new, old) computed before the update lands
    (``methods.py:116-126`` / ``:152-171``)."""
    if pt.recurrent:
        seq = flat.reshape(pt.seq_len, pt.features)
        y = forward_rnn(pt, flat, seq).reshape(-1)
    else:
        p = pt.num_weights
        # positional feature = index / cells, the reference's un-normalized
        # divisor quirk (methods.py:154)
        idx = jnp.arange(p, dtype=flat.dtype) / pt.cells
        cols = [flat, idx] + [jnp.zeros_like(flat)] * (pt.features - 2)
        x = jnp.stack(cols, axis=1)
        y = forward_ff(pt, flat, x)[:, 0]
    loss = jnp.mean((y - flat) ** 2)
    return y, loss


@functools.partial(jax.jit, static_argnames=("pt", "epochs"))
def fit(pt: ProtoTopology, flat: jnp.ndarray, epochs: int = 500
        ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The prototype "training" loop: ``epochs`` self-applications,
    returning (final_flat, (epochs,) losses) — no gradients anywhere
    (``methods.py:110-129``)."""

    def step(w, _):
        new, loss = apply_self(pt, w)
        return new, loss

    final, losses = jax.lax.scan(step, flat, None, length=epochs)
    return final, losses


def init_proto(pt: ProtoTopology, key: jax.Array, dtype=jnp.float32) -> jnp.ndarray:
    """Glorot-uniform kernels / orthogonal recurrent kernels, matching the
    keras defaults the prototype inherits (``methods.py:43-50``)."""
    from .init import _glorot_uniform, _orthogonal

    parts = []
    keys = jax.random.split(key, len(pt.layer_shapes))
    for i, (shape, k) in enumerate(zip(pt.layer_shapes, keys)):
        recurrent_kernel = pt.recurrent and i < 2 * pt.layers and i % 2 == 1
        init = _orthogonal if recurrent_kernel else _glorot_uniform
        parts.append(init(k, shape, dtype).reshape(-1))
    return jnp.concatenate(parts)
