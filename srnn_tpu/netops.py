"""Named network-level operators from the reference API surface.

``NeuralNetwork`` exposes four interaction verbs (``network.py:112-131``)
whose names are part of the paper's vocabulary; they are thin compositions
of ``apply_to_weights`` in functional form (weights in, weights out — the
caller decides where results land, there is no hidden mutation):

  * :func:`attack`       — self applied to OTHER; result replaces other
                           (``network.py:116-118``)
  * :func:`fuck`         — self applied to other; result replaces SELF
                           (reference's name, ``network.py:120-122``)
  * :func:`self_attack`  — ``attack`` on one's own weights, iterated
                           (``network.py:124-127``)
  * :func:`meet`         — attack a copy; returns the transformed copy,
                           leaving both originals intact (``network.py:129-131``)

Plus the static helpers ``weights_to_string`` (``network.py:31-41``) and
``are_weights_within`` (``network.py:54-62``).
"""

from typing import Iterable

import jax
import jax.numpy as jnp
import numpy as np

from .nets import apply_to_weights
from .ops.flatten import unflatten
from .topology import Topology


def attack(topo: Topology, self_flat: jnp.ndarray, other_flat: jnp.ndarray,
           key=None) -> jnp.ndarray:
    """Self applied to other's weights -> other's NEW weights.

    The caller stores the result into the victim's slot, which is what the
    reference's in-place ``other_network.set_weights(...)`` does."""
    return apply_to_weights(topo, self_flat, other_flat, key)


def fuck(topo: Topology, self_flat: jnp.ndarray, other_flat: jnp.ndarray,
         key=None) -> jnp.ndarray:
    """Self applied to other's weights -> SELF's new weights
    (the reference's name for absorbing an other, ``network.py:120-122``)."""
    return apply_to_weights(topo, self_flat, other_flat, key)


absorb = fuck  # polite alias


def self_attack(topo: Topology, flat: jnp.ndarray, iterations: int = 1,
                key=None) -> jnp.ndarray:
    """``iterations`` rounds of attacking oneself (``network.py:124-127``).
    NOTE the reference re-reads its own (just-updated) weights each round,
    so iteration i+1 uses the output of iteration i as BOTH net and target."""
    w = flat
    keys = [None] * iterations if key is None else jax.random.split(key, iterations)
    for k in keys:
        w = apply_to_weights(topo, w, w, k)
    return w


def meet(topo: Topology, self_flat: jnp.ndarray, other_flat: jnp.ndarray,
         key=None) -> jnp.ndarray:
    """Attack a deepcopy of other (``network.py:129-131``): functionally
    identical to :func:`attack` — provided for API parity; the functional
    style never mutates, so every attack already 'meets'."""
    return apply_to_weights(topo, self_flat, other_flat, key)


def are_weights_within(flat: jnp.ndarray, lower: float, upper: float) -> jnp.ndarray:
    """All weights inside [lower, upper] inclusive (``network.py:54-62``)."""
    return jnp.all((flat >= lower) & (flat <= upper), axis=-1)


def weights_to_string(topo: Topology, flat) -> str:
    """Human-readable kernel dump (``weights_to_string``,
    ``network.py:31-41``): one block per layer, one bracketed row per cell."""
    lines: Iterable[str] = []
    out = []
    for kernel in unflatten(topo, jnp.asarray(flat)):
        rows = np.asarray(kernel)
        out.append("\n".join(
            "[" + " ".join(f"{w:10.7f}" for w in row) + "]" for row in rows))
    return "\n\n".join(out)
