"""Self-contained interactive HTML trajectory plots.

The reference's analysis CLI writes *offline plotly HTML* per artifact —
a rotatable 3-D view of per-particle weight-space trajectories
(``visualization.py:119-179``, ``plotly.offline.plot`` to ``.html``).
Plotly is not in this image, so this module emits a dependency-free HTML
file with a small inline canvas renderer instead: drag to orbit, wheel to
zoom, same visual contract as the reference plot (x/y = PCA components,
z = time, red start / black end markers, one colored line per particle
lifetime).

The file is fully self-contained (data embedded as JSON, no network),
so it opens anywhere — the same property the reference got from
``include_plotlyjs=True`` offline plots.
"""

import html as _html
import json
import os
from typing import Dict, List

import matplotlib
import matplotlib.colors
import numpy as np

from .viz import extract_pca

# same tab20 cycle as the PNG renderer, derived so the two can't drift
# (colormap registry access only — no pyplot state machine / backend side
# effects in this otherwise matplotlib-free module)
_PALETTE = tuple(
    matplotlib.colors.to_hex(matplotlib.colormaps["tab20"](i))
    for i in range(20)
)

_TEMPLATE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>%(title)s</title>
<style>
 body { margin:0; font:13px sans-serif; background:#fff; color:#222; }
 #hud { position:fixed; top:8px; left:10px; user-select:none; }
 canvas { display:block; cursor:grab; }
</style></head>
<body>
<div id="hud"><b>%(title)s</b> &mdash; drag to orbit, wheel to zoom,
double-click to reset. %(n_traj)d trajectories.</div>
<canvas id="c"></canvas>
<script>
const TRAJS = %(data)s;            // [{xyz: [[x,y,z],...], color}]
const AXES = ["PCA 1", "PCA 2", "time"];
const cv = document.getElementById("c"), ctx = cv.getContext("2d");
let yaw = -0.9, pitch = 0.5, zoom = 1.0;
function resize() { cv.width = innerWidth; cv.height = innerHeight; draw(); }
function proj(p) {                 // orthographic orbit camera
  const cy = Math.cos(yaw), sy = Math.sin(yaw);
  const cp = Math.cos(pitch), sp = Math.sin(pitch);
  const x = p[0] * cy + p[1] * sy;
  const y = -p[0] * sy + p[1] * cy;
  const z = p[2];
  const u = x, v = y * sp + z * cp;         // screen-plane coords
  const s = 0.36 * Math.min(cv.width, cv.height) * zoom;
  return [cv.width / 2 + u * s, cv.height / 2 - v * s];
}
function line(a, b, color, w) {
  ctx.strokeStyle = color; ctx.lineWidth = w;
  ctx.beginPath(); ctx.moveTo(a[0], a[1]); ctx.lineTo(b[0], b[1]); ctx.stroke();
}
function dot(p, color, r) {
  ctx.fillStyle = color;
  ctx.beginPath(); ctx.arc(p[0], p[1], r, 0, 6.2832); ctx.fill();
}
function draw() {
  ctx.clearRect(0, 0, cv.width, cv.height);
  // unit-box axes frame
  const C = [[-1,-1,-1],[1,-1,-1],[-1,1,-1],[-1,-1,1],[1,1,-1],[1,-1,1],[-1,1,1],[1,1,1]];
  const E = [[0,1],[0,2],[0,3],[1,4],[2,4],[1,5],[3,5],[2,6],[3,6],[4,7],[5,7],[6,7]];
  for (const [i, j] of E) line(proj(C[i]), proj(C[j]), "#ccc", 1);
  ctx.fillStyle = "#666";
  ctx.fillText(AXES[0], ...proj([1.12, -1, -1]));
  ctx.fillText(AXES[1], ...proj([-1, 1.12, -1]));
  ctx.fillText(AXES[2], ...proj([-1, -1, 1.12]));
  for (const t of TRAJS) {
    ctx.strokeStyle = t.color; ctx.lineWidth = 1.2; ctx.globalAlpha = 0.85;
    ctx.beginPath();
    const pts = t.xyz.map(proj);
    ctx.moveTo(pts[0][0], pts[0][1]);
    for (const p of pts) ctx.lineTo(p[0], p[1]);
    ctx.stroke();
    ctx.globalAlpha = 1.0;
    dot(pts[0], "red", 3.2);                     // start marker
    dot(pts[pts.length - 1], "black", 3.2);      // end marker
  }
}
let dragging = false, px = 0, py = 0;
cv.addEventListener("mousedown", e => { dragging = true; px = e.clientX; py = e.clientY; });
addEventListener("mouseup", () => dragging = false);
addEventListener("mousemove", e => {
  if (!dragging) return;
  yaw += (e.clientX - px) * 0.008; pitch += (e.clientY - py) * 0.008;
  pitch = Math.max(-1.55, Math.min(1.55, pitch));
  px = e.clientX; py = e.clientY; draw();
});
cv.addEventListener("wheel", e => {
  e.preventDefault(); zoom *= Math.exp(-e.deltaY * 0.001); draw();
}, { passive: false });
cv.addEventListener("dblclick", () => { yaw = -0.9; pitch = 0.5; zoom = 1.0; draw(); });
addEventListener("resize", resize);
resize();
</script></body></html>
"""


def write_html_trajectories_3d(artifact: Dict[str, np.ndarray], out_path: str,
                               title: str = "", extracted=None) -> str:
    """Render the 3-D PCA trajectory view as a standalone interactive HTML
    file (the TPU-native equivalent of ``plot_latent_trajectories_3D``'s
    plotly output, ``visualization.py:119-179``)."""
    trajs, mean, comps = extracted if extracted is not None else extract_pca(artifact)

    # normalize each display axis to [-1, 1] so the unit box fits any run
    xys = [(t["trajectory"] - mean) @ comps for t in trajs]
    xy_all = np.vstack(xys)
    t_max = max(int(t["time"][-1]) for t in trajs)
    lo, hi = xy_all.min(axis=0), xy_all.max(axis=0)
    span = np.where(hi - lo > 0, hi - lo, 1.0)

    data: List[dict] = []
    for i, (t, xy) in enumerate(zip(trajs, xys)):
        xy01 = 2.0 * (xy - lo) / span - 1.0
        z01 = 2.0 * t["time"] / max(t_max, 1) - 1.0
        xyz = np.column_stack([xy01, z01]).round(4)
        data.append({"xyz": xyz.tolist(), "color": _PALETTE[i % len(_PALETTE)]})

    html = _TEMPLATE % {
        "title": _html.escape(title or os.path.basename(out_path)),
        "n_traj": len(data),
        "data": json.dumps(data, separators=(",", ":")),
    }
    with open(out_path, "w") as f:
        f.write(html)
    return out_path
