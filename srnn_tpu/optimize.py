"""Gradient-free fixpoint search: the stochastic hill climber.

Prior-art parity for the EP prototype (``related/EP/src/NeuralNetwork.py``,
``fitByStochasticHillClimberV3``): repeatedly propose
noise-perturbed weight candidates around the incumbent, score each by how
close the net is to being its own fixpoint, and keep the best.  The EP
feature reductions {fft, rfft, mean, meanShuffled} map onto the main
framework's FFT / aggregating variants (SURVEY scope note), so the climber
here scores in the variant's own sample space via ``compute_samples``.

TPU-native twist: the reference evaluates its ``numberOtRandomShots``
serially through keras ``predict``; here all shots of a round evaluate as
ONE vmapped batch, and rounds are a ``lax.scan``.
"""

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from .nets import compute_samples
from .topology import Topology
from .train import predict


def fixpoint_loss(topo: Topology, flat: jnp.ndarray) -> jnp.ndarray:
    """MSE between the net's prediction on its own samples and the targets —
    0 iff the net is exactly its own fixpoint in sample space (the EP
    climber's objective, predictions vs feature-reduced weights)."""
    x, y = compute_samples(topo, flat)
    pred = predict(topo, flat, x)
    return jnp.mean((pred - y.reshape(pred.shape)) ** 2)


@functools.partial(jax.jit, static_argnames=("topo", "shots", "rounds"))
def hillclimb(
    topo: Topology,
    flat: jnp.ndarray,
    key: jax.Array,
    shots: int = 20,
    rounds: int = 100,
    std: float = 0.01,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Stochastic hill climbing toward a self-application fixpoint.

    Per round: draw ``shots`` gaussian perturbations (σ=``std``) of the
    incumbent (EP's ``standardDeviation``/``numberOtRandomShots`` knobs),
    score incumbent + shots in one vmapped batch, keep the argmin.  Returns
    (best_flat, (rounds,) best-loss trace).  Monotone non-increasing by
    construction.
    """

    def round_(carry, k):
        w, loss = carry
        noise = jax.random.normal(k, (shots,) + w.shape, w.dtype) * std
        cands = jnp.concatenate([w[None], w[None] + noise], axis=0)
        losses = jax.vmap(lambda c: fixpoint_loss(topo, c))(cands)
        best = jnp.argmin(losses)
        return (cands[best], losses[best]), losses[best]

    init = (flat, fixpoint_loss(topo, flat))
    (best, _), trace = jax.lax.scan(round_, init, jax.random.split(key, rounds))
    return best, trace
