"""Device-mesh helpers.

The reference is single-process with no parallelism of any kind (SURVEY
§2.5); this module is where the TPU build gets its scale-out instead:
a 1-D ``soup`` mesh over which the particle axis is sharded.  Collectives
ride ICI within a slice; multi-host/multi-slice (DCN) setups initialize via
``jax.distributed`` first.
"""

import os
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SOUP_AXIS = "soup"


def soup_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    """1-D mesh over the particle ('soup') axis.

    Uses all visible devices by default — on a pod slice these are the local
    chips plus, after ``initialize_distributed()``, every other host's chips.
    Requesting more devices than exist fails fast (a mis-scheduled job must
    not silently run with halved shards).
    """
    if devices is None:
        available = jax.devices()
        if n_devices is not None:
            if not 0 < n_devices <= len(available):
                raise ValueError(
                    f"requested {n_devices} devices but {len(available)} are available")
            available = available[:n_devices]
        devices = available
    return Mesh(np.asarray(devices), (SOUP_AXIS,))


def probe_devices(verify: bool = False):
    """Enumerate the devices that exist *right now* — the supervisor's
    re-ramp input after a device loss.  ``verify=True`` additionally
    round-trips one scalar through each device and drops any that fail
    (a half-dead slice can still enumerate chips it cannot use); plain
    enumeration is free and good enough for bring-up logging."""
    devices = jax.devices()
    if not verify:
        return devices
    alive = []
    for d in devices:
        try:
            jax.device_put(np.int32(0), d).block_until_ready()
            alive.append(d)
        except Exception:
            continue
    return alive


def global_device_put(x, sharding):
    """``jax.device_put`` that also works when ``sharding`` spans devices
    owned by OTHER processes (a multi-host mesh).

    The distributed contract that makes this correct: every process holds
    the same full host value ``x`` (seeds are deterministic functions of
    the replicated PRNG key; checkpoint restores read the same files), so
    each process contributes exactly its addressable shards via
    ``jax.make_array_from_callback`` and no data ever crosses DCN for
    placement.  Typed PRNG keys round-trip through their raw key data —
    they are only ever replicated (spec ``P()``), which holds for any
    rank, so the same sharding places the ``(… , impl)`` data array."""
    if sharding.is_fully_addressable:
        return jax.device_put(x, sharding)
    if isinstance(x, jax.Array) and jax.dtypes.issubdtype(
            x.dtype, jax.dtypes.prng_key):
        data = np.asarray(jax.random.key_data(x))
        impl = str(jax.random.key_impl(x))
        g = jax.make_array_from_callback(data.shape, sharding,
                                         lambda idx: data[idx])
        return jax.random.wrap_key_data(g, impl=impl)
    host = np.asarray(x)
    return jax.make_array_from_callback(host.shape, sharding,
                                        lambda idx: host[idx])


def shard_population(mesh: Mesh, pop: jax.Array) -> jax.Array:
    """Place a (N, ...) population with the leading axis sharded over the mesh."""
    return global_device_put(pop, NamedSharding(mesh, P(SOUP_AXIS)))


def replicate(mesh: Mesh, x) -> jax.Array:
    """Place a value fully replicated over the mesh (e.g. the shared
    ``self_flat`` argument of ``ring_rnn_apply``)."""
    return global_device_put(x, NamedSharding(mesh, P()))


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> bool:
    """Multi-host bring-up (DCN): wraps ``jax.distributed.initialize``.

    Legacy auto-detect spelling; production bring-up is
    ``distributed.bootstrap.ensure_initialized`` (idempotent, launcher
    env vars, gloo CPU collectives, host-loss fault typing) — the mega
    loops go through that path.

    No-op (returns False) when neither explicit arguments nor cluster env
    vars (``JAX_COORDINATOR_ADDRESS`` / TPU pod metadata) are present, so
    single-host runs and tests never pay for it.  Any explicit argument
    forces initialization (jax can auto-detect the rest on managed
    clusters).
    """
    explicit = (coordinator_address is not None or num_processes is not None
                or process_id is not None)
    if not explicit and "JAX_COORDINATOR_ADDRESS" not in os.environ \
            and os.environ.get("TPU_WORKER_HOSTNAMES") is None:
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True
