"""Sequence parallelism for giant particles: every transform sharded on the
weight axis.

The reference caps out at 2x2 nets (14-17 weights); nothing in it can grow a
particle past one host's memory (SURVEY §5 "long-context" row).  Here the
TARGET weight vector — the "sequence" every transform consumes — is sharded
over the mesh and each variant uses the cheapest collective that preserves
its exact semantics:

  * weightwise  — embarrassingly parallel over weight points: each device
    rewrites its local chunk with the replicated tiny MLP; NO collective.
  * aggregating — local partial segment sums + one ``psum`` of (k,) sums
    ('average'; the max aggregators use per-segment partial maxima + one
    ``pmax``); the k-vector MLP runs replicated; deaggregation is local
    replication.  (reference ``collect_weights`` chunk rule,
    ``network.py:388-403``.)
  * fft         — the truncated DFT/inverse pair becomes small cos-basis
    matmuls: a ``psum`` assembles the k input bins, each device synthesizes
    its local slice of the inverse transform.  Matches
    ``np.fft.fft(flat, n=k)`` / ``ifft(coeffs, n=P).real`` bit-for-bit in
    real arithmetic (reference ``network.py:444-453``), both fft and rfft
    modes.
  * recurrent   — a DISTRIBUTED associative scan (the "documented next
    step" of ``ring_rnn``): with the affine (linear-activation) recurrence,
    each device scans its chunk in O(log T/D) depth, all-gathers one
    (units x units, units) chunk summary, prefix-composes the D summaries
    locally, and finishes its outputs — O(T/D log) time instead of the
    ring pipeline's O(T) wavefront.

All functions are numerically equivalent to their single-device
counterparts in ``srnn_tpu.nets`` (same math, possibly reassociated) and
zero-pad the weight axis to a mesh multiple (safe: padded positions never
influence kept outputs — weightwise/agg/fft index positions explicitly, and
the recurrence is causal with padding at the tail).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.linalg import matmul
from ..ops.mlp import mlp_forward
from ..ops.flatten import unflatten
from ..topology import Topology, normalized_weight_coords, segments_for
from .mesh import SOUP_AXIS
from .compat import shard_map
from .ring_rnn import ring_rnn_apply


def _pad_to(x: jnp.ndarray, mult: int) -> jnp.ndarray:
    pad = (-x.shape[0]) % mult
    return jnp.pad(x, [(0, pad)] + [(0, 0)] * (x.ndim - 1)) if pad else x


# ---------------------------------------------------------------------------
# weightwise: pure map over weight points
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("topo", "mesh"))
def sharded_weightwise_apply(topo: Topology, mesh: Mesh, self_flat: jax.Array,
                             target_flat: jax.Array) -> jax.Array:
    """Weightwise transform with the (P,) target sharded over the mesh.

    Each device holds its chunk of the precomputed positional-encoding table
    (``network.py:239-255``) and runs the replicated MLP on its points only —
    the pure-map decomposition SURVEY §5 calls out.  No collectives.
    """
    assert topo.variant == "weightwise"
    n_dev = mesh.devices.size
    t = target_flat.shape[0]
    coords = jnp.asarray(normalized_weight_coords(topo), target_flat.dtype)
    tgt = _pad_to(target_flat, n_dev)
    crd = _pad_to(coords, n_dev)

    def body(self_flat, tgt_loc, crd_loc):
        pts = jnp.concatenate([tgt_loc[:, None], crd_loc], axis=1)
        return mlp_forward(topo, self_flat, pts)[:, 0]

    out = shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(SOUP_AXIS), P(SOUP_AXIS)),
        out_specs=P(SOUP_AXIS), check_vma=False,
    )(self_flat, tgt, crd)
    return out[:t]


# ---------------------------------------------------------------------------
# aggregating: psum of per-segment partial sums
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("topo", "mesh"))
def sharded_aggregating_apply(topo: Topology, mesh: Mesh, self_flat: jax.Array,
                              target_flat: jax.Array) -> jax.Array:
    """Aggregating transform with the (P,) target sharded over the mesh.

    Collect (reference chunks-of-``P//k``-with-leftovers-to-last rule,
    ``network.py:388-403``) becomes, per aggregator:

      * 'average'  — local one-hot partial sums -> ``psum`` of a (k,)
        vector -> divide by the constant counts;
      * 'max'      — local per-segment partial maxima -> ``pmax``;
      * 'max_buggy' — the falsy-max quirk (``network.py:303-308``) in its
        order-free closed form: a candidate wins only if nonzero OR it is
        the segment's first element, so the result is the masked max of
        {first} ∪ {nonzero rest}.  Identical to the sequential comparison
        chain for finite inputs; a NaN later in a segment propagates here
        where the chain would ignore it (divergent particles are
        respawned upstream, so the difference is unobservable in soups).

    The random shuffler needs a global permutation and raises.
    """
    assert topo.variant == "aggregating"
    if topo.shuffler != "not":
        raise NotImplementedError("sharded aggregating supports shuffler='not'")
    n_dev = mesh.devices.size
    p = target_flat.shape[0]
    k = topo.aggregates
    seg, counts = segments_for(p, k)
    # padded tail gets segment id k (an extra bin discarded after the
    # collective)
    seg_pad = _pad_to(jnp.asarray(seg, jnp.int32), n_dev)
    pad = seg_pad.shape[0] - p
    if pad:
        seg_pad = seg_pad.at[p:].set(k)
    tgt = _pad_to(target_flat, n_dev)
    counts = jnp.asarray(counts, target_flat.dtype)
    if topo.aggregator == "max_buggy":
        # constant mask: each segment's FIRST position is always a candidate
        starts = np.searchsorted(seg, np.arange(k))
        first_np = np.zeros(seg_pad.shape[0], bool)
        first_np[starts] = True
        first_pad = jnp.asarray(first_np)
    else:
        first_pad = jnp.zeros(seg_pad.shape[0], bool)

    def body(self_flat, tgt_loc, seg_loc, first_loc):
        onehot = jax.nn.one_hot(seg_loc, k + 1, dtype=tgt_loc.dtype)[:, :k]
        if topo.aggregator == "average":
            partial = matmul(topo, tgt_loc, onehot)        # (k,) local sums
            aggs = jax.lax.psum(partial, SOUP_AXIS) / counts
        else:
            if topo.aggregator == "max_buggy":
                cand = first_loc | (tgt_loc != 0.0)
                vals = jnp.where(cand, tgt_loc, -jnp.inf)
            else:  # real max (quirk deliberately fixed, aggregating.py:41-45)
                vals = tgt_loc
            partial = jax.ops.segment_max(vals, seg_loc, num_segments=k + 1)[:k]
            aggs = jax.lax.pmax(partial, SOUP_AXIS)
        new_aggs = mlp_forward(topo, self_flat, aggs[None, :])[0]
        return matmul(topo, onehot, new_aggs)              # local deaggregate

    out = shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(SOUP_AXIS), P(SOUP_AXIS), P(SOUP_AXIS)),
        out_specs=P(SOUP_AXIS), check_vma=False,
    )(self_flat, tgt, seg_pad, first_pad)
    return out[:p]


# ---------------------------------------------------------------------------
# fft: distributed truncated DFT as cos-basis matmuls
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("topo", "mesh"))
def sharded_fft_apply(topo: Topology, mesh: Mesh, self_flat: jax.Array,
                      target_flat: jax.Array) -> jax.Array:
    """FFT transform with the (P,) source/target sharded over the mesh.

    The reference keeps only REAL parts on both sides of the net
    (``network.py:444-453``, keras float32 casts), so the whole round trip
    is real arithmetic:

      * 'fft' mode: ``fft(flat, n=k)`` crops to the first k weights —
        local masked partial sums against a (k, k) cos basis, one psum.
      * 'rfft' mode: the first k bins of the FULL-length real FFT — same
        partial-sum shape with the (k, P) basis rows evaluated locally.
      * inverse: ``ifft(c, n=P).real`` / ``irfft(c, n=P)`` synthesize from
        k real coefficients — each device emits its local slice via a
        (T_loc, k) cos matrix.  (irfft doubles the non-DC bins.)

    The psum moves k floats; everything else is local.  The self weights
    stay replicated; ``fft_use_target`` picks which vector feeds the DFT
    (quirk §2.4.2).  The random shuffler needs a global permutation and
    raises.
    """
    assert topo.variant == "fft"
    if topo.shuffler != "not":
        raise NotImplementedError("sharded fft supports shuffler='not'")
    n_dev = mesh.devices.size
    p = target_flat.shape[0]
    k = topo.aggregates
    src = target_flat if topo.fft_use_target else self_flat
    assert src.shape[0] == p, "sharded fft: source and target must share length"
    tgt = _pad_to(src, n_dev)
    padded = tgt.shape[0]
    t_loc = padded // n_dev
    dtype = target_flat.dtype

    def body(tgt_loc):
        d = jax.lax.axis_index(SOUP_AXIS)
        gidx = d * t_loc + jnp.arange(t_loc)               # global positions
        j = jnp.arange(k, dtype=dtype)                     # bin indices
        if topo.fft_mode == "rfft":
            # Re rfft(flat)[j] = sum_t flat_t cos(2 pi j t / P) over ALL t
            ang = 2.0 * jnp.pi * j[None, :] * gidx[:, None].astype(dtype) / p
            keep = (gidx < p)[:, None].astype(dtype)
        else:
            # fft(flat, n=k): crop to first k samples, length-k DFT
            ang = 2.0 * jnp.pi * j[None, :] * gidx[:, None].astype(dtype) / k
            keep = (gidx < k)[:, None].astype(dtype)
        partial = (tgt_loc[:, None] * jnp.cos(ang) * keep).sum(axis=0)
        coeffs = jax.lax.psum(partial, SOUP_AXIS)          # (k,) real bins
        new_c = mlp_forward(topo, self_flat, coeffs[None, :])[0]
        # local slice of the inverse transform
        ang_i = 2.0 * jnp.pi * j[None, :] * gidx[:, None].astype(dtype) / p
        basis = jnp.cos(ang_i)
        if topo.fft_mode == "rfft":
            # irfft doubles every bin except DC (and Nyquist, absent: k-1 < P/2)
            scale = jnp.where(j > 0, 2.0, 1.0).astype(dtype)
            return basis @ (new_c * scale) / p
        return basis @ new_c / p

    out = shard_map(
        lambda t_: body(t_), mesh=mesh,
        in_specs=(P(SOUP_AXIS),), out_specs=P(SOUP_AXIS), check_vma=False,
    )(tgt)
    return out[:p].astype(dtype)


# ---------------------------------------------------------------------------
# recurrent: distributed associative scan
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("topo", "mesh"))
def rnn_associative_apply(topo: Topology, mesh: Mesh, self_flat: jax.Array,
                          target_flat: jax.Array) -> jax.Array:
    """Sequence-parallel recurrent transform via a distributed associative
    scan (upgrades ``ring_rnn_apply``'s O(T) wavefront to O(T/D log) time).

    Requires the affine recurrence (``activation='linear'``, the reference
    default every experiment ran with — quirk §2.4.11).  Per layer:

      1. local ``associative_scan`` of the composed affine maps
         ``(A, b): h -> h @ A + b`` over this device's chunk;
      2. ``all_gather`` of the (units x units, units) chunk summary — the
         only communication, D tiny tensors per layer;
      3. every device prefix-composes the summaries below its rank into its
         incoming hidden state (h0 = 0, keras default) and finishes
         ``y_t = h_in @ Acum_t + bcum_t`` locally.
    """
    assert topo.variant == "recurrent"
    assert topo.activation == "linear", (
        "distributed associative scan requires activation='linear'; "
        "use ring_rnn_apply for nonlinear recurrences")
    n_dev = mesh.devices.size
    t = target_flat.shape[0]
    tgt = _pad_to(target_flat, n_dev)
    mats = unflatten(topo, self_flat)

    def body(self_flat, tgt_loc):
        del self_flat  # mats closed over (replicated constants)
        d = jax.lax.axis_index(SOUP_AXIS)
        x = tgt_loc[:, None]
        for layer, (_, units) in enumerate(topo.rnn_layer_dims):
            kernel, recurrent = mats[2 * layer], mats[2 * layer + 1]
            t_loc = x.shape[0]
            b = matmul(topo, x, kernel)                        # (T_loc, u)
            a = jnp.broadcast_to(recurrent, (t_loc, units, units))

            def combine(lhs, rhs):
                a1, b1 = lhs
                a2, b2 = rhs
                return (matmul(topo, a1, a2),
                        matmul(topo, b1[:, None, :], a2)[:, 0, :] + b2)

            a_cum, b_cum = jax.lax.associative_scan(combine, (a, b))
            # chunk summary -> every device; prefix-compose ranks below mine
            a_all = jax.lax.all_gather(a_cum[-1], SOUP_AXIS)   # (D, u, u)
            b_all = jax.lax.all_gather(b_cum[-1], SOUP_AXIS)   # (D, u)
            h_in = jnp.zeros((units,), x.dtype)
            for r in range(n_dev - 1):                         # h0 = 0
                nxt = matmul(topo, h_in, a_all[r]) + b_all[r]
                h_in = jnp.where(d > r, nxt, h_in)
            x = matmul(topo, h_in[None, :], a_cum)[:, 0, :] + b_cum
        return x[:, 0]

    out = shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(SOUP_AXIS)), out_specs=P(SOUP_AXIS), check_vma=False,
    )(self_flat, tgt)
    return out[:t]


# ---------------------------------------------------------------------------
# dispatcher
# ---------------------------------------------------------------------------


def sharded_apply_to_weights(topo: Topology, mesh: Mesh, self_flat: jax.Array,
                             target_flat: jax.Array) -> jax.Array:
    """Variant dispatch for weight-axis-sharded self-application — the
    giant-particle twin of ``nets.apply_to_weights``.  The recurrent variant
    routes on ``topo.rnn_scan``: 'associative' (linear) takes the
    distributed scan, 'sequential' the ``ppermute`` ring."""
    if topo.variant == "weightwise":
        return sharded_weightwise_apply(topo, mesh, self_flat, target_flat)
    if topo.variant == "aggregating":
        return sharded_aggregating_apply(topo, mesh, self_flat, target_flat)
    if topo.variant == "fft":
        return sharded_fft_apply(topo, mesh, self_flat, target_flat)
    if topo.variant == "recurrent":
        if topo.rnn_scan == "associative":
            return rnn_associative_apply(topo, mesh, self_flat, target_flat)
        return ring_rnn_apply(topo, mesh, self_flat, target_flat)
    raise ValueError(f"unknown variant {topo.variant!r}")
