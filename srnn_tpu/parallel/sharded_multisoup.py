"""Heterogeneous (mixed-architecture) soup sharded over a device mesh.

The EP-flavored scale-out of ``srnn_tpu.multisoup`` (SURVEY §2.5 expert-
parallel row, generalizing the reference's separate homogeneous soups,
``mixed-soup.py:66-68``): every TYPE's particle axis is sharded over the
same 1-D soup mesh — device d owns rows [d*N_t/D, (d+1)*N_t/D) of every
type t.  Cross-type attacks need every victim to be able to read any
attacker's weights, so each generation starts with one small ``all_gather``
per type (particles are tiny — a 1M-particle 3-type soup gathers ~60 MB
total), after which the T^2 masked cross-apply runs on local victim rows
only.

The sharded step is **semantically identical** to ``evolve_multi_step``
under matched keys (tests assert):

  * all gate/target draws come from the replicated soup key — identical
    streams on every device, local slices taken per shard;
  * same-type imitation teachers are re-gathered POST-attack, matching the
    single-device phase ordering;
  * respawn uids use the GLOBAL per-type dead-rank (all_gather of the
    death mask + cumsum) with the single-device type-major block order,
    and fresh replacements replicate the single-device per-type draw
    (``fresh_rows(topo, re_keys[t], N_t)``) and slice the local rows.

All integer state (uids, next_uid, event actions/counterparts) is EXACT.
Weights match to reduction-reassociation tolerance, not bitwise: the
aggregating/fft/recurrent transforms contain row-internal reductions whose
XLA tiling legitimately differs between the unsharded (N_t-row) and
sharded (N_t/D-row) batch shapes.  (The homogeneous weightwise popmajor
path IS bitwise — every op there is elementwise over lanes.)
"""

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..init import fresh_lanes, fresh_rows
from ..multisoup import (
    MultiSoupConfig,
    MultiSoupEvents,
    MultiSoupState,
    _check_popmajor_multi,
    count_multi,
    seed_multi,
)
from ..nets.cross import cross_apply
from ..ops.predicates import count_classes, is_diverged, is_zero
from ..engine import classify_batch
from ..soup import (
    ACT_DIV_DEAD,
    ACT_NONE,
    ACT_ZERO_DEAD,
    _event_record,
    _learn_epochs,
    _train_epochs,
)
from .mesh import SOUP_AXIS
from .compat import shard_map


def _mstate_specs(t: int, int8: bool = False) -> MultiSoupState:
    # int8 populations carry per-type per-particle scale vectors that shard
    # with the particle axis like uids; f32/bf16 states have scales=None
    # (empty subtree), so the spec tree mirrors that None-for-None
    return MultiSoupState(
        weights=tuple(P(SOUP_AXIS) for _ in range(t)),
        uids=tuple(P(SOUP_AXIS) for _ in range(t)),
        next_uid=P(),
        time=P(),
        key=P(),
        scales=tuple(P(SOUP_AXIS) for _ in range(t)) if int8 else None,
    )


def _mevent_specs(config: MultiSoupConfig) -> MultiSoupEvents:
    t = len(config.topos)
    return MultiSoupEvents(
        action=tuple(P(SOUP_AXIS) for _ in range(t)),
        counterpart=tuple(P(SOUP_AXIS) for _ in range(t)),
        loss=tuple(P(SOUP_AXIS) for _ in range(t)),
    )


def _local_evolve_multi(config: MultiSoupConfig, state: MultiSoupState,
                        lins=None, win=None, lincfg=None):
    """Per-device body: ``state.weights[t]``/``uids[t]`` hold the LOCAL
    (N_t/D, P_t) shards; scalars and the key are replicated.  With a
    lineage carry (``lins``/``win``/``lincfg``) the advanced per-type
    carries + the per-shard edge window ride along (mint bases from
    all-gathered mask ranks, chained type-major — the uid-block order)."""
    from ..multisoup import _type_scales
    from ..soup import _downcast, _upcast

    n = config.total
    offs = config.offsets
    d = jax.lax.axis_index(SOUP_AXIS)
    int8 = config.population_dtype == "int8"
    key, k_ag, k_at, k_lg, k_lt, k_re = jax.random.split(state.key, 6)
    w_loc = [_upcast(config, w, _type_scales(state, t))
             for t, w in enumerate(state.weights)]
    n_locs = [w.shape[0] for w in w_loc]

    # start-of-generation gathers: attacker weight tables + uid tables
    # (storage dtype on the wire — exact bf16->f32 upcast after, see
    # sharded_soup._local_evolve; int8 additionally gathers the per-type
    # scale vectors and dequantizes after, elementwise per particle so
    # gather-then-dequant equals dequant-then-gather bitwise)
    all_s = tuple(jax.lax.all_gather(s, SOUP_AXIS, tiled=True)
                  for s in state.scales) if int8 else None
    all_w = tuple(_upcast(config, jax.lax.all_gather(w, SOUP_AXIS,
                                                     tiled=True),
                          None if all_s is None else all_s[t])
                  for t, w in enumerate(state.weights))
    all_uids_t = tuple(jax.lax.all_gather(u, SOUP_AXIS, tiled=True)
                       for u in state.uids)
    all_uids = jnp.concatenate(all_uids_t)

    # --- attack draws (global, replicated) ------------------------------
    if config.attacking_rate > 0:
        attack_gate = jax.random.uniform(k_ag, (n,)) < config.attacking_rate
        attack_tgt = jax.random.randint(k_at, (n,), 0, n)
        att_idx = jax.ops.segment_max(
            jnp.where(attack_gate, jnp.arange(n), -1), attack_tgt,
            num_segments=n)
    else:
        attack_gate = jnp.zeros(n, bool)
        attack_tgt = jnp.zeros(n, jnp.int32)
        att_idx = jnp.full(n, -1, jnp.int32)
    lin_info = []

    new_weights, new_uids, actions, counterparts, losses = [], [], [], [], []
    new_scales = []
    total_deaths = jnp.int32(0)
    re_keys = jax.random.split(k_re, len(config.topos))
    for t, topo in enumerate(config.topos):
        tc = config.type_config(t)
        n_t = config.sizes[t]
        n_loc = n_locs[t]
        start = offs[t] + d * n_loc  # this shard's GLOBAL index range
        w_t = w_loc[t]

        def sl(arr, start=start, n_loc=n_loc):
            return jax.lax.dynamic_slice_in_dim(arr, start, n_loc)

        # --- attack on local victims (T^2 masked cross-apply) -----------
        with jax.named_scope("multisoup.attack"):
            if config.attacking_rate > 0:
                att_b = sl(att_idx)
                out = w_t
                for a, attacker_topo in enumerate(config.topos):
                    mask = (att_b >= offs[a]) & (att_b < offs[a + 1])
                    rows = all_w[a][jnp.clip(att_b - offs[a], 0,
                                             config.sizes[a] - 1)]
                    attacked = jax.vmap(
                        lambda s, v: cross_apply(attacker_topo, s, topo, v)
                    )(rows, w_t)
                    out = jnp.where(mask[:, None], attacked, out)
                w_t = out

        # --- learn_from (same-type teachers, POST-attack re-gather) -----
        with jax.named_scope("multisoup.learn_from"):
            if config.learn_from_rate > 0:
                learn_gate = sl(jax.random.uniform(k_lg, (n,))) \
                    < config.learn_from_rate
                learn_tgt_full = jax.random.randint(
                    jax.random.fold_in(k_lt, t), (n_t,), 0, n_t)
                learn_tgt = jax.lax.dynamic_slice_in_dim(
                    learn_tgt_full, d * n_loc, n_loc)
                if config.learn_from_severity > 0:
                    post_attack = jax.lax.all_gather(w_t, SOUP_AXIS, tiled=True)
                    learned, _ = jax.vmap(
                        lambda wi, ow: _learn_epochs(tc, wi, ow)
                    )(w_t, post_attack[learn_tgt])
                    w_t = jnp.where(learn_gate[:, None], learned, w_t)
                learn_cp = all_uids_t[t][learn_tgt]
            else:
                learn_gate = jnp.zeros(n_loc, bool)
                learn_tgt = jnp.zeros(n_loc, jnp.int32)
                learn_cp = jnp.zeros(n_loc, jnp.int32)

        # --- train ------------------------------------------------------
        with jax.named_scope("multisoup.train"):
            if config.train > 0:
                w_t, loss_t = jax.vmap(lambda wi: _train_epochs(tc, wi))(w_t)
            else:
                loss_t = jnp.zeros(n_loc, w_t.dtype)

        # --- respawn: global per-type dead-rank, replicated fresh draws -
        with jax.named_scope("multisoup.respawn"):
            dead_div = is_diverged(w_t) if tc.remove_divergent \
                else jnp.zeros(n_loc, bool)
            dead_zero = (is_zero(w_t, tc.epsilon) & ~dead_div) \
                if tc.remove_zero else jnp.zeros(n_loc, bool)
            dead = dead_div | dead_zero
            all_dead = jax.lax.all_gather(dead, SOUP_AXIS, tiled=True)  # (n_t,)
            rank = jnp.cumsum(all_dead) - 1
            rank_loc = jax.lax.dynamic_slice_in_dim(rank, d * n_loc, n_loc)
            fresh = fresh_rows(topo, re_keys[t], n_t, config.respawn_draws)
            fresh_loc = jax.lax.dynamic_slice_in_dim(fresh, d * n_loc, n_loc,
                                                     axis=0)
            w_t = jnp.where(dead[:, None], fresh_loc, w_t)
            uid_base = state.next_uid + total_deaths
            uids_t = jnp.where(dead, uid_base + rank_loc.astype(jnp.int32),
                               state.uids[t])
            total_deaths = total_deaths + all_dead.sum(dtype=jnp.int32)
            death_action = jnp.full(n_loc, ACT_NONE, jnp.int32)
            death_action = jnp.where(dead_div, ACT_DIV_DEAD, death_action)
            death_action = jnp.where(dead_zero, ACT_ZERO_DEAD, death_action)
            death_cp = jnp.where(dead, uids_t, -1)
        if lins is not None:
            lin_info.append((sl(att_idx), learn_gate, learn_tgt, dead))

        action, counterpart = _event_record(
            n_loc, sl(attack_gate), all_uids[sl(attack_tgt)],
            learn_gate, learn_cp, config.train > 0, death_action, death_cp)

        stored_t, scales_t = _downcast(config, w_t)
        new_weights.append(stored_t)
        new_scales.append(scales_t)
        new_uids.append(uids_t)
        actions.append(action)
        counterparts.append(counterpart)
        losses.append(loss_t)

    new_state = MultiSoupState(
        weights=tuple(new_weights), uids=tuple(new_uids),
        next_uid=state.next_uid + total_deaths, time=state.time + 1, key=key,
        scales=tuple(new_scales) if int8 else None)
    events = MultiSoupEvents(tuple(actions), tuple(counterparts),
                             tuple(losses))
    if lins is not None:
        from ..multisoup import _record_multi_lineage

        new_lins, win = _record_multi_lineage(lins, win, state.time,
                                              lin_info, lincfg,
                                              axes=SOUP_AXIS)
        return new_state, events, new_lins, win
    return new_state, events


def _local_evolve_multi_popmajor(config: MultiSoupConfig,
                                 state: MultiSoupState,
                                 wT_locs: Tuple[jnp.ndarray, ...],
                                 lins=None, win=None, lincfg=None):
    """Lane-major per-device body: ``wT_locs[t]`` is the LOCAL (P_t, N_t/D)
    lane shard of type t (``state.weights`` carries only uid/scalar
    metadata).  Same collectives and draw structure as
    ``_local_evolve_multi``; the heavy phases run the per-variant popmajor
    kernels (``ops/popmajor*.py``), cross-type attacks via
    ``cross_apply_popmajor``.  The lineage carry threads exactly as in
    ``_local_evolve_multi`` (globally-ranked mint bases, type-major)."""
    from ..multisoup import _fused_type_route, _type_scales
    from ..ops.popmajor import learn_epochs_popmajor, train_epochs_popmajor
    from ..ops.popmajor_cross import cross_apply_popmajor
    from ..soup import _downcast, _upcast

    fused = config.generation_impl == "fused"
    apply_impl = "xla" if fused else config.apply_impl

    n = config.total
    offs = config.offsets
    d = jax.lax.axis_index(SOUP_AXIS)
    int8 = config.population_dtype == "int8"
    key, k_ag, k_at, k_lg, k_lt, k_re = jax.random.split(state.key, 6)
    # storage-dtype shards ride the start-of-generation gather (bf16 ships
    # half the bytes; the upcast after is exact; int8 adds the per-type
    # scale gathers — dequant commutes with the gather); the per-type
    # POST-attack re-gathers stay f32 — mid-generation values, see
    # sharded_soup
    all_sT = tuple(jax.lax.all_gather(s, SOUP_AXIS, tiled=True)
                   for s in state.scales) if int8 else None
    all_wT = tuple(_upcast(config, jax.lax.all_gather(wT, SOUP_AXIS,
                                                      axis=1, tiled=True),
                           None if all_sT is None else all_sT[t], paxis=-1)
                   for t, wT in enumerate(wT_locs))
    wT_locs = tuple(_upcast(config, wT, _type_scales(state, t), paxis=-1)
                    for t, wT in enumerate(wT_locs))
    n_locs = [wT.shape[1] for wT in wT_locs]
    all_uids_t = tuple(jax.lax.all_gather(u, SOUP_AXIS, tiled=True)
                       for u in state.uids)
    all_uids = jnp.concatenate(all_uids_t)

    if config.attacking_rate > 0:
        attack_gate = jax.random.uniform(k_ag, (n,)) < config.attacking_rate
        attack_tgt = jax.random.randint(k_at, (n,), 0, n)
        att_idx = jax.ops.segment_max(
            jnp.where(attack_gate, jnp.arange(n), -1), attack_tgt,
            num_segments=n)
    else:
        attack_gate = jnp.zeros(n, bool)
        attack_tgt = jnp.zeros(n, jnp.int32)
        att_idx = jnp.full(n, -1, jnp.int32)
    lin_info = []

    new_wTs, new_uids, actions, counterparts, losses = [], [], [], [], []
    new_scales = []
    total_deaths = jnp.int32(0)
    re_keys = jax.random.split(k_re, len(config.topos))
    for t, topo in enumerate(config.topos):
        n_t = config.sizes[t]
        n_loc = n_locs[t]
        start = offs[t] + d * n_loc
        wT_t = wT_locs[t]

        def sl(arr, start=start, n_loc=n_loc):
            return jax.lax.dynamic_slice_in_dim(arr, start, n_loc)

        # --- attack on local victims (T^2 masked lane cross-apply) ------
        with jax.named_scope("multisoup.attack"):
            if config.attacking_rate > 0:
                att_b = sl(att_idx)
                out = wT_t
                for a, attacker_topo in enumerate(config.topos):
                    mask = (att_b >= offs[a]) & (att_b < offs[a + 1])
                    selfT = all_wT[a][:, jnp.clip(att_b - offs[a], 0,
                                                  config.sizes[a] - 1)]
                    attacked = cross_apply_popmajor(attacker_topo, selfT, topo,
                                                    wT_t,
                                                    impl=apply_impl)
                    out = jnp.where(mask[None, :], attacked, out)
                wT_t = out

        # learn draws are shared by both routes (the event record needs
        # them even when severity is 0); same key stream either way
        if config.learn_from_rate > 0:
            learn_gate = sl(jax.random.uniform(k_lg, (n,))) \
                < config.learn_from_rate
            learn_tgt_full = jax.random.randint(
                jax.random.fold_in(k_lt, t), (n_t,), 0, n_t)
            learn_tgt = jax.lax.dynamic_slice_in_dim(
                learn_tgt_full, d * n_loc, n_loc)
            learn_cp = all_uids_t[t][learn_tgt]
        else:
            learn_gate = jnp.zeros(n_loc, bool)
            learn_tgt = jnp.zeros(n_loc, jnp.int32)
            learn_cp = jnp.zeros(n_loc, jnp.int32)
        sgd_learn = config.learn_from_rate > 0 \
            and config.learn_from_severity > 0

        if fused and _fused_type_route(config, topo):
            # --- fused learn+train+respawn: one launch per shard --------
            # (cross-type attack above ran in XLA, so imitation columns
            # gather from the post-attack all_gather, no in-kernel
            # recompute; fresh/rank streams identical to the phase chain)
            from ..ops.pallas_generation import generation_popmajor

            with jax.named_scope("multisoup.fused_generation"):
                otherT = None
                if sgd_learn:
                    post_attack = jax.lax.all_gather(wT_t, SOUP_AXIS,
                                                     axis=1, tiled=True)
                    otherT = post_attack[:, learn_tgt]
                freshT = fresh_lanes(topo, re_keys[t], n_t,
                                     config.respawn_draws)
                freshT_loc = jax.lax.dynamic_slice_in_dim(
                    freshT, d * n_loc, n_loc, axis=1)
                wT_t, loss_t, dead_div, dead_zero = generation_popmajor(
                    topo, wT_t, freshT_loc, otherT=otherT,
                    learn_gate=learn_gate if sgd_learn else None,
                    severity=config.learn_from_severity if sgd_learn else 0,
                    train=config.train, lr=config.lr,
                    remove_divergent=config.remove_divergent,
                    remove_zero=config.remove_zero, epsilon=config.epsilon)
        else:
            # --- learn_from (same-type teachers, POST-attack re-gather) -
            with jax.named_scope("multisoup.learn_from"):
                if sgd_learn:
                    post_attack = jax.lax.all_gather(wT_t, SOUP_AXIS, axis=1,
                                                     tiled=True)
                    learned, _ = learn_epochs_popmajor(
                        topo, wT_t, post_attack[:, learn_tgt],
                        config.learn_from_severity, config.lr,
                        config.train_mode, config.train_impl)
                    wT_t = jnp.where(learn_gate[None, :], learned, wT_t)

            # --- train --------------------------------------------------
            with jax.named_scope("multisoup.train"):
                if config.train > 0:
                    wT_t, loss_t = train_epochs_popmajor(
                        topo, wT_t, config.train, config.lr,
                        config.train_mode, config.train_impl)
                else:
                    loss_t = jnp.zeros(n_loc, wT_t.dtype)

            # --- respawn predicates + replacement select ----------------
            with jax.named_scope("multisoup.respawn"):
                dead_div = is_diverged(wT_t, axis=0) \
                    if config.remove_divergent else jnp.zeros(n_loc, bool)
                dead_zero = (is_zero(wT_t, config.epsilon, axis=0)
                             & ~dead_div) \
                    if config.remove_zero else jnp.zeros(n_loc, bool)
                freshT = fresh_lanes(topo, re_keys[t], n_t,
                                     config.respawn_draws)
                freshT_loc = jax.lax.dynamic_slice_in_dim(
                    freshT, d * n_loc, n_loc, axis=1)
                wT_t = jnp.where((dead_div | dead_zero)[None, :], freshT_loc,
                                 wT_t)

        # --- shared bookkeeping: global per-type dead-rank uid blocks ---
        dead = dead_div | dead_zero
        all_dead = jax.lax.all_gather(dead, SOUP_AXIS, tiled=True)  # (n_t,)
        rank = jnp.cumsum(all_dead) - 1
        rank_loc = jax.lax.dynamic_slice_in_dim(rank, d * n_loc, n_loc)
        uid_base = state.next_uid + total_deaths
        uids_t = jnp.where(dead, uid_base + rank_loc.astype(jnp.int32),
                           state.uids[t])
        total_deaths = total_deaths + all_dead.sum(dtype=jnp.int32)
        death_action = jnp.full(n_loc, ACT_NONE, jnp.int32)
        death_action = jnp.where(dead_div, ACT_DIV_DEAD, death_action)
        death_action = jnp.where(dead_zero, ACT_ZERO_DEAD, death_action)
        death_cp = jnp.where(dead, uids_t, -1)
        if lins is not None:
            lin_info.append((sl(att_idx), learn_gate, learn_tgt, dead))

        action, counterpart = _event_record(
            n_loc, sl(attack_gate), all_uids[sl(attack_tgt)],
            learn_gate, learn_cp, config.train > 0, death_action, death_cp)

        stored_t, scales_t = _downcast(config, wT_t, paxis=-1)
        new_wTs.append(stored_t)
        new_scales.append(scales_t)
        new_uids.append(uids_t)
        actions.append(action)
        counterparts.append(counterpart)
        losses.append(loss_t)

    new_state = MultiSoupState(
        weights=state.weights, uids=tuple(new_uids),
        next_uid=state.next_uid + total_deaths, time=state.time + 1, key=key,
        scales=tuple(new_scales) if int8 else None)
    events = MultiSoupEvents(tuple(actions), tuple(counterparts),
                             tuple(losses))
    if lins is not None:
        from ..multisoup import _record_multi_lineage

        new_lins, win = _record_multi_lineage(lins, win, state.time,
                                              lin_info, lincfg,
                                              axes=SOUP_AXIS)
        return new_state, events, tuple(new_wTs), new_lins, win
    return new_state, events, tuple(new_wTs)


def _local_multi_popmajor_step(config: MultiSoupConfig,
                               state: MultiSoupState):
    """Single-step wrapper: transpose local (N/D, P) shards in and out."""
    new_state, events, wTs = _local_evolve_multi_popmajor(
        config, state, tuple(w.T for w in state.weights))
    return new_state._replace(weights=tuple(wT.T for wT in wTs)), events


def _sharded_evolve_multi_step(config: MultiSoupConfig, mesh: Mesh,
                               state: MultiSoupState):
    """One mixed-soup generation with every type's particle axis sharded."""
    if config.layout == "popmajor":
        _check_popmajor_multi(config)
        body = functools.partial(_local_multi_popmajor_step, config)
    elif config.layout == "rowmajor":
        if config.generation_impl != "phases":
            raise ValueError(
                "generation_impl='fused' is the popmajor lane megakernel; "
                "the row-major multisoup needs generation_impl='phases'")
        body = functools.partial(_local_evolve_multi, config)
    else:
        raise ValueError(f"unknown multisoup layout {config.layout!r}")
    int8 = config.population_dtype == "int8"
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(_mstate_specs(len(config.topos), int8),),
        out_specs=(_mstate_specs(len(config.topos), int8),
                   _mevent_specs(config)),
        check_vma=False,
    )
    return fn(state)


#: jitted sharded mixed-soup step + buffer-donating twin (state dead after
#: the call; rebinding callers only — see ``soup.evolve_step_donated``).
sharded_evolve_multi_step = jax.jit(_sharded_evolve_multi_step,
                                    static_argnames=("config", "mesh"))
sharded_evolve_multi_step_donated = jax.jit(
    _sharded_evolve_multi_step, static_argnames=("config", "mesh"),
    donate_argnums=(2,))


def _multi_metrics_specs(t: int):
    """Replicated placement of the per-type ``SoupMetrics`` carries
    (global after the in-body psum)."""
    from ..telemetry.device import SoupMetrics

    return tuple(SoupMetrics(generations=P(), actions=P(), loss_sum=P())
                 for _ in range(t))


def _multi_health_specs(t: int):
    """Replicated placement of the per-type ``HealthStats`` carries
    (global after the in-body psum/pmin/pmax)."""
    from ..telemetry.device import HealthStats

    return tuple(
        HealthStats(checks=P(), nonfinite=P(), nonfinite_peak=P(),
                    zero=P(), zero_peak=P(), norm_min=P(), norm_max=P(),
                    norm_hist=P())
        for _ in range(t))


def _sharded_evolve_multi(config: MultiSoupConfig, mesh: Mesh,
                          state: MultiSoupState, generations: int = 1,
                          metrics: bool = False, health: bool = False,
                          lineage: bool = False, lineage_state=None,
                          lineage_capacity: int = 4096):
    """Scan ``generations`` sharded mixed-soup steps inside ONE shard_map
    (collectives stay inside the scan).  The popmajor layout keeps every
    per-type local shard transposed (P_t, N_t/D) across generations.

    ``metrics=True`` additionally returns the GLOBAL per-type
    ``telemetry.device.SoupMetrics`` carries (per-shard accumulation
    inside the scan, one psum per type at the shard boundary);
    ``health=True`` the GLOBAL per-type ``telemetry.device.HealthStats``
    carries (counts psum'd, extrema pmin/pmax'd); ``lineage=True``
    (``lineage_state`` = per-type sharded-placed lineage carries, one
    shared pid space) the replication-dynamics triple
    ``(lineage_states, per-shard window, per-type FixpointStats)``.
    Return order: ``final``, metrics carries, health carries, lineage."""
    if config.layout not in ("rowmajor", "popmajor"):
        raise ValueError(f"unknown multisoup layout {config.layout!r}")
    if metrics:
        from ..telemetry.device import (accumulate_soup_metrics,
                                        psum_soup_metrics,
                                        zero_soup_metrics)

        def acc(ms, ev):
            return tuple(accumulate_soup_metrics(m, a, l) for m, a, l
                         in zip(ms, ev.action, ev.loss))

        def flush(ms):
            return tuple(psum_soup_metrics(m, SOUP_AXIS) for m in ms)

    if health:
        from ..telemetry.device import (accumulate_health, psum_health,
                                        zero_health)

        def acc_h(hs, ws, scs, axis):
            # int8 health folds read the dequantized f32 view; f32/bf16
            # read storage directly, exactly as before (axis=0 is the
            # lane-major (P, N/D) layout, particle axis last)
            from ..soup import _stored_view

            if scs is None:
                scs = (None,) * len(ws)
            paxis = -1 if axis == 0 else 0
            return tuple(
                accumulate_health(h, _stored_view(config, w, sc, paxis),
                                  axis, config.epsilon)
                for h, w, sc in zip(hs, ws, scs))

        def flush_h(hs):
            return tuple(psum_health(h, SOUP_AXIS) for h in hs)

    lincfg = None
    if lineage:
        if lineage_state is None or len(lineage_state) != len(config.topos):
            raise ValueError(
                "lineage=True needs lineage_state= (per-type carries from "
                "telemetry.dynamics.seed_lineage_blocks, sharded-placed)")
        from ..soup import _lineage_caps
        from ..telemetry.dynamics import (close_window, fixpoint_specs,
                                          lineage_specs, psum_fixpoints,
                                          window_specs, zero_window)

        n_dev = mesh.devices.size
        lincfg = (tuple(_lineage_caps(n_t // n_dev, config, lineage_capacity)
                        for n_t in config.sizes), lineage_capacity)

    def m0():
        return tuple(zero_soup_metrics() for _ in config.topos) \
            if metrics else None

    def h0():
        return tuple(zero_health() for _ in config.topos) \
            if health else None

    def close(lins, ws, axis, scales=None):
        from ..nets import apply_to_weights
        from ..ops.popmajor import apply_popmajor

        from ..soup import _upcast

        new_lins, stats = [], []
        for t, (lin_t, w_t) in enumerate(zip(lins, ws)):
            topo = config.topos[t]
            w_t = _upcast(config, w_t,
                          None if scales is None else scales[t],
                          paxis=-1 if axis == 0 else 0)
            if axis == 0:
                fw = apply_popmajor(topo, w_t, w_t)
            else:
                fw = jax.vmap(
                    lambda wi, topo=topo: apply_to_weights(topo, wi, wi))(w_t)
            lin_t, s = close_window(lin_t, w_t, fw, axis, config.epsilon)
            new_lins.append(lin_t)
            stats.append(psum_fixpoints(s, SOUP_AXIS))
        return tuple(new_lins), tuple(stats)

    def pack(final, ms, hs, ltriple=None):
        out = (final,)
        if metrics:
            out += (flush(ms),)
        if health:
            out += (flush_h(hs),)
        if lineage:
            out += (ltriple,)
        return out if len(out) > 1 else final

    nt = len(config.topos)
    int8 = config.population_dtype == "int8"
    in_specs = (_mstate_specs(nt, int8),)
    out_specs = (_mstate_specs(nt, int8),)
    if metrics:
        out_specs += (_multi_metrics_specs(nt),)
    if health:
        out_specs += (_multi_health_specs(nt),)
    if lineage:
        in_specs += (tuple(lineage_specs(SOUP_AXIS) for _ in range(nt)),)
        out_specs += ((tuple(lineage_specs(SOUP_AXIS) for _ in range(nt)),
                       window_specs(SOUP_AXIS),
                       tuple(fixpoint_specs() for _ in range(nt))),)
    if len(out_specs) == 1:
        out_specs = out_specs[0]
    if config.layout == "popmajor":
        _check_popmajor_multi(config)

        def local_run_t(st: MultiSoupState, *lin_args):
            light = st._replace(weights=tuple(
                jnp.zeros((0,), w.dtype) for w in st.weights))
            l0 = lin_args[0] if lineage else None
            w0 = zero_window(lineage_capacity) if lineage else None

            def body(carry, _):
                s, wTs, ms, hs, lins, win = carry
                if lineage:
                    new_s, ev, new_wTs, lins, win = \
                        _local_evolve_multi_popmajor(config, s, wTs, lins,
                                                     win, lincfg)
                else:
                    new_s, ev, new_wTs = _local_evolve_multi_popmajor(
                        config, s, wTs)
                if metrics:
                    ms = acc(ms, ev)
                if health:
                    hs = acc_h(hs, new_wTs, new_s.scales, 0)
                return (new_s, new_wTs, ms, hs, lins, win), None

            (final, wTs, ms, hs, lins, win), _ = jax.lax.scan(
                body, (light, tuple(w.T for w in st.weights), m0(), h0(),
                       l0, w0), None, length=generations)
            final = final._replace(weights=tuple(wT.T for wT in wTs))
            ltriple = None
            if lineage:
                lins, stats = close(lins, wTs, 0, final.scales)
                ltriple = (lins, win, stats)
            return pack(final, ms, hs, ltriple)

        fn = shard_map(
            local_run_t,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=False,
        )
        return fn(state, lineage_state) if lineage else fn(state)

    def local_run(st: MultiSoupState, *lin_args):
        l0 = lin_args[0] if lineage else None
        w0 = zero_window(lineage_capacity) if lineage else None

        def body(carry, _):
            s, ms, hs, lins, win = carry
            if lineage:
                new_s, ev, lins, win = _local_evolve_multi(config, s, lins,
                                                           win, lincfg)
            else:
                new_s, ev = _local_evolve_multi(config, s)
            if metrics:
                ms = acc(ms, ev)
            if health:
                hs = acc_h(hs, new_s.weights, new_s.scales, -1)
            return (new_s, ms, hs, lins, win), None

        (final, ms, hs, lins, win), _ = jax.lax.scan(
            body, (st, m0(), h0(), l0, w0), None, length=generations)
        ltriple = None
        if lineage:
            lins, stats = close(lins, final.weights, -1, final.scales)
            ltriple = (lins, win, stats)
        return pack(final, ms, hs, ltriple)

    fn = shard_map(
        local_run,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=False,
    )
    return fn(state, lineage_state) if lineage else fn(state)


sharded_evolve_multi = jax.jit(
    _sharded_evolve_multi,
    static_argnames=("config", "mesh", "generations", "metrics", "health",
                     "lineage", "lineage_capacity"))
sharded_evolve_multi_donated = jax.jit(
    _sharded_evolve_multi,
    static_argnames=("config", "mesh", "generations", "metrics", "health",
                     "lineage", "lineage_capacity"),
    donate_argnums=(2,))


@functools.partial(jax.jit, static_argnames=("config", "mesh"))
def sharded_count_multi(config: MultiSoupConfig, mesh: Mesh,
                        state: MultiSoupState) -> jnp.ndarray:
    """(T, 5) per-type global class histograms: local classify + psum."""
    nt = len(config.topos)
    int8 = config.population_dtype == "int8"

    def local_count(*args):
        from ..soup import _stored_view

        w_locs, s_locs = args[:nt], args[nt:] if int8 else (None,) * nt
        rows = [count_classes(classify_batch(
                    config.topos[t],
                    _stored_view(config, w_locs[t], s_locs[t]),
                    config.epsilon))
                for t in range(nt)]
        return jax.lax.psum(jnp.stack(rows), SOUP_AXIS)

    n_in = nt * 2 if int8 else nt
    fn = shard_map(
        local_count,
        mesh=mesh,
        in_specs=tuple(P(SOUP_AXIS) for _ in range(n_in)),
        out_specs=P(),
        check_vma=False,
    )
    return fn(*state.weights, *state.scales) if int8 else fn(*state.weights)


def place_sharded_multi_state(mesh: Mesh, state: MultiSoupState
                              ) -> MultiSoupState:
    """Place an existing ``MultiSoupState`` (fresh-seeded or
    checkpoint-restored) with the per-type particle sharding."""
    n_dev = mesh.devices.size
    for t, w in enumerate(state.weights):
        if w.shape[0] % n_dev:
            raise ValueError(
                f"type-{t} population {w.shape[0]} must be divisible by the "
                f"mesh's {n_dev} devices (each device owns an equal shard "
                "per type)")
    from .mesh import global_device_put
    specs = _mstate_specs(len(state.weights),
                          int8=state.scales is not None)
    return jax.tree.map(
        lambda x, spec: global_device_put(x, NamedSharding(mesh, spec)),
        state, specs)


def make_sharded_multi_state(config: MultiSoupConfig, mesh: Mesh,
                             key: jax.Array) -> MultiSoupState:
    """Seed a mixed population already placed with the per-type sharding."""
    return place_sharded_multi_state(mesh, seed_multi(config, key))
