from .mesh import soup_mesh, shard_population, replicate, initialize_distributed
from .sharded_soup import (
    make_sharded_state,
    sharded_evolve,
    sharded_evolve_step,
    sharded_count,
)
from .ring_rnn import ring_rnn_apply

__all__ = [
    "soup_mesh",
    "shard_population",
    "replicate",
    "initialize_distributed",
    "make_sharded_state",
    "sharded_evolve_step",
    "sharded_evolve",
    "sharded_count",
    "ring_rnn_apply",
]
