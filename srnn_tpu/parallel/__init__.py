from .mesh import (soup_mesh, shard_population, replicate,
                   initialize_distributed, probe_devices,
                   global_device_put)
from .sharded_soup import (
    make_sharded_state,
    place_sharded_state,
    sharded_evolve,
    sharded_evolve_donated,
    sharded_evolve_step,
    sharded_evolve_step_donated,
    sharded_count,
)
from .sharded_multisoup import (
    make_sharded_multi_state,
    place_sharded_multi_state,
    sharded_evolve_multi,
    sharded_evolve_multi_donated,
    sharded_evolve_multi_step,
    sharded_evolve_multi_step_donated,
    sharded_count_multi,
)
from .ring_rnn import ring_rnn_apply
from .sharded_apply import (
    rnn_associative_apply,
    sharded_aggregating_apply,
    sharded_apply_to_weights,
    sharded_fft_apply,
    sharded_weightwise_apply,
)
from .multihost import (DCN_AXIS, multislice_soup_mesh, reramp_soup_mesh,
                        slice_groups)

__all__ = [
    "DCN_AXIS",
    "multislice_soup_mesh",
    "probe_devices",
    "reramp_soup_mesh",
    "slice_groups",
    "soup_mesh",
    "global_device_put",
    "shard_population",
    "replicate",
    "initialize_distributed",
    "make_sharded_state",
    "place_sharded_state",
    "sharded_evolve_step",
    "sharded_evolve_step_donated",
    "sharded_evolve",
    "sharded_evolve_donated",
    "sharded_count",
    "make_sharded_multi_state",
    "place_sharded_multi_state",
    "sharded_evolve_multi_step",
    "sharded_evolve_multi_step_donated",
    "sharded_evolve_multi",
    "sharded_evolve_multi_donated",
    "sharded_count_multi",
    "ring_rnn_apply",
    "rnn_associative_apply",
    "sharded_apply_to_weights",
    "sharded_weightwise_apply",
    "sharded_aggregating_apply",
    "sharded_fft_apply",
]
