"""Sequence/context parallelism for the recurrent transform.

The recurrent variant consumes the target's flat weights as ONE sequence of
length T = P (reference ``network.py:544-564``); at mega-particle sizes that
sequence no longer fits one device's sweet spot, and the recurrence is the
only transform that cannot be sliced embarrassingly (SURVEY §5
"long-context" row).  This module shards the TIME axis across the mesh and
passes the hidden state around a ``ppermute`` ring — the RNN analog of ring
attention's block hand-off:

  device 0: scans its chunk, hands h to device 1, which scans its chunk, ...

The wavefront runs D stages per layer; each stage every device executes its
local ``lax.scan`` (compute is masked-redundant — only the device whose turn
it is keeps the result, the standard simple pipeline).  Wall-clock per layer
stays O(T) like the serial scan — the win is MEMORY (each device holds T/D
of the sequence) plus layer-level pipelining across the stack.  For the
default linear activation the recurrence is affine; the single-device
O(log T)-depth fast path is ``Topology(rnn_scan='associative')``
(``nets/recurrent.py``), and a distributed associative scan (O(T/D) time)
remains the documented next step for multi-device long sequences.
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.activations import resolve_activation
from ..ops.flatten import unflatten
from ..ops.linalg import matmul
from ..topology import Topology
from .mesh import SOUP_AXIS
from .compat import shard_map


def _local_forward(topo: Topology, n_dev: int, self_flat, seq_loc):
    """Per-device body: seq_loc (T/D, 1) chunk of the global sequence."""
    act = resolve_activation(topo.activation)
    mats = unflatten(topo, self_flat)
    d = jax.lax.axis_index(SOUP_AXIS)
    ring = [(j, (j + 1) % n_dev) for j in range(n_dev)]

    x = seq_loc
    for layer, (_, units) in enumerate(topo.rnn_layer_dims):
        kernel, recurrent = mats[2 * layer], mats[2 * layer + 1]

        def step(h, xt, kernel=kernel, recurrent=recurrent, act=act):
            h_new = act(matmul(topo, xt, kernel) + matmul(topo, h, recurrent))
            return h_new, h_new

        h_in = jnp.zeros((units,), dtype=x.dtype)
        ys = jnp.zeros((x.shape[0], units), dtype=x.dtype)
        for stage in range(n_dev):
            h_last, ys_stage = jax.lax.scan(step, h_in, x)
            mine = d == stage
            ys = jnp.where(mine, ys_stage, ys)
            # ring hand-off: the active device's final h reaches stage+1
            h_recv = jax.lax.ppermute(jnp.where(mine, h_last, h_in), SOUP_AXIS, ring)
            h_in = jnp.where(d == stage + 1, h_recv, h_in)
        x = ys
    return x


@functools.partial(jax.jit, static_argnames=("topo", "mesh"))
def ring_rnn_apply(topo: Topology, mesh: Mesh, self_flat: jax.Array,
                   target_flat: jax.Array) -> jax.Array:
    """Sequence-parallel equivalent of ``recurrent.apply``.

    ``self_flat`` is replicated (the net's own parameters); ``target_flat``
    (T,) is sharded over the mesh on the time axis.  T need not divide the
    mesh: the tail is zero-padded to a multiple of D and sliced back — safe
    because the recurrence is causal, so padding after position T cannot
    affect the kept outputs.  (Real particle sequences have odd T — e.g.
    P=17 for the width-2 depth-2 net — so padding is the common case.)
    Numerically identical to the single-device scan.
    """
    assert topo.variant == "recurrent"
    n_dev = mesh.devices.size
    t = target_flat.shape[0]
    pad = (-t) % n_dev
    if pad:
        target_flat = jnp.pad(target_flat, (0, pad))

    def body(self_flat, tgt_loc):
        return _local_forward(topo, n_dev, self_flat, tgt_loc[:, None])[:, 0]

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(SOUP_AXIS)),
        out_specs=P(SOUP_AXIS),
        check_vma=False,
    )
    out = fn(self_flat, target_flat)
    return out[:t] if pad else out
