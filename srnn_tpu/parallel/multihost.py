"""Multi-slice mesh construction (DCN tier of SURVEY §2.5).

Single-slice soups scale over ICI via ``sharded_soup`` + ``soup_mesh``;
process bring-up is ``mesh.initialize_distributed``.  Beyond one slice
(multi-pod), the mesh needs an outer axis spanning slices over DCN with the
inner axis staying on ICI.  The collectives in ``sharded_soup`` are
axis-name-agnostic, so the same ``shard_map`` body runs unchanged on these
meshes — the all-gather of a mega-soup's weight matrix is the only
DCN-crossing traffic, one fused collective per generation.
"""

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from .mesh import SOUP_AXIS

DCN_AXIS = "slices"


def multislice_soup_mesh(num_slices: int,
                         devices: Optional[Sequence] = None) -> Mesh:
    """(slices, particles) mesh: outer axis crosses DCN, inner axis rides
    ICI.  Shard soups with ``P((DCN_AXIS, SOUP_AXIS))`` on the particle
    dimension so each slice owns a contiguous block and intra-slice
    exchange stays on ICI."""
    devs = np.asarray(devices if devices is not None else jax.devices())
    if devs.size % num_slices:
        raise ValueError(
            f"{devs.size} devices do not split into {num_slices} slices")
    grid = devs.reshape(num_slices, devs.size // num_slices)
    return Mesh(grid, (DCN_AXIS, SOUP_AXIS))
