"""Multi-slice mesh construction (DCN tier of SURVEY §2.5).

Single-slice soups scale over ICI via ``sharded_soup`` + ``soup_mesh``;
process bring-up is ``mesh.initialize_distributed``.  Beyond one slice
(multi-pod), the mesh needs an outer axis spanning slices over DCN with the
inner axis staying on ICI.  The collectives in ``sharded_soup`` are
axis-name-agnostic, so the same ``shard_map`` body runs unchanged on these
meshes — the all-gather of a mega-soup's weight matrix is the only
DCN-crossing traffic, one fused collective per generation.
"""

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from .mesh import SOUP_AXIS

DCN_AXIS = "slices"


def multislice_soup_mesh(num_slices: int,
                         devices: Optional[Sequence] = None) -> Mesh:
    """(slices, particles) mesh: outer axis crosses DCN, inner axis rides
    ICI.  Shard soups with ``P((DCN_AXIS, SOUP_AXIS))`` on the particle
    dimension so each slice owns a contiguous block and intra-slice
    exchange stays on ICI."""
    devs = np.asarray(devices if devices is not None else jax.devices())
    if devs.size % num_slices:
        raise ValueError(
            f"{devs.size} devices do not split into {num_slices} slices")
    grid = devs.reshape(num_slices, devs.size // num_slices)
    return Mesh(grid, (DCN_AXIS, SOUP_AXIS))


def slice_groups(devices) -> "list[list]":
    """Partition devices by the slice they live on, parsed from whatever
    topology attributes the platform exposes (``slice_index`` on TPU,
    ``process_index`` as the multi-host fallback, one group when neither
    varies) — the mesh-from-topology idiom: derive placement from the
    devices actually present instead of from a config that described the
    hardware the run *used to* have."""
    groups: "dict[int, list]" = {}
    for d in devices:
        key = getattr(d, "slice_index", None)
        if key is None:
            key = getattr(d, "process_index", 0) or 0
        groups.setdefault(int(key), []).append(d)
    return [groups[k] for k in sorted(groups)]


def reramp_soup_mesh(devices=None) -> Mesh:
    """Rebuild the largest *regular* mesh the SURVIVING devices support —
    the topology re-ramp step after a partial loss (a preempted slice, a
    dead host).  Slices that kept their full (modal) chip count form the
    DCN axis of a fresh ``(slices, soup)`` mesh; when fewer than two
    whole slices survive — or the survivors are ragged — the largest
    single intact group becomes a 1-D soup mesh, ICI-only.  Raises
    ``ValueError`` when nothing survives (the supervisor then degrades
    to the process-restart tier, ``scripts/tpu_watch.sh``)."""
    devs = list(devices if devices is not None else jax.devices())
    if not devs:
        raise ValueError("no surviving devices to re-ramp onto")
    groups = slice_groups(devs)
    sizes = [len(g) for g in groups]
    modal = max(set(sizes), key=lambda s: (sizes.count(s), s))
    whole = [g for g in groups if len(g) == modal]
    if len(whole) >= 2:
        return Mesh(np.asarray(whole), (DCN_AXIS, SOUP_AXIS))
    return Mesh(np.asarray(max(groups, key=len)), (SOUP_AXIS,))
