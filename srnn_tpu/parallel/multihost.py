"""Multi-slice mesh construction (DCN tier of SURVEY §2.5).

Single-slice soups scale over ICI via ``sharded_soup`` + ``soup_mesh``;
process bring-up is ``distributed.bootstrap`` (wrapping
``jax.distributed``).  Beyond one slice (multi-pod, or a multi-process
CPU mesh in CI), the mesh needs an outer axis spanning slices over DCN
with the inner axis staying on ICI.  The collectives in ``sharded_soup``
are axis-name-agnostic, so the same ``shard_map`` body runs unchanged on
these meshes — the all-gather of a mega-soup's weight matrix is the only
DCN-crossing traffic, one fused collective per generation.

Since the distributed tier landed, :func:`reramp_soup_mesh` is the LIVE
mesh builder for every multislice run (``setups.common.build_soup_mesh``
routes through it at bring-up AND after a loss), not just recovery
documentation: the mega loops publish their population sizes and this
module picks the largest regular mesh the survivors support whose device
count divides every published shard.
"""

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from .mesh import SOUP_AXIS

DCN_AXIS = "slices"

#: CI/bring-up override: partition an otherwise-flat topology into N
#: equal contiguous slice groups (see :func:`slice_groups`)
FORCE_SLICES_ENV = "SRNN_FORCE_SLICES"


def multislice_soup_mesh(num_slices: int,
                         devices: Optional[Sequence] = None) -> Mesh:
    """(slices, particles) mesh: outer axis crosses DCN, inner axis rides
    ICI.  Shard soups with ``P((DCN_AXIS, SOUP_AXIS))`` on the particle
    dimension so each slice owns a contiguous block and intra-slice
    exchange stays on ICI."""
    devs = np.asarray(devices if devices is not None else jax.devices())
    if devs.size % num_slices:
        raise ValueError(
            f"{devs.size} devices do not split into {num_slices} slices")
    grid = devs.reshape(num_slices, devs.size // num_slices)
    return Mesh(grid, (DCN_AXIS, SOUP_AXIS))


def slice_groups(devices, force_slices: Optional[int] = None) -> "list[list]":
    """Partition devices by the slice they live on, parsed from whatever
    topology attributes the platform exposes (``slice_index`` on TPU,
    ``process_index`` as the multi-host fallback, one group when neither
    varies) — the mesh-from-topology idiom: derive placement from the
    devices actually present instead of from a config that described the
    hardware the run *used to* have.

    ``force_slices`` (or env ``SRNN_FORCE_SLICES``) splits an
    otherwise-FLAT topology into N equal contiguous groups — the
    CI/bring-up knob that lets the multislice tier (2-D mesh, host-loss
    re-ramp) run on a single process whose devices expose no slice
    structure.  A real topology (distinct slice/process indices) always
    wins over the override, and an override that does not divide the
    device count is ignored (a ragged forced grid would only fail later
    in mesh construction)."""
    devices = list(devices)
    # slice_index wins when it actually VARIES; a constant value (CPU
    # devices expose slice_index=0 on every process) carries no topology
    # information and would hide the per-process structure a multi-host
    # CPU mesh does have
    slice_keys = [getattr(d, "slice_index", None) for d in devices]
    use_slice = not any(k is None for k in slice_keys) \
        and len(set(slice_keys)) > 1
    groups: "dict[int, list]" = {}
    for d in devices:
        key = getattr(d, "slice_index", None) if use_slice else None
        if key is None:
            key = getattr(d, "process_index", 0) or 0
        groups.setdefault(int(key), []).append(d)
    out = [groups[k] for k in sorted(groups)]
    if len(out) == 1:
        if force_slices is None:
            force_slices = int(os.environ.get(FORCE_SLICES_ENV, "0") or 0)
        flat = out[0]
        if force_slices > 1 and len(flat) >= force_slices \
                and len(flat) % force_slices == 0:
            per = len(flat) // force_slices
            out = [flat[i * per:(i + 1) * per] for i in range(force_slices)]
    return out


def reramp_soup_mesh(devices=None, shard_sizes: Sequence[int] = ()) -> Mesh:
    """Build the largest *regular* mesh the given devices support — the
    live mesh builder for multislice runs, at bring-up and after a
    partial loss (a preempted slice, a dead host).

    Slices that kept their full (modal) chip count form the DCN axis of a
    ``(slices, soup)`` mesh; when fewer than two whole slices remain — or
    the survivors are ragged — the largest single intact group becomes a
    1-D soup mesh, ICI-only.  ``shard_sizes`` (the population sizes the
    loops publish) constrains the choice to device counts the shards
    actually divide over: trailing whole slices are dropped first (a
    2-slice grid whose total does not divide snaps to fewer slices before
    giving up regularity), then the 1-D fallback shrinks its device
    count — the same divisor snap ``AttemptContext.mesh_devices`` applies
    to 1-D budgets, made slice-aware.  Raises ``ValueError`` when nothing
    survives (the supervisor then degrades to the process-restart tier,
    ``scripts/tpu_watch.sh`` / the ``distributed.launch`` re-ramp)."""
    devs = list(devices if devices is not None else jax.devices())
    if not devs:
        raise ValueError("no surviving devices to re-ramp onto")
    sizes = tuple(int(s) for s in shard_sizes)

    def divides(n: int) -> bool:
        return n > 0 and not any(s % n for s in sizes)

    groups = slice_groups(devs)
    lens = [len(g) for g in groups]
    modal = max(set(lens), key=lambda s: (lens.count(s), s))
    whole = [g for g in groups if len(g) == modal]
    while len(whole) >= 2 and not divides(len(whole) * modal):
        whole.pop()
    if len(whole) >= 2:
        return Mesh(np.asarray(whole), (DCN_AXIS, SOUP_AXIS))
    best = max(groups, key=len)
    n = len(best)
    while n > 1 and not divides(n):
        n -= 1
    return Mesh(np.asarray(best[:n]), (SOUP_AXIS,))
