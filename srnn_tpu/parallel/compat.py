"""JAX version-compat shims for the parallel layer.

``shard_map`` has moved twice across the JAX versions this package must
run on: newest releases export it as ``jax.shard_map`` (keyword-only
``mesh=``/``in_specs=``/``out_specs=`` and a ``check_vma`` flag), while
older ones only ship ``jax.experimental.shard_map.shard_map`` (positional
mesh/specs allowed and the same flag spelled ``check_rep``).  Every module
here imports :func:`shard_map` from THIS shim so the rest of the parallel
layer can write the modern spelling (``check_vma=...``) and run on either.
"""

import inspect

try:  # jax >= 0.6: public top-level export
    from jax import shard_map as _shard_map
except ImportError:  # older jax: experimental home
    from jax.experimental.shard_map import shard_map as _shard_map

_ACCEPTED = frozenset(inspect.signature(_shard_map).parameters)


def shard_map(f, **kwargs):
    """``jax.shard_map`` with the replication-check flag translated to
    whatever the installed JAX spells it (``check_vma`` <-> ``check_rep``).

    Callers use keyword arguments only (mesh=, in_specs=, out_specs=,
    check_vma=) — both upstream signatures accept those.
    """
    if "check_vma" in kwargs and "check_vma" not in _ACCEPTED:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    elif "check_rep" in kwargs and "check_rep" not in _ACCEPTED:
        kwargs["check_vma"] = kwargs.pop("check_rep")
    return _shard_map(f, **kwargs)
