"""Soup evolution sharded over a device mesh via ``shard_map``.

Scale-out design (SURVEY §2.5 / §7.6), built not ported — the reference has
no distributed backend at all:

  * The particle axis is sharded: each device owns ``N / D`` rows of the
    ``(N, P)`` weight matrix and does ALL heavy work (self-applications,
    SGD epochs) only for its shard.
  * The soup PRNG key is **replicated**; every device derives the same
    global gate/target draws with cheap O(N) scalar ops, so no RNG
    communication is needed and the sharded soup is bit-deterministic.
  * Counterpart weights (attackers seen by local victims, imitation targets
    of local learners) come from ONE ``all_gather`` of the weight matrix per
    generation.  Particles are tiny (P ~ 14 floats), so even a 1M-particle
    soup gathers ~56 MB — well within HBM and ICI budget; this is by far
    the simplest correct exchange and it rides ICI as a single fused
    collective.  (A ppermute ring exchange would only pay off for particles
    orders of magnitude larger.)
  * Respawned particles draw fresh uids from per-device blocks computed
    with an ``all_gather`` of death counts — monotone unique uids without a
    host round-trip.

Row-major semantics match ``soup._evolve_parallel`` with two
sharding-induced differences: (a) imitation targets read
start-of-generation weights (the all_gather snapshot) rather than
post-attack ones — visible only when a particle learns from a victim
attacked in the same generation; (b) respawn draws fold the device index
into the key, so fresh particles differ from the unsharded stream (same
distribution).  Attack/train phases are bit-identical under matched keys,
which tests assert.

The population-major layout (``layout='popmajor'``, the fast (P, N)
lane-major path for mega-soups) is ALSO sharded here — each device owns a
(P, N/D) lane shard — and its sharded step is **fully bitwise** vs the
single-device popmajor step, respawn and imitation included (see
``_local_evolve_popmajor``).
"""

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..init import fresh_lanes
from ..nets import apply_to_weights
from ..ops.predicates import count_classes, is_diverged, is_zero
from ..soup import (
    ACT_DIV_DEAD,
    ACT_NONE,
    ACT_ZERO_DEAD,
    SoupConfig,
    SoupEvents,
    SoupState,
    _check_popmajor,
    _event_record,
    _learn_epochs,
    _respawn,
    _train_epochs,
    seed,
)
from ..engine import classify_batch
from .mesh import SOUP_AXIS
from .compat import shard_map


def _soup_axes(mesh: Mesh):
    """The mesh axis name(s) the particle dimension is sharded over.

    A 1-D ``soup_mesh`` uses the single ICI axis; a
    ``multihost.multislice_soup_mesh`` adds the outer DCN axis, and the
    particle dimension shards over BOTH (``P((DCN_AXIS, SOUP_AXIS))``) so
    each slice owns a contiguous block.  Every collective in the local
    bodies takes this name (or tuple of names) — the bodies are genuinely
    axis-agnostic, which is what makes the same code the DCN tier."""
    return tuple(mesh.axis_names) if len(mesh.axis_names) > 1 else SOUP_AXIS


def _state_specs(axes=SOUP_AXIS, int8=False):
    # int8 populations carry a per-particle scale vector that shards with
    # the particle axis like uids; f32/bf16 states have scales=None (empty
    # subtree), so the spec tree must mirror that None-for-None
    return SoupState(
        weights=P(axes),
        uids=P(axes),
        next_uid=P(),
        time=P(),
        key=P(),
        scales=P(axes) if int8 else None,
    )


def _event_specs(axes=SOUP_AXIS):
    return SoupEvents(action=P(axes), counterpart=P(axes), loss=P(axes))


def _local_evolve(config: SoupConfig, state: SoupState,
                  axes=SOUP_AXIS, lin=None, win=None, lincfg=None):
    """Per-device body. ``state.weights``/``uids`` hold the LOCAL shard;
    scalars and the key are replicated.  ``axes`` is the mesh axis name (or
    tuple: multislice DCN+ICI) the particle dimension shards over.  With a
    lineage carry (``lin``/``win``/``lincfg``, see ``telemetry.dynamics``)
    the advanced carries ride along — mint bases come from the
    all-gathered mask ranks, so pids stay globally unique."""
    from ..soup import _downcast, _upcast

    n = config.size
    w_loc = _upcast(config, state.weights, state.scales)
    n_loc = w_loc.shape[0]
    d = jax.lax.axis_index(axes)
    start = d * n_loc
    topo = config.topo
    has_attacker = jnp.zeros(n_loc, bool)
    att_loc = jnp.full(n_loc, -1, jnp.int32)

    key, k_ag, k_at, k_lg, k_lt, k_re = jax.random.split(state.key, 6)

    # one collective: everyone sees the start-of-generation population.
    # The gather ships the STORAGE dtype and upcasts after — for bf16
    # populations that halves the dominant collective's bytes, and the
    # bf16->f32 cast is exact so the values are identical either way.
    # int8 gathers codes + per-particle scales (quarter the bytes plus an
    # O(N) vector) and dequantizes after — elementwise per particle, so
    # gather-then-dequant equals dequant-then-gather bitwise
    all_s = jax.lax.all_gather(state.scales, axes, tiled=True) \
        if config.population_dtype == "int8" else None
    all_w = _upcast(config, jax.lax.all_gather(state.weights, axes,
                                               tiled=True), all_s)  # (N, P)

    # --- attack ---------------------------------------------------------
    with jax.named_scope("soup.attack"):
        if config.attacking_rate > 0:
            attack_gate = jax.random.uniform(k_ag, (n,)) < config.attacking_rate
            attack_tgt = jax.random.randint(k_at, (n,), 0, n)
            att_idx = jax.ops.segment_max(
                jnp.where(attack_gate, jnp.arange(n), -1), attack_tgt, num_segments=n)
            att_loc = jax.lax.dynamic_slice_in_dim(att_idx, start, n_loc)
            has_attacker = att_loc >= 0
            attacker_w = all_w[jnp.clip(att_loc, 0)]
            attacked = jax.vmap(lambda s, t: apply_to_weights(topo, s, t))(attacker_w, w_loc)
            w_loc = jnp.where(has_attacker[:, None], attacked, w_loc)
            attack_gate_loc = jax.lax.dynamic_slice_in_dim(attack_gate, start, n_loc)
            attack_tgt_loc = jax.lax.dynamic_slice_in_dim(attack_tgt, start, n_loc)
        else:
            attack_gate_loc = jnp.zeros(n_loc, bool)
            attack_tgt_loc = jnp.zeros(n_loc, jnp.int32)

    # --- learn_from -----------------------------------------------------
    # imitation targets come from the start-of-generation gather; the
    # single-device path uses post-attack weights, an intra-generation
    # staleness difference only for the rare learn-from-an-attacked-victim
    with jax.named_scope("soup.learn_from"):
        if config.learn_from_rate > 0:
            learn_gate = jax.random.uniform(k_lg, (n,)) < config.learn_from_rate
            learn_tgt = jax.random.randint(k_lt, (n,), 0, n)
            learn_gate_loc = jax.lax.dynamic_slice_in_dim(learn_gate, start, n_loc)
            learn_tgt_loc = jax.lax.dynamic_slice_in_dim(learn_tgt, start, n_loc)
            if config.learn_from_severity > 0:
                learned, _ = jax.vmap(lambda wi, ow: _learn_epochs(config, wi, ow))(
                    w_loc, all_w[learn_tgt_loc])
                w_loc = jnp.where(learn_gate_loc[:, None], learned, w_loc)
        else:
            learn_gate_loc = jnp.zeros(n_loc, bool)
            learn_tgt_loc = jnp.zeros(n_loc, jnp.int32)

    # --- train ----------------------------------------------------------
    with jax.named_scope("soup.train"):
        if config.train > 0:
            w_loc, train_loss = jax.vmap(lambda wi: _train_epochs(config, wi))(w_loc)
        else:
            train_loss = jnp.zeros(n_loc, w_loc.dtype)

    # --- respawn with per-device uid blocks -----------------------------
    # pre-count deaths to carve a uid block for this device, then reuse the
    # single-device respawn with that block base — one semantic source
    with jax.named_scope("soup.respawn"):
        dead_now = jnp.zeros(n_loc, bool)
        if config.remove_divergent:
            dead_now = dead_now | is_diverged(w_loc)
        if config.remove_zero:
            dead_now = dead_now | is_zero(w_loc, config.epsilon)
        local_deaths = dead_now.sum(dtype=jnp.int32)
        deaths_by_dev = jax.lax.all_gather(local_deaths, axes)  # (D,)
        my_uid_base = state.next_uid + jnp.sum(
            jnp.where(jnp.arange(deaths_by_dev.shape[0]) < d, deaths_by_dev, 0))
        new_w, new_uids, _, death_action, death_cp = _respawn(
            config, w_loc, state.uids, my_uid_base, jax.random.fold_in(k_re, d))
        next_uid = state.next_uid + deaths_by_dev.sum()

    # --- event record (last action wins, shared tail) -------------------
    # uid of a global index: gather from the uid table
    all_uids = jax.lax.all_gather(state.uids, axes, tiled=True)
    action, counterpart = _event_record(
        n_loc, attack_gate_loc, all_uids[attack_tgt_loc],
        learn_gate_loc, all_uids[learn_tgt_loc],
        config.train > 0, death_action, death_cp)

    stored, scales = _downcast(config, new_w)
    new_state = SoupState(stored, new_uids, next_uid,
                          state.time + 1, key, scales)
    events = SoupEvents(action, counterpart, train_loss)
    if lin is None:
        return new_state, events
    from ..telemetry.dynamics import lookup_pids, record_step

    caps, capacity = lincfg
    lin, win = record_step(
        lin, win, gen=state.time, attacked=has_attacker,
        attacker_pid=lookup_pids(lin.pid, jnp.clip(att_loc, 0), axes),
        learn_gate=learn_gate_loc, learn_tgt=learn_tgt_loc,
        dead=death_action != ACT_NONE, caps=caps, capacity=capacity,
        axes=axes)
    return new_state, events, lin, win


def _local_evolve_popmajor(config: SoupConfig, state: SoupState,
                           wT_loc: jnp.ndarray, axes=SOUP_AXIS,
                           lin=None, win=None, lincfg=None):
    """Per-device popmajor generation body: ``wT_loc`` is the LOCAL (P, N/D)
    lane-major shard; ``state.weights`` is ignored (uids are the local shard,
    scalars/key replicated).

    Unlike the row-major sharded path, this one is **fully bitwise** vs the
    single-device popmajor step (``soup._evolve_parallel_popmajor``):

      * gates/targets come from the replicated key (same draws);
      * imitation targets are re-gathered AFTER the attack phase, so a
        particle learning from a just-attacked victim sees the same
        post-attack weights the single-device path uses;
      * respawn draws the SAME global fresh population
        (``fresh_lanes(topo, k_re, N)``) on every device and slices its
        shard, and fresh uids use the GLOBAL dead-rank (all_gather of the
        death mask + cumsum) — identical uids, identical weights.

    All heavy per-lane math is elementwise over the lane axis, so slicing
    lanes across devices cannot reassociate anything; tests assert exact
    equality over multi-generation full-dynamics runs.
    """
    from ..ops.popmajor import (apply_popmajor, learn_epochs_popmajor,
                                train_epochs_popmajor)
    from ..soup import _downcast, _fused_kernel_route, _phases_view, _upcast

    if config.generation_impl == "fused":
        if _fused_kernel_route(config):
            return _local_fused_popmajor(config, state, wT_loc, axes, lin,
                                         win, lincfg)
        config = _phases_view(config)

    n = config.size
    n_loc = wT_loc.shape[1]
    d = jax.lax.axis_index(axes)
    start = d * n_loc
    topo = config.topo
    # keep the storage-dtype shard for the start-of-generation gather (bf16
    # ships half the bytes; the upcast after is exact) — the POST-attack
    # re-gather below must stay f32: its values are mid-generation compute
    # results and a bf16 bounce there would round where the single-device
    # path does not
    wT_store = wT_loc
    wT_loc = _upcast(config, wT_loc, state.scales, paxis=-1)
    has_attacker = jnp.zeros(n_loc, bool)
    att_loc = jnp.full(n_loc, -1, jnp.int32)

    key, k_ag, k_at, k_lg, k_lt, k_re = jax.random.split(state.key, 6)

    # --- attack (soup.py:56-61); last-attacker-wins, same as single-device
    with jax.named_scope("soup.attack"):
        if config.attacking_rate > 0:
            all_sT = jax.lax.all_gather(state.scales, axes, tiled=True) \
                if config.population_dtype == "int8" else None
            all_wT = _upcast(config, jax.lax.all_gather(wT_store, axes,
                                                        axis=1, tiled=True),
                             all_sT, paxis=-1)
            attack_gate = jax.random.uniform(k_ag, (n,)) < config.attacking_rate
            attack_tgt = jax.random.randint(k_at, (n,), 0, n)
            att_idx = jax.ops.segment_max(
                jnp.where(attack_gate, jnp.arange(n), -1), attack_tgt, num_segments=n)
            att_loc = jax.lax.dynamic_slice_in_dim(att_idx, start, n_loc)
            has_attacker = att_loc >= 0
            if config.attack_impl == "compact":
                from ..soup import _attack_capacity, _attack_popmajor_compact

                # per-shard capacity over the shard's own lane count; a shard
                # that overflows falls back to full width for that step only
                wT_loc = _attack_popmajor_compact(
                    topo, wT_loc, att_loc, has_attacker,
                    _attack_capacity(n_loc, config.attacking_rate),
                    source=all_wT)
            else:
                attacked = apply_popmajor(
                    topo, all_wT[:, jnp.clip(att_loc, 0)], wT_loc,
                    impl=config.apply_impl)
                wT_loc = jnp.where(has_attacker[None, :], attacked, wT_loc)
            attack_gate_loc = jax.lax.dynamic_slice_in_dim(attack_gate, start, n_loc)
            attack_tgt_loc = jax.lax.dynamic_slice_in_dim(attack_tgt, start, n_loc)
        else:
            attack_gate_loc = jnp.zeros(n_loc, bool)
            attack_tgt_loc = jnp.zeros(n_loc, jnp.int32)

    # --- learn_from (soup.py:62-68): POST-attack re-gather for exact parity
    with jax.named_scope("soup.learn_from"):
        if config.learn_from_rate > 0:
            learn_gate = jax.random.uniform(k_lg, (n,)) < config.learn_from_rate
            learn_tgt = jax.random.randint(k_lt, (n,), 0, n)
            learn_gate_loc = jax.lax.dynamic_slice_in_dim(learn_gate, start, n_loc)
            learn_tgt_loc = jax.lax.dynamic_slice_in_dim(learn_tgt, start, n_loc)
            if config.learn_from_severity > 0:
                post_attack = jax.lax.all_gather(wT_loc, axes, axis=1, tiled=True)
                if config.learn_from_impl == "compact":
                    from ..soup import (_attack_capacity,
                                        _learn_popmajor_compact)

                    wT_loc = _learn_popmajor_compact(
                        config, wT_loc, learn_gate_loc, learn_tgt_loc,
                        _attack_capacity(n_loc, config.learn_from_rate),
                        source=post_attack)
                else:
                    learned, _ = learn_epochs_popmajor(
                        topo, wT_loc, post_attack[:, learn_tgt_loc],
                        config.learn_from_severity, config.lr,
                        config.train_mode, config.train_impl)
                    wT_loc = jnp.where(learn_gate_loc[None, :], learned, wT_loc)
        else:
            learn_gate_loc = jnp.zeros(n_loc, bool)
            learn_tgt_loc = jnp.zeros(n_loc, jnp.int32)

    # --- train (soup.py:69-76) ------------------------------------------
    with jax.named_scope("soup.train"):
        if config.train > 0:
            wT_loc, train_loss = train_epochs_popmajor(
                topo, wT_loc, config.train, config.lr, config.train_mode,
                config.train_impl)
        else:
            train_loss = jnp.zeros(n_loc, wT_loc.dtype)

    # --- respawn (soup.py:77-86): global-rank uids + replicated fresh draws
    with jax.named_scope("soup.respawn"):
        dead_div = is_diverged(wT_loc, axis=0) if config.remove_divergent \
            else jnp.zeros(n_loc, bool)
        dead_zero = (is_zero(wT_loc, config.epsilon, axis=0) & ~dead_div) \
            if config.remove_zero else jnp.zeros(n_loc, bool)
        dead = dead_div | dead_zero
        all_dead = jax.lax.all_gather(dead, axes, tiled=True)  # (N,) device order
        rank = jnp.cumsum(all_dead) - 1
        rank_loc = jax.lax.dynamic_slice_in_dim(rank, start, n_loc)
        # every device draws the same global fresh population and keeps its
        # columns: bitwise-identical replacements to the single-device k_re
        # stream (in either respawn_draws mode)
        freshT = fresh_lanes(topo, k_re, n, config.respawn_draws)
        freshT_loc = jax.lax.dynamic_slice_in_dim(freshT, start, n_loc, axis=1)
        wT_loc = jnp.where(dead[None, :], freshT_loc, wT_loc)
        uids = jnp.where(dead, state.next_uid + rank_loc.astype(jnp.int32),
                         state.uids)
        next_uid = state.next_uid + all_dead.sum(dtype=jnp.int32)
        death_action = jnp.full(n_loc, ACT_NONE, jnp.int32)
        death_action = jnp.where(dead_div, ACT_DIV_DEAD, death_action)
        death_action = jnp.where(dead_zero, ACT_ZERO_DEAD, death_action)
        death_cp = jnp.where(dead, uids, -1)
    wT_loc, scales = _downcast(config, wT_loc, paxis=-1)

    # --- event record (last action wins) --------------------------------
    all_uids = jax.lax.all_gather(state.uids, axes, tiled=True)
    action, counterpart = _event_record(
        n_loc, attack_gate_loc, all_uids[attack_tgt_loc],
        learn_gate_loc, all_uids[learn_tgt_loc],
        config.train > 0, death_action, death_cp)

    new_state = SoupState(state.weights, uids, next_uid, state.time + 1, key,
                          scales)
    events = SoupEvents(action, counterpart, train_loss)
    if lin is None:
        return new_state, events, wT_loc
    from ..telemetry.dynamics import lookup_pids, record_step

    caps, capacity = lincfg
    lin, win = record_step(
        lin, win, gen=state.time, attacked=has_attacker,
        attacker_pid=lookup_pids(lin.pid, jnp.clip(att_loc, 0), axes),
        learn_gate=learn_gate_loc, learn_tgt=learn_tgt_loc, dead=dead,
        caps=caps, capacity=capacity, axes=axes)
    return new_state, events, wT_loc, lin, win


def _local_fused_popmajor(config: SoupConfig, state: SoupState,
                          wT_loc: jnp.ndarray, axes=SOUP_AXIS,
                          lin=None, win=None, lincfg=None):
    """Per-device fused-generation body (``ops.pallas_generation``):
    ONE pre-attack all_gather serves both the attacker columns and the
    imitation counterparts (the kernel re-applies the counterpart's
    attack in-block, so the phase chain's second, post-attack gather
    disappears) — psum/all-gather only at the kernel boundary.  Respawn
    uids use the same global dead-rank and replicated fresh draw as the
    phase chain, so pids/uids stay bit-identical to the single-device
    fused step.  Mosaic backends only (``soup._fused_kernel_route``)."""
    from ..ops.pallas_generation import generation_popmajor
    from ..soup import _downcast, _upcast

    n = config.size
    n_loc = wT_loc.shape[1]
    d = jax.lax.axis_index(axes)
    start = d * n_loc
    topo = config.topo
    has_attacker = jnp.zeros(n_loc, bool)
    att_loc = jnp.full(n_loc, -1, jnp.int32)

    key, k_ag, k_at, k_lg, k_lt, k_re = jax.random.split(state.key, 6)

    # int8 dequantizes BEFORE the gather (the kernel sees f32 rows, same
    # quantize-point contract as the single-device fused step); the one
    # collective ships f32 here — correctness over collective bytes, the
    # phase chain keeps the code+scale gather for the bandwidth-sensitive
    # tier.  bf16 stays raw: its in-kernel cast protocol is unchanged.
    if config.population_dtype == "int8":
        wT_loc = _upcast(config, wT_loc, state.scales, paxis=-1)

    attacking = config.attacking_rate > 0
    learning = config.learn_from_rate > 0
    sgd_learn = learning and config.learn_from_severity > 0

    all_wT = jax.lax.all_gather(wT_loc, axes, axis=1, tiled=True) \
        if (attacking or sgd_learn) else None

    if attacking:
        attack_gate = jax.random.uniform(k_ag, (n,)) < config.attacking_rate
        attack_tgt = jax.random.randint(k_at, (n,), 0, n)
        att_idx = jax.ops.segment_max(
            jnp.where(attack_gate, jnp.arange(n), -1), attack_tgt,
            num_segments=n)
        att_loc = jax.lax.dynamic_slice_in_dim(att_idx, start, n_loc)
        has_attacker = att_loc >= 0
        attack_gate_loc = jax.lax.dynamic_slice_in_dim(attack_gate, start,
                                                       n_loc)
        attack_tgt_loc = jax.lax.dynamic_slice_in_dim(attack_tgt, start,
                                                      n_loc)
    else:
        att_idx = jnp.full(n, -1, jnp.int32)
        attack_gate_loc = jnp.zeros(n_loc, bool)
        attack_tgt_loc = jnp.zeros(n_loc, jnp.int32)
    if learning:
        learn_gate = jax.random.uniform(k_lg, (n,)) < config.learn_from_rate
        learn_tgt = jax.random.randint(k_lt, (n,), 0, n)
        learn_gate_loc = jax.lax.dynamic_slice_in_dim(learn_gate, start,
                                                      n_loc)
        learn_tgt_loc = jax.lax.dynamic_slice_in_dim(learn_tgt, start, n_loc)
    else:
        learn_gate_loc = jnp.zeros(n_loc, bool)
        learn_tgt_loc = jnp.zeros(n_loc, jnp.int32)

    attackerT = all_wT[:, jnp.clip(att_loc, 0)] if attacking else None
    otherT = other_attackerT = other_attacked = None
    if sgd_learn:
        otherT = all_wT[:, learn_tgt_loc]
        if attacking:
            other_att = att_idx[learn_tgt_loc]
            other_attackerT = all_wT[:, jnp.clip(other_att, 0)]
            other_attacked = other_att >= 0
    # replicated global fresh draw, local slice — bitwise the single-device
    # respawn stream (same discipline as the phase chain)
    freshT = fresh_lanes(topo, k_re, n, config.respawn_draws)
    freshT_loc = jax.lax.dynamic_slice_in_dim(freshT, start, n_loc, axis=1)

    with jax.named_scope("soup.fused_generation"):
        wT_loc, train_loss, dead_div, dead_zero = generation_popmajor(
            topo, wT_loc, freshT_loc, attackerT,
            has_attacker if attacking else None, otherT, other_attackerT,
            other_attacked, learn_gate_loc if sgd_learn else None,
            severity=config.learn_from_severity if sgd_learn else 0,
            train=config.train, lr=config.lr,
            remove_divergent=config.remove_divergent,
            remove_zero=config.remove_zero, epsilon=config.epsilon)

    scales = state.scales
    if config.population_dtype == "int8":
        wT_loc, scales = _downcast(config, wT_loc, paxis=-1)

    dead = dead_div | dead_zero
    all_dead = jax.lax.all_gather(dead, axes, tiled=True)
    rank = jnp.cumsum(all_dead) - 1
    rank_loc = jax.lax.dynamic_slice_in_dim(rank, start, n_loc)
    uids = jnp.where(dead, state.next_uid + rank_loc.astype(jnp.int32),
                     state.uids)
    next_uid = state.next_uid + all_dead.sum(dtype=jnp.int32)
    death_action = jnp.full(n_loc, ACT_NONE, jnp.int32)
    death_action = jnp.where(dead_div, ACT_DIV_DEAD, death_action)
    death_action = jnp.where(dead_zero, ACT_ZERO_DEAD, death_action)
    death_cp = jnp.where(dead, uids, -1)

    all_uids = jax.lax.all_gather(state.uids, axes, tiled=True)
    action, counterpart = _event_record(
        n_loc, attack_gate_loc, all_uids[attack_tgt_loc],
        learn_gate_loc, all_uids[learn_tgt_loc],
        config.train > 0, death_action, death_cp)

    new_state = SoupState(state.weights, uids, next_uid, state.time + 1, key,
                          scales)
    events = SoupEvents(action, counterpart, train_loss)
    if lin is None:
        return new_state, events, wT_loc
    from ..telemetry.dynamics import lookup_pids, record_step

    caps, capacity = lincfg
    lin, win = record_step(
        lin, win, gen=state.time, attacked=has_attacker,
        attacker_pid=lookup_pids(lin.pid, jnp.clip(att_loc, 0), axes),
        learn_gate=learn_gate_loc, learn_tgt=learn_tgt_loc, dead=dead,
        caps=caps, capacity=capacity, axes=axes)
    return new_state, events, wT_loc, lin, win


def _local_popmajor_step(config: SoupConfig, state: SoupState,
                         axes=SOUP_AXIS):
    """Single-step wrapper: transpose the local (N/D, P) shard in and out."""
    new_state, events, wT = _local_evolve_popmajor(config, state,
                                                   state.weights.T, axes)
    return new_state._replace(weights=wT.T), events


def _sharded_evolve_step(config: SoupConfig, mesh: Mesh, state: SoupState):
    """One generation with the particle axis sharded over ``mesh``."""
    axes = _soup_axes(mesh)
    if config.layout == "popmajor":
        _check_popmajor(config)
        body = functools.partial(_local_popmajor_step, config, axes=axes)
    elif config.layout == "rowmajor":
        if config.attack_impl != "full" or config.learn_from_impl != "full":
            raise ValueError(
                "attack_impl/learn_from_impl='compact' compact lanes of "
                "the popmajor layout; layout='rowmajor' needs 'full'")
        if config.generation_impl != "phases":
            raise ValueError(
                "generation_impl='fused' is the popmajor lane megakernel; "
                "layout='rowmajor' needs generation_impl='phases'")
        body = functools.partial(_local_evolve, config, axes=axes)
    else:
        raise ValueError(f"unknown soup layout {config.layout!r}")
    int8 = config.population_dtype == "int8"
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(_state_specs(axes, int8),),
        out_specs=(_state_specs(axes, int8), _event_specs(axes)),
        check_vma=False,
    )
    return fn(state)


#: jitted sharded step + its buffer-donating twin: the donated spelling
#: rewrites every device's population shard in place (state dead after the
#: call; rebinding callers only — see ``soup.evolve_step_donated``).
sharded_evolve_step = jax.jit(_sharded_evolve_step,
                              static_argnames=("config", "mesh"))
sharded_evolve_step_donated = jax.jit(_sharded_evolve_step,
                                      static_argnames=("config", "mesh"),
                                      donate_argnums=(2,))


def _metrics_specs():
    """Replicated placement of a flushed ``SoupMetrics`` carry (global
    after the in-body psum)."""
    from ..telemetry.device import SoupMetrics

    return SoupMetrics(generations=P(), actions=P(), loss_sum=P())


def _health_specs():
    """Replicated placement of a flushed ``HealthStats`` carry (global
    after the in-body psum/pmin/pmax)."""
    from ..telemetry.device import HealthStats

    return HealthStats(checks=P(), nonfinite=P(), nonfinite_peak=P(),
                       zero=P(), zero_peak=P(), norm_min=P(), norm_max=P(),
                       norm_hist=P())


def _sharded_evolve(config: SoupConfig, mesh: Mesh, state: SoupState,
                    generations: int = 1, metrics: bool = False,
                    health: bool = False, lineage: bool = False,
                    lineage_state=None, lineage_capacity: int = 4096):
    """Scan ``generations`` sharded steps (collectives stay inside the scan —
    one compiled program for the whole evolution).

    In the popmajor layout the whole scan runs inside ONE ``shard_map`` with
    the local shard kept transposed (P, N/D) across generations — one
    transpose at entry/exit instead of two per step, mirroring the
    single-device ``soup.evolve`` fast path.

    ``metrics=True`` additionally returns the GLOBAL
    ``telemetry.device.SoupMetrics`` carry: per-shard accumulation inside
    the scan, one psum at the shard boundary — no per-generation host
    syncs, state bit-identical to the unmetered program.  ``health=True``
    does the same for the GLOBAL ``telemetry.device.HealthStats`` carry
    (counts/hist psum'd, extrema pmin/pmax'd; peaks are a shard-wise upper
    bound).

    ``lineage=True`` (``lineage_state`` = the sharded-placed
    ``telemetry.dynamics.LineageState``) threads the replication-dynamics
    carry: pids mint from globally-ranked bases (popmajor assigns
    BIT-IDENTICAL pids to the single-device run; row-major differs only
    where its documented respawn-stream difference changes who dies), the
    per-SHARD edge windows concatenate at the boundary, and the fixpoint
    census is psum'd global.  Runs inside ONE ``shard_map`` for both
    layouts.  Return order: ``final``, metrics carry, health carry,
    ``(lineage_state, window, fixpoint_stats)``."""
    axes = _soup_axes(mesh)
    if metrics:
        from ..telemetry.device import (accumulate_soup_metrics,
                                        psum_soup_metrics,
                                        zero_soup_metrics)
    if health:
        from ..telemetry.device import (accumulate_health, psum_health,
                                        zero_health)
    lincfg = None
    if lineage:
        if lineage_state is None:
            raise ValueError("lineage=True needs lineage_state= (seed with "
                             "telemetry.dynamics.seed_lineage, place with "
                             "place_lineage)")
        from ..soup import _lineage_caps
        from ..telemetry.dynamics import (close_window, fixpoint_specs,
                                          lineage_specs, psum_fixpoints,
                                          window_specs, zero_window)

        n_loc = config.size // mesh.devices.size
        lincfg = (_lineage_caps(n_loc, config, lineage_capacity),
                  lineage_capacity)

    def pack(final, m, h, ltriple=None):
        out = (final,)
        if metrics:
            out += (m,)
        if health:
            out += (h,)
        if lineage:
            out += (ltriple,)
        return out if len(out) > 1 else final

    int8 = config.population_dtype == "int8"

    def in_specs():
        specs = (_state_specs(axes, int8),)
        if lineage:
            specs += (lineage_specs(axes),)
        return specs

    def out_specs():
        specs = (_state_specs(axes, int8),)
        if metrics:
            specs += (_metrics_specs(),)
        if health:
            specs += (_health_specs(),)
        if lineage:
            specs += ((lineage_specs(axes), window_specs(axes),
                       fixpoint_specs()),)
        return specs if len(specs) > 1 else specs[0]

    if config.layout == "popmajor":
        _check_popmajor(config)

        def local_run(st: SoupState, *lin_args):
            light = st._replace(weights=jnp.zeros((0,), st.weights.dtype))
            m0 = zero_soup_metrics() if metrics else None
            h0 = zero_health() if health else None
            l0 = lin_args[0] if lineage else None
            w0 = zero_window(lineage_capacity) if lineage else None

            def body(carry, _):
                s, wT, m, h, lin, win = carry
                if lineage:
                    new_s, ev, new_wT, lin, win = _local_evolve_popmajor(
                        config, s, wT, axes, lin, win, lincfg)
                else:
                    new_s, ev, new_wT = _local_evolve_popmajor(config, s,
                                                               wT, axes)
                if metrics:
                    m = accumulate_soup_metrics(m, ev.action, ev.loss)
                if health:
                    # int8 health folds read the dequantized f32 view
                    # (raw codes mean nothing without their scales);
                    # f32/bf16 read storage directly, exactly as before
                    from ..soup import _stored_view

                    h = accumulate_health(
                        h, _stored_view(config, new_wT, new_s.scales,
                                        paxis=-1), 0, config.epsilon)
                return (new_s, new_wT, m, h, lin, win), None

            (final, wT, m, h, lin, win), _ = jax.lax.scan(
                body, (light, st.weights.T, m0, h0, l0, w0), None,
                length=generations)
            final = final._replace(weights=wT.T)
            ltriple = None
            if lineage:
                from ..ops.popmajor import apply_popmajor
                from ..soup import _stored_view

                wc = _stored_view(config, wT, final.scales, paxis=-1)
                fw = apply_popmajor(config.topo, wc, wc)
                lin, fstats = close_window(lin, wc, fw, 0, config.epsilon)
                ltriple = (lin, win, psum_fixpoints(fstats, axes))
            return pack(final,
                        psum_soup_metrics(m, axes) if metrics else None,
                        psum_health(h, axes) if health else None,
                        ltriple)

        fn = shard_map(
            local_run,
            mesh=mesh,
            in_specs=in_specs(),
            out_specs=out_specs(),
            check_vma=False,
        )
        return fn(state, lineage_state) if lineage else fn(state)

    if lineage:
        # row-major + lineage: the scan moves inside ONE shard_map (the
        # per-step spelling cannot thread the per-shard window buffers)
        from ..nets import apply_to_weights as _apply

        def local_run_rm(st: SoupState, l0):
            w0 = zero_window(lineage_capacity)
            m0 = zero_soup_metrics() if metrics else None
            h0 = zero_health() if health else None

            def body(carry, _):
                s, m, h, lin, win = carry
                new_s, ev, lin, win = _local_evolve(config, s, axes, lin,
                                                    win, lincfg)
                if metrics:
                    m = accumulate_soup_metrics(m, ev.action, ev.loss)
                if health:
                    from ..soup import _stored_view

                    h = accumulate_health(
                        h, _stored_view(config, new_s.weights, new_s.scales),
                        -1, config.epsilon)
                return (new_s, m, h, lin, win), None

            (final, m, h, lin, win), _ = jax.lax.scan(
                body, (st, m0, h0, l0, w0), None, length=generations)
            from ..soup import _stored_view

            wc = _stored_view(config, final.weights, final.scales)
            fw = jax.vmap(lambda wi: _apply(config.topo, wi, wi))(wc)
            lin, fstats = close_window(lin, wc, fw, -1, config.epsilon)
            return pack(final,
                        psum_soup_metrics(m, axes) if metrics else None,
                        psum_health(h, axes) if health else None,
                        (lin, win, psum_fixpoints(fstats, axes)))

        fn = shard_map(
            local_run_rm,
            mesh=mesh,
            in_specs=in_specs(),
            out_specs=out_specs(),
            check_vma=False,
        )
        return fn(state, lineage_state)

    m0 = zero_soup_metrics() if metrics else None
    h0 = zero_health() if health else None

    def body(carry, _):
        fn_state, m, h = carry
        new_state, ev = sharded_evolve_step(config, mesh, fn_state)
        if metrics:
            # events come back particle-sharded; the bincount reduction is
            # GSPMD's to place (one small collective per generation)
            m = accumulate_soup_metrics(m, ev.action, ev.loss)
        if health:
            from ..soup import _stored_view

            h = accumulate_health(
                h, _stored_view(config, new_state.weights, new_state.scales),
                -1, config.epsilon)
        return (new_state, m, h), None

    (final, m, h), _ = jax.lax.scan(body, (state, m0, h0), None,
                                    length=generations)
    return pack(final, m, h)


sharded_evolve = jax.jit(_sharded_evolve,
                         static_argnames=("config", "mesh", "generations",
                                          "metrics", "health", "lineage",
                                          "lineage_capacity"))
sharded_evolve_donated = jax.jit(_sharded_evolve,
                                 static_argnames=("config", "mesh",
                                                  "generations", "metrics",
                                                  "health", "lineage",
                                                  "lineage_capacity"),
                                 donate_argnums=(2,))


@functools.partial(jax.jit, static_argnames=("config", "mesh"))
def sharded_count(config: SoupConfig, mesh: Mesh, state: SoupState) -> jnp.ndarray:
    """(5,) global class histogram: local classify + psum."""

    axes = _soup_axes(mesh)

    def local_count(w_loc, s_loc=None):
        from ..soup import _stored_view

        return count_classes(classify_batch(
            config.topo, _stored_view(config, w_loc, s_loc), config.epsilon))

    if config.population_dtype == "int8":
        fn = shard_map(
            lambda w, s: jax.lax.psum(local_count(w, s), axes),
            mesh=mesh,
            in_specs=(P(axes), P(axes)),
            out_specs=P(),
            check_vma=False,
        )
        return fn(state.weights, state.scales)
    fn = shard_map(
        lambda w: jax.lax.psum(local_count(w), axes),
        mesh=mesh,
        in_specs=(P(axes),),
        out_specs=P(),
        check_vma=False,
    )
    return fn(state.weights)


def place_sharded_state(mesh: Mesh, state: SoupState) -> SoupState:
    """Place an existing ``SoupState`` (fresh-seeded or checkpoint-restored)
    with the soup sharding: particle-axis arrays sharded, scalars/key
    replicated."""
    n = state.weights.shape[0]
    n_dev = mesh.devices.size
    if n % n_dev:
        # fail fast with the same clear message the fresh-start path gives —
        # e.g. a checkpoint resumed on a host with a different device count
        raise ValueError(
            f"soup size {n} must be divisible by the mesh's {n_dev} devices "
            f"(each device owns an equal shard)")
    from .mesh import global_device_put
    specs = _state_specs(_soup_axes(mesh), int8=state.scales is not None)
    return jax.tree.map(
        lambda x, spec: global_device_put(x, NamedSharding(mesh, spec)),
        state, specs)


def make_sharded_state(config: SoupConfig, mesh: Mesh, key: jax.Array) -> SoupState:
    """Seed a population already placed with the soup sharding (divisibility
    validated by ``place_sharded_state``)."""
    return place_sharded_state(mesh, seed(config, key))
