"""Soup evolution sharded over a device mesh via ``shard_map``.

Scale-out design (SURVEY §2.5 / §7.6), built not ported — the reference has
no distributed backend at all:

  * The particle axis is sharded: each device owns ``N / D`` rows of the
    ``(N, P)`` weight matrix and does ALL heavy work (self-applications,
    SGD epochs) only for its shard.
  * The soup PRNG key is **replicated**; every device derives the same
    global gate/target draws with cheap O(N) scalar ops, so no RNG
    communication is needed and the sharded soup is bit-deterministic.
  * Counterpart weights (attackers seen by local victims, imitation targets
    of local learners) come from ONE ``all_gather`` of the weight matrix per
    generation.  Particles are tiny (P ~ 14 floats), so even a 1M-particle
    soup gathers ~56 MB — well within HBM and ICI budget; this is by far
    the simplest correct exchange and it rides ICI as a single fused
    collective.  (A ppermute ring exchange would only pay off for particles
    orders of magnitude larger.)
  * Respawned particles draw fresh uids from per-device blocks computed
    with an ``all_gather`` of death counts — monotone unique uids without a
    host round-trip.

Semantics match ``soup._evolve_parallel`` with two sharding-induced
differences: (a) imitation targets read start-of-generation weights (the
all_gather snapshot) rather than post-attack ones — visible only when a
particle learns from a victim attacked in the same generation; (b) respawn
draws fold the device index into the key, so fresh particles differ from
the unsharded stream (same distribution).  Attack/train phases are
bit-identical under matched keys, which tests assert.
"""

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..nets import apply_to_weights
from ..ops.predicates import count_classes, is_diverged, is_zero
from ..soup import (
    SoupConfig,
    SoupEvents,
    SoupState,
    _event_record,
    _learn_epochs,
    _respawn,
    _train_epochs,
    seed,
)
from ..engine import classify_batch
from .mesh import SOUP_AXIS


def _state_specs():
    return SoupState(
        weights=P(SOUP_AXIS),
        uids=P(SOUP_AXIS),
        next_uid=P(),
        time=P(),
        key=P(),
    )


def _event_specs():
    return SoupEvents(action=P(SOUP_AXIS), counterpart=P(SOUP_AXIS), loss=P(SOUP_AXIS))


def _local_evolve(config: SoupConfig, state: SoupState) -> Tuple[SoupState, SoupEvents]:
    """Per-device body. ``state.weights``/``uids`` hold the LOCAL shard;
    scalars and the key are replicated."""
    n = config.size
    w_loc = state.weights
    n_loc = w_loc.shape[0]
    d = jax.lax.axis_index(SOUP_AXIS)
    start = d * n_loc
    topo = config.topo

    key, k_ag, k_at, k_lg, k_lt, k_re = jax.random.split(state.key, 6)

    # one collective: everyone sees the start-of-generation population
    all_w = jax.lax.all_gather(w_loc, SOUP_AXIS, tiled=True)  # (N, P)

    # --- attack ---------------------------------------------------------
    if config.attacking_rate > 0:
        attack_gate = jax.random.uniform(k_ag, (n,)) < config.attacking_rate
        attack_tgt = jax.random.randint(k_at, (n,), 0, n)
        att_idx = jax.ops.segment_max(
            jnp.where(attack_gate, jnp.arange(n), -1), attack_tgt, num_segments=n)
        att_loc = jax.lax.dynamic_slice_in_dim(att_idx, start, n_loc)
        has_attacker = att_loc >= 0
        attacker_w = all_w[jnp.clip(att_loc, 0)]
        attacked = jax.vmap(lambda s, t: apply_to_weights(topo, s, t))(attacker_w, w_loc)
        w_loc = jnp.where(has_attacker[:, None], attacked, w_loc)
        attack_gate_loc = jax.lax.dynamic_slice_in_dim(attack_gate, start, n_loc)
        attack_tgt_loc = jax.lax.dynamic_slice_in_dim(attack_tgt, start, n_loc)
    else:
        attack_gate_loc = jnp.zeros(n_loc, bool)
        attack_tgt_loc = jnp.zeros(n_loc, jnp.int32)

    # --- learn_from -----------------------------------------------------
    # imitation targets come from the start-of-generation gather; the
    # single-device path uses post-attack weights, an intra-generation
    # staleness difference only for the rare learn-from-an-attacked-victim
    if config.learn_from_rate > 0:
        learn_gate = jax.random.uniform(k_lg, (n,)) < config.learn_from_rate
        learn_tgt = jax.random.randint(k_lt, (n,), 0, n)
        learn_gate_loc = jax.lax.dynamic_slice_in_dim(learn_gate, start, n_loc)
        learn_tgt_loc = jax.lax.dynamic_slice_in_dim(learn_tgt, start, n_loc)
        if config.learn_from_severity > 0:
            learned, _ = jax.vmap(lambda wi, ow: _learn_epochs(config, wi, ow))(
                w_loc, all_w[learn_tgt_loc])
            w_loc = jnp.where(learn_gate_loc[:, None], learned, w_loc)
    else:
        learn_gate_loc = jnp.zeros(n_loc, bool)
        learn_tgt_loc = jnp.zeros(n_loc, jnp.int32)

    # --- train ----------------------------------------------------------
    if config.train > 0:
        w_loc, train_loss = jax.vmap(lambda wi: _train_epochs(config, wi))(w_loc)
    else:
        train_loss = jnp.zeros(n_loc, w_loc.dtype)

    # --- respawn with per-device uid blocks -----------------------------
    # pre-count deaths to carve a uid block for this device, then reuse the
    # single-device respawn with that block base — one semantic source
    dead_now = jnp.zeros(n_loc, bool)
    if config.remove_divergent:
        dead_now = dead_now | is_diverged(w_loc)
    if config.remove_zero:
        dead_now = dead_now | is_zero(w_loc, config.epsilon)
    local_deaths = dead_now.sum(dtype=jnp.int32)
    deaths_by_dev = jax.lax.all_gather(local_deaths, SOUP_AXIS)  # (D,)
    my_uid_base = state.next_uid + jnp.sum(
        jnp.where(jnp.arange(deaths_by_dev.shape[0]) < d, deaths_by_dev, 0))
    new_w, new_uids, _, death_action, death_cp = _respawn(
        config, w_loc, state.uids, my_uid_base, jax.random.fold_in(k_re, d))
    next_uid = state.next_uid + deaths_by_dev.sum()

    # --- event record (last action wins, shared tail) -------------------
    # uid of a global index: gather from the uid table
    all_uids = jax.lax.all_gather(state.uids, SOUP_AXIS, tiled=True)
    action, counterpart = _event_record(
        n_loc, attack_gate_loc, all_uids[attack_tgt_loc],
        learn_gate_loc, all_uids[learn_tgt_loc],
        config.train > 0, death_action, death_cp)

    new_state = SoupState(new_w, new_uids, next_uid, state.time + 1, key)
    return new_state, SoupEvents(action, counterpart, train_loss)


@functools.partial(jax.jit, static_argnames=("config", "mesh"))
def sharded_evolve_step(config: SoupConfig, mesh: Mesh, state: SoupState):
    """One generation with the particle axis sharded over ``mesh``."""
    if config.layout != "rowmajor":
        raise NotImplementedError(
            f"sharded soup supports layout='rowmajor' (got {config.layout!r}); "
            "the population-major layout is single-device for now")
    fn = shard_map(
        functools.partial(_local_evolve, config),
        mesh=mesh,
        in_specs=(_state_specs(),),
        out_specs=(_state_specs(), _event_specs()),
        check_vma=False,
    )
    return fn(state)


@functools.partial(jax.jit, static_argnames=("config", "mesh", "generations"))
def sharded_evolve(config: SoupConfig, mesh: Mesh, state: SoupState, generations: int = 1):
    """Scan ``generations`` sharded steps (collectives stay inside the scan —
    one compiled program for the whole evolution)."""

    def body(fn_state, _):
        new_state, _ev = sharded_evolve_step(config, mesh, fn_state)
        return new_state, None

    final, _ = jax.lax.scan(body, state, None, length=generations)
    return final


@functools.partial(jax.jit, static_argnames=("config", "mesh"))
def sharded_count(config: SoupConfig, mesh: Mesh, state: SoupState) -> jnp.ndarray:
    """(5,) global class histogram: local classify + psum."""

    def local_count(w_loc):
        return count_classes(classify_batch(config.topo, w_loc, config.epsilon))

    fn = shard_map(
        lambda w: jax.lax.psum(local_count(w), SOUP_AXIS),
        mesh=mesh,
        in_specs=(P(SOUP_AXIS),),
        out_specs=P(),
        check_vma=False,
    )
    return fn(state.weights)


def make_sharded_state(config: SoupConfig, mesh: Mesh, key: jax.Array) -> SoupState:
    """Seed a population already placed with the soup sharding."""
    n_dev = mesh.devices.size
    if config.size % n_dev:
        raise ValueError(
            f"soup size {config.size} must be divisible by the mesh's "
            f"{n_dev} devices (each device owns an equal shard)")
    state = seed(config, key)
    specs = _state_specs()
    return jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)), state, specs)
