"""srnn_tpu — a TPU-native framework for self-replicating neural networks.

A from-scratch JAX/XLA rebuild of the capabilities of
``illiumst/self-replicating-neural-networks`` (mounted read-only at
/root/reference): networks that consume their own weights and emit new
weights, fixpoint analysis of repeated self-application, and population
("Soup") dynamics — redesigned for TPU:

  * a particle is a row of a struct-of-arrays pytree, not an object holding
    a keras model;
  * self-application, predicates, training and soup evolution are pure
    jitted functions; ``vmap`` supplies the population axis and
    ``shard_map`` over a ``jax.sharding.Mesh`` supplies ICI scale-out;
  * the reference's per-scalar ``model.predict`` hot loop (SURVEY §3.1)
    becomes one batched matmul chain on the MXU.
"""

from .topology import Topology
from .init import init_flat, init_population
from .nets import apply_to_weights, compute_samples, apply_fn, samples_fn
from .ops import (
    CLASS_NAMES,
    classify,
    is_diverged,
    is_fixpoint,
    is_zero,
)
from .engine import (
    classify_batch,
    fixpoint_density,
    run_fixpoint,
    run_fixpoint_donated,
    run_known_fixpoint_variation,
    run_mixed_fixpoint,
    run_mixed_fixpoint_donated,
    run_training,
    run_training_donated,
)
from .train import fit_epoch, learn_from, train_step
from .soup import (SoupConfig, SoupState, count, evolve, evolve_donated,
                   evolve_step, evolve_step_donated, seed)
from .experiment import (
    Experiment,
    load_artifact,
    restore_checkpoint,
    save_artifact,
    save_checkpoint,
)
from .fixtures import identity_fixpoint_flat, vary

__version__ = "0.1.0"

__all__ = [
    "Topology",
    "init_flat",
    "init_population",
    "apply_to_weights",
    "compute_samples",
    "apply_fn",
    "samples_fn",
    "CLASS_NAMES",
    "classify",
    "is_diverged",
    "is_fixpoint",
    "is_zero",
    "classify_batch",
    "fixpoint_density",
    "run_fixpoint",
    "run_fixpoint_donated",
    "run_known_fixpoint_variation",
    "run_mixed_fixpoint",
    "run_mixed_fixpoint_donated",
    "run_training",
    "run_training_donated",
    "fit_epoch",
    "learn_from",
    "train_step",
    "SoupConfig",
    "SoupState",
    "count",
    "evolve",
    "evolve_donated",
    "evolve_step",
    "evolve_step_donated",
    "seed",
    "Experiment",
    "load_artifact",
    "restore_checkpoint",
    "save_artifact",
    "save_checkpoint",
    "identity_fixpoint_flat",
    "vary",
]
