"""srnnlint core: the shared pass infrastructure.

One file walker (:class:`AnalysisContext` — every ``.py`` under the
package parsed ONCE and shared by all passes, plus the shell scripts for
the textual checks), one finding type (:class:`Finding` — ``file:line``,
severity, stable ``pass/code`` identity), and one waiver/baseline file
(:func:`load_waivers` — every waiver carries a REASON; a reasonless or
unused waiver is itself reported).

Walk-root policy lives here and nowhere else: ``__pycache__``,
``__graft_entry__.py`` and the ``benchmarks/`` scratch tree are excluded
from every pass via :data:`SKIP_DIR_NAMES` / :data:`SKIP_FILE_NAMES`
instead of per-gate hardcoded skips (the three pre-framework gates each
re-invented a subset of this).

Passes are plain objects (:class:`PassSpec`) registered in
``analysis.passes.PASSES``; ``run_analysis`` executes a selection against
a context and splits the findings into active / waived.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field, replace
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

#: directory basenames never descended into, anywhere under a walk root
SKIP_DIR_NAMES = {"__pycache__", ".git", ".jax_cache", ".bench_triage",
                  ".pytest_cache", "node_modules"}
#: file basenames never analyzed (the graft shim is generated scaffolding)
SKIP_FILE_NAMES = {"__graft_entry__.py"}
#: repo-root directories that are scratch/vendored/fixture-bearing, not
#: product surface — the context's repo walk prunes them (``benchmarks/``
#: holds throwaway measurement scripts, ``tests/`` deliberately contains
#: pass-tripping fixture snippets, the rest is artifacts)
SKIP_REPO_DIRS = {"benchmarks", "results_tpu", "native", "examples",
                  "tests"}

ERROR = "error"
WARNING = "warning"


@dataclass(frozen=True)
class Finding:
    """One static-analysis finding.

    Identity for waiver matching is ``(pass_id, code, path)`` — line
    numbers shift too easily to key a baseline on them.
    """
    pass_id: str
    code: str
    path: str            # repo-relative, '/'-separated
    line: int
    message: str
    severity: str = ERROR

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.severity} "
                f"[{self.pass_id}/{self.code}] {self.message}")

    def as_dict(self) -> dict:
        return {"pass": self.pass_id, "code": self.code, "path": self.path,
                "line": self.line, "severity": self.severity,
                "message": self.message}


@dataclass(frozen=True)
class ParsedModule:
    """One parsed python file: repo-relative path, package-relative path
    (``""``-prefixed paths are outside the package), AST, and source."""
    rel: str        # repo-relative, e.g. "srnn_tpu/soup.py"
    pkg_rel: str    # package-relative, e.g. "soup.py" ("" if outside)
    path: str       # absolute
    tree: ast.AST
    text: str


@dataclass(frozen=True)
class ShellFile:
    rel: str
    path: str
    text: str


class AnalysisContext:
    """Everything a pass may look at, walked and parsed exactly once."""

    def __init__(self, repo_root: str, modules: List[ParsedModule],
                 shell_files: List[ShellFile],
                 parse_errors: Optional[List[Finding]] = None):
        self.repo_root = repo_root
        self.modules = modules
        self.shell_files = shell_files
        #: one core/E001 finding per file the compiler rejected — folded
        #: into every run_analysis result, because a pass silently seeing
        #: an empty AST is all seven gates disabled for that file
        self.parse_errors = list(parse_errors or ())
        self._by_rel = {m.rel: m for m in modules}

    def module(self, rel: str) -> Optional[ParsedModule]:
        return self._by_rel.get(rel)

    def package_modules(self) -> List[ParsedModule]:
        return [m for m in self.modules if m.rel.startswith("srnn_tpu/")]

    @classmethod
    def from_root(cls, repo_root: str,
                  package: str = "srnn_tpu") -> "AnalysisContext":
        repo_root = os.path.abspath(repo_root)
        modules: List[ParsedModule] = []
        parse_errors: List[Finding] = []
        pkg_root = os.path.join(repo_root, package)
        # the walk starts at the REPO root (bench.py and scripts/*.py are
        # analyzable surface for passes that want them; package_modules()
        # is the package-only view) — SKIP_REPO_DIRS prunes the scratch
        # trees in exactly one place
        for path in iter_python_files(repo_root, repo_root=repo_root):
            rel = os.path.relpath(path, repo_root).replace(os.sep, "/")
            pkg_rel = os.path.relpath(path, pkg_root).replace(os.sep, "/") \
                if (path.startswith(pkg_root + os.sep)) else ""
            with open(path, encoding="utf-8") as f:
                text = f.read()
            try:
                tree = ast.parse(text, filename=rel)
            except SyntaxError as e:
                # the passes see an empty AST (they cannot reason about a
                # file the compiler rejects), but the failure is SURFACED
                # as a finding — otherwise every gate silently reports
                # clean on the broken file
                parse_errors.append(Finding(
                    pass_id="core", code="E001", path=rel,
                    line=e.lineno or 1,
                    message=f"unparseable file ({e.msg}) — every pass is "
                            "blind to it until this is fixed"))
                tree = ast.Module(body=[], type_ignores=[])
                text = f"# UNPARSEABLE: {e}\n"
            modules.append(ParsedModule(rel=rel, pkg_rel=pkg_rel, path=path,
                                        tree=tree, text=text))
        shell: List[ShellFile] = []
        scripts = os.path.join(repo_root, "scripts")
        if os.path.isdir(scripts):
            for fname in sorted(os.listdir(scripts)):
                if not fname.endswith(".sh"):
                    continue
                path = os.path.join(scripts, fname)
                with open(path, encoding="utf-8") as f:
                    shell.append(ShellFile(rel=f"scripts/{fname}", path=path,
                                           text=f.read()))
        return cls(repo_root, modules, shell, parse_errors=parse_errors)


def iter_python_files(root: str,
                      repo_root: Optional[str] = None) -> Iterable[str]:
    """Every analyzable ``.py`` under ``root``, honoring the shared skip
    policy: ``__pycache__`` trees and ``__graft_entry__.py`` everywhere,
    plus the repo-root scratch dirs (:data:`SKIP_REPO_DIRS`) — the latter
    keyed on ``repo_root`` specifically, so a package subdirectory that
    happens to share a scratch name is still analyzed."""
    repo_root = os.path.abspath(repo_root) if repo_root else None
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in SKIP_DIR_NAMES
                             and not (os.path.abspath(dirpath) == repo_root
                                      and d in SKIP_REPO_DIRS))
        for fname in sorted(filenames):
            if not fname.endswith(".py") or fname in SKIP_FILE_NAMES:
                continue
            yield os.path.join(dirpath, fname)


# ---------------------------------------------------------------------------
# pass registry plumbing
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PassSpec:
    """One registered pass: a stable id, a one-line title, whether the
    ``--fast`` preflight tier includes it, and the run callable
    (``ctx -> iterable of Finding``)."""
    id: str
    title: str
    run: Callable[[AnalysisContext], Iterable[Finding]]
    fast: bool = True


# ---------------------------------------------------------------------------
# waivers / baseline
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Waiver:
    pass_id: str
    path: str
    code: str
    reason: str
    line: int           # line in the waiver file, for reporting
    #: optional message-substring narrowing (``match="..."`` at the start
    #: of the reason) — without it a (pass, file, code) waiver would also
    #: swallow every FUTURE distinct finding of that code in the file
    match: Optional[str] = None

    def matches(self, f: Finding) -> bool:
        return (self.pass_id == f.pass_id and self.code == f.code
                and self.path == f.path
                and (self.match is None or self.match in f.message))


def default_waiver_file(repo_root: str) -> str:
    return os.path.join(repo_root, "srnn_tpu", "analysis", "waivers.txt")


def load_waivers(path: str) -> Tuple[List[Waiver], List[Finding]]:
    """Parse the waiver/baseline file.

    One waiver per line: ``pass-id  repo/rel/path  CODE  reason...`` —
    whitespace-separated, ``#`` comments and blank lines ignored.  The
    reason is REQUIRED: a reasonless waiver is reported as a finding
    (``waivers/W001``) instead of silently suppressing anything.  The
    reason may begin with ``match="<substring>"`` to waive only findings
    whose message contains the substring — strongly preferred, since a
    bare (pass, file, code) waiver also covers future distinct findings
    of the same code in that file.
    """
    match_re = re.compile(r'^match="([^"]+)"\s*(.*)$')
    waivers: List[Waiver] = []
    problems: List[Finding] = []
    if not os.path.exists(path):
        return waivers, problems
    rel = os.path.basename(path)
    with open(path, encoding="utf-8") as f:
        for lineno, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split(None, 3)
            if len(parts) < 4 or not parts[3].strip():
                problems.append(Finding(
                    pass_id="waivers", code="W001", path=rel, line=lineno,
                    message="malformed waiver — need "
                            "'pass-id path CODE reason...' with a "
                            "non-empty reason"))
                continue
            reason = parts[3].strip()
            match = None
            m = match_re.match(reason)
            if m:
                match, rest = m.group(1), m.group(2).strip()
                if not rest:
                    problems.append(Finding(
                        pass_id="waivers", code="W001", path=rel,
                        line=lineno,
                        message='match="..." needs a reason after it'))
                    continue
                reason = rest
            waivers.append(Waiver(pass_id=parts[0], path=parts[1],
                                  code=parts[2], reason=reason,
                                  line=lineno, match=match))
    return waivers, problems


@dataclass
class AnalysisResult:
    findings: List[Finding] = field(default_factory=list)   # active
    waived: List[Tuple[Finding, Waiver]] = field(default_factory=list)
    unused_waivers: List[Waiver] = field(default_factory=list)
    pass_ids: List[str] = field(default_factory=list)

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == ERROR]

    @property
    def exit_code(self) -> int:
        return 1 if self.errors else 0


def run_analysis(ctx: AnalysisContext, passes: Sequence[PassSpec],
                 waiver_file: Optional[str] = None) -> AnalysisResult:
    """Run ``passes`` over ``ctx`` and fold in the waiver file.

    An unused waiver becomes a WARNING finding (stale baselines rot);
    a malformed one an ERROR.  Findings come back sorted by location.
    """
    if waiver_file is None:
        waiver_file = default_waiver_file(ctx.repo_root)
    waivers, waiver_problems = load_waivers(waiver_file)
    # files the walker could not parse are findings in EVERY run — a pass
    # seeing their empty AST would otherwise report clean on them
    raw: List[Finding] = list(ctx.parse_errors)
    for spec in passes:
        for f in spec.run(ctx):
            if f.pass_id != spec.id:
                f = replace(f, pass_id=spec.id)
            raw.append(f)
    result = AnalysisResult(pass_ids=[p.id for p in passes])
    used: Dict[int, int] = {}
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.code)):
        for i, w in enumerate(waivers):
            if w.matches(f):
                used[i] = used.get(i, 0) + 1
                result.waived.append((f, w))
                break
        else:
            result.findings.append(f)
    result.findings.extend(waiver_problems)
    wrel = os.path.relpath(waiver_file, ctx.repo_root).replace(os.sep, "/")
    ran = set(result.pass_ids)
    for i, w in enumerate(waivers):
        # a waiver can only be judged stale by a run that included its
        # pass — single-pass runs must not flag the others' waivers
        if i not in used and w.pass_id in ran:
            result.unused_waivers.append(w)
            result.findings.append(Finding(
                pass_id="waivers", code="W002", path=wrel, line=w.line,
                severity=WARNING,
                message=f"unused waiver ({w.pass_id}/{w.code} on {w.path}) "
                        "— the finding it covered is gone; delete the line"))
    return result


# ---------------------------------------------------------------------------
# small AST helpers shared by several passes
# ---------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> Optional[str]:
    """Rightmost name of the callee: ``f`` for both ``f(...)`` and
    ``mod.f(...)``."""
    if isinstance(node.func, ast.Name):
        return node.func.id
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return None


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
