"""Pass ``jit-purity``: no host-side effects syntactically inside traced
code.

A function that runs under ``jax.jit`` / ``shard_map`` / ``pallas_call``
or as a ``lax.scan`` body executes its Python exactly ONCE, at trace
time.  ``time.time()`` reads the clock when the program is *compiled*,
``np.random`` draws a constant that is baked into the executable,
``print`` fires once and never again, file I/O happens on the tracing
host at the wrong moment, and a ``global`` mutation is invisible to
retraces — every one of them is a silent wrong-answer generator, which
for this repo means silent bit-drift between spellings that the whole
parity discipline exists to prevent.

Traced functions are discovered per module:

  * ``@jax.jit`` / ``@jit`` / ``@functools.partial(jax.jit, ...)``
    decorators;
  * ``name = jax.jit(_fn, ...)`` wrapper assignments;
  * first arguments of ``lax.scan`` / ``shard_map`` / ``pallas_call``
    calls (local function names and lambdas).

The whole body of a traced function counts, nested defs included — a
host effect in a nested helper still fires at trace time.  Uses of
``jax.debug.print`` / ``jax.random`` are of course fine (attribute
calls on ``jax`` never match these patterns).

Codes:
  * ``J001`` — ``print()`` inside traced code (trace-time only; use
    ``jax.debug.print`` for per-step output).
  * ``J002`` — ``time.*`` call inside traced code.
  * ``J003`` — ``np.random.*`` / ``numpy.random.*`` / stdlib
    ``random.*`` inside traced code (use ``jax.random`` with a threaded
    key).
  * ``J004`` — host file I/O (``open``, ``os.*`` file ops, ``shutil.*``)
    inside traced code.
  * ``J005`` — ``global`` mutation inside traced code.
"""

import ast
from typing import Dict, List, Set

from ..core import AnalysisContext, Finding, PassSpec, call_name, dotted_name

#: callees whose FIRST positional argument is traced
TRACING_CALLS = {"scan", "shard_map", "pallas_call"}

#: os.* attrs that are file I/O (reading the env is trace-legal, if ugly)
OS_FILE_OPS = {"open", "remove", "unlink", "rename", "replace", "makedirs",
               "mkdir", "rmdir", "write", "read", "fsync", "truncate"}


def _is_jit_expr(node: ast.AST) -> bool:
    """``jax.jit`` / ``jit`` or ``functools.partial(jax.jit, ...)``."""
    name = dotted_name(node)
    if name in ("jax.jit", "jit"):
        return True
    if isinstance(node, ast.Call):
        fname = dotted_name(node.func)
        if fname in ("functools.partial", "partial") and node.args:
            return _is_jit_expr(node.args[0])
    return False


def _traced_defs(tree: ast.AST) -> List[ast.AST]:
    """FunctionDef/Lambda nodes traced by jit/scan/shard_map/pallas_call."""
    defs_by_name: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)
    traced: List[ast.AST] = []
    traced_ids: Set[int] = set()

    def mark_name(name: str) -> None:
        for d in defs_by_name.get(name, ()):
            if id(d) not in traced_ids:
                traced_ids.add(id(d))
                traced.append(d)

    def mark_arg(arg: ast.AST) -> None:
        if isinstance(arg, ast.Name):
            mark_name(arg.id)
        elif isinstance(arg, ast.Lambda) and id(arg) not in traced_ids:
            traced_ids.add(id(arg))
            traced.append(arg)

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_jit_expr(d) for d in node.decorator_list):
                mark_name(node.name)
        elif isinstance(node, ast.Call):
            if _is_jit_expr(node.func) and node.args:
                mark_arg(node.args[0])
            elif call_name(node) in TRACING_CALLS and node.args:
                mark_arg(node.args[0])
    return traced


def _host_random_imported(tree: ast.AST) -> bool:
    """True when the module's bare ``random`` name is a HOST RNG —
    stdlib ``import random`` or ``from numpy import random``.
    ``from jax import random`` (the common trace-safe spelling) must not
    make ``random.split(key)`` look like a host call; an unrecognized
    provenance stays quiet (a false J003 would force a bogus waiver)."""
    host = False
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "random" and alias.asname in (None,
                                                               "random"):
                    host = True
        elif isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if (alias.asname or alias.name) != "random":
                    continue
                if node.module in ("numpy", "np"):
                    host = True
                elif node.module != "random":
                    return False  # jax.random or another traced namespace
    return host


def _violations(body_root: ast.AST, rel: str, flag_bare_random: bool):
    nodes = ast.walk(body_root.body if isinstance(body_root, ast.Lambda)
                     else body_root)
    for node in nodes:
        if isinstance(node, ast.Global):
            yield Finding(
                pass_id=PASS.id, code="J005", path=rel, line=node.lineno,
                message="global mutation inside traced code — retraces "
                        "never see it; thread state through the carry")
            continue
        if not isinstance(node, ast.Call):
            continue
        cname = call_name(node)
        chain = dotted_name(node.func) or ""
        root = chain.split(".", 1)[0]
        if isinstance(node.func, ast.Name) and cname == "print":
            yield Finding(
                pass_id=PASS.id, code="J001", path=rel, line=node.lineno,
                message="print() inside traced code fires once at trace "
                        "time and never per step — use jax.debug.print "
                        "or hoist to the host loop")
        elif root == "time":
            yield Finding(
                pass_id=PASS.id, code="J002", path=rel, line=node.lineno,
                message=f"{chain}() inside traced code reads the clock at "
                        "COMPILE time — measure around the dispatch on "
                        "the host instead")
        elif chain.startswith(("np.random.", "numpy.random.")) \
                or (root == "random" and flag_bare_random):
            yield Finding(
                pass_id=PASS.id, code="J003", path=rel, line=node.lineno,
                message=f"{chain}() inside traced code bakes one host draw "
                        "into the executable — use jax.random with a "
                        "threaded key")
        elif (isinstance(node.func, ast.Name) and cname == "open") \
                or (root == "os" and cname in OS_FILE_OPS) \
                or root == "shutil":
            yield Finding(
                pass_id=PASS.id, code="J004", path=rel, line=node.lineno,
                message=f"host file I/O ({chain or cname}) inside traced "
                        "code runs at trace time on the tracing host — "
                        "move it to the chunk finisher / BackgroundWriter")


def run(ctx: AnalysisContext):
    for mod in ctx.package_modules():
        flag_bare_random = _host_random_imported(mod.tree)
        for traced in _traced_defs(mod.tree):
            yield from _violations(traced, mod.rel, flag_bare_random)


PASS = PassSpec(
    id="jit-purity",
    title="no time/np.random/print/file-I/O/global-mutation inside "
          "jitted, shard_mapped, pallas, or scanned bodies",
    run=run)
