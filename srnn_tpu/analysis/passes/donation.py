"""Pass ``donation-safety``: the PR 3 ordering invariant, machine-checked.

The production hot loops dispatch ``*_donated`` jitted steps: XLA reuses
the input population buffers in place, so the Python-side value a local
still refers to is GARBAGE after the dispatch.  The only sanctioned
pattern (``utils/pipeline.py``) is

    snap = snapshot(state)          # async device copy dispatched FIRST
    out  = evolve_donated(cfg, state)   # ...then the donating dispatch
    state = out[0]                  # the local rebinds to the new buffers

Two things can silently break it, and until this pass both were enforced
only by convention and runtime parity tests:

  * reading a local AFTER it was passed in a donated position, before it
    is rebound (``D001``) — on CPU this often *works* (the backend may
    not alias), so it ships and corrupts on TPU;
  * snapshotting a tree AFTER the donating dispatch already consumed it
    (``D002``) — the snapshot captures poisoned bytes, and the triage
    bundle / checkpoint built from it replays garbage.

Scope and honesty notes: the analysis is per-function and syntactic.  It
tracks bare-name locals only (no attribute roots), treats branches as
may-donate (a name donated in ANY branch arm counts, cleared only by a
rebind on that path), runs loop bodies twice to catch loop-carried
use-after-donate, and does not follow donated arguments through calls to
local helper functions or into lambda bodies.  Donated argument
positions come from :data:`DONATED_POSITIONS`; an unknown ``*_donated``
callee conservatively treats every bare-name argument after the first
(the config slot) as donated.

Codes:
  * ``D001`` — local read after being passed in a donated position, with
    no rebinding in between.
  * ``D002`` — ``snapshot()`` of a tree AFTER the donating dispatch that
    consumed it (the PR 3 snapshot-before-donation ordering invariant).
"""

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import AnalysisContext, Finding, PassSpec, call_name

#: donating callables -> 0-based positions of the donated arguments
#: (mirrors each jit wrapper's ``donate_argnums``; keep in sync when a
#: new ``*_donated`` twin ships — unknown names fall back to the
#: conservative every-arg-after-the-first rule)
DONATED_POSITIONS: Dict[str, Tuple[int, ...]] = {
    "evolve_donated": (1,),
    "evolve_step_donated": (1,),
    "evolve_multi_donated": (1,),
    "evolve_multi_step_donated": (1,),
    "sharded_evolve_donated": (2,),
    "sharded_evolve_step_donated": (2,),
    "sharded_evolve_multi_donated": (2,),
    "sharded_evolve_multi_step_donated": (2,),
    "run_fixpoint_donated": (1,),
    "run_mixed_fixpoint_donated": (1,),
    "run_training_donated": (1,),
    "run_fixpoint_stacked_donated": (1,),
    "evolve_stacked_donated": (1,),
    "evolve_stacked_step_donated": (1,),
    "evolve_multi_stacked_donated": (1,),
}

#: names whose call reads a tree for the async pre-donation copy
SNAPSHOT_NAMES = {"snapshot"}


def _donated_positions(name: str) -> Optional[Tuple[int, ...]]:
    if name in DONATED_POSITIONS:
        return DONATED_POSITIONS[name]
    if name.endswith("_donated"):
        return None  # unknown donating callee: sentinel for "all but arg 0"
    return ()


class _Donation:
    __slots__ = ("line", "callee")

    def __init__(self, line: int, callee: str):
        self.line = line
        self.callee = callee


class _Scope:
    """Linear may-donate analysis of one function body."""

    def __init__(self, mod_rel: str, findings: List[Finding]):
        self.rel = mod_rel
        self.findings = findings
        self.donated: Dict[str, _Donation] = {}
        #: aliases of donating callables (``run = sharded_evolve_donated
        #: if owned else sharded_evolve``)
        self.aliases: Dict[str, str] = {}
        self._reported: Set[Tuple[int, str, str]] = set()

    # -- expression handling ---------------------------------------------

    def _donating_callee(self, node: ast.Call) -> Optional[str]:
        name = call_name(node)
        if name is None:
            return None
        if name in self.aliases:
            name = self.aliases[name]
        pos = _donated_positions(name)
        if pos == ():
            return None
        return name

    def _donated_args(self, node: ast.Call, callee: str) -> List[ast.Name]:
        pos = _donated_positions(callee)
        args = []
        if pos is None:
            pos = tuple(range(1, len(node.args)))
        for i in pos:
            if i < len(node.args) and isinstance(node.args[i], ast.Name):
                args.append(node.args[i])
        return args

    def _report(self, code: str, line: int, name: str, msg: str) -> None:
        key = (line, name, code)
        if key in self._reported:
            return
        self._reported.add(key)
        self.findings.append(Finding(pass_id=PASS.id, code=code,
                                     path=self.rel, line=line, message=msg))

    def eval_expr(self, node: Optional[ast.AST]) -> None:
        """Walk one expression: flag reads of donated names, then apply
        any new donations it performs (the donating occurrence itself is
        not a read)."""
        if node is None:
            return
        donations: List[Tuple[ast.Call, str]] = []
        donating_arg_ids: Set[int] = set()
        snapshot_args: Dict[int, int] = {}  # id(Name node) -> call lineno
        for sub in ast.walk(node):
            if isinstance(sub, ast.Lambda):
                continue  # bodies run later (or never); see module doc
            if not isinstance(sub, ast.Call):
                continue
            callee = self._donating_callee(sub)
            if callee is not None:
                donations.append((sub, callee))
                for arg in self._donated_args(sub, callee):
                    donating_arg_ids.add(id(arg))
            cname = call_name(sub)
            if cname in SNAPSHOT_NAMES:
                for arg in ast.walk(sub):
                    if isinstance(arg, ast.Name) \
                            and isinstance(arg.ctx, ast.Load):
                        snapshot_args.setdefault(id(arg), sub.lineno)
        lambda_nodes = [n for n in ast.walk(node)
                        if isinstance(n, ast.Lambda)]

        def inside_lambda(n: ast.AST) -> bool:
            return any(n is sub for lam in lambda_nodes
                       for sub in ast.walk(lam.body))

        for sub in ast.walk(node):
            if not (isinstance(sub, ast.Name)
                    and isinstance(sub.ctx, ast.Load)):
                continue
            don = self.donated.get(sub.id)
            if don is None or id(sub) in donating_arg_ids \
                    or inside_lambda(sub):
                continue
            if id(sub) in snapshot_args:
                self._report(
                    "D002", sub.lineno, sub.id,
                    f"snapshot of {sub.id!r} AFTER {don.callee}() already "
                    f"donated its buffers (line {don.line}) — dispatch the "
                    "snapshot BEFORE the donating step (PR 3 ordering "
                    "invariant) or snapshot the step's OUTPUT")
            else:
                self._report(
                    "D001", sub.lineno, sub.id,
                    f"{sub.id!r} read after being donated to "
                    f"{don.callee}() (line {don.line}) with no rebinding "
                    "in between — the buffer is garbage after the donating "
                    "dispatch; rebind from the step's output or snapshot() "
                    "first")
        for call, callee in donations:
            for arg in self._donated_args(call, callee):
                self.donated[arg.id] = _Donation(call.lineno, callee)

    # -- binding handling -------------------------------------------------

    def _clear_target(self, target: ast.AST) -> None:
        for sub in ast.walk(target):
            if isinstance(sub, ast.Name):
                self.donated.pop(sub.id, None)
                # rebinding also retires any donating-callable alias the
                # name held — `run = evolve` after `run = evolve_donated`
                # must stop treating run() as donating
                self.aliases.pop(sub.id, None)

    def _maybe_alias(self, node: ast.Assign) -> None:
        if len(node.targets) != 1 or not isinstance(node.targets[0], ast.Name):
            return
        cands: List[ast.AST] = [node.value]
        if isinstance(node.value, ast.IfExp):
            cands = [node.value.body, node.value.orelse]
        for cand in cands:
            name = None
            if isinstance(cand, ast.Name):
                name = cand.id
            elif isinstance(cand, ast.Attribute):
                name = cand.attr
            if name is not None and _donated_positions(name) != ():
                self.aliases[node.targets[0].id] = name
                return

    # -- statement walk ---------------------------------------------------

    def run_body(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            self.run_stmt(stmt)

    def _branch(self, bodies: List[List[ast.stmt]]) -> None:
        """May-analysis over alternative branches: each runs from a copy
        of the current state; the merged state keeps a name donated if ANY
        branch ends with it donated."""
        pre = dict(self.donated)
        merged: Dict[str, _Donation] = {}
        for body in bodies:
            self.donated = dict(pre)
            self.run_body(body)
            merged.update(self.donated)
        self.donated = merged

    def run_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # analyzed as its own scope by the pass driver; the def just
            # (re)binds its name here
            self.donated.pop(stmt.name, None)
            return
        if isinstance(stmt, ast.ClassDef):
            self.donated.pop(stmt.name, None)
            return
        if isinstance(stmt, ast.Assign):
            self.eval_expr(stmt.value)
            # clear first (retires stale donated marks AND aliases), then
            # record the fresh alias if this assignment creates one
            for t in stmt.targets:
                self._clear_target(t)
            self._maybe_alias(stmt)
            return
        if isinstance(stmt, ast.AugAssign):
            self.eval_expr(stmt.value)
            self.eval_expr(stmt.target)  # augmented target is also a read
            self._clear_target(stmt.target)
            return
        if isinstance(stmt, ast.AnnAssign):
            self.eval_expr(stmt.value)
            if stmt.value is not None:
                self._clear_target(stmt.target)
            return
        if isinstance(stmt, (ast.Expr, ast.Return)):
            self.eval_expr(stmt.value)
            return
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                self._clear_target(t)
            return
        if isinstance(stmt, ast.If):
            self.eval_expr(stmt.test)
            self._branch([stmt.body, stmt.orelse])
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self.eval_expr(stmt.iter)
            pre = dict(self.donated)
            self._clear_target(stmt.target)
            # two passes over the body: the second catches loop-carried
            # use-after-donate (donated at the bottom, read at the top of
            # the next iteration)
            self.run_body(stmt.body)
            self._clear_target(stmt.target)
            self.run_body(stmt.body)
            post = self.donated
            self.donated = dict(pre)
            self.donated.update(post)   # may-donate: 0 or >=1 iterations
            self.run_body(stmt.orelse)
            return
        if isinstance(stmt, ast.While):
            self.eval_expr(stmt.test)
            pre = dict(self.donated)
            self.run_body(stmt.body)
            self.run_body(stmt.body)
            post = self.donated
            self.donated = dict(pre)
            self.donated.update(post)
            self.run_body(stmt.orelse)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self.eval_expr(item.context_expr)
                if item.optional_vars is not None:
                    self._clear_target(item.optional_vars)
            self.run_body(stmt.body)
            return
        if isinstance(stmt, ast.Try):
            pre = dict(self.donated)
            self.run_body(stmt.body)
            post_body = dict(self.donated)
            # handlers may run from anywhere in the body: start them from
            # the union of pre and post-body state
            merged = dict(pre)
            merged.update(post_body)
            ends = [post_body]
            for handler in stmt.handlers:
                self.donated = dict(merged)
                if handler.name:
                    self.donated.pop(handler.name, None)
                self.run_body(handler.body)
                ends.append(dict(self.donated))
            self.donated = {}
            for e in ends:
                self.donated.update(e)
            self.run_body(stmt.orelse)
            self.run_body(stmt.finalbody)
            return
        if isinstance(stmt, (ast.Raise, ast.Assert)):
            self.eval_expr(getattr(stmt, "exc", None)
                           or getattr(stmt, "test", None))
            return
        if isinstance(stmt, ast.Match):
            self.eval_expr(stmt.subject)
            for case in stmt.cases:
                self.eval_expr(case.guard)
            self._branch([case.body for case in stmt.cases] + [[]])
            return
        # Import/Global/Nonlocal/Pass/Break/Continue: nothing to track
        return


def _function_scopes(tree: ast.AST):
    """Every function body in the module (module top level included),
    each analyzed independently."""
    yield tree.body
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node.body


def run(ctx: AnalysisContext):
    for mod in ctx.package_modules():
        findings: List[Finding] = []
        for body in _function_scopes(mod.tree):
            scope = _Scope(mod.rel, findings)
            scope.run_body(body)
        yield from findings


PASS = PassSpec(
    id="donation-safety",
    title="no use-after-donate; snapshots dispatch before the donating "
          "step (PR 3 ordering invariant)",
    run=run)
