"""Pass ``flag-parity``: the four evolve surfaces stay interchangeable,
and the AOT warmup spelling zoo covers every flag combination the
production setups can dispatch.

ROADMAP item 1 documents the tax this pass collects up front: every new
static flag (``metrics=``, ``health=``, ``lineage=``, …) must be
hand-threaded through four near-copy evolve surfaces
(``soup.evolve``, ``multisoup.evolve_multi``,
``parallel.sharded_evolve``, ``parallel.sharded_evolve_multi``) and the
``utils/aot.py`` warmup spelling zoo, and PRs 2–7 each re-paid it.
Until the carry-plugin refactor lands, this pass makes the invariant
machine-checked instead of reviewer-checked:

  * **surface parity** — the four private ``_evolve*`` bodies must
    expose identical keyword flags with identical defaults (soup's
    ``record`` is the one documented per-surface extra: trajectory
    recording predates the carry contract and has no sharded twin);
  * **static-argnames parity** — every flag must be listed in
    ``static_argnames`` of BOTH jit wrappers (plain + ``_donated``) of
    its surface, except ``lineage_state`` which is a traced carry and
    must NOT be static;
  * **warmup coverage** — every carry-flag combination
    (``metrics``/``health``/``lineage``) that a ``setups/`` dispatch can
    reach must have a matching warmup entry in ``utils/aot.py``, or a
    production run's first chunk re-pays the compile the AOT subsystem
    exists to remove.  Setups' flag dicts are tracked through the
    ``kw = {...}; if cond: kw["health"] = True; run(..., **kw)`` idiom
    (additions under a conditional make the flag optional, and the
    check covers the whole lattice of reachable combinations).

Codes:
  * ``F001`` — contract flag missing on a surface.
  * ``F002`` — contract flag default differs between surfaces.
  * ``F003`` — flag missing from a jit wrapper's ``static_argnames``.
  * ``F004`` — ``lineage_state`` (a traced carry) listed as static.
  * ``F005`` — a surface function or jit wrapper could not be located
    (the registry below went stale — update it with the refactor).
  * ``F010`` — a setups dispatch reaches a flag combination with no
    matching ``utils/aot.py`` warmup entry.
  * ``F011`` — a warmup-entries generator in ``utils/aot.py`` could not
    be parsed (the zoo moved; update the registry below).
  * ``F012`` — a dispatch's flags could not be resolved statically
    (warning; the coverage check cannot see through it).
"""

import ast
import itertools
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from ..core import (AnalysisContext, Finding, PassSpec, WARNING, call_name,
                    dotted_name)

#: the carry flags whose combinations define the warmup spelling zoo
CARRY_FLAGS = ("metrics", "health", "lineage")

#: surface id -> (module rel, private fn, jit wrapper names,
#:               aot entries generator, allowed per-surface extra flags)
SURFACES = {
    "soup.evolve": (
        "srnn_tpu/soup.py", "_evolve",
        ("evolve", "evolve_donated"), "_soup_entries",
        # trajectory recording predates the carry contract and has no
        # sharded twin; it rides only the single-device surface
        frozenset({"record"})),
    "multisoup.evolve_multi": (
        "srnn_tpu/multisoup.py", "_evolve_multi",
        ("evolve_multi", "evolve_multi_donated"), "_multi_entries",
        frozenset()),
    "parallel.sharded_evolve": (
        "srnn_tpu/parallel/sharded_soup.py", "_sharded_evolve",
        ("sharded_evolve", "sharded_evolve_donated"), "_sharded_entries",
        frozenset()),
    "parallel.sharded_evolve_multi": (
        "srnn_tpu/parallel/sharded_multisoup.py", "_sharded_evolve_multi",
        ("sharded_evolve_multi", "sharded_evolve_multi_donated"),
        "_sharded_multi_entries",
        frozenset()),
    # the serve tenant-axis surfaces (PR 10) hold the SAME flag contract:
    # a carry flag that skips them silently desynchronizes the stacked
    # spelling from the solo one it must stay bitwise-equal to
    "serve.evolve_stacked": (
        "srnn_tpu/serve/tenant.py", "_evolve_stacked",
        ("evolve_stacked", "evolve_stacked_donated"), "_stacked_entries",
        # record rides the stacked surface exactly like soup.evolve's
        frozenset({"record"})),
    "serve.evolve_multi_stacked": (
        "srnn_tpu/serve/tenant.py", "_evolve_multi_stacked",
        ("evolve_multi_stacked", "evolve_multi_stacked_donated"),
        "_stacked_multi_entries",
        frozenset()),
}

#: dispatch callee name -> surface id (what the setups call)
DISPATCH_NAMES: Dict[str, str] = {}
for _sid, (_, _, _wrappers, _, _) in SURFACES.items():
    for _w in _wrappers:
        DISPATCH_NAMES[_w] = _sid

#: the carry flag that is traced, not static
TRACED_FLAGS = frozenset({"lineage_state"})

AOT_REL = "srnn_tpu/utils/aot.py"
#: modules whose dispatches the warmup-coverage check walks: the setups
#: (production entry points), the experiment service (its executors
#: dispatch the same surfaces plus the stacked twins), and the
#: distributed tier (its entry points ride the same sharded surfaces —
#: a distributed dispatch that reached an unwarmed spelling would repay
#: the compile on EVERY process at once)
DISPATCH_PREFIXES = ("srnn_tpu/setups/", "srnn_tpu/serve/",
                     "srnn_tpu/distributed/")


def _find_def(tree: ast.AST, name: str) -> Optional[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == name:
            return node
    return None


def _keyword_flags(fn: ast.FunctionDef) -> Dict[str, str]:
    """Parameters with defaults -> unparsed default literal."""
    flags: Dict[str, str] = {}
    pos = fn.args.args
    defaults = fn.args.defaults
    for arg, default in zip(pos[len(pos) - len(defaults):], defaults):
        flags[arg.arg] = ast.unparse(default)
    for arg, default in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
        if default is not None:
            flags[arg.arg] = ast.unparse(default)
    return flags


def _static_argnames(tree: ast.AST, wrapper: str) \
        -> Optional[Tuple[int, Set[str]]]:
    """(lineno, static names) of ``wrapper = jax.jit(_fn, static_argnames=
    (...))`` — also matches the ``jax.jit(\n _fn, ...)`` multiline and
    bare ``jit`` spellings."""
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == wrapper
                and isinstance(node.value, ast.Call)):
            continue
        callee = dotted_name(node.value.func)
        if callee not in ("jax.jit", "jit"):
            continue
        for kw in node.value.keywords:
            if kw.arg == "static_argnames":
                names = {e.value for e in ast.walk(kw.value)
                         if isinstance(e, ast.Constant)
                         and isinstance(e.value, str)}
                return node.lineno, names
        return node.lineno, set()
    return None


def _surface_parity(ctx: AnalysisContext):
    per_surface: Dict[str, Dict[str, str]] = {}
    for sid, (rel, fn_name, wrappers, _entries, extras) in SURFACES.items():
        mod = ctx.module(rel)
        fn = _find_def(mod.tree, fn_name) if mod else None
        if fn is None:
            yield Finding(
                pass_id=PASS.id, code="F005", path=rel, line=1,
                message=f"surface {sid}: {fn_name}() not found — the "
                        "flag-parity registry is stale; update "
                        "analysis/passes/flag_parity.py alongside the "
                        "refactor")
            continue
        flags = {k: v for k, v in _keyword_flags(fn).items()
                 if k not in extras}
        per_surface[sid] = flags
        # static_argnames discipline on both wrappers
        static_expected = set(flags) - TRACED_FLAGS
        for wrapper in wrappers:
            got = _static_argnames(mod.tree, wrapper)
            if got is None:
                yield Finding(
                    pass_id=PASS.id, code="F005", path=rel, line=fn.lineno,
                    message=f"surface {sid}: jit wrapper {wrapper!r} not "
                            "found — update the flag-parity registry")
                continue
            lineno, names = got
            for flag in sorted(static_expected - names):
                yield Finding(
                    pass_id=PASS.id, code="F003", path=rel, line=lineno,
                    message=f"{wrapper}: flag {flag!r} missing from "
                            "static_argnames — a non-static flag retraces "
                            "per value instead of selecting a program")
            for flag in sorted(TRACED_FLAGS & names):
                yield Finding(
                    pass_id=PASS.id, code="F004", path=rel, line=lineno,
                    message=f"{wrapper}: {flag!r} is a traced carry and "
                            "must NOT be in static_argnames")
    if not per_surface:
        return
    contract: Set[str] = set()
    for flags in per_surface.values():
        contract |= set(flags)
    for sid, flags in per_surface.items():
        rel, fn_name = SURFACES[sid][0], SURFACES[sid][1]
        mod = ctx.module(rel)
        fn = _find_def(mod.tree, fn_name)
        for flag in sorted(contract - set(flags)):
            holders = sorted(s for s, f in per_surface.items() if flag in f)
            yield Finding(
                pass_id=PASS.id, code="F001", path=rel, line=fn.lineno,
                message=f"surface {sid} is missing flag {flag!r} "
                        f"(present on {', '.join(holders)}) — the four "
                        "evolve surfaces must expose identical static "
                        "keyword flags")
        for flag, default in sorted(flags.items()):
            others = {s: f[flag] for s, f in per_surface.items()
                      if flag in f and f[flag] != default}
            if others and sid == min(s for s, f in per_surface.items()
                                     if flag in f):
                detail = ", ".join(f"{s}={d}" for s, d in sorted(
                    others.items()))
                yield Finding(
                    pass_id=PASS.id, code="F002", path=rel, line=fn.lineno,
                    message=f"flag {flag!r} default {default} differs "
                            f"across surfaces ({detail}) — identical "
                            "defaults are part of the contract")


# ---------------------------------------------------------------------------
# warmup coverage
# ---------------------------------------------------------------------------


def _warmed_combos(ctx: AnalysisContext):
    """surface id -> set of warmed carry-flag combos, from the kwargs
    dict literal of every ``yield (name, fn, args, {kwargs})`` in the
    surface's entries generator in utils/aot.py."""
    warmed: Dict[str, Set[FrozenSet[str]]] = {}
    problems: List[Finding] = []
    mod = ctx.module(AOT_REL)
    if mod is None:
        problems.append(Finding(
            pass_id=PASS.id, code="F011", path=AOT_REL, line=1,
            message="utils/aot.py not found — warmup coverage cannot run"))
        return warmed, problems
    for sid, (_rel, _fn, _wrappers, entries_fn, _extras) in SURFACES.items():
        fn = _find_def(mod.tree, entries_fn)
        if fn is None:
            problems.append(Finding(
                pass_id=PASS.id, code="F011", path=AOT_REL, line=1,
                message=f"warmup entries generator {entries_fn}() not "
                        f"found for surface {sid} — the spelling zoo "
                        "moved; update analysis/passes/flag_parity.py"))
            continue
        combos: Set[FrozenSet[str]] = set()
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Yield)
                    and isinstance(node.value, ast.Tuple)
                    and node.value.elts
                    and isinstance(node.value.elts[-1], ast.Dict)):
                continue
            keys = {k.value for k in node.value.elts[-1].keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
            combos.add(frozenset(keys & set(CARRY_FLAGS)))
        if not combos:
            problems.append(Finding(
                pass_id=PASS.id, code="F011", path=AOT_REL, line=fn.lineno,
                message=f"{entries_fn}() yields no parseable warmup "
                        "entries — the zoo extraction went stale"))
            continue
        warmed[sid] = combos
    return warmed, problems


class _DictFlags:
    """required / optional carry flags accumulated into one dict local."""

    def __init__(self, required: Set[str] = None, optional: Set[str] = None):
        self.required = set(required or ())
        self.optional = set(optional or ())


def _scope_nodes(body: List[ast.stmt]):
    """Every AST node belonging to this scope — nested function/class
    bodies are their own scopes and are NOT descended into (lambdas are:
    they cannot rebind, so their calls belong to the enclosing scope)."""
    stack = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue  # its body is its own scope (visited separately)
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _classify_flag(value: ast.AST) -> Optional[bool]:
    """How a flag binding contributes to the reachable-combo lattice:
    True = definitely passed (constant truthy), False = definitely absent
    (constant falsy == the default), None = runtime-dependent (optional)
    — the SAME semantics the direct-keyword path uses."""
    if isinstance(value, ast.Constant):
        return bool(value.value)
    return None


def _dict_flag_sets(node: ast.Dict) -> "tuple[Set[str], Set[str]]":
    """(required, optional) carry flags of one dict literal, value-aware."""
    required: Set[str] = set()
    optional: Set[str] = set()
    for k, v in zip(node.keys, node.values):
        if not (isinstance(k, ast.Constant) and k.value in CARRY_FLAGS):
            continue
        cls = _classify_flag(v)
        if cls is True:
            required.add(k.value)
        elif cls is None:
            optional.add(k.value)
    return required, optional


def _collect_dict_flags(fn_body: List[ast.stmt],
                        out: Dict[str, _DictFlags],
                        conditional: bool = False) -> None:
    """Track ``kw = {...}`` / ``kw["health"] = True`` / ``kw.update(...)``
    over ONE scope's body (nested defs excluded — they are their own
    scopes); additions under any conditional — or with a non-constant
    value — are optional."""
    for stmt in fn_body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            name = stmt.targets[0].id
            value = stmt.value
            # an UNCONDITIONAL dict-literal assignment re-initializes the
            # local: its keys are required from there on even under a loop
            # (the dispatch it feeds sits under the same loop) and earlier
            # tracked state is dead.  A CONDITIONAL reassignment may or
            # may not run, so the post-state is either the old or the new
            # dict: required shrinks to the intersection, everything else
            # becomes optional — never wipe a reachable combination.
            new = None
            if isinstance(value, ast.Dict):
                req, opt = _dict_flag_sets(value)
                new = _DictFlags(required=req, optional=opt)
            elif isinstance(value, ast.IfExp) \
                    and isinstance(value.body, ast.Dict) \
                    and isinstance(value.orelse, ast.Dict):
                req_b, opt_b = _dict_flag_sets(value.body)
                req_o, opt_o = _dict_flag_sets(value.orelse)
                always = req_b & req_o
                new = _DictFlags(
                    required=always,
                    optional=(req_b | opt_b | req_o | opt_o) - always)
            if new is not None:
                old = out.get(name)
                if conditional and old is not None:
                    required = old.required & new.required
                    new = _DictFlags(
                        required=required,
                        optional=(old.required | old.optional
                                  | new.required | new.optional) - required)
                out[name] = new
        elif isinstance(stmt, ast.Assign) \
                and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Subscript) \
                and isinstance(stmt.targets[0].value, ast.Name):
            name = stmt.targets[0].value.id
            key = stmt.targets[0].slice
            if isinstance(key, ast.Constant) and key.value in CARRY_FLAGS:
                d = out.setdefault(name, _DictFlags())
                cls = _classify_flag(stmt.value)
                if cls is True and not conditional:
                    d.required.add(key.value)
                elif cls is not False:
                    d.optional.add(key.value)
        elif isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            if isinstance(call.func, ast.Attribute) \
                    and call.func.attr == "update" \
                    and isinstance(call.func.value, ast.Name):
                name = call.func.value.id
                d = out.setdefault(name, _DictFlags())
                pairs = [(kw.arg, kw.value) for kw in call.keywords
                         if kw.arg in CARRY_FLAGS]
                for arg in call.args:
                    if isinstance(arg, ast.Dict):
                        pairs += [(k.value, v) for k, v
                                  in zip(arg.keys, arg.values)
                                  if isinstance(k, ast.Constant)
                                  and k.value in CARRY_FLAGS]
                for flag, v in pairs:
                    cls = _classify_flag(v)
                    if cls is True and not conditional:
                        d.required.add(flag)
                    elif cls is not False:
                        d.optional.add(flag)
        # recurse into compound statements; everything below a branch,
        # loop, or match arm is conditional
        for body in (getattr(stmt, "body", None), getattr(stmt, "orelse",
                                                          None),
                     getattr(stmt, "finalbody", None)):
            if isinstance(body, list):
                _collect_dict_flags(body, out, conditional=True)
        for handler in getattr(stmt, "handlers", []) or []:
            _collect_dict_flags(handler.body, out, conditional=True)
        for case in getattr(stmt, "cases", []) or []:
            _collect_dict_flags(case.body, out, conditional=True)


def _combo_name(combo: FrozenSet[str]) -> str:
    if not combo:
        return "(no carry flags)"
    order = {f: i for i, f in enumerate(CARRY_FLAGS)}
    tags = {"metrics": "metered", "health": "health", "lineage": "lineage"}
    return "." + ".".join(tags[f] for f in sorted(combo, key=order.get))


def _warmup_coverage(ctx: AnalysisContext):
    warmed, problems = _warmed_combos(ctx)
    yield from problems
    if not warmed:
        return
    setups = [m for m in ctx.package_modules()
              if m.rel.startswith(DISPATCH_PREFIXES)]
    for mod in setups:
        scopes = [mod.tree.body] + [
            n.body for n in ast.walk(mod.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        # module-wide FALLBACK environments, used only for names a scope
        # does not define itself — dicts/aliases passed into local
        # helpers as parameters (the ``_evolve(s, gens, owned, health,
        # lkw)`` idiom).  Same-named locals in different functions never
        # shadow each other: the scope-local environment wins.
        module_env: Dict[str, _DictFlags] = {}
        module_aliases: Dict[str, str] = {}
        for body in scopes:
            _collect_dict_flags(body, module_env)
            _collect_aliases(body, module_aliases)
        for body in scopes:
            yield from _scope_dispatches(mod, body, warmed,
                                         module_env, module_aliases)


def _collect_aliases(body: List[ast.stmt], out: Dict[str, str]) -> None:
    """``run = sharded_evolve_donated if c else ...`` alias tracking,
    scoped like :func:`_collect_dict_flags`."""
    for node in _scope_nodes(body):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            cands = [node.value]
            if isinstance(node.value, ast.IfExp):
                cands = [node.value.body, node.value.orelse]
            for cand in cands:
                name = cand.id if isinstance(cand, ast.Name) else (
                    cand.attr if isinstance(cand, ast.Attribute)
                    else None)
                if name in DISPATCH_NAMES:
                    out[node.targets[0].id] = name
                    break


def _scope_dispatches(mod, body: List[ast.stmt],
                      warmed: Dict[str, Set[FrozenSet[str]]],
                      module_env: Dict[str, _DictFlags],
                      module_aliases: Dict[str, str]):
    local_env: Dict[str, _DictFlags] = {}
    local_aliases: Dict[str, str] = {}
    _collect_dict_flags(body, local_env)
    _collect_aliases(body, local_aliases)
    for node in _scope_nodes(body):
        if not isinstance(node, ast.Call):
            continue
        cname = call_name(node)
        cname = local_aliases.get(cname, module_aliases.get(cname, cname))
        sid = DISPATCH_NAMES.get(cname)
        if sid is None or sid not in warmed:
            # a surface whose entries generator went stale already
            # reported F011; don't crash the rest of the coverage scan
            continue
        required: Set[str] = set()
        optional: Set[str] = set()
        resolved = True
        for kw in node.keywords:
            if kw.arg is None:
                star = kw.value
                d = None
                if isinstance(star, ast.Name):
                    # scope-local definition wins; the module-wide union
                    # is only a fallback for names this scope never
                    # defines (helper parameters like ``lkw``)
                    d = local_env.get(star.id, module_env.get(star.id))
                if d is not None:
                    required |= d.required
                    optional |= d.optional
                else:
                    resolved = False
            elif kw.arg in CARRY_FLAGS:
                if isinstance(kw.value, ast.Constant):
                    if kw.value.value:
                        required.add(kw.arg)
                else:
                    optional.add(kw.arg)
        if not resolved:
            yield Finding(
                pass_id=PASS.id, code="F012", path=mod.rel,
                line=node.lineno, severity=WARNING,
                message=f"dispatch of {sid} passes **kwargs this pass "
                        "cannot resolve statically — warmup coverage "
                        "is blind here; build the flag dict as a "
                        "tracked local literal")
            continue
        optional -= required
        for extra in itertools.chain.from_iterable(
                itertools.combinations(sorted(optional), r)
                for r in range(len(optional) + 1)):
            combo = frozenset(required | set(extra))
            if combo not in warmed[sid]:
                yield Finding(
                    pass_id=PASS.id, code="F010", path=mod.rel,
                    line=node.lineno,
                    message=f"dispatch of {sid} can reach flag combo "
                            f"{_combo_name(combo)} but utils/aot.py "
                            "warms no such spelling — the first chunk "
                            "of that run re-pays the compile; add the "
                            "warmup entry or waive with a reason")


def run(ctx: AnalysisContext):
    yield from _surface_parity(ctx)
    yield from _warmup_coverage(ctx)


PASS = PassSpec(
    id="flag-parity",
    title="four evolve surfaces expose identical static flags; every "
          "setups flag combo has an AOT warmup spelling",
    run=run)
