"""Pass ``thread-hygiene``: every thread started under ``srnn_tpu/``
must go through ``utils.pipeline.spawn_thread`` — the package's thread
factory — so it is (a) registered with the join-on-exit registry that the
shutdown tests audit (``pipeline.live_threads()``) and (b) non-daemon
unless explicitly opted out, so interpreter exit can never strand
buffered I/O (a daemon writer dying mid-fsync is a silent data-loss
path).

Migrated from the pre-framework ``tests/test_thread_hygiene.py`` walker,
including the daemon whitelist and its max-ONE-reviewed-site-per-file
rule.  The factory's runtime half (spawn lands in ``live_threads()``,
joins out of it) stays a runtime test in the wrapper.

Codes:
  * ``H001`` — direct ``Thread()`` construction outside the factory.
  * ``H002`` — ``spawn_thread(daemon=True)`` in an unwhitelisted file.
  * ``H003`` — a SECOND daemon site in a whitelisted file.
"""

import ast

from ..core import AnalysisContext, Finding, PassSpec

#: the factory's own home — the one sanctioned Thread() call site
FACTORY_FILE = "utils/pipeline.py"

#: reviewed daemon-thread call sites (pkg-relative file -> justification),
#: ONE per file — a second daemon call in a whitelisted file still fails,
#: so the BackgroundWriter (buffered I/O, same file as the ChunkDriver)
#: can never silently go daemon.  Both sites are deliberately NOT
#: joinable: they exist to escape/observe a thread that is presumed
#: wedged below Python, own no buffered I/O, and a non-daemon spelling
#: would hang interpreter exit on the very wedge they watch for.
DAEMON_WHITELIST = {
    "utils/pipeline.py":
        "ChunkDriver stall deadline: the watched finisher thread IS the "
        "presumed-wedged thread",
    "telemetry/flightrec.py":
        "StallSentinel dead-man's switch: fires while the main thread "
        "hangs in a dead backend call",
    "telemetry/profiler.py":
        "SamplingProfiler forensic observer: samples threads that may "
        "be wedged, owns no buffered I/O (flushes ride the run's "
        "writer); non-daemon would hang exit on the wedge it observes",
}


def _is_thread_ctor(node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "Thread":
        return True  # threading.Thread(...), x.Thread(...)
    return isinstance(f, ast.Name) and f.id == "Thread"


def _is_spawn_thread(node: ast.Call) -> bool:
    return (isinstance(node.func, (ast.Name, ast.Attribute))
            and (getattr(node.func, "id", None) == "spawn_thread"
                 or getattr(node.func, "attr", None) == "spawn_thread"))


def run(ctx: AnalysisContext):
    for mod in ctx.package_modules():
        daemon_sites = 0
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if _is_thread_ctor(node) and mod.pkg_rel != FACTORY_FILE:
                yield Finding(
                    pass_id=PASS.id, code="H001", path=mod.rel,
                    line=node.lineno,
                    message="direct Thread() — use "
                            "utils.pipeline.spawn_thread "
                            "(join-on-exit registry)")
            if _is_spawn_thread(node):
                for kw in node.keywords:
                    if (kw.arg == "daemon"
                            and isinstance(kw.value, ast.Constant)
                            and kw.value.value is True):
                        daemon_sites += 1
                        if mod.pkg_rel not in DAEMON_WHITELIST:
                            yield Finding(
                                pass_id=PASS.id, code="H002", path=mod.rel,
                                line=node.lineno,
                                message="spawn_thread(daemon=True) — daemon "
                                        "threads can strand buffered I/O at "
                                        "interpreter exit; justify and "
                                        "whitelist in analysis/passes/"
                                        "threads.py if truly needed")
                        elif daemon_sites > 1:
                            yield Finding(
                                pass_id=PASS.id, code="H003", path=mod.rel,
                                line=node.lineno,
                                message="second spawn_thread(daemon=True) in "
                                        "a whitelisted file — the whitelist "
                                        "covers ONE reviewed site per file; "
                                        "review this one separately")


PASS = PassSpec(
    id="thread-hygiene",
    title="threads only via utils.pipeline.spawn_thread; daemon sites "
          "whitelisted one-per-file",
    run=run)
