"""Pass ``stray-prints``: runtime output must route through
``Experiment.log`` / the telemetry sinks, never bare ``print()``.

Migrated from the pre-framework ``tests/test_no_stray_prints.py`` walker.
A ``print(...)`` call outside the sanctioned modules — the reference
``PrintingObject`` shim, ``experiment.py`` (whose ``log``/``__enter__``
ARE the human stdout channel), and the CLI entry points — is a finding
unless it explicitly routes via a ``file=`` keyword (diagnostics
deliberately sent to stderr, e.g. backend-init retries, stay legal
everywhere).

Codes:
  * ``P001`` — bare ``print()`` outside the sanctioned output channels.
"""

import ast

from ..core import AnalysisContext, Finding, PassSpec

#: package-relative modules whose stdout prints ARE their contract
ALLOWED_FILES = {
    "utils/printing.py",     # the reference PrintingObject parity shim
    "experiment.py",         # Experiment.log is the human stdout channel
    "precompile.py",         # CLI: prints its one JSON result line
    "viz.py",                # CLI: run-dir walker output
    "telemetry/report.py",   # CLI: renders the telemetry summary
    "telemetry/watch.py",    # CLI: the live watch console — stdout IS
                             # its product (snapshots + refresh frames)
    "telemetry/archive.py",  # CLI: ingest/gc result lines + --json docs
    "analysis/__main__.py",  # CLI: this analyzer's own report output
    "serve/__main__.py",     # CLI: service startup line + stats JSON
    "serve/pool.py",         # CLI tier: the fleet front's [w<i>] worker
                             # relay + lifecycle lines are its stdout job
    "distributed/launch.py",  # CLI: worker-output relay IS its stdout job
}
#: CLI entry-point trees (every setup is a __main__-dispatched script)
ALLOWED_DIRS = ("setups/",)


def run(ctx: AnalysisContext):
    for mod in ctx.package_modules():
        if mod.pkg_rel in ALLOWED_FILES or mod.pkg_rel.startswith(ALLOWED_DIRS):
            continue
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"):
                continue
            if any(kw.arg == "file" for kw in node.keywords):
                continue  # explicitly routed (stderr diagnostics)
            yield Finding(
                pass_id=PASS.id, code="P001", path=mod.rel,
                line=node.lineno,
                message="bare print() outside the sanctioned output "
                        "channels — route through Experiment.log / "
                        "telemetry sinks, or print(..., file=sys.stderr) "
                        "for diagnostics")


PASS = PassSpec(
    id="stray-prints",
    title="runtime output routes through Experiment.log/telemetry, "
          "never bare print()",
    run=run)
