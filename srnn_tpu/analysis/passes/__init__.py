"""The srnnlint pass catalog.

Import order is presentation order in ``--list``.  Adding a pass:
write ``passes/<name>.py`` exposing a module-level ``PASS``
(:class:`~srnn_tpu.analysis.core.PassSpec`), import it here, append to
``ALL_PASSES`` — the CLI, the pytest gate, and the waiver machinery pick
it up with no further wiring (see DESIGN.md §14).
"""

from typing import Dict, List

from ..core import PassSpec
from . import (donation, fault_taxonomy, flag_parity, jit_purity,
               metric_names, prints, span_names, threads)

ALL_PASSES: List[PassSpec] = [
    prints.PASS,
    threads.PASS,
    metric_names.PASS,
    span_names.PASS,
    donation.PASS,
    flag_parity.PASS,
    jit_purity.PASS,
    fault_taxonomy.PASS,
]

PASSES_BY_ID: Dict[str, PassSpec] = {p.id: p for p in ALL_PASSES}


def select(ids=None, fast_only: bool = False) -> List[PassSpec]:
    chosen = ALL_PASSES if not ids else [PASSES_BY_ID[i] for i in ids]
    if fast_only:
        chosen = [p for p in chosen if p.fast]
    return chosen
