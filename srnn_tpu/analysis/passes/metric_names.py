"""Pass ``metric-names``: every metric registered anywhere under
``srnn_tpu/`` must be declared in the canonical table
(``telemetry.names``) with the right kind and follow the naming
convention — the collection-time tripwire for the next ``zweo``-style
drift.

Migrated from the pre-framework ``tests/test_metric_names.py`` walker:
the AST half (literal ``.counter("…")``/``.gauge("…")``/
``.histogram("…")`` registrations, including the ``g = registry.gauge;
g("…")`` aliasing idiom the hot paths use) lives here; the runtime
halves (the ``EVENT_COUNTERS`` table import, the ``ACTION_NAMES``
spelling assertion) stay runtime tests in the wrapper.

Codes:
  * ``M001`` — registered metric name missing from ``CANONICAL_METRICS``.
  * ``M002`` — registered with a kind different from its declaration.
  * ``M003`` — a canonical name violates the naming convention.
  * ``M004`` — the AST scan found no registrations at all (the pass
    itself would be dead — fail loudly).
  * ``M005`` — metric LIVENESS: a name declared in the canonical table
    has no emission site anywhere in the package — neither a literal
    registration nor the name spelled in a runtime table
    (``EVENT_COUNTERS``-style dicts, gauge-name loops).  A declared-but-
    never-emitted metric is dashboard debt; delete it or emit it.
  * ``M006`` — REFERENCE validity (the inverse of M005, for the live
    telemetry plane): every registry name an alert rule references
    (a ``Rule(metric="…")`` call — ``telemetry.alerts``) or the scraped
    /healthz endpoint surfaces (the ``HEALTHZ_METRICS`` allowlist —
    ``telemetry.exporter``) must exist in ``CANONICAL_METRICS``.  A rule
    watching a name nobody can ever emit would silently never fire —
    worse than no rule, because the operator believes the condition is
    covered.
"""

import ast

from ..core import AnalysisContext, Finding, PassSpec

_KINDS = ("counter", "gauge", "histogram")


def _registrations(tree):
    """(kind, name, lineno) for every literal metric registration in one
    module, resolving single-letter aliases like ``g = registry.gauge``."""
    aliases = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Attribute) \
                and node.value.attr in _KINDS:
            aliases[node.targets[0].id] = node.value.attr
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        arg0 = node.args[0]
        if not (isinstance(arg0, ast.Constant) and isinstance(arg0.value, str)):
            continue
        f = node.func
        kind = None
        if isinstance(f, ast.Attribute) and f.attr in _KINDS:
            kind = f.attr
        elif isinstance(f, ast.Name) and f.id in aliases:
            kind = aliases[f.id]
        if kind is not None:
            yield kind, arg0.value, node.lineno


_NAMES_REL = "srnn_tpu/telemetry/names.py"


def _emitted_names(ctx: AnalysisContext, canonical) -> set:
    """Every canonical name with emission EVIDENCE in the package: a
    literal registration, or the name spelled as a string constant in any
    module other than the declaration table itself (covers the runtime-
    table idioms — ``EVENT_COUNTERS`` values, per-gauge name loops —
    where the registration call's first argument is a variable).

    KNOWN-WEAK by design: *any* string constant counts, so a name spelled
    in a non-emitting context (a log message, an unused dict, a report
    field list) keeps a dead metric alive and M005 stays silent.  The
    gate catches the common failure — a declaration landing with no code
    at all (it caught ``serve_tenant_flops_total`` during development) —
    not a determined one; restricting evidence to registration-call
    argument positions would mean teaching the pass every runtime-table
    shape, and a false M005 on a live metric costs more than a missed
    dead one."""
    emitted = set()
    for mod in ctx.package_modules():
        if mod.rel == _NAMES_REL:
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and node.value in canonical:
                emitted.add(node.value)
    return emitted


def _referenced_names(ctx: AnalysisContext):
    """(name, rel, lineno, where) for every metric name the live
    telemetry plane REFERENCES: the ``metric=`` keyword of any
    ``Rule(...)`` call (the declarative alert tables — rules built
    anywhere in the package, not just the default sets), and every
    string element of a module-level ``HEALTHZ_METRICS`` tuple/list
    (the scraped-endpoint allowlist)."""
    for mod in ctx.package_modules():
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                f = node.func
                fname = getattr(f, "id", None) or getattr(f, "attr", None)
                if fname == "Rule":
                    for kw in node.keywords:
                        if kw.arg == "metric" \
                                and isinstance(kw.value, ast.Constant) \
                                and isinstance(kw.value.value, str):
                            yield (kw.value.value, mod.rel, node.lineno,
                                   "alert rule")
            elif isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == "HEALTHZ_METRICS" \
                    and isinstance(node.value, (ast.Tuple, ast.List)):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) \
                            and isinstance(elt.value, str):
                        yield (elt.value, mod.rel, elt.lineno,
                               "healthz allowlist")


def run(ctx: AnalysisContext):
    # the canonical table and convention checker are the product source of
    # truth — import them instead of re-parsing (the CLI already paid the
    # package import; drifting a re-implementation would defeat the gate)
    from ...telemetry.names import CANONICAL_METRICS, check_name

    seen = False
    for mod in ctx.package_modules():
        for kind, name, lineno in _registrations(mod.tree):
            seen = True
            declared = CANONICAL_METRICS.get(name)
            if declared is None:
                yield Finding(
                    pass_id=PASS.id, code="M001", path=mod.rel, line=lineno,
                    message=f"metric {name!r} not in telemetry.names."
                            "CANONICAL_METRICS — declare it (and check the "
                            "spelling: this gate exists because of "
                            "'zweo_dead')")
            elif declared != kind:
                yield Finding(
                    pass_id=PASS.id, code="M002", path=mod.rel, line=lineno,
                    message=f"metric {name!r} registered as {kind}, "
                            f"declared as {declared}")
    names_mod = ctx.module("srnn_tpu/telemetry/names.py")
    names_rel = names_mod.rel if names_mod else "srnn_tpu/telemetry/names.py"
    for name, kind in CANONICAL_METRICS.items():
        if kind not in _KINDS:
            yield Finding(pass_id=PASS.id, code="M003", path=names_rel,
                          line=1, message=f"{name}: unknown kind {kind!r}")
            continue
        for problem in check_name(name, kind):
            yield Finding(pass_id=PASS.id, code="M003", path=names_rel,
                          line=1, message=problem)
    # M006 runs even when the registration scan is empty (M004): a rule
    # table referencing phantom names is wrong independently of whether
    # any registrations were found
    for name, rel, lineno, where in _referenced_names(ctx):
        if name not in CANONICAL_METRICS:
            yield Finding(
                pass_id=PASS.id, code="M006", path=rel, line=lineno,
                message=f"{where} references metric {name!r} which is "
                        "not in telemetry.names.CANONICAL_METRICS — a "
                        "rule/allowlist over a name nobody can emit "
                        "would silently never fire; declare the metric "
                        "or fix the spelling")
    if not seen:
        yield Finding(
            pass_id=PASS.id, code="M004",
            path="srnn_tpu/telemetry/names.py", line=1,
            message="AST scan found no metric registrations — the "
                    "metric-names pass is broken or the walk roots moved")
        return
    # liveness (M005): every declared name needs at least one emission
    # site in the package — skipped when the registration scan itself is
    # broken (M004), because then NOTHING would look alive
    emitted = _emitted_names(ctx, CANONICAL_METRICS)
    for name in sorted(set(CANONICAL_METRICS) - emitted):
        yield Finding(
            pass_id=PASS.id, code="M005", path=names_rel, line=1,
            message=f"metric {name!r} is declared in CANONICAL_METRICS "
                    "but has no emission site in the package — delete "
                    "the declaration or emit it")


PASS = PassSpec(
    id="metric-names",
    title="every registered metric is declared in telemetry.names with "
          "the right kind and convention",
    run=run)
