"""Pass ``span-names``: every span name the package emits must be
declared in the canonical table (``telemetry.names.CANONICAL_SPANS``) —
the tracing twin of the ``metric-names`` gate.  A typo'd span name is
worse than a typo'd metric: the fleet merge groups trace families by
name prefix (``serve.``/``front.`` pick the serve lane) and the
``--trace-request`` critical path keys on ``serve.ticket`` literally, so
a drifted spelling silently falls out of every view while the emitting
code looks healthy.

Recognized emission positions (the package's three span idioms):

  * ``<stream>.emit("name", ...)`` / ``<stream>.timed("name", ...)`` —
    the SpanStream API.  A literal first argument is checked exactly;
    an f-string first argument (``f"{stage}.chunk"``) contributes its
    trailing constant as SUFFIX evidence for liveness, since the full
    name is runtime data.  Only dotted literals count: ``emit`` is a
    common method name, and span names are dotted by convention.
  * ``span="name"`` keywords — the serve tier's
    ``_event_row(kind="span", span=..., ...)`` rows.
  * ``_span_row(ticket, "name", ...)`` — the pool front helper.

Codes:
  * ``S001`` — an emitted span name is missing from ``CANONICAL_SPANS``.
  * ``S002`` — span LIVENESS: a declared name has no evidence anywhere
    in the package.  Evidence is KNOWN-WEAK by design, mirroring M005:
    any whole string constant equal to the name (covers the
    ``relay_name = "front.replay" if ... else "front.relay"`` variable
    idiom), or an f-string suffix match (``f"{stage}.chunk"`` keeps
    every declared ``*.chunk`` alive) — a name spelled in a non-emitting
    context stays "live", because a false S002 on a real span costs more
    than a missed dead one.
  * ``S003`` — the scan found no span emissions at all (the pass itself
    would be dead — fail loudly).
  * ``S004`` — a declared name violates the naming convention
    (``telemetry.names.check_span_name``: dotted lowercase).
"""

import ast

from ..core import AnalysisContext, Finding, PassSpec, call_name, const_str

_NAMES_REL = "srnn_tpu/telemetry/names.py"
_STREAM_METHODS = ("emit", "timed")


def _fstring_suffix(node):
    """The trailing constant of an f-string (``f"{stage}.chunk"`` ->
    ``".chunk"``), or None — the only part of a runtime-composed span
    name the AST can vouch for."""
    if isinstance(node, ast.JoinedStr) and node.values:
        tail = node.values[-1]
        s = const_str(tail)
        if s and s.startswith("."):
            return s
    return None


def _emissions(tree):
    """(name_or_None, suffix_or_None, lineno) for every recognized span
    emission position in one module."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fname = call_name(node)
        if fname in _STREAM_METHODS and isinstance(node.func,
                                                   ast.Attribute) \
                and node.args:
            lit = const_str(node.args[0])
            if lit is not None and "." in lit:
                yield lit, None, node.lineno
            else:
                suffix = _fstring_suffix(node.args[0])
                if suffix is not None:
                    yield None, suffix, node.lineno
        if fname == "_span_row" and len(node.args) >= 2:
            lit = const_str(node.args[1])
            if lit is not None:
                yield lit, None, node.lineno
        for kw in node.keywords:
            if kw.arg == "span":
                lit = const_str(kw.value)
                if lit is not None:
                    yield lit, None, node.lineno


def run(ctx: AnalysisContext):
    # import the product table rather than re-parsing it — same source-
    # of-truth rule as the metric-names pass
    from ...telemetry.names import CANONICAL_SPANS, check_span_name

    seen = False
    suffixes = set()
    spelled = set()
    for mod in ctx.package_modules():
        if mod.rel == _NAMES_REL:
            continue
        for name, suffix, lineno in _emissions(mod.tree):
            seen = True
            if suffix is not None:
                suffixes.add(suffix)
                continue
            if name not in CANONICAL_SPANS:
                yield Finding(
                    pass_id=PASS.id, code="S001", path=mod.rel,
                    line=lineno,
                    message=f"span {name!r} not in telemetry.names."
                            "CANONICAL_SPANS — declare it (the fleet "
                            "merge and --trace-request key on canonical "
                            "spellings)")
        # liveness evidence: whole string constants anywhere in the
        # module (known-weak, see module docstring)
        for node in ast.walk(mod.tree):
            s = const_str(node)
            if s is not None and s in CANONICAL_SPANS:
                spelled.add(s)
    names_mod = ctx.module(_NAMES_REL)
    names_rel = names_mod.rel if names_mod else _NAMES_REL
    for name in CANONICAL_SPANS:
        for problem in check_span_name(name):
            yield Finding(pass_id=PASS.id, code="S004", path=names_rel,
                          line=1, message=problem)
    if not seen:
        yield Finding(
            pass_id=PASS.id, code="S003", path=names_rel, line=1,
            message="AST scan found no span emissions — the span-names "
                    "pass is broken or the emission idioms moved")
        return
    for name in sorted(CANONICAL_SPANS):
        if name in spelled:
            continue
        if any(name.endswith(sfx) for sfx in suffixes):
            continue
        yield Finding(
            pass_id=PASS.id, code="S002", path=names_rel, line=1,
            message=f"span {name!r} is declared in CANONICAL_SPANS but "
                    "has no emission evidence in the package — delete "
                    "the declaration or emit it")


PASS = PassSpec(
    id="span-names",
    title="every emitted span name is declared in telemetry.names."
          "CANONICAL_SPANS (and every declared span is emitted)",
    run=run)
