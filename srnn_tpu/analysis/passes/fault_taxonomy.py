"""Pass ``fault-taxonomy``: the resilience layer's classifier, raise
sites, status vocabulary, and exit codes stay mutually consistent.

PR 7's supervisor turns faults into recoveries only when three
independently-edited artifacts agree: the exception types the package
raises, the ``classify_fault`` kind table that maps them, and the
0/3/69/75 exit-code vocabulary that ``setups/__main__.py`` emits and the
shell watch tier (``scripts/tpu_watch.sh`` / ``tpu_window.sh``) branches
on.  Each has already drifted once (the ``tpu_window.sh`` accelerator
gate used exit 3 until it collided with ``EXIT_RECOVERED``).  This pass
checks, statically:

  * every ``raise`` site of a taxonomy exception (``StallError``,
    ``WriterError``, ``Preempted``) anywhere in the package has a
    matching ``isinstance`` arm in ``classify_fault`` (``T001``);
  * every XLA status string named in ``resilience/supervisor.py``'s
    regexes is a REAL XLA/absl status (``T002`` — a typo'd status
    silently reclassifies a deterministic failure as retryable), and
    every status-bearing regex is actually consulted (``T003``);
  * the supervisor's exit-code constants are each named in
    ``setups/__main__.py`` (``T004``) and handled by a ``case`` arm in
    each watch script (``T005``, textual), and no script claims a
    supervisor code for its own ``exit`` (``T006`` — the PR 7 collision,
    machine-checked);
  * the experiment service's dispatch-thread retry menu
    (``serve/service.py``'s ``DISPATCH_RETRYABLE``) names only kinds the
    supervisor's ``RETRYABLE`` tuple declares retryable (``T008`` — a
    drifted member would retry a fault the taxonomy calls fatal, or
    vice versa), and the serve chaos fault menu
    (``resilience/chaos.py``'s ``SERVE_FAULT_KINDS``) names only
    retryable kind VALUES (``T009`` — the injector must drill the retry
    ladder, not silently exercise the fatal path);
  * the serve pool's worker-death menu (``serve/pool.py``'s
    ``WORKER_DEATH_EXC``) names only CONNECTION-class exceptions
    (``T010`` — the front treats these as "worker gone, replay its
    journal suffix"; a computational or protocol exception in the tuple
    would silently convert a reproducible bug into a replay storm).

Codes: ``T001``–``T010`` above; ``T007`` when the supervisor module or
``classify_fault`` itself cannot be located (stale registry).
"""

import ast
import re
from typing import Dict, List, Optional, Set

from ..core import AnalysisContext, Finding, PassSpec, dotted_name

SUPERVISOR_REL = "srnn_tpu/resilience/supervisor.py"
MAIN_REL = "srnn_tpu/setups/__main__.py"
SERVICE_REL = "srnn_tpu/serve/service.py"
CHAOS_REL = "srnn_tpu/resilience/chaos.py"
POOL_REL = "srnn_tpu/serve/pool.py"
WATCH_SCRIPTS = ("scripts/tpu_watch.sh", "scripts/tpu_window.sh")

#: the taxonomy exception types whose raise sites must classify
#: (HostLost/CoordinatorTimeout are the distributed tier's host-loss
#: faults — chaos and bootstrap raise them, classify_fault must map them)
TAXONOMY_EXCEPTIONS = ("StallError", "WriterError", "Preempted",
                       "HostLost", "CoordinatorTimeout")

#: the exception classes that legitimately mean "the worker process is
#: gone / unreachable" from the front's side of a Unix socket — the only
#: names serve/pool.py's WORKER_DEATH_EXC may carry (TimeoutError is the
#: deliberate stall-is-loss policy: a wedged worker is treated as dead)
CONNECTION_EXCEPTIONS = frozenset({
    "ConnectionRefusedError", "ConnectionResetError", "BrokenPipeError",
    "FileNotFoundError", "TimeoutError", "ConnectionAbortedError",
    "ConnectionError", "EOFError",
})

#: the canonical XLA/absl status vocabulary (status.proto)
XLA_STATUSES = frozenset({
    "OK", "CANCELLED", "UNKNOWN", "INVALID_ARGUMENT", "DEADLINE_EXCEEDED",
    "NOT_FOUND", "ALREADY_EXISTS", "PERMISSION_DENIED",
    "RESOURCE_EXHAUSTED", "FAILED_PRECONDITION", "ABORTED", "OUT_OF_RANGE",
    "UNIMPLEMENTED", "INTERNAL", "UNAVAILABLE", "DATA_LOSS",
    "UNAUTHENTICATED",
})

_STATUS_TOKEN_RE = re.compile(r"[A-Z][A-Z_]{2,}")
_CASE_ARM_RE = re.compile(r"^\s*([0-9|* ]+)\)", re.MULTILINE)
_EXIT_LITERAL_RE = re.compile(r"\bexit\s+(\d+)\b")


def _raise_name(node: ast.Raise) -> Optional[str]:
    exc = node.exc
    if isinstance(exc, ast.Call):
        exc = exc.func
    name = dotted_name(exc) if exc is not None else None
    return name.rsplit(".", 1)[-1] if name else None


def _classifier_types(fn: ast.FunctionDef) -> Set[str]:
    """Type names appearing as the second isinstance() argument anywhere
    in classify_fault (tuples flattened, attribute tails taken)."""
    types: Set[str] = set()
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "isinstance"
                and len(node.args) == 2):
            continue
        second = node.args[1]
        elts = second.elts if isinstance(second, ast.Tuple) else [second]
        for e in elts:
            name = dotted_name(e)
            if name:
                types.add(name.rsplit(".", 1)[-1])
    return types


def _exit_constants(tree: ast.AST) -> Dict[str, int]:
    consts: Dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id.startswith("EXIT_") \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, int):
            consts[node.targets[0].id] = node.value.value
    return consts


def _regex_literals(tree: ast.AST) -> Dict[str, "tuple[int, str]"]:
    """module-level ``NAME_RE = re.compile("...")`` -> (line, pattern)."""
    out: Dict[str, "tuple[int, str]"] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id.endswith("_RE") \
                and isinstance(node.value, ast.Call) \
                and dotted_name(node.value.func) == "re.compile" \
                and node.value.args \
                and isinstance(node.value.args[0], ast.Constant) \
                and isinstance(node.value.args[0].value, str):
            out[node.targets[0].id] = (node.lineno, node.value.args[0].value)
    return out


def _module_tuple(tree: ast.AST, target: str,
                  extract) -> Optional["tuple[int, list]"]:
    """Module-level ``TARGET = (...)`` -> (line, [extract(elt) != None])."""
    for node in tree.body if hasattr(tree, "body") else []:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == target \
                and isinstance(node.value, ast.Tuple):
            vals = [v for v in map(extract, node.value.elts)
                    if v is not None]
            return node.lineno, vals
    return None


def _name_tuple(tree: ast.AST, target: str) -> Optional["tuple[int, list]"]:
    """Module-level ``TARGET = (A, B, ...)`` of Names -> (line, [names])."""
    return _module_tuple(
        tree, target,
        lambda e: e.id if isinstance(e, ast.Name) else None)


def _string_tuple(tree: ast.AST, target: str) -> Optional["tuple[int, list]"]:
    """Module-level ``TARGET = ("a", "b", ...)`` -> (line, [strings])."""
    return _module_tuple(
        tree, target,
        lambda e: e.value if isinstance(e, ast.Constant)
        and isinstance(e.value, str) else None)


def _kind_constants(tree: ast.AST) -> Dict[str, str]:
    """Module-level ``NAME = "snake_value"`` fault-kind constants."""
    consts: Dict[str, str] = {}
    for node in tree.body if hasattr(tree, "body") else []:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id.isupper() \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            consts[node.targets[0].id] = node.value.value
    return consts


def run(ctx: AnalysisContext):
    sup = ctx.module(SUPERVISOR_REL)
    if sup is None:
        yield Finding(pass_id=PASS.id, code="T007", path=SUPERVISOR_REL,
                      line=1,
                      message="resilience/supervisor.py not found — the "
                              "fault-taxonomy pass registry is stale")
        return
    classify = None
    for node in ast.walk(sup.tree):
        if isinstance(node, ast.FunctionDef) and node.name == "classify_fault":
            classify = node
            break
    if classify is None:
        yield Finding(pass_id=PASS.id, code="T007", path=sup.rel, line=1,
                      message="classify_fault() not found in supervisor.py "
                              "— update the fault-taxonomy pass")
        return
    handled = _classifier_types(classify)

    # T001: every taxonomy raise site classifies
    for mod in ctx.package_modules():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Raise):
                continue
            name = _raise_name(node)
            if name in TAXONOMY_EXCEPTIONS and name not in handled:
                yield Finding(
                    pass_id=PASS.id, code="T001", path=mod.rel,
                    line=node.lineno,
                    message=f"raise {name} has no isinstance arm in "
                            "classify_fault — the supervisor would "
                            "classify it FATAL by fallthrough; add it to "
                            "the kind table deliberately")

    # T002/T003: status regexes
    regexes = _regex_literals(sup.tree)
    sup_src_names = {n.id for n in ast.walk(sup.tree)
                     if isinstance(n, ast.Name)
                     and isinstance(n.ctx, ast.Load)}
    for rname, (lineno, pattern) in sorted(regexes.items()):
        tokens = set(_STATUS_TOKEN_RE.findall(pattern))
        if not tokens:
            continue
        for tok in sorted(tokens):
            if tok not in XLA_STATUSES:
                yield Finding(
                    pass_id=PASS.id, code="T002", path=sup.rel, line=lineno,
                    message=f"{rname} names {tok!r}, which is not an XLA/"
                            "absl status — a typo here silently "
                            "reclassifies the fault")
        if rname not in sup_src_names:
            yield Finding(
                pass_id=PASS.id, code="T003", path=sup.rel, line=lineno,
                message=f"{rname} is compiled but never consulted — the "
                        "statuses it names are unreachable in the "
                        "classifier")

    # exit-code vocabulary
    exits = _exit_constants(sup.tree)
    vocab = dict(sorted(exits.items()))
    main_mod = ctx.module(MAIN_REL)
    if main_mod is not None:
        # a code only counts as "named" when it appears in exit-code
        # CONTEXT (its line, or a neighbor, mentions "exit") — an
        # unrelated standalone digit elsewhere must not satisfy the check
        lines = main_mod.text.splitlines()
        for const, code in vocab.items():
            named = any(
                re.search(rf"\b{code}\b", line)
                and any("exit" in lines[j].lower()
                        for j in range(max(0, i - 1),
                                       min(len(lines), i + 2)))
                for i, line in enumerate(lines))
            if not named:
                yield Finding(
                    pass_id=PASS.id, code="T004", path=main_mod.rel, line=1,
                    message=f"exit code {code} ({const}) is not named in "
                            "exit-code context in setups/__main__.py — "
                            "the CLI contract doc/mapping went stale")
    for script_rel in WATCH_SCRIPTS:
        sh = next((s for s in ctx.shell_files if s.rel == script_rel), None)
        if sh is None:
            continue
        arm_codes: Set[int] = set()
        for m in _CASE_ARM_RE.finditer(sh.text):
            for tok in m.group(1).split("|"):
                tok = tok.strip()
                if tok.isdigit():
                    arm_codes.add(int(tok))
        for const, code in vocab.items():
            if code not in arm_codes:
                line = 1 + sh.text[:sh.text.find("case")].count("\n") \
                    if "case" in sh.text else 1
                yield Finding(
                    pass_id=PASS.id, code="T005", path=sh.rel, line=line,
                    message=f"supervisor exit code {code} ({const}) has no "
                            "case arm — the watch tier would read it as a "
                            "wedge")
        # strip comments before hunting exit literals — a comment that
        # NAMES a supervisor code (e.g. "ended in exit 75") is fine
        code_only = "\n".join(line.split("#", 1)[0]
                              for line in sh.text.splitlines())
        for m in _EXIT_LITERAL_RE.finditer(code_only):
            code = int(m.group(1))
            if code in vocab.values():
                const = next(k for k, v in vocab.items() if v == code)
                # offset is into code_only; its per-line strip preserved
                # line structure, so count newlines in the SAME text
                line = 1 + code_only[:m.start()].count("\n")
                yield Finding(
                    pass_id=PASS.id, code="T006", path=sh.rel, line=line,
                    message=f"script claims 'exit {code}' for itself, but "
                            f"{code} means {const} in the supervisor "
                            "vocabulary — pick an unclaimed code (the "
                            "PR 7 accelerator-gate collision)")

    # T008/T009: the serve tier's fault menus stay inside the
    # supervisor's RETRYABLE taxonomy (the service retries and the chaos
    # injector drills exactly — only — what the taxonomy calls transient)
    retryable = _name_tuple(sup.tree, "RETRYABLE")
    kind_consts = _kind_constants(sup.tree)
    svc = ctx.module(SERVICE_REL)
    if svc is not None and retryable is not None:
        tup = _name_tuple(svc.tree, "DISPATCH_RETRYABLE")
        if tup is None:
            yield Finding(
                pass_id=PASS.id, code="T008", path=svc.rel, line=1,
                message="serve/service.py has no module-level "
                        "DISPATCH_RETRYABLE tuple — the supervised-"
                        "dispatch retry menu went unscannable; update "
                        "the fault-taxonomy pass alongside the refactor")
        else:
            line, names = tup
            for name in names:
                if name not in retryable[1]:
                    yield Finding(
                        pass_id=PASS.id, code="T008", path=svc.rel,
                        line=line,
                        message=f"DISPATCH_RETRYABLE names {name}, which "
                                "is not in the supervisor's RETRYABLE "
                                "tuple — the service would retry a fault "
                                "the taxonomy classifies fatal")
    chaos_mod = ctx.module(CHAOS_REL)
    if chaos_mod is not None and retryable is not None:
        menu = _string_tuple(chaos_mod.tree, "SERVE_FAULT_KINDS")
        retry_values = {kind_consts[n] for n in retryable[1]
                        if n in kind_consts}
        if menu is None:
            # a silent skip here is the exact rot this pass exists to
            # catch: the menu went unscannable, report it like T008 does
            yield Finding(
                pass_id=PASS.id, code="T009", path=chaos_mod.rel, line=1,
                message="resilience/chaos.py has no module-level "
                        "SERVE_FAULT_KINDS string tuple — the serve "
                        "chaos fault menu went unscannable; update the "
                        "fault-taxonomy pass alongside the refactor")
        elif not retry_values:
            yield Finding(
                pass_id=PASS.id, code="T009", path=sup.rel,
                line=retryable[0],
                message="no RETRYABLE member resolves to a module-level "
                        "string kind constant — the serve chaos menu "
                        "cannot be checked; update the fault-taxonomy "
                        "pass alongside the refactor")
        else:
            for val in menu[1]:
                if val not in retry_values:
                    yield Finding(
                        pass_id=PASS.id, code="T009", path=chaos_mod.rel,
                        line=menu[0],
                        message=f"SERVE_FAULT_KINDS names {val!r}, which "
                                "is not a retryable fault-kind value in "
                                "the supervisor — serve_dispatch_fault "
                                "would drill the fatal path, not the "
                                "retry ladder")

    # T010: the pool front's worker-death menu is connection-class only
    # (anything else in the tuple turns a reproducible fault into an
    # unbounded replay ladder across surviving workers)
    pool_mod = ctx.module(POOL_REL)
    if pool_mod is not None:
        tup = _name_tuple(pool_mod.tree, "WORKER_DEATH_EXC")
        if tup is None:
            yield Finding(
                pass_id=PASS.id, code="T010", path=pool_mod.rel, line=1,
                message="serve/pool.py has no module-level "
                        "WORKER_DEATH_EXC tuple — the worker-death menu "
                        "went unscannable; update the fault-taxonomy "
                        "pass alongside the refactor")
        else:
            line, names = tup
            for name in names:
                if name not in CONNECTION_EXCEPTIONS:
                    yield Finding(
                        pass_id=PASS.id, code="T010", path=pool_mod.rel,
                        line=line,
                        message=f"WORKER_DEATH_EXC names {name}, which is "
                                "not a connection-class exception — the "
                                "front would reclassify a reproducible "
                                "fault as a worker death and replay it "
                                "fleet-wide")


PASS = PassSpec(
    id="fault-taxonomy",
    title="raise sites classify, XLA statuses are real, and the "
          "0/3/69/75 exit vocabulary agrees across python and shell",
    run=run)
