"""``python -m srnn_tpu.analysis`` — the srnnlint CLI.

Runs the registered passes over the repo and reports findings as
``file:line: severity [pass/code] message`` text (or ``--json``).
Exit codes: 0 — clean (or every finding explicitly waived with a
reason); 1 — unwaived error findings (and ONLY that); 2 — usage error;
3 — the analyzer itself crashed.  The distinction between 1 and 3 is
load-bearing for ``bench.py``'s preflight, which fails the bench on 1
but records 3 as inconclusive (an analyzer bug must never block a
measurement run); ``scripts/run_tests.sh`` is deliberately STRICT and
fails its srnnlint group on any nonzero exit — the test suite is where
a crashed analyzer should be noticed.

``--fast`` selects the preflight tier (every pass marked fast — today
that is all of them; the flag exists so a future expensive pass cannot
slow the run_tests.sh / bench.py preflights down).  ``--update-baseline``
appends waiver stubs for the current unwaived findings to the waiver
file; each stub still needs a human-written reason before it suppresses
anything (a reasonless waiver is itself a finding).
"""

import argparse
import json
import os
import sys
import time

from .core import (AnalysisContext, default_waiver_file, run_analysis)
from .passes import ALL_PASSES, PASSES_BY_ID, select


def _repo_root() -> str:
    # srnn_tpu/analysis/__main__.py -> repo root two levels above the pkg
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m srnn_tpu.analysis",
        description="srnnlint: project static analysis "
                    "(donation safety, flag parity, jit purity, fault "
                    "taxonomy, prints/threads/metric-name hygiene)")
    parser.add_argument("passes", nargs="*",
                        help="pass ids to run (default: all); see --list")
    parser.add_argument("--list", action="store_true",
                        help="list registered passes and exit")
    parser.add_argument("--fast", action="store_true",
                        help="run only the fast preflight tier")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable output")
    parser.add_argument("--root", default=None,
                        help="repo root (default: autodetect from the "
                             "installed package)")
    parser.add_argument("--waivers", default=None,
                        help="waiver file (default: "
                             "srnn_tpu/analysis/waivers.txt)")
    parser.add_argument("--update-baseline", action="store_true",
                        help="append waiver stubs for current unwaived "
                             "findings (reasons still required by hand)")
    args = parser.parse_args(argv)

    if args.list:
        for p in ALL_PASSES:
            tier = "fast" if p.fast else "slow"
            print(f"{p.id:18s} [{tier}] {p.title}")
        return 0
    unknown = [p for p in args.passes if p not in PASSES_BY_ID]
    if unknown:
        print(f"unknown pass id(s): {', '.join(unknown)} — see --list",
              file=sys.stderr)
        return 2

    t0 = time.monotonic()
    root = os.path.abspath(args.root) if args.root else _repo_root()
    try:
        ctx = AnalysisContext.from_root(root)
        passes = select(args.passes or None, fast_only=args.fast)
        waiver_file = args.waivers or default_waiver_file(root)
        result = run_analysis(ctx, passes, waiver_file=waiver_file)
    except Exception:  # analyzer bug: exit 3, never the findings code 1
        import traceback

        traceback.print_exc()
        print("srnnlint: internal error (exit 3) — this is an analyzer "
              "bug, not a finding", file=sys.stderr)
        return 3
    elapsed = time.monotonic() - t0

    if args.update_baseline:
        stubs = [f for f in result.findings if f.pass_id != "waivers"]
        if stubs:
            with open(waiver_file, "a", encoding="utf-8") as f:
                f.write("# --- baseline stubs (write a real reason or "
                        "fix the finding) ---\n")
                for fd in stubs:
                    f.write(f"# {fd.pass_id} {fd.path} {fd.code} "
                            f"TODO-reason: {fd.message[:60]}\n")
            print(f"wrote {len(stubs)} commented waiver stub(s) to "
                  f"{waiver_file}; uncomment with a reason to activate",
                  file=sys.stderr)

    if args.as_json:
        print(json.dumps({
            "findings": [f.as_dict() for f in result.findings],
            "waived": [{**f.as_dict(), "reason": w.reason}
                       for f, w in result.waived],
            "passes": result.pass_ids,
            "files": len(ctx.modules) + len(ctx.shell_files),
            "elapsed_s": round(elapsed, 3),
            "exit_code": result.exit_code,
        }))
        return result.exit_code

    for f in result.findings:
        print(f.render())
    n_err = len(result.errors)
    n_warn = len(result.findings) - n_err
    print(f"srnnlint: {len(ctx.modules)} modules + "
          f"{len(ctx.shell_files)} scripts, {len(result.pass_ids)} "
          f"pass(es) in {elapsed:.1f}s — {n_err} error(s), "
          f"{n_warn} warning(s), {len(result.waived)} waived")
    if result.waived and not result.findings:
        for f, w in result.waived:
            print(f"  waived: {f.location()} [{f.pass_id}/{f.code}] — "
                  f"{w.reason}")
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
