"""srnnlint — the project's JAX-aware static-analysis framework.

One walker, one finding type, one waiver file, seven passes (see
``analysis.passes``).  ``python -m srnn_tpu.analysis`` is the CLI
(text or ``--json``, nonzero exit on unwaived findings); the pytest
gate in ``tests/test_analysis.py`` runs the same passes in-process.

The repo's bit-exactness guarantees — bit-identical carries,
donation-safe snapshots, deterministic resume — are enforced at runtime
by the parity suites; this package is the layer that catches the
*classes* of mistake those suites can only catch one concrete instance
of: use-after-donate, a static flag missing on one of the four evolve
surfaces, host effects inside traced code, a fault type the supervisor
would misclassify, a stale exit code in the watch scripts.
"""

from .core import (AnalysisContext, AnalysisResult, Finding, PassSpec,
                   run_analysis)
from .passes import ALL_PASSES, PASSES_BY_ID, select

__all__ = [
    "AnalysisContext",
    "AnalysisResult",
    "Finding",
    "PassSpec",
    "run_analysis",
    "ALL_PASSES",
    "PASSES_BY_ID",
    "select",
]
