"""Process bring-up for the distributed runtime tier.

One mega run spanning many processes (TPU pod hosts, or a multi-process
CPU mesh in CI) needs exactly three facts before jax touches a device:
where the coordinator lives, how many processes participate, and which
one this is.  The launcher (``distributed.launch``) exports them as
``SRNN_DIST_*`` env vars; managed clusters can instead rely on jax's own
cluster detection; explicit CLI flags (``--dist-coordinator`` etc.) win
over both.  :func:`ensure_initialized` is the ONE entry every mega loop
calls first — it is idempotent, a no-op for single-process runs (tests
and solo runs never pay for it), and hardened for both TPU pods and
multi-process CPU meshes (where it selects the gloo collectives
implementation before the backend initializes).

Failure vocabulary (classified by ``resilience.classify_fault``):

  * :class:`CoordinatorTimeout` — the coordinator could not be reached
    (or bring-up died) within ``SRNN_DIST_TIMEOUT_S``.  A wedged or dead
    coordinator is indistinguishable from a lost host at this layer, so
    both classify ``host_loss``.
  * :class:`HostLost` — a peer process (a slice's host) is gone
    mid-run.  Raised by the chaos injector's ``host_loss@G`` event and by
    any runtime detection a backend offers; in a multi-process run the
    supervisor converts it into :data:`resilience.EXIT_HOST_LOST` so the
    launcher tier can re-ramp (fewer processes, resumed from the last
    durable checkpoint — ``jax.distributed`` topology is fixed for a
    process's lifetime, so in-process recovery is impossible across
    hosts).  Single-process multislice runs recover in-process like a
    device loss, re-ramping via ``parallel.reramp_soup_mesh``.
"""

from __future__ import annotations

import os
import sys
from typing import Optional

COORD_ENV = "SRNN_DIST_COORD"
PROCS_ENV = "SRNN_DIST_PROCS"
PID_ENV = "SRNN_DIST_PID"
TIMEOUT_ENV = "SRNN_DIST_TIMEOUT_S"

#: default bring-up deadline: long enough for a pod's stragglers, short
#: enough that CI notices a dead coordinator inside one test timeout
DEFAULT_TIMEOUT_S = 120.0


class CoordinatorTimeout(Exception):
    """Distributed bring-up failed: the coordinator never answered (or
    rejected us) within the deadline.  Classified ``host_loss``."""


class HostLost(Exception):
    """A peer process (slice host) is gone mid-run.  Classified
    ``host_loss``: multi-process runs exit ``EXIT_HOST_LOST`` for the
    launcher tier to re-ramp; single-process multislice runs re-ramp
    in-process from the surviving slices."""


class DistContext:
    """What one process knows about the distributed run it belongs to."""

    def __init__(self, active: bool, process_id: int = 0,
                 num_processes: int = 1,
                 coordinator: Optional[str] = None):
        self.active = active
        self.process_id = int(process_id)
        self.num_processes = int(num_processes)
        self.coordinator = coordinator

    @property
    def primary(self) -> bool:
        """Process 0 owns ALL host I/O except per-process heartbeats
        (the process-0 I/O contract, DESIGN §16)."""
        return self.process_id == 0

    def __repr__(self):
        return (f"DistContext(active={self.active}, "
                f"process={self.process_id}/{self.num_processes})")


#: the process-wide bring-up result; ``jax.distributed`` can initialize
#: once per process, so this is initialize-once by construction
_CONTEXT: Optional[DistContext] = None

_INACTIVE = DistContext(active=False)


def _resolve(args) -> "tuple[Optional[str], Optional[int], Optional[int]]":
    """(coordinator, num_processes, process_id) from CLI flags first
    (explicit wins), then the launcher's env vars; all-``None`` means
    single-process."""
    coord = getattr(args, "dist_coordinator", None) if args is not None \
        else None
    nproc = getattr(args, "dist_processes", None) if args is not None \
        else None
    pid = getattr(args, "dist_process_id", None) if args is not None \
        else None
    if coord is None and nproc is None and pid is None:
        coord = os.environ.get(COORD_ENV) or None
        if coord:
            nproc = int(os.environ.get(PROCS_ENV, "0") or 0) or None
            pid = int(os.environ.get(PID_ENV, "-1"))
            pid = pid if pid >= 0 else None
    return coord, nproc, pid


def _cpu_backend_selected() -> bool:
    """Will jax resolve to the CPU backend?  Checked WITHOUT touching
    devices (bring-up must precede the first device probe).  The setups'
    config-level pin (``SRNN_SETUPS_PLATFORM``/``force_cpu``) and the
    env-level pin both count."""
    if os.environ.get("SRNN_SETUPS_PLATFORM") == "cpu":
        return True
    import jax

    cfg = getattr(jax.config, "jax_platforms", None) or ""
    env = os.environ.get("JAX_PLATFORMS", "")
    return "cpu" in (cfg or env).split(",")[:1]


def ensure_initialized(args=None) -> DistContext:
    """Idempotent multi-process bring-up; returns the process's
    :class:`DistContext` (``active=False`` for plain single-process
    runs).  Must run before anything probes devices."""
    global _CONTEXT
    if _CONTEXT is not None:
        return _CONTEXT
    coord, nproc, pid = _resolve(args)
    if coord is None and nproc is None and pid is None:
        _CONTEXT = _INACTIVE
        return _CONTEXT
    if coord is None or nproc is None or pid is None:
        # a PARTIAL spec must fail loudly: silently running solo would
        # leave the correctly-configured peers blocking on a coordinator
        # that never forms until their bring-up timeout
        raise SystemExit(
            "distributed bring-up needs all three of coordinator address, "
            "process count and process id (SRNN_DIST_COORD/_PROCS/_PID or "
            "--dist-coordinator/--dist-processes/--dist-process-id); got "
            f"coordinator={coord!r}, processes={nproc!r}, id={pid!r}")
    if int(nproc) <= 1:
        # a 1-process "distributed" job (the launcher's re-ramp floor) is
        # just a solo run — no coordinator needed
        _CONTEXT = _INACTIVE
        return _CONTEXT
    import jax

    if _cpu_backend_selected():
        # multi-process CPU meshes need a cross-process collectives
        # implementation; gloo is the one jaxlib ships.  Harmless if the
        # run later resolves to a non-CPU backend (the option is only
        # consulted by the CPU client).
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # pragma: no cover - older jaxlib without gloo
            print("distributed: this jaxlib has no CPU collectives "
                  "implementation; multi-process CPU meshes will fail at "
                  "the first collective", file=sys.stderr, flush=True)
    timeout = float(os.environ.get(TIMEOUT_ENV, "") or DEFAULT_TIMEOUT_S)
    try:
        jax.distributed.initialize(
            coordinator_address=coord, num_processes=int(nproc),
            process_id=int(pid), initialization_timeout=int(timeout))
    except Exception as e:
        raise CoordinatorTimeout(
            f"distributed bring-up failed for process {pid}/{nproc} "
            f"(coordinator {coord}, timeout {timeout:g}s): "
            f"{type(e).__name__}: {e}") from e
    _CONTEXT = DistContext(active=True, process_id=int(pid),
                           num_processes=int(nproc), coordinator=coord)
    print(f"distributed: process {pid}/{nproc} up "
          f"(coordinator {coord}, {jax.local_device_count()} local / "
          f"{jax.device_count()} global devices)", file=sys.stderr,
          flush=True)
    return _CONTEXT


def context() -> DistContext:
    """The bring-up result so far (inactive when nothing initialized)."""
    return _CONTEXT if _CONTEXT is not None else _INACTIVE


def add_distributed_args(p):
    """The explicit-flag spelling of the launcher env vars, for driving a
    worker by hand (managed clusters usually auto-detect instead)."""
    p.add_argument("--dist-coordinator", default=None, metavar="HOST:PORT",
                   help="jax.distributed coordinator address (usually set "
                        "via SRNN_DIST_COORD by distributed.launch)")
    p.add_argument("--dist-processes", type=int, default=None, metavar="N",
                   help="total process count of the distributed run")
    p.add_argument("--dist-process-id", type=int, default=None, metavar="I",
                   help="this process's id (0 = primary, owns host I/O)")
    return p
