"""Process-0-gated host I/O for distributed runs.

The contract (DESIGN §16): a distributed mega run produces EXACTLY the
artifact set a single-process run produces — one ``log.txt``, one
``events.jsonl``, one ``metrics.prom``, one ``lineage.jsonl``, one
checkpoint stream — written by process 0 alone.  Every other process
contributes through the device-side psum/gather shard boundaries the
sharded evolve paths already have, plus the host-side collective gathers
here; the only per-process files are heartbeats (``events-p<i>.jsonl``,
so the watch tier can tell a wedged worker from a wedged coordinator)
and the capture store's per-process ``.traj`` shards (merged offline,
pre-existing contract).

Collective discipline: :func:`fetch_tree` dispatches cross-process
gathers, so every process MUST call it at the same point of the loop in
the same order — the mega loops call it synchronously on the loop thread
(never from the background writer, whose thread would interleave
collectives differently per process and deadlock the mesh).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np

#: fixed broadcast frame for the run-dir announcement (paths longer than
#: this are refused at broadcast time, not corrupted)
_RUN_DIR_FRAME = 1024

#: fleet-observatory hook: when a run installs its ``SpanStream`` here
#: (``setups.common.make_spans``), every collective in this module times
#: itself and emits a structured span row — per process, so the merged
#: timeline shows WHICH process sat in a gather.  ``None`` (the default,
#: and every non-mega caller's state) is free: one predicate per call.
_SPAN_SINK = None


def set_span_sink(emit) -> None:
    """Install (or clear, with ``None``) the collective span emitter:
    a callable ``emit(name, dur_s, **labels)``."""
    global _SPAN_SINK
    _SPAN_SINK = emit


def _emit_span(name: str, t0: float, **labels) -> None:
    sink = _SPAN_SINK
    if sink is not None:
        try:
            sink(name, time.monotonic() - t0, **labels)
        except Exception:
            pass  # observability must never take down a collective path


def fetch_tree(tree):
    """Materialize a (possibly multi-process-sharded) pytree on host.

    Replicated leaves resolve locally; particle-sharded leaves gather via
    ``multihost_utils.process_allgather`` (a collective — see the module
    docstring for the ordering contract).  Typed PRNG keys (always
    replicated) round-trip through their raw key data so the returned
    tree still checkpoint-saves like a live state.  Single-process trees
    pass through as plain numpy, so callers need no mode split."""
    import jax
    from jax.experimental import multihost_utils

    gathers = [0]

    def one(x):
        if not isinstance(x, jax.Array):
            return x
        if jax.dtypes.issubdtype(x.dtype, jax.dtypes.prng_key):
            data = one(jax.random.key_data(x))
            return jax.random.wrap_key_data(
                np.asarray(data), impl=str(jax.random.key_impl(x)))
        if x.is_fully_addressable or x.sharding.is_fully_replicated:
            return np.asarray(x)
        gathers[0] += 1
        return np.asarray(multihost_utils.process_allgather(x, tiled=True))

    t0 = time.monotonic()
    out = jax.tree.map(one, tree)
    _emit_span("hostio.fetch_tree", t0, collectives=gathers[0])
    return out


def broadcast_run_dir(run_dir) -> str:
    """Announce the primary's run directory to every process (process 0
    passes the path, everyone else ``None``) — the one piece of host
    state workers need that only process 0 can mint (the Experiment dir
    name embeds a timestamp)."""
    from jax.experimental import multihost_utils

    buf = np.zeros(_RUN_DIR_FRAME, np.uint8)
    if run_dir:
        raw = os.path.abspath(run_dir).encode()
        if len(raw) > _RUN_DIR_FRAME:
            raise ValueError(f"run dir path over {_RUN_DIR_FRAME} bytes: "
                             f"{run_dir!r}")
        buf[:len(raw)] = np.frombuffer(raw, np.uint8)
    # the broadcast is a psum under the hood and may promote the dtype
    # (uint8 -> int32 observed); cast back before reading the bytes
    t0 = time.monotonic()
    out = np.asarray(multihost_utils.broadcast_one_to_all(buf)).astype(
        np.uint8)
    _emit_span("hostio.broadcast_run_dir", t0)
    path = bytes(out).rstrip(b"\x00").decode()
    if not path:
        raise RuntimeError("run-dir broadcast produced an empty path "
                           "(primary announced before creating its "
                           "Experiment?)")
    return path


class WorkerLog:
    """Experiment-shaped sink for NON-primary processes.

    ``log()`` prints to stderr with a ``[p<i>]`` prefix (the launcher
    already prefixes each worker's stream, so a worker's narration stays
    attributable without duplicating the run log), and ``event()``
    appends to the per-process ``events-p<i>.jsonl`` — which is where
    this process's heartbeats land.  Everything else an Experiment offers
    (artifact saves, the exit-time ``log.txt``/``meta.json``) is the
    primary's job and no-ops here."""

    def __init__(self, run_dir: str, process_id: int):
        self.dir = run_dir
        self.process_id = int(process_id)
        self.seed = None
        self._t0 = time.time()
        self._lock = threading.Lock()
        self._events = open(
            os.path.join(run_dir, f"events-p{self.process_id}.jsonl"), "a")

    # -- Experiment surface used by the mega loops -----------------------

    def log(self, message, **event_fields):
        print(f"[p{self.process_id}] {message}", file=sys.stderr, flush=True)
        if event_fields:
            self.event(message=str(message), **event_fields)

    def event(self, _fsync: bool = False, **fields):
        fields.setdefault("t", time.time() - self._t0)
        fields.setdefault("process", self.process_id)
        with self._lock:
            self._events.write(json.dumps(fields, default=str) + "\n")
            self._events.flush()
            if _fsync:
                os.fsync(self._events.fileno())

    def save(self, **kwargs):
        return {}

    def save_log(self, log_name: str = "log"):
        pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_value, traceback):
        self._events.close()
        return False
