"""The distributed runtime tier: multi-host / multislice mega runs.

Three layers (DESIGN §16):

  * ``bootstrap`` — per-process ``jax.distributed`` bring-up from the
    launcher's env vars or explicit flags; hardened for TPU pods AND
    multi-process CPU meshes (gloo collectives), idempotent, no-op for
    single-process runs.
  * ``hostio`` — the process-0 I/O contract: collective host gathers
    (``fetch_tree``), the run-dir broadcast, and the ``WorkerLog``
    Experiment shim non-primary processes log through.
  * ``launch`` — the process-restart tier: ``python -m
    srnn_tpu.distributed.launch --processes N -- mega_soup …`` spawns the
    workers, relays their output, re-ramps on host loss (fewer
    processes, resumed from the last durable checkpoint) and propagates
    exit codes cleanly.
"""

from .bootstrap import (CoordinatorTimeout, DistContext, HostLost,
                        add_distributed_args, context, ensure_initialized)

__all__ = [
    "CoordinatorTimeout",
    "DistContext",
    "HostLost",
    "add_distributed_args",
    "context",
    "ensure_initialized",
]
