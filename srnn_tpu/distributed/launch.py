"""Multi-process launcher: the process-restart tier of the recovery ladder.

    python -m srnn_tpu.distributed.launch --processes 2 -- \\
        mega_soup --smoke --sharded --seed 3 --root experiments

Spawns N worker processes running ``python -m srnn_tpu.setups <cmd…>``,
wires them into one ``jax.distributed`` job (free coordinator port on
localhost, ``SRNN_DIST_*`` env vars consumed by
``distributed.bootstrap``), prefixes each worker's output with
``[p<i>]``, and propagates exit codes cleanly:

  * all workers 0 → 0 (or 3/``recovered`` when a re-ramp round was
    needed — the supervisor vocabulary, launcher tier);
  * any worker exits ``EXIT_HOST_LOST`` (71) → the **re-ramp**: remaining
    workers are reaped, the job relaunches with one fewer process on the
    surviving topology, resuming the run dir from its last durable
    checkpoint (``--resume`` injected; any ``--chaos`` schedule is
    stripped — resumes are chaos-free, matching the in-process
    supervisor's contract).  Bounded by ``--max-reramps``.
  * a worker killed by signal S → 128+S (e.g. a SIGKILLed worker → 137);
  * otherwise the first failing worker's code (75 preempted-clean and
    69 retries-exhausted pass through for the watch tier).

The launcher itself never initializes a jax backend (no device probe, no
``jax.distributed`` membership — only the package import runs): a wedged
accelerator tunnel cannot hang the tier whose whole job is reaping
wedged workers.  On a real pod the per-host process manager plays this
role; the CPU spelling here is what makes the whole distributed tier
CI-testable on one machine.
"""

from __future__ import annotations

import argparse
import os
import re
import socket
import subprocess
import sys
import time

#: mirrors ``resilience.supervisor`` — spelled here as literals so this
#: module stays importable without touching the resilience layer (the
#: parent tier must not depend on worker-side machinery); equality is
#: asserted by tests/test_distributed.py
EXIT_HOST_LOST = 71
EXIT_RECOVERED = 3

#: how long peers may keep running after a CLEAN worker exit before
#: being reaped (generous: a slow peer may still be flushing its final
#: checkpoint; a worker wedged after its peers finished must still be
#: bounded).  Failures use the much shorter --grace-s.
CLEAN_EXIT_GRACE_S = float(os.environ.get("SRNN_LAUNCH_EXIT_GRACE_S",
                                          "300"))

_CREATED_RE = re.compile(r"\*\* created (.+?) \*\*")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="spawn a multi-process srnn_tpu run "
                    "(see srnn_tpu/distributed/launch.py)")
    p.add_argument("--processes", type=int, required=True, metavar="N",
                   help="worker process count (each becomes one "
                        "jax.distributed process / one 'slice')")
    p.add_argument("--module", default="srnn_tpu.setups",
                   help="worker module run as python -m MODULE CMD…")
    p.add_argument("--max-reramps", type=int, default=2, metavar="K",
                   help="host-loss re-launch budget: each round drops one "
                        "process and resumes from the last durable "
                        "checkpoint (0 = propagate 71 to the watch tier)")
    p.add_argument("--grace-s", type=float, default=30.0, metavar="S",
                   help="after the first worker failure, how long peers "
                        "may keep running (they are usually wedged in a "
                        "collective whose participant died) before being "
                        "reaped")
    p.add_argument("--coordinator-port", type=int, default=0, metavar="P",
                   help="jax.distributed coordinator port (0 = pick a "
                        "free one)")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="worker command: setup name + flags (a leading "
                        "'--' separator is accepted and dropped)")
    return p


def _free_port() -> int:
    s = socket.socket()
    try:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]
    finally:
        s.close()


def _strip_flag(argv, flag: str, has_value: bool = True):
    """Remove ``flag [VALUE]`` / ``flag=VALUE`` occurrences."""
    out, skip = [], False
    for a in argv:
        if skip:
            skip = False
            continue
        if a == flag:
            skip = has_value
            continue
        if a.startswith(flag + "="):
            continue
        out.append(a)
    return out


def _log(msg: str) -> None:
    print(f"launch: {msg}", file=sys.stderr, flush=True)


def _stream(proc, idx: int, run_dir_box: dict) -> None:
    """Relay one worker's combined output with a [p<i>] prefix; the
    primary's Experiment-creation line additionally yields the run dir
    the re-ramp rounds resume."""
    for line in proc.stdout:
        line = line.rstrip("\n")
        m = _CREATED_RE.search(line)
        if m and idx == 0:
            run_dir_box["dir"] = m.group(1)
        print(f"[p{idx}] {line}", flush=True)


def _reap(procs, killed: set) -> None:
    for i, p in enumerate(procs):
        if p.poll() is None:
            killed.add(i)
            p.terminate()
    deadline = time.monotonic() + 10
    for p in procs:
        if p.poll() is None:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()


def launch_once(module: str, cmd, processes: int, grace_s: float,
                port: int = 0):
    """One launch round.  Returns ``(codes, launcher_killed, run_dir)``:
    per-worker exit codes, the set of workers this launcher reaped itself
    (their codes are consequences, not causes), and the primary's run
    dir if one was created."""
    port = port or _free_port()
    procs, threads = [], []
    run_dir_box: dict = {}
    for i in range(processes):
        env = dict(os.environ)
        env["SRNN_DIST_COORD"] = f"127.0.0.1:{port}"
        env["SRNN_DIST_PROCS"] = str(processes)
        env["SRNN_DIST_PID"] = str(i)
        p = subprocess.Popen(
            [sys.executable, "-u", "-m", module, *cmd],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        procs.append(p)
        # the package thread factory (join-on-exit registry): each relay
        # exits at its worker's pipe EOF, which the reap path guarantees
        from ..utils.pipeline import spawn_thread

        threads.append(spawn_thread(_stream, name=f"launch-relay-p{i}",
                                    args=(p, i, run_dir_box)))
    killed: set = set()
    first_exit_t = None
    any_failed = False
    while True:
        codes = [p.poll() for p in procs]
        if all(c is not None for c in codes):
            break
        exited = [c for c in codes if c is not None]
        if exited and first_exit_t is None:
            first_exit_t = time.monotonic()
            failed = [c for c in exited if c != 0]
            if failed:
                _log(f"worker failure (rc={failed[0]}); giving peers "
                     f"{grace_s:g}s to unwind")
        any_failed = any_failed or any(c != 0 for c in exited)
        # the reap deadline: short after a FAILURE (peers are usually
        # wedged in a collective whose participant died), generous after
        # a clean exit (a slow peer may legitimately still be writing its
        # final checkpoint) — but never unbounded: a worker that wedges
        # after its peers finished must not hang the launcher forever
        if first_exit_t is not None:
            deadline = grace_s if any_failed else max(grace_s,
                                                      CLEAN_EXIT_GRACE_S)
            if time.monotonic() - first_exit_t > deadline:
                _log("grace elapsed; reaping remaining workers")
                _reap(procs, killed)
        time.sleep(0.2)
    for t in threads:
        t.join(timeout=5)
    return [p.returncode for p in procs], killed, run_dir_box.get("dir")


def _propagate(codes, killed) -> int:
    """Map one round's worker exit codes to the launcher's (host-loss
    handled by the caller's re-ramp loop before this runs)."""
    meaningful = [(i, c) for i, c in enumerate(codes) if i not in killed]
    if any(c == EXIT_HOST_LOST for _, c in meaningful):
        return EXIT_HOST_LOST
    for _, c in meaningful:
        if c is not None and c < 0:
            return 128 - c  # killed by signal S -> 128+S
    for _, c in meaningful:
        if c:
            return c
    # only launcher-reaped workers failed (their deaths are consequences
    # of a failure whose owner exited 0?) — that cannot normally happen,
    # but never report success over a reaped worker
    return 0 if not killed else 1


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        print("launch: missing worker command (setup name + flags)",
              file=sys.stderr)
        return 2
    if args.processes < 1:
        print("launch: --processes must be >= 1", file=sys.stderr)
        return 2
    processes = args.processes
    reramps = 0
    # a launch that already resumes a run dir can re-ramp from round one
    # — the primary prints no '** created **' line on attach, so the
    # resume target is the only place the dir is spelled
    run_dir = None
    for i, a in enumerate(cmd):
        if a == "--resume" and i + 1 < len(cmd):
            run_dir = cmd[i + 1]
        elif a.startswith("--resume="):
            run_dir = a.split("=", 1)[1]
    while True:
        codes, killed, created = launch_once(
            args.module, cmd, processes, args.grace_s,
            port=args.coordinator_port if reramps == 0 else 0)
        run_dir = created or run_dir
        host_lost = any(c == EXIT_HOST_LOST for i, c in enumerate(codes)
                        if i not in killed)
        if host_lost and reramps < args.max_reramps and processes > 1 \
                and run_dir:
            # the re-ramp: one slice is gone; relaunch the survivors as a
            # fresh (smaller) jax.distributed job resuming the run dir.
            # Chaos schedules are stripped — resumes are chaos-free, the
            # same contract the in-process supervisor keeps.
            reramps += 1
            processes -= 1
            cmd = _strip_flag(cmd, "--chaos")
            cmd = _strip_flag(cmd, "--resume")
            cmd = cmd + ["--resume", run_dir]
            _log(f"host loss: re-ramp {reramps}/{args.max_reramps} — "
                 f"relaunching {processes} process(es), resuming {run_dir}")
            continue
        rc = _propagate(codes, killed)
        if rc == 0 and reramps:
            _log(f"run completed after {reramps} re-ramp round(s) — "
                 f"exiting {EXIT_RECOVERED} (recovered)")
            return EXIT_RECOVERED
        if rc:
            _log(f"worker exit codes {codes} -> exiting {rc}")
        return rc


if __name__ == "__main__":
    sys.exit(main())
