"""Run archive & cross-run observatory: the longitudinal index.

    python -m srnn_tpu.telemetry.archive ingest <results_root> [--json]
    python -m srnn_tpu.telemetry.archive gc <results_root> --keep N
    python -m srnn_tpu.telemetry.report <results_root> --runs [--json]
    python -m srnn_tpu.telemetry.report --compare <run_a> <run_b>
    python -m srnn_tpu.telemetry.watch <results_root> --archive

Every telemetry surface before this one (spans, cost ledger, alerts,
exemplars, fleet tracing) is scoped to ONE run dir, and ``regress.py``
only reads bench JSONs — but the paper's questions (which basin a soup
lands in, how often it diverges, how replication dynamics shift across
configs) are *cross-run*, and the ROADMAP item 5 controller cannot exist
without a queryable history of what ran, what it cost, and how it ended.
This module is that history: an **incremental, read-only ingester** that
scans a results root (mega run dirs and serve journal roots alike),
folds each run's trail into one per-run summary row, and maintains an
append-only indexed store.

Store layout (``<root>/.archive/`` by default, ``--store`` overrides):

  file            contract
  --------------  ----------------------------------------------------
  archive.jsonl   append-only: one ``{"kind":"run"}`` row per ingest of
                  a run whose watermark moved, plus ``{"kind":"alert"}``
                  rows for archive-drift latch transitions.  Appends are
                  flushed + fsync'd; readers skip unparseable lines
                  (the repo-wide jsonl contract).
  index.json      the compacted view: latest row per run + per-run-dir
                  watermarks + the drift latch.  Published atomically
                  (``utils.atomicio``: tmp + fsync + rename), so a
                  reader never sees a torn index.
  archive.prom    ``soup_archive_*`` gauges (textfile exposition) so the
                  node-exporter path that already scrapes run dirs can
                  scrape the observatory too.

Ingest discipline — the three properties everything else leans on:

  * **Read-only over run dirs.**  Nothing under a run dir is ever
    opened for writing, created, touched, or stat-mutated; the store
    lives outside them.  Ingesting a LIVE run perturbs nothing (asserted
    byte-for-byte in ``tests/test_archive.py``).
  * **Watermarked: re-ingest is O(new bytes).**  Each run dir's
    watermark is the ``(size, mtime_ns)`` vector of its folded files;
    an unchanged run costs a handful of ``stat`` calls and zero reads.
    The one exception is a run previously classified ``running`` — it is
    re-folded even on an unchanged watermark, because its outcome can
    decay to ``wedged`` by clock alone.
  * **Bounded tail reads.**  Event lanes, metric history and lineage are
    read through ``fleet.load_rows``-style bounded tails (the PR 12
    discipline), so one week-long run dir cannot wedge the ingester.

Outcome-classification ladder (first match wins; ``meta.json`` is the
exit evidence — ``Experiment.__exit__`` writes it with ``error=None`` on
a clean unwind, the fault's ``repr`` otherwise, and a SIGKILL leaves
none at all):

  evidence                                        outcome            exit
  ----------------------------------------------  -----------------  ----
  no meta.json, trail younger than ``stale_s``    running            —
  no meta.json, trail stale                       wedged             137†
  error=None, ``{"kind":"preempt"}`` row seen     preempted          75
  error=None, ``{"kind":"restart"}`` row seen     recovered          3
  error=None                                      clean              0
  error ~ Preempted                               preempted          75
  error ~ HostLost/CoordinatorTimeout             host-lost          71
  error != None after restarts                    retries-exhausted  69
  error != None, no restarts                      failed             1

  † a SIGKILLed (or truly wedged) run is indistinguishable post-mortem
    from any other meta-less death, so both land in ``wedged``; the
    supervisor's exit-code vocabulary (resilience/supervisor.py) is the
    source of the code column.

Drift: the newest finished run of each campaign (= config fingerprint)
is judged against the MEDIAN of its campaign history, per the tolerance
table ``ARCHIVE_DRIFT_LEGS`` — the same discipline as ``regress.py``'s
``LEGS``, including the minimum-history guard.  Breaches latch an
``archive_drift`` alert (state persisted in index.json, transitions
appended to archive.jsonl exactly once per edge — the ``AlertEngine``
semantics, persisted because ingest is a CLI, not a process).

Deliberately NOT archived: checkpoints, population arrays, triage
bundles, exemplar payloads, full span streams — anything O(run length).
The archive is the *card catalog*; the run dirs stay the library.

Pure stdlib + intra-telemetry imports (no jax, no numpy): ingest of a
dead fleet must work on a host with no backend at all.  The bench-round
sidecar (``BENCH_archive.jsonl``) is the one piece NOT implemented here
— bench.py's parent and regress.py are forbidden from importing
srnn_tpu (their un-wedgeable contract), so both carry the trivial row
format inline; :data:`BENCH_ARCHIVE_NAME` is the shared spelling.
"""

import argparse
import hashlib
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

STORE_DIRNAME = ".archive"
ARCHIVE_NAME = "archive.jsonl"
INDEX_NAME = "index.json"
PROM_NAME = "archive.prom"
INDEX_VERSION = 1

#: a meta-less run whose newest folded file is older than this is
#: ``wedged``, younger is ``running`` (heartbeats flush every few
#: seconds; 300s is > any legitimate gap between chunk finishers)
DEFAULT_STALE_S = 300.0

#: per-file tail bound for event lanes / metric history (the report
#: summary's bound: ≈ thousands of rows — plenty for rates, restarts and
#: alert trails; a week-long run's full trail is jq's job)
TAIL_BYTES = 4 << 20
#: lineage windows are wide rows; the census tail needs only the last few
LINEAGE_TAIL_BYTES = 1 << 20

#: config keys excluded from the campaign fingerprint: identity/location
#: knobs that vary across the arms of ONE campaign (a sweep re-seeds and
#: re-roots every arm; everything else changing means a different
#: experiment)
VOLATILE_CONFIG_KEYS = ("seed", "root", "run_dir", "resume", "socket",
                        "out", "port")

#: outcome -> supervisor exit code (resilience/supervisor.py vocabulary);
#: ``wedged`` carries 137 as the *typical* evidence (SIGKILL), see the
#: module-docstring ladder
EXIT_FOR_OUTCOME = {"clean": 0, "recovered": 3, "retries-exhausted": 69,
                    "host-lost": 71, "preempted": 75, "failed": 1,
                    "wedged": 137}
FINISHED_OUTCOMES = frozenset(EXIT_FOR_OUTCOME)

#: the drift tolerance table — leg -> (summary-row path, direction,
#: tolerance).  Same discipline as ``regress.py``'s LEGS: direction
#: "down" = lower-is-regression on the fresh/median ratio, "up" = higher
#: is; "up_abs" legs judge the absolute delta instead (nan-frac and
#: restart medians are legitimately 0.0, where a ratio is undefined and
#: any nonzero fresh value would scream).  Tolerances mirror the bench
#: table's reasoning: rates drift with host load (generous 50%); a run
#: 3x the campaign's median wall is a hang-class anomaly; >5% NaN above
#: the campaign norm is the flight recorder's own trip class; +2
#: restarts above the norm means the fault rate moved.
ARCHIVE_DRIFT_LEGS = {
    "gens_per_sec_p50": (("gens_per_sec", "p50"), "down", 0.50),
    "wall_seconds": (("wall_seconds",), "up", 3.00),
    "nan_frac_peak": (("nan_frac_peak",), "up_abs", 0.05),
    "restarts": (("restarts",), "up_abs", 2.0),
}
#: a campaign arms drift only past this many FINISHED history runs — a
#: 1-run "median" would whipsaw every verdict (regress.py's MIN_ROUNDS
#: reasoning)
MIN_DRIFT_HISTORY = 2

#: bench-round sidecar (lives NEXT TO the BENCH_*.json trajectory, not
#: in a results root): bench.py appends every round as a
#: ``{"kind": "bench_round", "t": ..., "result": {...}}`` line and
#: regress.py's ``--from-archive`` folds them into its history median.
#: BOTH sides implement the row inline in pure stdlib — importing this
#: module would pull the srnn_tpu package (and jax) into processes whose
#: contract is to stay un-wedgeable — so this constant is the shared
#: spelling, nothing more.
BENCH_ARCHIVE_NAME = "BENCH_archive.jsonl"


# ---------------------------------------------------------------------------
# discovery + watermark
# ---------------------------------------------------------------------------


def discover_run_dirs(root: str, skip: Tuple[str, ...] = ()) -> List[str]:
    """Every run dir under ``root``: a dir holding ``events.jsonl``,
    ``meta.json`` or ``journal.jsonl`` (the serve-pool front).  Run dirs
    are not descended into — a pool's ``workers/w<i>/`` lanes fold into
    their front's row (``fleet.event_paths`` owns that layout), and
    ckpt/triage subtrees are payload, not runs.  Hidden dirs (the store
    itself among them) are pruned."""
    out: List[str] = []
    skip_abs = {os.path.abspath(p) for p in skip}
    for dirpath, dirnames, filenames in os.walk(root):
        if os.path.abspath(dirpath) in skip_abs:
            dirnames[:] = []
            continue
        names = set(filenames)
        if "events.jsonl" in names or "meta.json" in names \
                or "journal.jsonl" in names:
            out.append(dirpath)
            dirnames[:] = []
            continue
        dirnames[:] = sorted(d for d in dirnames if not d.startswith("."))
    return sorted(out)


def _fold_paths(run_dir: str) -> Dict[str, str]:
    """relname -> abspath of every file one run's fold reads (and
    therefore every file in its watermark).  Event lanes come from
    ``fleet.event_paths`` — the ONE place the fleet file layout is
    spelled — plus worker journals for serve pools."""
    from .fleet import event_paths

    out: Dict[str, str] = {}
    for _proc, path in sorted(event_paths(run_dir).items()):
        if os.path.exists(path):
            out[os.path.relpath(path, run_dir)] = path
    for name in ("config.json", "meta.json", "metrics.prom",
                 "metrics_history.jsonl", "lineage.jsonl", "journal.jsonl"):
        path = os.path.join(run_dir, name)
        if os.path.exists(path):
            out[name] = path
    wdir = os.path.join(run_dir, "workers")
    if os.path.isdir(wdir):
        for w in sorted(os.listdir(wdir)):
            jp = os.path.join(wdir, w, "journal.jsonl")
            if os.path.exists(jp):
                out[os.path.relpath(jp, run_dir)] = jp
    return out


def watermark(run_dir: str) -> Dict[str, List[int]]:
    """``{relname: [size, mtime_ns]}`` over the fold set — equality with
    the stored vector means re-ingest owes this run zero reads."""
    wm: Dict[str, List[int]] = {}
    for rel, path in _fold_paths(run_dir).items():
        try:
            st = os.stat(path)
        except OSError:
            continue
        wm[rel] = [int(st.st_size), int(st.st_mtime_ns)]
    return wm


# ---------------------------------------------------------------------------
# per-run fold
# ---------------------------------------------------------------------------


def _load_json(path: str) -> dict:
    try:
        with open(path) as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) else {}
    except (OSError, ValueError):
        return {}


def config_fingerprint(config: dict) -> str:
    """Campaign identity: a stable digest of the config minus volatile
    identity knobs (module constant), so a seed sweep's arms group while
    any substantive knob change starts a new campaign."""
    stable = {str(k): config[k] for k in sorted(config)
              if str(k) not in VOLATILE_CONFIG_KEYS}
    blob = json.dumps(stable, sort_keys=True, default=str)
    return hashlib.sha1(blob.encode("utf-8")).hexdigest()[:12]


def _count_anomaly_captures(run_dir: str) -> int:
    """Published ``anomaly/<rule>-<seq>/`` bundle count (PR 20) — a
    bare dir listing, dot-tmp assembly dirs excluded by construction."""
    root = os.path.join(run_dir, "anomaly")
    try:
        return sum(1 for d in os.listdir(root)
                   if not d.startswith(".")
                   and os.path.isdir(os.path.join(root, d)))
    except OSError:
        return 0


def classify_outcome(meta: Optional[dict], restarts: int, preempts: int,
                     age_s: Optional[float],
                     stale_s: float = DEFAULT_STALE_S) -> str:
    """The module-docstring ladder, as code (first match wins)."""
    if not meta:
        if age_s is not None and age_s < stale_s:
            return "running"
        return "wedged"
    err = meta.get("error")
    if err is None:
        if preempts:
            return "preempted"
        if restarts:
            return "recovered"
        return "clean"
    err = str(err)
    if "Preempted" in err:
        return "preempted"
    if "HostLost" in err or "CoordinatorTimeout" in err:
        return "host-lost"
    if restarts:
        return "retries-exhausted"
    return "failed"


def _nan_frac_peak(event_metric_rows: List[dict], history_rows: List[dict],
                   prom: Dict[str, float]) -> Optional[float]:
    """Peak NaN fraction across every surface that carries it: metric
    flush rows (bare names), history rows (``srnn_``-prefixed) and the
    final textfile.  ``None`` = the run never measured health."""
    peak = None
    for row in event_metric_rows + history_rows:
        metrics = row.get("metrics")
        if not isinstance(metrics, dict):
            continue
        for key, v in metrics.items():
            if "soup_health_nan_frac" in key \
                    and isinstance(v, (int, float)):
                peak = v if peak is None else max(peak, v)
    for key, v in prom.items():
        if "soup_health_nan_frac" in key:
            peak = v if peak is None else max(peak, v)
    return peak


def _census_tail(run_dir: str,
                 tail_bytes: int = LINEAGE_TAIL_BYTES) -> Optional[dict]:
    """Last basin census from ``lineage.jsonl``'s bounded tail: the run's
    ending fixpoint population, the archive's fitness signal for the
    ROADMAP item 5 controller.  Handles both the homogeneous
    (``fixpoints``) and per-type (``fixpoints_by_type``) window shapes."""
    from .fleet import load_rows

    path = os.path.join(run_dir, "lineage.jsonl")
    if not os.path.exists(path):
        return None
    rows, _skipped = load_rows(path, 0, tail_bytes=tail_bytes)
    for row in reversed(rows):
        docs = [(None, row["fixpoints"])] if isinstance(
            row.get("fixpoints"), dict) else \
            list(row.get("fixpoints_by_type", {}).items()) \
            if isinstance(row.get("fixpoints_by_type"), dict) else []
        census = {}
        for tname, doc in docs:
            c = doc.get("census") if isinstance(doc, dict) else None
            if isinstance(c, dict):
                if tname is None:
                    census.update(c)
                else:
                    census[tname] = c
        if census:
            return {"gen": row.get("gen_end"), "census": census}
    return None


def fold_run_dir(run_dir: str, *, tail_bytes: int = TAIL_BYTES,
                 stale_s: float = DEFAULT_STALE_S,
                 now: Optional[float] = None) -> Optional[dict]:
    """One run dir -> one summary row (the ``{"kind":"run"}`` archive row
    minus store bookkeeping).  ``None`` when the dir holds none of the
    run-dir marker files.  Strictly read-only; every stream read is
    tail-bounded and skip-unparseable (torn tails counted in
    ``skipped_lines``, never fatal)."""
    from .fleet import event_paths, load_rows
    from .metrics import quantile_from_times
    from .timeseries import load_history_rows
    from .watch import parse_prometheus

    paths = _fold_paths(run_dir)
    if not paths:
        return None
    now = time.time() if now is None else now

    meta = _load_json(paths["meta.json"]) if "meta.json" in paths else None
    config = _load_json(paths["config.json"]) if "config.json" in paths \
        else {}

    rows: List[dict] = []
    skipped = 0
    for _proc, path in sorted(event_paths(run_dir).items()):
        if not os.path.exists(path):
            continue
        got, bad = load_rows(path, _proc, tail_bytes=tail_bytes)
        rows.extend(got)
        skipped += bad

    beats = [r for r in rows if r.get("kind") == "heartbeat"]
    beats.sort(key=lambda r: float(r.get("t", 0.0)))
    gps = [float(r["gens_per_sec"]) for r in beats
           if isinstance(r.get("gens_per_sec"), (int, float))]
    last_beat = beats[-1] if beats else {}

    restart_rows = [r for r in rows if r.get("kind") == "restart"]
    restarts = max([int(r.get("restarts", 0)) for r in restart_rows]
                   + [len(restart_rows)]) if restart_rows else 0
    preempts = sum(1 for r in rows if r.get("kind") == "preempt")
    watchdogs = sum(1 for r in rows if r.get("kind") == "watchdog")

    # alert trail: fired counts + which rules ended latched-firing
    alerts: Dict[str, int] = {}
    last_state: Dict[str, str] = {}
    for r in rows:
        if r.get("kind") != "alert":
            continue
        rule = str(r.get("rule", "?"))
        if r.get("state") == "firing":
            alerts[rule] = alerts.get(rule, 0) + 1
        last_state[rule] = str(r.get("state"))
    alerts_active = sorted(r for r, s in last_state.items() if s == "firing")

    # cost ledger evidence: every {"kind":"cost"} probe row the run
    # emitted (telemetry.costs); flops are per-entry program costs
    cost_rows = [r for r in rows if r.get("kind") == "cost"]
    flops_total = sum(float(r["flops"]) for r in cost_rows
                      if isinstance(r.get("flops"), (int, float)))

    metric_rows = [r for r in rows if r.get("kind") == "metrics"]
    history_rows = load_history_rows(
        paths["metrics_history.jsonl"],
        tail_bytes=tail_bytes) if "metrics_history.jsonl" in paths else []
    prom: Dict[str, float] = {}
    if "metrics.prom" in paths:
        try:
            with open(paths["metrics.prom"]) as f:
                prom = parse_prometheus(f.read())
        except OSError:
            prom = {}

    journal_rows = 0
    for rel, path in paths.items():
        if os.path.basename(rel) != "journal.jsonl":
            continue
        got, bad = load_rows(path, 0, tail_bytes=tail_bytes)
        journal_rows += len(got)
        skipped += bad

    # trail age drives the running/wedged split for meta-less dirs: the
    # newest mtime across the fold set is the last observable liveness
    ages = []
    for rel, path in paths.items():
        try:
            ages.append(now - os.stat(path).st_mtime)
        except OSError:
            pass
    age_s = min(ages) if ages else None

    outcome = classify_outcome(meta, restarts, preempts, age_s,
                               stale_s=stale_s)
    rate = {}
    if gps:
        rate = {"p50": round(quantile_from_times(gps, 0.5), 4),
                "max": round(max(gps), 4), "last": round(gps[-1], 4)}

    captures = _count_anomaly_captures(run_dir)
    row = {
        "kind": "run",
        "dir": os.path.abspath(run_dir),
        "run_kind": "serve" if "journal.jsonl" in paths else "mega",
        "name": (meta or {}).get("name") or os.path.basename(run_dir),
        "seed": (meta or {}).get("seed", config.get("seed")),
        "outcome": outcome,
        "exit_code": EXIT_FOR_OUTCOME.get(outcome),
        "wall_seconds": (meta or {}).get("wall_seconds"),
        "restarts": restarts,
        "preempts": preempts,
        "watchdog_trips": watchdogs,
        "generation": {k: last_beat.get(k) for k in
                       ("generation", "total_generations")
                       if last_beat.get(k) is not None} or None,
        "gens_per_sec": rate or None,
        "nan_frac_peak": _nan_frac_peak(metric_rows, history_rows, prom),
        "flops_total": flops_total,
        "cost_entries": len(cost_rows),
        "alerts": alerts,
        "alerts_active": alerts_active,
        # anomaly black-box presence (PR 20): published bundle count —
        # a cheap dir listing; the bundles themselves stay in the run
        # dir and render via report --profile
        "anomaly_captures": captures,
        "census_tail": _census_tail(run_dir, tail_bytes=LINEAGE_TAIL_BYTES),
        "journal_rows": journal_rows,
        "config_fingerprint": config_fingerprint(config),
        "config": {k: v for k, v in sorted(config.items())
                   if isinstance(v, (str, int, float, bool))
                   or v is None},
        "event_rows": len(rows),
        "skipped_lines": skipped,
        "age_s": round(age_s, 1) if age_s is not None else None,
    }
    return row


# ---------------------------------------------------------------------------
# store
# ---------------------------------------------------------------------------


def _empty_index() -> dict:
    return {"version": INDEX_VERSION, "runs": {}, "watermarks": {},
            "drift_alert": {"state": None}}


def load_index(store: str) -> dict:
    doc = _load_json(os.path.join(store, INDEX_NAME))
    if doc.get("version") != INDEX_VERSION \
            or not isinstance(doc.get("runs"), dict):
        return _empty_index()
    doc.setdefault("watermarks", {})
    doc.setdefault("drift_alert", {"state": None})
    return doc


def _append_rows(store: str, rows: List[dict]) -> None:
    """Append + flush + fsync — the jsonl contract every other journal in
    the repo keeps (a torn tail costs one row to a skip-unparseable
    reader, never the store)."""
    if not rows:
        return
    path = os.path.join(store, ARCHIVE_NAME)
    with open(path, "a") as f:
        for row in rows:
            f.write(json.dumps(row, default=str) + "\n")
        f.flush()
        os.fsync(f.fileno())


def _write_index(store: str, index: dict) -> None:
    from ..utils.atomicio import atomic_write_text

    atomic_write_text(os.path.join(store, INDEX_NAME),
                      json.dumps(index, indent=1, default=str))


def _write_prom(store: str, index: dict, ingested: int,
                drift: dict) -> None:
    """The ``soup_archive_*`` exposition (canonical names —
    telemetry.names): observatory size, this pass's appends, and the
    drift verdicts as labeled ratio gauges."""
    from .metrics import MetricsRegistry

    reg = MetricsRegistry()
    reg.gauge("soup_archive_runs",
              "runs in the longitudinal index").set(
        len(index.get("runs", {})))
    reg.counter("soup_archive_runs_ingested_total",
                "run rows appended by this ingest pass").inc(ingested)
    ratio_g = reg.gauge("soup_archive_drift_ratio",
                        "newest finished run vs campaign history median, "
                        "per drift leg (down-bad legs; up_abs legs carry "
                        "the absolute delta)")
    for fp, camp in sorted(drift.get("campaigns", {}).items()):
        for leg, verdict in sorted(camp.get("legs", {}).items()):
            val = verdict.get("ratio", verdict.get("delta"))
            if isinstance(val, (int, float)):
                ratio_g.set(val, leg=leg, campaign=fp)
    reg.gauge("soup_archive_drift_legs",
              "drift legs outside tolerance across all campaigns").set(
        len(drift.get("findings", [])))
    reg.write_textfile(os.path.join(store, PROM_NAME))


# ---------------------------------------------------------------------------
# drift: campaign medians + the persisted latch
# ---------------------------------------------------------------------------


def _get(doc: dict, path: Tuple[str, ...]):
    cur = doc
    for key in path:
        if not isinstance(cur, dict):
            return None
        cur = cur.get(key)
    return cur if isinstance(cur, (int, float)) else None


def _median(xs: List[float]) -> float:
    xs = sorted(xs)
    n = len(xs)
    return xs[n // 2] if n % 2 else 0.5 * (xs[n // 2 - 1] + xs[n // 2])


def compute_drift(runs: Dict[str, dict]) -> dict:
    """Per-campaign drift verdicts: the newest FINISHED run of each
    config fingerprint vs the median of its predecessors, per
    ``ARCHIVE_DRIFT_LEGS``.  Returns ``{"campaigns": {fp: {...}},
    "findings": [...]}`` — findings are the breaches (what the latch and
    the ``soup_archive_drift_legs`` gauge count)."""
    by_fp: Dict[str, List[Tuple[str, dict]]] = {}
    for key in sorted(runs):
        row = runs[key]
        if row.get("outcome") not in FINISHED_OUTCOMES:
            continue  # a live run has no final numbers to judge
        by_fp.setdefault(str(row.get("config_fingerprint")), []).append(
            (key, row))
    campaigns: Dict[str, dict] = {}
    findings: List[dict] = []
    for fp, members in sorted(by_fp.items()):
        members.sort(key=lambda kr: (kr[1].get("ingested_at") or 0,
                                     kr[0]))
        newest_key, newest = members[-1]
        legs: Dict[str, dict] = {}
        for leg, (path, direction, tol) in ARCHIVE_DRIFT_LEGS.items():
            series = [(_k, _get(r, path)) for _k, r in members]
            values = [(k, v) for k, v in series if v is not None]
            fresh = _get(newest, path)
            verdict: dict = {"fresh": fresh, "direction": direction,
                             "tolerance": tol,
                             "timeline": [v for _k, v in values]}
            hist = [v for k, v in values if k != newest_key]
            if fresh is None:
                verdict["verdict"] = "no fresh value"
            elif len(hist) < MIN_DRIFT_HISTORY:
                verdict["verdict"] = \
                    f"insufficient history (<{MIN_DRIFT_HISTORY} runs)"
            else:
                med = _median(hist)
                verdict["median"] = round(med, 4)
                if direction == "up_abs":
                    delta = fresh - med
                    verdict["delta"] = round(delta, 4)
                    drifted = delta > tol
                else:
                    if med <= 0:
                        verdict["verdict"] = "zero median"
                        legs[leg] = verdict
                        continue
                    ratio = fresh / med
                    verdict["ratio"] = round(ratio, 4)
                    drifted = (ratio < 1.0 - tol) if direction == "down" \
                        else (ratio > 1.0 + tol)
                verdict["verdict"] = "DRIFT" if drifted else "ok"
                if drifted:
                    findings.append({
                        "campaign": fp, "leg": leg, "run": newest_key,
                        "fresh": fresh, "median": verdict["median"],
                        "direction": direction, "tolerance": tol,
                        "message": f"{newest_key}: {leg} {fresh:.4g} vs "
                                   f"campaign {fp} median "
                                   f"{verdict['median']:.4g} "
                                   f"(tolerance {direction} {tol:g})"})
            legs[leg] = verdict
        campaigns[fp] = {"runs": len(members), "newest": newest_key,
                         "legs": legs}
    return {"campaigns": campaigns, "findings": findings}


def _latch_drift(index: dict, drift: dict,
                 now: float) -> List[dict]:
    """The persisted drift latch: exactly one ``{"kind":"alert"}`` row
    per firing/cleared EDGE (AlertEngine semantics; state survives in
    index.json because each ingest is a fresh process)."""
    state = index.setdefault("drift_alert", {"state": None})
    firing = bool(drift.get("findings"))
    transitions: List[dict] = []
    if firing and state.get("state") != "firing":
        state.update(state="firing", since=now)
        transitions.append({
            "kind": "alert", "rule": "archive_drift", "state": "firing",
            "t": now,
            "findings": [f["message"] for f in drift["findings"]]})
    elif not firing and state.get("state") == "firing":
        state.update(state="cleared", since=now)
        transitions.append({"kind": "alert", "rule": "archive_drift",
                            "state": "cleared", "t": now})
    state["findings"] = len(drift.get("findings", []))
    return transitions


# ---------------------------------------------------------------------------
# ingest / gc
# ---------------------------------------------------------------------------


def _run_key(run_dir: str, root: str) -> str:
    key = os.path.relpath(run_dir, root)
    return os.path.basename(os.path.abspath(run_dir)) if key == "." else key


def ingest(root: str, store: Optional[str] = None, *,
           stale_s: float = DEFAULT_STALE_S, tail_bytes: int = TAIL_BYTES,
           now: Optional[float] = None) -> dict:
    """One incremental ingest pass over ``root``.  Unchanged runs cost
    stat calls only (watermark); a fully-unchanged pass with no drift
    transition writes NOTHING (byte-identical store — the watermark
    no-op the CI smoke asserts)."""
    root = os.path.abspath(root)
    store = os.path.abspath(store) if store \
        else os.path.join(root, STORE_DIRNAME)
    now = time.time() if now is None else now
    index = load_index(store)
    run_dirs = discover_run_dirs(root, skip=(store,))
    appended: List[dict] = []
    unchanged = 0
    for run_dir in run_dirs:
        key = _run_key(run_dir, root)
        wm = watermark(run_dir)
        prev = index["runs"].get(key)
        # an unchanged 'running' row still re-folds: its outcome decays
        # to 'wedged' by clock alone (no byte ever changes)
        if prev is not None and index["watermarks"].get(key) == wm \
                and prev.get("outcome") != "running":
            unchanged += 1
            continue
        row = fold_run_dir(run_dir, tail_bytes=tail_bytes,
                           stale_s=stale_s, now=now)
        if row is None:
            continue
        row["run"] = key
        row["ingested_at"] = now
        if prev is not None and index["watermarks"].get(key) == wm \
                and prev.get("outcome") == row["outcome"]:
            unchanged += 1  # live run, still live, nothing new on disk
            continue
        index["runs"][key] = row
        index["watermarks"][key] = wm
        appended.append(row)
    drift = compute_drift(index["runs"])
    transitions = _latch_drift(index, drift, now)
    appended.extend(transitions)
    wrote = False
    if appended or not os.path.exists(os.path.join(store, INDEX_NAME)):
        os.makedirs(store, exist_ok=True)
        _append_rows(store, appended)
        _write_index(store, index)
        _write_prom(store, index,
                    sum(1 for r in appended if r.get("kind") == "run"),
                    drift)
        wrote = True
    return {"root": root, "store": store,
            "scanned": len(run_dirs),
            "ingested": [r["run"] for r in appended
                         if r.get("kind") == "run"],
            "unchanged": unchanged,
            "runs": len(index["runs"]),
            "drift": drift,
            "alert_transitions": transitions,
            "wrote": wrote,
            "no_data": not index["runs"]}


def gc(root: str, store: Optional[str] = None, *, keep: Optional[int] = None,
       max_age_days: Optional[float] = None,
       now: Optional[float] = None) -> dict:
    """Bounded retention over the STORE ONLY (run dirs are never
    touched — deleting experiments is an operator decision, not a cache
    policy): drop indexed runs beyond ``keep`` newest and/or older than
    ``max_age_days`` since ingest, then compact ``archive.jsonl`` down
    to one row per surviving run plus the alert-transition tail."""
    from ..utils.atomicio import atomic_write_text

    root = os.path.abspath(root)
    store = os.path.abspath(store) if store \
        else os.path.join(root, STORE_DIRNAME)
    now = time.time() if now is None else now
    index = load_index(store)
    ordered = sorted(index["runs"],
                     key=lambda k: (index["runs"][k].get("ingested_at")
                                    or 0, k))
    pruned: List[str] = []
    if max_age_days is not None:
        horizon = now - max_age_days * 86400.0
        pruned += [k for k in ordered
                   if (index["runs"][k].get("ingested_at") or 0) < horizon]
    if keep is not None and keep >= 0:
        survivors = [k for k in ordered if k not in set(pruned)]
        if len(survivors) > keep:
            pruned += survivors[:len(survivors) - keep]
    for key in pruned:
        index["runs"].pop(key, None)
        index["watermarks"].pop(key, None)
    # compact: surviving runs' latest rows + the recent alert trail (the
    # full append history of pruned runs is exactly what gc retires)
    alert_tail: List[dict] = []
    path = os.path.join(store, ARCHIVE_NAME)
    if os.path.exists(path):
        from .fleet import load_rows

        rows, _bad = load_rows(path, 0, tail_bytes=TAIL_BYTES)
        alert_tail = [r for r in rows if r.get("kind") == "alert"][-100:]
        for r in alert_tail:
            r.pop("process", None)
    lines = [json.dumps(index["runs"][k], default=str)
             for k in sorted(index["runs"],
                             key=lambda k: (index["runs"][k].get(
                                 "ingested_at") or 0, k))]
    lines += [json.dumps(r, default=str) for r in alert_tail]
    os.makedirs(store, exist_ok=True)
    atomic_write_text(path, "".join(line + "\n" for line in lines))
    _write_index(store, index)
    return {"store": store, "pruned": sorted(pruned),
            "kept": len(index["runs"])}


# ---------------------------------------------------------------------------
# cross-run views: run table, campaign rollups, compare
# ---------------------------------------------------------------------------


def campaign_rollups(runs: Dict[str, dict]) -> List[dict]:
    """Group the indexed runs by config fingerprint: outcome histogram,
    rate median, summed flops, the seeds swept — the sortable campaign
    table under ``report --runs``."""
    by_fp: Dict[str, List[dict]] = {}
    for key in sorted(runs):
        row = runs[key]
        by_fp.setdefault(str(row.get("config_fingerprint")), []).append(row)
    out = []
    for fp, members in sorted(by_fp.items()):
        outcomes: Dict[str, int] = {}
        rates, seeds = [], []
        flops = 0.0
        for r in members:
            outcomes[str(r.get("outcome"))] = \
                outcomes.get(str(r.get("outcome")), 0) + 1
            v = _get(r, ("gens_per_sec", "p50"))
            if v is not None:
                rates.append(v)
            if r.get("seed") is not None:
                seeds.append(r["seed"])
            flops += float(r.get("flops_total") or 0.0)
        # the knobs shared by EVERY member — what defines the campaign
        shared = None
        for r in members:
            cfg = {k: v for k, v in (r.get("config") or {}).items()
                   if k not in VOLATILE_CONFIG_KEYS}
            shared = cfg if shared is None else \
                {k: v for k, v in shared.items()
                 if k in cfg and cfg[k] == v}
        out.append({"fingerprint": fp, "runs": len(members),
                    "outcomes": outcomes,
                    "gens_per_sec_p50_median":
                        round(_median(rates), 4) if rates else None,
                    "flops_total": flops,
                    "seeds": sorted(set(seeds), key=str),
                    "config": shared or {}})
    return out


def runs_doc(root: str, store: Optional[str] = None, *,
             stale_s: float = DEFAULT_STALE_S,
             tail_bytes: int = TAIL_BYTES,
             now: Optional[float] = None) -> dict:
    """Ingest + build the ``report --runs`` document (the machine
    contract ROADMAP item 5's controller consumes): the sorted run
    table, campaign rollups, drift verdicts and the latch state."""
    res = ingest(root, store, stale_s=stale_s, tail_bytes=tail_bytes,
                 now=now)
    index = load_index(res["store"])
    runs = [index["runs"][k] for k in sorted(index["runs"])]
    return {"root": res["root"], "store": res["store"],
            "no_data": not runs,
            "runs": runs,
            "campaigns": campaign_rollups(index["runs"]),
            "drift": res["drift"],
            "drift_alert": index.get("drift_alert", {}),
            "ingest": {"scanned": res["scanned"],
                       "ingested": res["ingested"],
                       "unchanged": res["unchanged"]}}


#: numeric summary fields --compare reports deltas on
_COMPARE_FIELDS = ("wall_seconds", "restarts", "preempts",
                   "watchdog_trips", "flops_total", "nan_frac_peak",
                   "anomaly_captures", "event_rows", "journal_rows")


def compare_runs(a_dir: str, b_dir: str, *,
                 tail_bytes: int = TAIL_BYTES,
                 stale_s: float = DEFAULT_STALE_S,
                 now: Optional[float] = None) -> Optional[dict]:
    """``report --compare``'s document: config diff + metric/census
    deltas between two run dirs (folded directly — no store needed).
    ``None`` when either dir is not a run dir (the no-data contract)."""
    a = fold_run_dir(a_dir, tail_bytes=tail_bytes, stale_s=stale_s,
                     now=now)
    b = fold_run_dir(b_dir, tail_bytes=tail_bytes, stale_s=stale_s,
                     now=now)
    if a is None or b is None:
        return None
    ca, cb = a.get("config") or {}, b.get("config") or {}
    config_diff = {
        "only_a": {k: ca[k] for k in sorted(set(ca) - set(cb))},
        "only_b": {k: cb[k] for k in sorted(set(cb) - set(ca))},
        "changed": {k: [ca[k], cb[k]]
                    for k in sorted(set(ca) & set(cb)) if ca[k] != cb[k]},
        "same_campaign":
            a["config_fingerprint"] == b["config_fingerprint"]}
    deltas: Dict[str, dict] = {}
    for field in _COMPARE_FIELDS + ("gens_per_sec.p50", "gens_per_sec.max"):
        path = tuple(field.split("."))
        va, vb = _get(a, path), _get(b, path)
        if va is None and vb is None:
            continue
        d: Dict[str, object] = {"a": va, "b": vb}
        if va is not None and vb is not None:
            d["delta"] = round(vb - va, 6)
            if va:
                d["ratio"] = round(vb / va, 4)
        deltas[field] = d
    census = None
    ta, tb = a.get("census_tail"), b.get("census_tail")
    if ta or tb:
        cta = (ta or {}).get("census") or {}
        ctb = (tb or {}).get("census") or {}
        flat_a = {k: v for k, v in cta.items()
                  if isinstance(v, (int, float))}
        flat_b = {k: v for k, v in ctb.items()
                  if isinstance(v, (int, float))}
        census = {basin: {"a": flat_a.get(basin), "b": flat_b.get(basin),
                          "delta": (flat_b.get(basin, 0)
                                    - flat_a.get(basin, 0))}
                  for basin in sorted(set(flat_a) | set(flat_b))}
    return {"a": {"dir": a["dir"], "name": a["name"], "seed": a["seed"],
                  "outcome": a["outcome"],
                  "fingerprint": a["config_fingerprint"]},
            "b": {"dir": b["dir"], "name": b["name"], "seed": b["seed"],
                  "outcome": b["outcome"],
                  "fingerprint": b["config_fingerprint"]},
            "config_diff": config_diff,
            "deltas": deltas,
            "census": census}


# ---------------------------------------------------------------------------
# renderers (report --runs / --compare and watch --archive share these)
# ---------------------------------------------------------------------------


def render_table(doc: dict, out) -> None:
    from .timeseries import sparkline

    w = out.write
    w(f"archive: {doc['root']} — {len(doc['runs'])} run(s), "
      f"{len(doc['campaigns'])} campaign(s)  "
      f"[+{len(doc['ingest']['ingested'])} ingested, "
      f"{doc['ingest']['unchanged']} unchanged]\n")
    w(f"  {'run':<28} {'outcome':<18} {'rc':>4} {'restarts':>8} "
      f"{'gens/s p50':>11} {'nan peak':>9} {'campaign':<12}\n")
    for r in doc["runs"]:
        rate = _get(r, ("gens_per_sec", "p50"))
        nan = r.get("nan_frac_peak")
        w(f"  {str(r.get('run', r['name']))[:28]:<28} "
          f"{r['outcome']:<18} "
          f"{r['exit_code'] if r['exit_code'] is not None else '-':>4} "
          f"{r['restarts']:>8} "
          f"{rate if rate is not None else '-':>11} "
          f"{f'{nan:.3f}' if nan is not None else '-':>9} "
          f"{r['config_fingerprint']:<12}\n")
        if r.get("alerts_active"):
            w(f"      !! alerts latched firing: "
              f"{', '.join(r['alerts_active'])}\n")
    for c in doc["campaigns"]:
        outcomes = " ".join(f"{k}={v}"
                            for k, v in sorted(c["outcomes"].items()))
        w(f"  campaign {c['fingerprint']}: {c['runs']} run(s)  "
          f"[{outcomes}]  gens/s p50 median="
          f"{c['gens_per_sec_p50_median']}  "
          f"seeds={c['seeds']}\n")
    drift = doc.get("drift") or {}
    for fp, camp in sorted((drift.get("campaigns") or {}).items()):
        for leg, v in sorted(camp["legs"].items()):
            line = v.get("timeline") or []
            verdict = v.get("verdict", "?")
            if verdict in ("ok", "DRIFT"):
                w(f"  drift {fp}/{leg:<18} {verdict:<6} "
                  f"fresh={v.get('fresh')} median={v.get('median')} "
                  f"{sparkline(line, width=24)}\n")
    for f in (drift.get("findings") or []):
        w(f"  !! drift: {f['message']}\n")
    state = (doc.get("drift_alert") or {}).get("state")
    if state == "firing":
        w("  !! archive_drift alert LATCHED FIRING\n")


def render_compare(doc: dict, out) -> None:
    w = out.write
    w(f"compare: {doc['a']['dir']}\n")
    w(f"     vs: {doc['b']['dir']}\n")
    w(f"  a: {doc['a']['name']} seed={doc['a']['seed']} "
      f"outcome={doc['a']['outcome']} "
      f"campaign={doc['a']['fingerprint']}\n")
    w(f"  b: {doc['b']['name']} seed={doc['b']['seed']} "
      f"outcome={doc['b']['outcome']} "
      f"campaign={doc['b']['fingerprint']}\n")
    cd = doc["config_diff"]
    w(f"  config: {'same campaign' if cd['same_campaign'] else 'DIFFERENT campaigns'}\n")
    for k, (va, vb) in sorted(cd["changed"].items()):
        w(f"    {k}: {va} -> {vb}\n")
    for side in ("only_a", "only_b"):
        for k, v in sorted(cd[side].items()):
            w(f"    {k}: {side.replace('_', ' ')} = {v}\n")
    for field, d in sorted(doc["deltas"].items()):
        extra = f"  ({d['ratio']}x)" if "ratio" in d else ""
        w(f"  {field:<20} a={d['a']}  b={d['b']}"
          + (f"  delta={d['delta']}{extra}" if "delta" in d else "")
          + "\n")
    if doc.get("census"):
        w("  census tail deltas:\n")
        for basin, d in doc["census"].items():
            w(f"    {basin:<16} a={d['a']}  b={d['b']}  "
              f"delta={d['delta']:+}\n")


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = p.add_subparsers(dest="cmd", required=True)
    pi = sub.add_parser("ingest", help="incremental ingest of a results "
                                       "root into its archive store")
    pi.add_argument("root")
    pi.add_argument("--store", default=None,
                    help=f"store dir (default <root>/{STORE_DIRNAME})")
    pi.add_argument("--stale-s", type=float, default=DEFAULT_STALE_S,
                    help="running/wedged staleness split for meta-less "
                         "run dirs")
    pi.add_argument("--json", action="store_true")
    pg = sub.add_parser("gc", help="bounded retention over the STORE "
                                   "(never touches run dirs)")
    pg.add_argument("root")
    pg.add_argument("--store", default=None)
    pg.add_argument("--keep", type=int, default=None,
                    help="keep only the newest N indexed runs")
    pg.add_argument("--max-age-days", type=float, default=None,
                    help="drop runs ingested longer ago than this")
    pg.add_argument("--json", action="store_true")
    args = p.parse_args(argv)
    if not os.path.isdir(args.root):
        print(f"archive: {args.root}: not a directory", file=sys.stderr)
        return 2
    if args.cmd == "ingest":
        res = ingest(args.root, args.store, stale_s=args.stale_s)
        if args.json:
            print(json.dumps(res, indent=1, default=str))
        else:
            print(f"archive: {res['store']}: {res['runs']} run(s) indexed "
                  f"(+{len(res['ingested'])} ingested, "
                  f"{res['unchanged']} unchanged)")
            for t in res["alert_transitions"]:
                print(f"  alert {t['rule']} -> {t['state']}")
        if res["no_data"]:
            print(f"archive: {args.root}: no run dirs found — nothing "
                  "ingested", file=sys.stderr)
            return 2
        return 0
    if args.cmd == "gc":
        if args.keep is None and args.max_age_days is None:
            print("archive gc: give --keep and/or --max-age-days",
                  file=sys.stderr)
            return 2
        res = gc(args.root, args.store, keep=args.keep,
                 max_age_days=args.max_age_days)
        if args.json:
            print(json.dumps(res, indent=1, default=str))
        else:
            print(f"archive gc: kept {res['kept']}, pruned "
                  f"{len(res['pruned'])}")
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
