"""Run heartbeats: periodic liveness rows so a killed or timed-out run
leaves an attributable trail.

The bench trajectory motivating this (``BENCH_r05.json``) ends in
``"full: deadline exhausted"`` after four opaque stage timeouts — nothing
recorded which stage, generation range, or compile step consumed the
budget.  A :class:`Heartbeat` row carries exactly that attribution:
stage, generation (of total), generations/sec, host RSS, and device
memory, written through ``Experiment.event`` with ``fsync`` so the tail
survives a SIGKILL.

Helpers are fail-soft: a platform without ``/proc`` or device memory
stats yields rows without those fields, never an exception in the run
loop.
"""

import os
import resource
import sys
import time
from typing import Dict, Optional


def rss_bytes() -> Optional[int]:
    """Current resident set size of this process, or ``None`` when the
    platform offers no way to read it."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * resource.getpagesize()
    except (OSError, IndexError, ValueError):
        pass
    try:
        # portable fallback: PEAK rss — labeled the same, still
        # monotone-useful for leak spotting.  ru_maxrss units differ by
        # platform: KiB on linux, BYTES on macOS (the platform where this
        # fallback is actually the taken path, /proc being absent)
        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return peak if sys.platform == "darwin" else peak * 1024
    except (OSError, ValueError):
        return None


def device_memory_stats() -> Optional[Dict[str, int]]:
    """Allocator stats of the first local device (``bytes_in_use`` /
    ``peak_bytes_in_use`` where the backend reports them — TPU and GPU
    do, CPU returns ``None``).  Never raises."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    out = {}
    for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
        if k in stats:
            out[k] = int(stats[k])
    return out or None


class Heartbeat:
    """Emitter of ``{"kind": "heartbeat", ...}`` rows for one run stage.

    >>> hb = Heartbeat(exp, stage="mega_soup", total_generations=1000)
    >>> hb.beat(generation=100, gens_per_sec=28.5)

    Rows are fsync'd (the whole point is surviving a kill); each row also
    carries ``beat`` (a per-instance sequence number) and the seconds
    since the previous beat, so a trail's cadence is self-describing.

    ``fsync_every=N`` amortizes the sync on slow storage: every row is
    still flushed (OS-cache durable), but only every N-th row pays the
    fsync.  The default (1) keeps the kill-survival guarantee row-by-row.
    ``writer`` (a ``utils.pipeline.BackgroundWriter``) moves the sink
    write — fsync AND gauge updates, as one ordered job — off the
    producing thread; the row is still composed (rss/device stats
    sampled) at beat time.
    """

    def __init__(self, exp, stage: str, total_generations: Optional[int] = None,
                 registry=None, fsync_every: int = 1, writer=None):
        self.exp = exp
        self.stage = stage
        self.total_generations = total_generations
        self.registry = registry
        self.fsync_every = max(1, int(fsync_every))
        self.writer = writer
        self.count = 0
        self._last_t: Optional[float] = None

    def beat(self, generation: Optional[int] = None,
             gens_per_sec: Optional[float] = None, **extra) -> dict:
        now = time.monotonic()
        row = {"stage": self.stage, "beat": self.count}
        if generation is not None:
            row["generation"] = int(generation)
        if self.total_generations is not None:
            row["total_generations"] = int(self.total_generations)
        if gens_per_sec is not None:
            row["gens_per_sec"] = round(float(gens_per_sec), 3)
        if self._last_t is not None:
            row["since_last_s"] = round(now - self._last_t, 3)
        rss = rss_bytes()
        if rss is not None:
            row["rss_mb"] = round(rss / 2 ** 20, 1)
        dev = device_memory_stats()
        if dev is not None:
            row["device_memory"] = dev
        row.update(extra)
        fsync = (self.count % self.fsync_every) == 0

        def sink():
            # ONE job: row write + gauge updates, all values precomputed
            # at beat time.  Riding the writer as a unit keeps registry
            # mutations totally ordered with the queued flush_events
            # snapshots — chunk k's metrics row can never see beat k+1's
            # gauges.
            self.exp.event(_fsync=fsync, kind="heartbeat", **row)
            if self.registry is not None:
                g = self.registry.gauge
                if generation is not None:
                    g("heartbeat_generation",
                      help="last heartbeat's generation").set(
                          int(generation), stage=self.stage)
                if gens_per_sec is not None:
                    g("gens_per_sec", help="generations per second",
                      unit="1/s").set(round(float(gens_per_sec), 3),
                                      stage=self.stage)
                if rss is not None:
                    g("rss_bytes", help="host resident set size",
                      unit="bytes").set(rss, stage=self.stage)

        if self.writer is not None:
            self.writer.submit(sink)
        else:
            sink()
        self.count += 1
        self._last_t = now
        return row
