"""The cost plane: a durable per-executable compile/FLOP/memory ledger.

The fleet observatory (PR 12) answers *where time goes between
processes*; this module answers *where compute goes inside a dispatch*.
Every ``utils.aot.aot_compile``/``warmup`` build (and the FIRST
in-process memo hit per entry — hit totals live in the accumulator and
metrics; a long-lived service hitting the memo once per dispatch must
not grow the ledger without bound) appends one row to an append-only
``compile_ledger.jsonl`` living next to the persistent executable
cache, carrying:

  * build provenance — entry name, backend, ``cached`` (in-process memo
    hit), ``persistent`` (on-disk cache engaged), lower/compile seconds;
  * XLA cost analysis — ``Compiled.cost_analysis()`` HLO flops and
    bytes-accessed (``None`` on backends that do not report them — the
    reader contract is graceful nulls, never a crash);
  * XLA memory analysis — ``memory_analysis()`` temp/argument/output/
    alias bytes (the donation story in numbers; empty for
    cache-deserialized executables, which is itself recorded).

Writer discipline matches the serve journal: single-line JSON appends,
flushed per row; readers (:func:`read_ledger`) skip unparseable lines —
the torn tail of a killed process costs one row, never the ledger.
Ledger I/O failures are collected (:func:`consume_ledger_errors`) and
surfaced by the bench stage log; they never break a compile path.

The same data is exported three ways:

  * process RUNTIME metrics at record time and per-run registries via
    :func:`fold_cost_metrics` — the registered ``soup_compile_seconds_
    total`` / ``soup_aot_cache_{hits,misses}_total`` counters and
    ``soup_hlo_flops{entry=}`` / ``soup_hbm_bytes{entry=,kind=}`` gauges
    (``telemetry/names.py``), folded into each run's ``metrics.prom``;
  * a ``{"kind": "cost", ...}`` events.jsonl row per probed run entry
    (``setups.common.probe_run_costs``) that ``report`` turns into the
    derived apps/s-vs-HLO-flops roofline line;
  * per-tenant attribution in the experiment service
    (``serve_tenant_flops_total`` — ``serve/service.py`` divides a
    dispatch's program flops across its stacked tenants).

Everything here is host-side bookkeeping over compile-time metadata:
the cost plane can never perturb run results (``--no-costs`` on the
mega loops is the A/B oracle for exactly that claim, tested).
"""

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

#: set to "1" to disable the ledger + cost metrics entirely
DISABLE_ENV = "SRNN_NO_COST_LEDGER"
#: explicit ledger path override (default: next to the persistent cache)
LEDGER_PATH_ENV = "SRNN_COST_LEDGER"

LEDGER_NAME = "compile_ledger.jsonl"

_lock = threading.Lock()
_errors: List[str] = []
#: entries whose cached:true row was already appended this process — a
#: long-lived service hits the memo once per dispatch, and appending an
#: identical hit row each time would grow the never-rotated ledger
#: without bound (hit TOTALS live in the accumulator/metrics; the ledger
#: records that hits happen, once per entry)
_hit_logged: set = set()

#: process-level accumulation folded into run registries on demand
_ACC = {
    "hits": 0,
    "misses": 0,
    "lower_seconds": 0.0,
    "compile_seconds": 0.0,
    "entry_flops": {},       # entry -> last non-null HLO flops
    "entry_bytes": {},       # entry -> last non-null bytes-accessed
    "hbm_bytes": {},         # (entry, kind) -> bytes
}


def enabled() -> bool:
    return os.environ.get(DISABLE_ENV, "0") in ("", "0")


def ledger_path() -> Optional[str]:
    """Resolve the ledger location: the ``SRNN_COST_LEDGER`` override
    first, else ``compile_ledger.jsonl`` next to (inside) the persistent
    executable cache dir — the cache and its cost evidence travel
    together.  ``None`` when the cost plane is disabled."""
    if not enabled():
        return None
    override = os.environ.get(LEDGER_PATH_ENV)
    if override:
        return override
    from ..utils import aot

    base = aot._cache_dir_enabled or aot.default_cache_dir()
    return os.path.join(base, LEDGER_NAME)


def reset_for_tests() -> None:
    """Drop the process accumulator + error list (tests only)."""
    with _lock:
        _ACC.update(hits=0, misses=0, lower_seconds=0.0,
                    compile_seconds=0.0, entry_flops={}, entry_bytes={},
                    hbm_bytes={})
        _errors.clear()
        _hit_logged.clear()


def consume_ledger_errors() -> List[str]:
    """Drain the collected ledger-write failures (the bench children lift
    these into their result so the parent's stage_log row names them)."""
    with _lock:
        out, _errors[:] = list(_errors), []
    return out


# ---------------------------------------------------------------------------
# extraction (graceful nulls: backends vary in what they report)
# ---------------------------------------------------------------------------


def _first_number(d: dict, key: str) -> Optional[float]:
    v = d.get(key)
    return float(v) if isinstance(v, (int, float)) else None


def extract_costs(compiled: Any) -> Dict[str, Optional[float]]:
    """Pull the cost/memory analysis out of one ``jax.stages.Compiled``.

    Every field may be ``None``: ``cost_analysis`` raises or omits keys
    on some backends, and a cache-deserialized executable reports an
    empty ``memory_analysis`` (stats are not serialized) — zeros there
    are recorded as-is, they are the deserialization fingerprint."""
    out: Dict[str, Optional[float]] = {
        "flops": None, "bytes_accessed": None,
        "temp_bytes": None, "argument_bytes": None, "output_bytes": None,
        "alias_bytes": None, "generated_code_bytes": None,
    }
    try:
        ca = compiled.cost_analysis()
        # jax 0.4.x returns a list with one dict per computation; newer
        # versions a plain dict — normalize to the first/only mapping
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            out["flops"] = _first_number(ca, "flops")
            out["bytes_accessed"] = _first_number(ca, "bytes accessed")
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        for field, attr in (("temp_bytes", "temp_size_in_bytes"),
                            ("argument_bytes", "argument_size_in_bytes"),
                            ("output_bytes", "output_size_in_bytes"),
                            ("alias_bytes", "alias_size_in_bytes"),
                            ("generated_code_bytes",
                             "generated_code_size_in_bytes")):
            v = getattr(ma, attr, None)
            if isinstance(v, (int, float)):
                out[field] = float(v)
    except Exception:
        pass
    return out


#: the ``soup_hbm_bytes`` gauge's ``kind=`` label values, in ledger-row
#: field order (alias bytes = donation's win; see DESIGN §19)
HBM_KINDS = ("temp", "argument", "output", "alias")


# ---------------------------------------------------------------------------
# recording (called by utils.aot on every compile/memo hit)
# ---------------------------------------------------------------------------


def record_compile(entry: str, *, cached: bool, lower_s: float,
                   compile_s: float, persistent: bool,
                   compiled: Any = None, backend: str = "") -> None:
    """Fold one aot_compile outcome into the ledger + accumulator +
    RUNTIME metrics.  Fail-soft by construction — cost bookkeeping must
    never break a compile path; write failures are collected for the
    bench stage log instead of raised."""
    if not enabled():
        return
    costs = extract_costs(compiled) if (compiled is not None
                                        and not cached) else {}
    row = {"entry": entry, "cached": bool(cached),
           "backend": backend, "persistent": bool(persistent),
           "lower_s": round(float(lower_s), 4),
           "compile_s": round(float(compile_s), 4),
           "wall": round(time.time(), 3)}
    row.update(costs)
    with _lock:
        if cached:
            _ACC["hits"] += 1
            if entry in _hit_logged:
                # the hit is COUNTED (accumulator above; folded at the
                # next miss/first-hit/explicit fold) but not re-appended,
                # and the per-dispatch hot path skips the file I/O + fold
                return
            _hit_logged.add(entry)
        else:
            _ACC["misses"] += 1
            _ACC["lower_seconds"] += float(lower_s)
            _ACC["compile_seconds"] += float(compile_s)
            if costs.get("flops") is not None:
                _ACC["entry_flops"][entry] = costs["flops"]
            if costs.get("bytes_accessed") is not None:
                _ACC["entry_bytes"][entry] = costs["bytes_accessed"]
            for kind in HBM_KINDS:
                v = costs.get(f"{kind}_bytes")
                if v is not None:
                    _ACC["hbm_bytes"][(entry, kind)] = v
    _append_row(row)
    try:
        from .metrics import RUNTIME

        fold_cost_metrics(RUNTIME)
    except Exception:
        pass


def _append_row(row: dict) -> None:
    path = ledger_path()
    if path is None:
        return
    try:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps(row) + "\n")
            f.flush()
    except Exception as e:
        with _lock:
            _errors.append(f"cost ledger append failed: "
                           f"{type(e).__name__}: {e}")


def read_ledger(path: Optional[str] = None) -> Tuple[List[dict], int]:
    """Parse the ledger; returns ``(rows, skipped)`` where ``skipped``
    counts unparseable lines (the torn tail of a killed process) — same
    reader contract as the fleet merge and the serve journal."""
    path = path or ledger_path()
    rows: List[dict] = []
    skipped = 0
    if path is None:
        return rows, skipped
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return rows, skipped
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            skipped += 1
            continue
        if not isinstance(row, dict):
            skipped += 1
            continue
        rows.append(row)
    return rows, skipped


# ---------------------------------------------------------------------------
# metric export (names.py: the registered cost metrics)
# ---------------------------------------------------------------------------


def snapshot() -> dict:
    """Accumulator copy (the fold source + the tests' oracle)."""
    with _lock:
        return {"hits": _ACC["hits"], "misses": _ACC["misses"],
                "lower_seconds": _ACC["lower_seconds"],
                "compile_seconds": _ACC["compile_seconds"],
                "entry_flops": dict(_ACC["entry_flops"]),
                "entry_bytes": dict(_ACC["entry_bytes"]),
                "hbm_bytes": dict(_ACC["hbm_bytes"])}


def entry_flops(entry: str) -> Optional[float]:
    """Last-known HLO flops of one compiled entry (``None`` when the
    backend reported none) — the serve tier's attribution source."""
    with _lock:
        return _ACC["entry_flops"].get(entry)


def fold_cost_metrics(registry) -> None:
    """Fold the process accumulator into ``registry`` (a run's registry
    or RUNTIME): counters advance by delta (safe to call repeatedly),
    gauges are last-value.  Eagerly registers every cost metric so a
    run's ``metrics.prom`` always exposes the series — a backend that
    reports no flops shows the registered zero-state, not a missing
    family."""
    snap = snapshot()
    c = registry.counter(
        "soup_compile_seconds_total",
        help="backend compile seconds spent by aot_compile builds",
        unit="seconds")
    c.inc(max(0.0, snap["compile_seconds"] - c.value()))
    c = registry.counter(
        "soup_aot_cache_hits_total",
        help="aot_compile calls served from the in-process executable "
             "memo")
    c.inc(max(0, snap["hits"] - c.value()))
    c = registry.counter(
        "soup_aot_cache_misses_total",
        help="aot_compile calls that lowered+compiled (a persistent "
             "on-disk cache hit still counts here, just with near-zero "
             "compile seconds)")
    c.inc(max(0, snap["misses"] - c.value()))
    flops_g = registry.gauge(
        "soup_hlo_flops",
        help="XLA cost-analysis HLO flops of the compiled entry")
    for entry, flops in snap["entry_flops"].items():
        flops_g.set(flops, entry=entry)
    hbm_g = registry.gauge(
        "soup_hbm_bytes",
        help="XLA memory-analysis bytes of the compiled entry "
             "(kind=temp/argument/output/alias)", unit="bytes")
    for (entry, kind), b in snap["hbm_bytes"].items():
        hbm_g.set(b, entry=entry, kind=kind)


# ---------------------------------------------------------------------------
# roofline derivation (the report line)
# ---------------------------------------------------------------------------


def roofline(cost_row: dict, gens_per_sec: Optional[float]) -> dict:
    """Derive the apps/s-vs-HLO-flops roofline numbers from one
    ``{"kind": "cost"}`` event row (flops of the chunk program, its
    generation count and particle count) and the run's measured rate.
    Every output may be ``None`` — backends without cost analysis or a
    run killed before its first heartbeat still render, just sparser."""
    flops = cost_row.get("flops")
    gens = cost_row.get("generations") or 0
    particles = cost_row.get("particles") or 0
    out = {
        "entry": cost_row.get("entry"),
        "flops_per_generation": (flops / gens) if flops and gens else None,
        "flops_per_app": (flops / (gens * particles))
        if flops and gens and particles else None,
        "apps_per_sec": (gens_per_sec * particles)
        if gens_per_sec and particles else None,
        "flops_per_sec": None,
    }
    if out["flops_per_generation"] is not None and gens_per_sec:
        out["flops_per_sec"] = out["flops_per_generation"] * gens_per_sec
    return out
