"""Summarize a run directory's telemetry trail — or a triage bundle.

    python -m srnn_tpu.telemetry.report <run_dir> [--json]
    python -m srnn_tpu.telemetry.report --fleet <run_dir> [--json]
    python -m srnn_tpu.telemetry.report --trace <run_dir> [--json]
    python -m srnn_tpu.telemetry.report --trace-request <ticket> <run_dir>
    python -m srnn_tpu.telemetry.report --triage <bundle_dir> [--json]
    python -m srnn_tpu.telemetry.report --dynamics <run_dir> [--json]
    python -m srnn_tpu.telemetry.report --profile <run_dir> [--json]
    python -m srnn_tpu.telemetry.report <results_root> --runs [--json]
    python -m srnn_tpu.telemetry.report --compare <run_a> <run_b> [--json]

Reads ``meta.json`` + ``events.jsonl`` (the ``Experiment`` channel the
mega-run loops, heartbeats and metric flushes all write through) and
renders what a post-mortem needs first: did the run finish, where was it
last alive (stage / generation / gens-per-sec / memory), what do the
final cumulative metrics say, and where did the wall time go (spans).
Works on killed runs — heartbeat rows are fsync'd, and cumulative metric
snapshots mean the last row is the whole story.  Distributed run dirs
additionally fold every worker's ``events-p<i>.jsonl`` heartbeat lane in
(stage labels like ``mega_soup@p1/2``), so a multi-process run no longer
renders as a single-process one.

``--fleet`` renders the full fleet observatory view instead
(``telemetry.fleet``): ONE merged cross-process timeline, a per-process
lane table, and the straggler attribution (who is slowest, skew ratio,
generations of lag).

``--trace`` exports that merged timeline as a Chrome/Perfetto-loadable
``trace.json`` (one lane group per process: host spans, serve-ticket
slices, gens/sec counter tracks, restart/watchdog markers) and links any
triage bundle's armed ``jax.profiler`` device trace from the same
document.

Plain reports on cost-plane runs additionally render a ``cost:`` block —
the chunk program's HLO flops/bytes (``telemetry.costs``) and the derived
apps/s-vs-HLO-flops roofline at the run's measured p50 rate.

``--triage`` renders a flight-recorder bundle (``telemetry.flightrec``):
the trip reason and thresholds, the ring tail, the health trajectory
(NaN/zero fractions + gens/sec over the ring), the population snapshot's
shapes/dtypes, and a pointer to the captured profiler trace.

``--dynamics`` renders a ``--lineage`` run's replication-dynamics trail
(``telemetry.genealogy`` over ``lineage.jsonl``): the dominant-lineage
table, clone-survival stats, attack/imitation graph stats, the basin
transition matrix and the fixpoint census trajectory.

``--profile`` renders the continuous-profiling plane (``telemetry.
profiler``): the sampler's meta row, the top folded stacks per thread,
the last chunk's device-busy / host-blocked / idle decomposition, and
the index of anomaly-capture bundles with what each one holds.

``--runs`` flips the positional to a RESULTS ROOT and renders the
cross-run observatory (``telemetry.archive``): an incremental ingest of
every run dir under the root, then the sortable run table (outcome,
restarts, gens/sec, NaN peak), campaign rollups grouped by config
fingerprint, and the drift timelines vs each campaign's history median.

``--compare RUN_A RUN_B`` (RUN_B is the positional) renders the config
diff and metric/census deltas between two run dirs — folded directly,
no archive store needed.
"""

import argparse
import json
import os
import sys
from typing import Any, Dict, List

from .metrics import quantile_from_times

#: the reference's persisted zero-respawn typo; rows written before the
#: rename may carry it as a dict key — normalized on load so existing run
#: dirs keep rendering (the counter name never carried the typo)
_LEGACY_KEYS = {"zweo_dead": "zero_dead"}

#: metrics_history.jsonl is append-only and unbounded — the summary
#: reads a bounded tail (each row is a full registry dump, so 4MB is
#: hundreds of samples; watch uses a smaller bound for its refresh loop)
_HISTORY_TAIL_BYTES = 4 << 20


def _normalize_legacy(row: Any) -> Any:
    """Recursively rename legacy (misspelled) keys in one event row."""
    if isinstance(row, dict):
        return {_LEGACY_KEYS.get(k, k): _normalize_legacy(v)
                for k, v in row.items()}
    if isinstance(row, list):
        return [_normalize_legacy(v) for v in row]
    return row


def load_events(run_dir: str) -> List[dict]:
    path = os.path.join(run_dir, "events.jsonl")
    if not os.path.exists(path):
        return []
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(_normalize_legacy(json.loads(line)))
            except json.JSONDecodeError:
                pass  # torn tail of a killed run: keep what parses
    return rows


def _load_json(run_dir: str, name: str) -> dict:
    path = os.path.join(run_dir, name)
    if not os.path.exists(path):
        return {}
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}


def summarize(run_dir: str) -> dict:
    """Machine-readable summary (the ``--json`` output; the text renderer
    formats this)."""
    events = load_events(run_dir)
    meta = _load_json(run_dir, "meta.json")
    config = _load_json(run_dir, "config.json")

    by_kind: Dict[str, List[dict]] = {}
    for e in events:
        by_kind.setdefault(str(e.get("kind", "log")), []).append(e)

    # distributed run dirs: fold every worker's heartbeat lane in — their
    # stage labels are per-process (mega_soup@p1/2), so the stages stay
    # distinct rows instead of mixing into the primary's
    from .fleet import load_rows, worker_event_paths

    worker_files = sorted(worker_event_paths(run_dir).items())
    worker_beats = []
    for process, path in worker_files:
        rows, _skipped = load_rows(path, process)
        worker_beats.extend(r for r in rows if r.get("kind") == "heartbeat")
    # order by run-relative stamp so "last" really is the latest beat
    # even when a worker file's tail was rewritten out of order
    worker_beats.sort(key=lambda r: float(r.get("t", 0.0)))

    heartbeats: Dict[str, dict] = {}
    for hb in by_kind.get("heartbeat", []) + worker_beats:
        stage = str(hb.get("stage", "?"))
        s = heartbeats.setdefault(stage, {"beats": 0, "gens_per_sec": []})
        s["beats"] += 1
        s["last"] = {k: hb[k] for k in
                     ("generation", "total_generations", "gens_per_sec",
                      "rss_mb", "device_memory", "t") if k in hb}
        if "gens_per_sec" in hb:
            s["gens_per_sec"].append(float(hb["gens_per_sec"]))
    for s in heartbeats.values():
        gps = s.pop("gens_per_sec")
        if gps:
            s["gens_per_sec"] = {
                "min": min(gps), "max": max(gps),
                "p50": quantile_from_times(gps, 0.5),
            }

    spans: Dict[str, dict] = {}
    for sp in by_kind.get("span", []):
        name = str(sp.get("span", "?"))
        s = spans.setdefault(name, {"count": 0, "total_s": 0.0})
        s["count"] += 1
        s["total_s"] += float(sp.get("seconds", 0.0))
    for s in spans.values():
        s["total_s"] = round(s["total_s"], 3)

    metric_rows = by_kind.get("metrics", [])
    final_metrics = dict(metric_rows[-1].get("metrics", {})) \
        if metric_rows else {}

    # cost observatory: the {"kind":"cost"} probe rows (telemetry.costs)
    # + the run's p50 rate -> the derived apps/s-vs-HLO-flops roofline
    costs = []
    from .costs import roofline

    rates = [float(hb["gens_per_sec"]["p50"]) for hb in heartbeats.values()
             if isinstance(hb.get("gens_per_sec"), dict)]
    p50 = max(rates) if rates else None
    for row in by_kind.get("cost", []):
        costs.append({"row": {k: row.get(k) for k in
                              ("entry", "flops", "bytes_accessed",
                               "temp_bytes", "argument_bytes",
                               "output_bytes", "alias_bytes",
                               "generations", "particles", "cached",
                               "compile_s", "ledger")},
                      "roofline": roofline(row, p50)})

    # live telemetry plane (PR 15): rate-over-time digests from the
    # metrics_history.jsonl stream + the alert engine's transition trail.
    # The stream is append-only and unbounded, so this reader
    # tail-bounds like every other (4MB ≈ hundreds of full-registry
    # rows — plenty for sparklines and trailing rates; a week-long run's
    # full trail is jq's job, not the summary's)
    from .timeseries import summarize_history

    history = summarize_history(
        os.path.join(run_dir, "metrics_history.jsonl"),
        tail_bytes=_HISTORY_TAIL_BYTES)
    alerts_by_rule: Dict[str, dict] = {}
    for row in by_kind.get("alert", []):
        rule = str(row.get("rule", "?"))
        d = alerts_by_rule.setdefault(
            rule, {"fired": 0, "cleared": 0, "last_state": None})
        state = row.get("state")
        if state == "firing":
            d["fired"] += 1
        elif state == "cleared":
            d["cleared"] += 1
        d["last_state"] = state
        if row.get("value") is not None:
            d["last_value"] = row["value"]

    return {
        "run_dir": os.path.abspath(run_dir),
        "meta": meta,
        "config": config,
        "event_counts": {k: len(v) for k, v in sorted(by_kind.items())},
        "worker_files": [os.path.basename(p) for _i, p in worker_files],
        "heartbeats": heartbeats,
        "spans": spans,
        "costs": costs,
        "history": history,
        "alerts": {"rows": len(by_kind.get("alert", [])),
                   "by_rule": alerts_by_rule},
        "metrics": final_metrics,
        "metrics_flushes": len(metric_rows),
        "has_prom_file": os.path.exists(
            os.path.join(run_dir, "metrics.prom")),
    }


def _render(s: dict, out) -> None:
    w = out.write
    meta = s["meta"]
    w(f"run: {s['run_dir']}\n")
    if meta:
        status = "FAILED: " + str(meta["error"]) if meta.get("error") \
            else "completed"
        w(f"  name={meta.get('name')} seed={meta.get('seed')} "
          f"wall={meta.get('wall_seconds', 0):.1f}s  {status}\n")
    elif not s["event_counts"]:
        w("  (no meta.json and no events.jsonl — not a telemetry run dir)\n")
    if s["config"]:
        knobs = " ".join(f"{k}={v}" for k, v in sorted(s["config"].items())
                         if not isinstance(v, (list, dict)))
        w(f"  config: {knobs}\n")
    if s["event_counts"]:
        w("  events: " + "  ".join(f"{k}={n}" for k, n
                                   in s["event_counts"].items()) + "\n")
    if s.get("worker_files"):
        w(f"  worker event files ({len(s['worker_files'])}, heartbeat "
          "lanes folded below; full timeline: report --fleet): "
          + ", ".join(s["worker_files"]) + "\n")

    if s["heartbeats"]:
        w("heartbeats:\n")
        for stage, hb in sorted(s["heartbeats"].items()):
            last = hb.get("last", {})
            gen = last.get("generation")
            tot = last.get("total_generations")
            where = f"gen {gen}/{tot}" if gen is not None and tot \
                else (f"gen {gen}" if gen is not None else "")
            gps = hb.get("gens_per_sec")
            rate = (f"  gens/s p50={gps['p50']:.2f} "
                    f"[{gps['min']:.2f}..{gps['max']:.2f}]") if gps else ""
            mem = f"  rss={last['rss_mb']}MB" if "rss_mb" in last else ""
            dev = last.get("device_memory") or {}
            if "bytes_in_use" in dev:
                mem += f"  dev={dev['bytes_in_use'] / 2**20:.0f}MB"
            w(f"  {stage}: {hb['beats']} beats, last at {where}"
              f"{rate}{mem}\n")
    else:
        w("heartbeats: none recorded\n")

    if s["spans"]:
        w("spans (wall seconds):\n")
        for name, sp in sorted(s["spans"].items(),
                               key=lambda kv: -kv[1]["total_s"]):
            w(f"  {name}: {sp['total_s']}s over {sp['count']} blocks\n")

    for c in s.get("costs", []):
        row, rf = c["row"], c["roofline"]
        flops = row.get("flops")
        w(f"cost: {row.get('entry')} — "
          + (f"{flops:.3g} HLO flops/chunk" if flops is not None
             else "no cost analysis on this backend (null)")
          + (f" ({row['generations']} gens x {row['particles']} "
             f"particles)" if row.get("generations") else "")
          + (f", compile {row['compile_s']}s" if row.get("compile_s")
             else "")
          + "\n")
        if rf.get("flops_per_app") is not None:
            line = (f"  roofline: {rf['flops_per_app']:.3g} flops/app")
            if rf.get("apps_per_sec") is not None:
                line += (f" -> {rf['apps_per_sec']:.3g} apps/s at p50 = "
                         f"{rf['flops_per_sec']:.3g} HLO FLOP/s achieved")
            w(line + "\n")

    hist = s.get("history")
    if hist and hist.get("series"):
        w(f"history ({hist['samples']} samples over {hist['span_s']}s, "
          "metrics_history.jsonl):\n")
        for name, d in sorted(hist["series"].items()):
            line = f"  {name}: {d['spark']} last={d['last']}"
            if "rate_per_s" in d:
                line += f"  rate={d['rate_per_s']}/s"
            else:
                line += f"  [{d['min']}..{d['max']}]"
            w(line + "\n")

    alerts = s.get("alerts") or {}
    if alerts.get("rows"):
        w(f"alerts ({alerts['rows']} transition row(s)):\n")
        for rule, d in sorted(alerts["by_rule"].items()):
            w(f"  {rule}: fired {d['fired']}x"
              + (f", last value {d['last_value']}"
                 if d.get("last_value") is not None else "")
              + f", last state {d['last_state']}\n")

    if s["metrics"]:
        w(f"metrics (cumulative, {s['metrics_flushes']} flushes"
          + (", metrics.prom present" if s["has_prom_file"] else "")
          + "):\n")
        for name, value in sorted(s["metrics"].items()):
            w(f"  {name} = {value}\n")
    else:
        w("metrics: none recorded\n")


# ---------------------------------------------------------------------------
# triage bundles (telemetry.flightrec)
# ---------------------------------------------------------------------------


def _snapshot_info(bundle_dir: str) -> Dict[str, Any]:
    """Shapes/dtypes of the bundle's population snapshot.  Tries the
    homogeneous restore first, then the heterogeneous one; a bundle whose
    checkpoint cannot be restored (missing orbax, foreign layout) still
    reports the directory listing."""
    import glob as _glob

    ckpts = sorted(p for p in _glob.glob(os.path.join(bundle_dir,
                                                      "ckpt-gen*"))
                   if p.rsplit("gen", 1)[1].isdigit())
    if not ckpts:
        return {}
    path = ckpts[-1]
    info: Dict[str, Any] = {"path": os.path.basename(path)}
    for name, restore in (("soup", "restore_checkpoint"),
                          ("multisoup", "restore_multi_checkpoint")):
        try:
            from .. import experiment

            state = getattr(experiment, restore)(path)
            import numpy as _np

            def leaf(x):
                return (f"{tuple(x.shape)} {x.dtype}"
                        if hasattr(x, "shape") else repr(x))

            fields = {}
            for k, v in state._asdict().items():
                fields[k] = ([leaf(_np.asarray(e)) for e in v]
                             if isinstance(v, (tuple, list))
                             else leaf(v))
            info["kind"] = name
            info["generation"] = int(state.time)
            info["fields"] = fields
            # an earlier restore flavor may have failed (and recorded its
            # error) before this one succeeded — success wins
            info.pop("restore_error", None)
            return info
        except Exception as e:
            info["restore_error"] = f"{type(e).__name__}: {e}"
    try:
        info["contents"] = sorted(os.listdir(path))
    except OSError:
        pass
    return info


def summarize_triage(bundle_dir: str) -> dict:
    """Machine-readable summary of one triage bundle (the ``--triage
    --json`` output; the text renderer formats this)."""
    trip = _load_json(bundle_dir, "trip.json")
    ring: List[dict] = []
    ring_path = os.path.join(bundle_dir, "ring.jsonl")
    if os.path.exists(ring_path):
        with open(ring_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ring.append(_normalize_legacy(json.loads(line)))
                except json.JSONDecodeError:
                    pass
    trajectory = [
        {k: r.get(k) for k in ("gen", "gens_per_sec")}
        | {"nan_frac": (r.get("health") or {}).get("nan_frac"),
           "zero_frac": (r.get("health") or {}).get("zero_frac"),
           "respawns": r.get("respawns")}
        for r in ring]
    trace_dir = os.path.join(bundle_dir, "trace")
    has_trace = os.path.isdir(trace_dir) and any(os.scandir(trace_dir))
    return {
        "bundle_dir": os.path.abspath(bundle_dir),
        "trip": trip,
        "config": _load_json(bundle_dir, "config.json"),
        "metrics": _load_json(bundle_dir, "metrics.json"),
        "ring_len": len(ring),
        "ring_tail": ring[-8:],
        "health_trajectory": trajectory,
        "snapshot": _snapshot_info(bundle_dir),
        "trace_dir": os.path.abspath(trace_dir) if has_trace else None,
    }


def summarize_profile(run_dir: str) -> dict:
    """Machine-readable summary of a run's continuous-profiling plane
    (the ``--profile --json`` output): the sampler's meta row, the
    top folded stacks per thread (from ``profile.folded``), the last
    chunk's utilization decomposition (from ``metrics.prom``), and the
    anomaly-capture index."""
    from .profiler import (PROFILE_FOLDED_NAME, PROFILE_JSONL_NAME,
                           capture_index)

    meta = None
    jsonl_path = os.path.join(run_dir, PROFILE_JSONL_NAME)
    if os.path.exists(jsonl_path):
        try:
            with open(jsonl_path) as f:
                first = json.loads(f.readline())
            if first.get("kind") == "profile_meta":
                meta = {k: v for k, v in first.items() if k != "kind"}
        except (OSError, json.JSONDecodeError, ValueError):
            pass
    # per-thread top stacks from the folded exchange format
    # (``thread;frame;... count``); totals normalize the percentages
    by_thread: Dict[str, List[tuple]] = {}
    totals: Dict[str, int] = {}
    folded_path = os.path.join(run_dir, PROFILE_FOLDED_NAME)
    if os.path.exists(folded_path):
        try:
            with open(folded_path) as f:
                for line in f:
                    line = line.rstrip("\n")
                    if not line or " " not in line:
                        continue
                    stack, _, count = line.rpartition(" ")
                    thread, _, frames = stack.partition(";")
                    try:
                        n = int(count)
                    except ValueError:
                        continue
                    totals[thread] = totals.get(thread, 0) + n
                    by_thread.setdefault(thread, []).append((frames, n))
        except OSError:
            pass
    top_stacks = {}
    for thread, stacks in sorted(by_thread.items()):
        stacks.sort(key=lambda sn: (-sn[1], sn[0]))
        total = totals[thread] or 1
        top_stacks[thread] = [
            {"stack": frames, "count": n,
             "share": round(n / total, 4)}
            for frames, n in stacks[:5]]
    utilization = {}
    prom_path = os.path.join(run_dir, "metrics.prom")
    if os.path.exists(prom_path):
        try:
            with open(prom_path) as f:
                for line in f:
                    if not line.startswith("srnn_soup_utilization_"):
                        continue
                    name, _, value = line.strip().rpartition(" ")
                    try:
                        utilization[name[len("srnn_soup_utilization_"):]] \
                            = float(value)
                    except ValueError:
                        pass
        except OSError:
            pass
    captures = capture_index(run_dir)
    return {
        "run_dir": os.path.abspath(run_dir),
        "meta": meta,
        "samples_by_thread": totals,
        "top_stacks": top_stacks,
        "utilization": utilization or None,
        "captures": captures,
        # the no-data contract's flag: a run that never profiled (or a
        # --no-profile run) has no folded tables AND no capture bundles
        "no_data": meta is None and not top_stacks and not captures,
    }


def _render_profile(s: dict, out) -> None:
    w = out.write
    w(f"profile: {s['run_dir']}\n")
    meta = s.get("meta")
    if meta:
        w(f"  sampler: {meta.get('hz')}Hz, {meta.get('samples')} samples "
          f"over {meta.get('uptime_s')}s, {meta.get('threads')} threads, "
          f"{meta.get('stacks')} stacks "
          f"({meta.get('overruns')} overruns, "
          f"{meta.get('stacks_dropped')} dropped)\n")
    util = s.get("utilization")
    if util:
        cells = "  ".join(f"{k}={100 * v:.1f}%"
                          for k, v in sorted(util.items()))
        w(f"utilization (last chunk): {cells}\n")
    if s["top_stacks"]:
        w("top stacks:\n")
        for thread, stacks in s["top_stacks"].items():
            w(f"  {thread} ({s['samples_by_thread'].get(thread, 0)} "
              "samples):\n")
            for st in stacks:
                # leaf-most frames are the story; keep the tail
                frames = st["stack"].split(";")
                shown = ";".join(frames[-3:])
                if len(frames) > 3:
                    shown = "...;" + shown
                w(f"    {100 * st['share']:5.1f}%  {shown}\n")
    caps = s.get("captures") or []
    if caps:
        w(f"anomaly captures ({len(caps)}, oldest first):\n")
        for c in caps:
            have = [k for k in ("samples", "threads", "metrics",
                                "exemplars", "trace") if c.get(k)]
            w(f"  {c['name']}: " + (", ".join(have) or "capture.json only")
              + "\n")
    else:
        w("anomaly captures: none (no alert fired, or captures "
          "evicted)\n")


def _fmt_frac(v) -> str:
    return f"{v:.4f}" if isinstance(v, (int, float)) else "-"


def _render_triage(s: dict, out) -> None:
    w = out.write
    trip = s["trip"]
    w(f"triage bundle: {s['bundle_dir']}\n")
    if trip:
        w(f"  tripped: {', '.join(trip.get('reasons', []))} "
          f"at generation {trip.get('generation')}\n")
        th = {k: v for k, v in (trip.get("thresholds") or {}).items()
              if v}
        if th:
            w("  thresholds: "
              + " ".join(f"{k}={v}" for k, v in sorted(th.items())) + "\n")
        backend = trip.get("backend") or {}
        if backend:
            w(f"  backend: {backend.get('backend')} x"
              f"{backend.get('device_count')} "
              f"jax={backend.get('jax_version')}\n")
        if trip.get("errors"):
            w(f"  bundle-write errors: {trip['errors']}\n")
    else:
        w("  (no trip.json — not a triage bundle?)\n")
    if s["config"]:
        knobs = " ".join(f"{k}={v}" for k, v in sorted(s["config"].items())
                         if not isinstance(v, (list, dict)))
        w(f"  config: {knobs}\n")

    traj = [t for t in s["health_trajectory"] if t.get("gen") is not None]
    if traj:
        w(f"health trajectory ({s['ring_len']} ring rows):\n")
        w("  gen      gens/s   nan_frac  zero_frac  respawns\n")
        for t in traj[-12:]:
            gps = t.get("gens_per_sec")
            w(f"  {t['gen']:<8} {gps if gps is not None else '-':<8} "
              f"{_fmt_frac(t.get('nan_frac')):<9} "
              f"{_fmt_frac(t.get('zero_frac')):<10} "
              f"{t.get('respawns') if t.get('respawns') is not None else '-'}"
              "\n")

    snap = s["snapshot"]
    if snap:
        w(f"snapshot: {snap.get('path')}")
        if "kind" in snap:
            w(f" ({snap['kind']}, generation {snap.get('generation')})\n")
            for k, v in snap["fields"].items():
                w(f"  {k}: {v}\n")
        else:
            w(f"  [{snap.get('restore_error', 'unrestorable')}]\n")
        w(f"  resume with: python -m srnn_tpu.setups <mega_...> "
          f"--resume {s['bundle_dir']}\n")
    else:
        w("snapshot: none (host-only bundle — stall or snapshot "
          "failure; see trip.json)\n")
    if s["trace_dir"]:
        w(f"profiler trace: {s['trace_dir']}\n")


# ---------------------------------------------------------------------------
# single-request traces (telemetry.fleet.trace_request)
# ---------------------------------------------------------------------------


def _render_trace_request(s: dict, out) -> None:
    w = out.write
    lanes = ", ".join(f"p{p}" for p in s["processes"])
    w(f"trace {s['ticket']} (trace_id={s['trace_id']}, via "
      f"{s['source']}): {len(s['spans'])} span(s) across {lanes}, "
      f"{s['cross_process_links']} cross-process link(s)\n")
    for r in s["spans"]:
        start = r.get("start_s")
        stamp = f"+{start:9.4f}s" if isinstance(start, (int, float)) \
            else "          ?"
        sec = r.get("seconds")
        dur = f"{sec:.4f}s" if isinstance(sec, (int, float)) else "?"
        extras = [f"{k}={r[k]}" for k in
                  ("worker", "worker_ticket", "replays", "replayed",
                   "error", "mode") if r.get(k) is not None]
        link = " <-hop" if r.get("remote_parent") is not None else ""
        w(f"  [p{r.get('process', 0)} {stamp}] {r.get('span', '?'):<16} "
          f"{dur:>10}{link}"
          + (("  " + " ".join(extras)) if extras else "") + "\n")
    if s["critical_path"]:
        w(f"critical path (serve.ticket {s['root_seconds']}s):\n")
        for c in sorted(s["critical_path"],
                        key=lambda c: -(c["seconds"] or 0.0)):
            frac = f" {c['fraction'] * 100:5.1f}%" \
                if c.get("fraction") is not None else ""
            w(f"  {c['span']:<16} {c['seconds']:.4f}s{frac}\n")


# ---------------------------------------------------------------------------
# replication dynamics (telemetry.genealogy over lineage.jsonl)
# ---------------------------------------------------------------------------


def _census_cells(c: dict) -> str:
    return " ".join(f"{k}={v}" for k, v in c.items() if v) or "-"


def _render_dynamics(s: dict, out) -> None:
    w = out.write
    header = s["header"]
    w(f"replication dynamics: {s['run_dir']}\n")
    w(f"  epoch {header.get('epoch', 0)} (of {s['epochs']}): "
      f"{header.get('n')} particles, {s['windows']} windows, "
      f"{s['minted']} instances minted, {s['alive']} alive\n")
    graph = s["graph"]
    if graph.get("edges_dropped"):
        w(f"  NOTE: {graph['edges_dropped']} edges dropped to window "
          "capacity — graph counts are lower bounds (census/births are "
          "exact)\n")

    w("dominant lineages (root -> live descendants):\n")
    w("  root     kind     birth  alive  minted\n")
    for r in s["dominant_lineages"][:10]:
        w(f"  {r['root']:<8} {r['kind']:<8} "
          f"{r['birth'] if r['birth'] is not None else '-':<6} "
          f"{r['alive']:<6} {r['minted']}\n")

    surv = s["survival"]
    if surv.get("terminated"):
        ls = surv["lifespan"]
        w(f"clone survival: {surv['terminated']} terminated, lifespan "
          f"p50={ls['p50']} p90={ls['p90']} max={ls['max']} generations\n")
        w("  survival curve: "
          + "  ".join(f">={p['generations']}g:{p['fraction']:.0%}"
                      for p in surv.get("curve", [])) + "\n")

    for name in ("attack", "imitation"):
        g = graph.get(name, {})
        if g.get("edges"):
            top = ", ".join(f"pid {t['pid']} x{t['count']}"
                            for t in g.get("top", [])[:3])
            w(f"{name} graph: {g['edges']} edges from {g['actors']} actors, "
              f"max out-degree {g['max_out_degree']} (top: {top})\n")

    basins = s["basins"]
    for tname, mat in sorted(s["basin_matrix"].items()):
        label = f" [{tname}]" if tname else ""
        w(f"basin transitions{label} (rows: from unknown+basins, cols: "
          + "/".join(basins) + "):\n")
        for i, src in enumerate(("unknown",) + tuple(basins)):
            w(f"  {src:<9} " + " ".join(f"{v:>8}" for v in mat[i]) + "\n")

    traj = s["census_trajectory"]
    if traj:
        w("fixpoint census trajectory:\n")
        for row in traj[-12:]:
            gen = row.get("gen")
            probe = " (probe)" if row.get("probe") else ""
            cells = {k: v for k, v in row.items()
                     if k not in ("gen", "probe")}
            if cells and all(isinstance(v, dict) for v in cells.values()):
                body = "  ".join(f"{t}[{_census_cells(c)}]"
                                 for t, c in cells.items())
            else:
                body = _census_cells(cells)
            w(f"  gen {gen}: {body}{probe}\n")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("run_dir", help="an Experiment run directory (or a "
                                   "triage bundle with --triage)")
    p.add_argument("--triage", action="store_true",
                   help="treat run_dir as a flight-recorder triage bundle")
    p.add_argument("--fleet", action="store_true",
                   help="render the fleet observatory view: merged "
                        "cross-process timeline, per-process lanes, "
                        "straggler attribution (telemetry.fleet)")
    p.add_argument("--trace", action="store_true",
                   help="export the merged fleet timeline (host spans of "
                        "every process + serve-ticket slices + heartbeat "
                        "counter tracks) as a Chrome/Perfetto-loadable "
                        "trace.json in the run dir; any triage bundle's "
                        "armed jax.profiler device trace is linked under "
                        "otherData.device_traces")
    p.add_argument("--trace-request", metavar="TICKET",
                   help="render ONE request's end-to-end trace: resolve "
                        "TICKET (front/worker ticket id or trace id) to "
                        "its span family across every process lane, with "
                        "the critical-path breakdown of the final "
                        "serve.ticket root; falls back to the exemplar "
                        "rings when the event files no longer hold it")
    p.add_argument("--dynamics", action="store_true",
                   help="render the run's replication-dynamics trail "
                        "(lineage.jsonl via telemetry.genealogy)")
    p.add_argument("--profile", action="store_true",
                   help="render the run's continuous-profiling plane: "
                        "sampler meta, top folded stacks per thread, "
                        "the last chunk's utilization decomposition and "
                        "the anomaly-capture index "
                        "(telemetry.profiler)")
    p.add_argument("--runs", action="store_true",
                   help="treat the positional as a RESULTS ROOT and "
                        "render the cross-run observatory: run table + "
                        "campaign rollups + drift timelines "
                        "(telemetry.archive; ingests incrementally into "
                        "<root>/.archive)")
    p.add_argument("--compare", metavar="RUN_A", default=None,
                   help="compare RUN_A against the positional run dir: "
                        "config diff + metric/census deltas "
                        "(telemetry.archive; no store involved)")
    p.add_argument("--json", action="store_true",
                   help="emit the machine-readable summary instead of text")
    args = p.parse_args(argv)
    if not os.path.isdir(args.run_dir):
        print(f"report: {args.run_dir}: not a directory", file=sys.stderr)
        return 2
    if args.runs:
        from .archive import render_table, runs_doc

        doc = runs_doc(args.run_dir)
        if doc["no_data"]:
            # the no-data contract (exit 2, explicit flag, no dead
            # artifact) — an empty results root must never produce an
            # empty-but-valid table the controller would trust
            if args.json:
                print(json.dumps(doc, indent=1, default=str))
            else:
                print(f"report: {args.run_dir}: no data yet — no run "
                      "dirs under this root to index", file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(doc, indent=1, default=str))
        else:
            render_table(doc, sys.stdout)
        return 0
    if args.compare:
        from .archive import compare_runs, render_compare

        if not os.path.isdir(args.compare):
            print(f"report: {args.compare}: not a directory",
                  file=sys.stderr)
            return 2
        doc = compare_runs(args.compare, args.run_dir)
        if doc is None:
            # same no-data contract: one side holds no run-dir marker
            # files, so there is nothing truthful to diff
            if args.json:
                print(json.dumps({"no_data": True, "a": args.compare,
                                  "b": args.run_dir}, indent=1))
            else:
                print(f"report: --compare: {args.compare} or "
                      f"{args.run_dir} is not a run dir (no events.jsonl/"
                      "meta.json/journal.jsonl)", file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(doc, indent=1, default=str))
        else:
            render_compare(doc, sys.stdout)
        return 0
    if args.trace:
        from ..utils.atomicio import atomic_write_text
        from .fleet import perfetto_trace

        doc = perfetto_trace(args.run_dir)
        if not doc["traceEvents"]:
            # the no-data contract (exit 2, no dead artifact) holds for
            # --json too: automation gets an explicit no_data flag
            # instead of an empty-but-valid trace document
            if args.json:
                doc["otherData"]["no_data"] = True
                print(json.dumps(doc, default=str))
            else:
                print(f"report: {args.run_dir}: no data yet — no span/"
                      "heartbeat rows to export (a just-created run "
                      "dir?)", file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(doc, default=str))
            return 0
        path = os.path.join(args.run_dir, "trace.json")
        atomic_write_text(path, json.dumps(doc, default=str))
        n_spans = sum(1 for e in doc["traceEvents"] if e.get("ph") == "X")
        print(f"trace: {path} — {len(doc['traceEvents'])} events "
              f"({n_spans} spans) across processes "
              f"{doc['otherData']['processes']}; load in "
              "ui.perfetto.dev or chrome://tracing")
        for d in doc["otherData"]["device_traces"]:
            print(f"  device trace (jax.profiler, TensorBoard-loadable): "
                  f"{d}")
        return 0
    if args.trace_request:
        from .fleet import trace_request

        s = trace_request(args.run_dir, args.trace_request)
        if s is None:
            # same no-data contract as --trace: exit 2, name the state
            print(f"report: {args.run_dir}: ticket "
                  f"{args.trace_request!r} not found in the merged "
                  "timeline or any exemplar ring (resolved root-only "
                  "tickets keep just their serve.ticket row)",
                  file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(s, indent=1, default=str))
        else:
            _render_trace_request(s, sys.stdout)
        return 0
    if args.fleet:
        from .fleet import fleet_summary, render_fleet

        s = fleet_summary(args.run_dir)
        if args.json:
            print(json.dumps(s, indent=1, default=str))
        else:
            render_fleet(s, sys.stdout)
        return 0
    if args.triage:
        s = summarize_triage(args.run_dir)
        if args.json:
            print(json.dumps(s, indent=1, default=str))
        else:
            _render_triage(s, sys.stdout)
        return 0
    if args.profile:
        s = summarize_profile(args.run_dir)
        if s["no_data"]:
            # the no-data contract: a --no-profile run (or a run dir
            # that never profiled) must never render an empty-but-valid
            # profile an operator would misread as "nothing was hot"
            if args.json:
                print(json.dumps(s, indent=1, default=str))
            else:
                print(f"report: {args.run_dir}: no profiling data — no "
                      "profile.folded/profile.jsonl and no anomaly "
                      "bundles (run without --no-profile)",
                      file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(s, indent=1, default=str))
        else:
            _render_profile(s, sys.stdout)
        return 0
    if args.dynamics:
        from .genealogy import summarize_dynamics

        try:
            s = summarize_dynamics(args.run_dir)
        except (FileNotFoundError, ValueError) as e:
            print(f"report: no lineage stream: {e} (run with --lineage)",
                  file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(s, indent=1, default=str))
        else:
            _render_dynamics(s, sys.stdout)
        return 0
    s = summarize(args.run_dir)
    if args.json:
        print(json.dumps(s, indent=1, default=str))
    else:
        _render(s, sys.stdout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
