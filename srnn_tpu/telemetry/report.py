"""Summarize a run directory's telemetry trail.

    python -m srnn_tpu.telemetry.report <run_dir> [--json]

Reads ``meta.json`` + ``events.jsonl`` (the ``Experiment`` channel the
mega-run loops, heartbeats and metric flushes all write through) and
renders what a post-mortem needs first: did the run finish, where was it
last alive (stage / generation / gens-per-sec / memory), what do the
final cumulative metrics say, and where did the wall time go (spans).
Works on killed runs — heartbeat rows are fsync'd, and cumulative metric
snapshots mean the last row is the whole story.
"""

import argparse
import json
import os
import sys
from typing import Dict, List

from .metrics import quantile_from_times


def load_events(run_dir: str) -> List[dict]:
    path = os.path.join(run_dir, "events.jsonl")
    if not os.path.exists(path):
        return []
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                pass  # torn tail of a killed run: keep what parses
    return rows


def _load_json(run_dir: str, name: str) -> dict:
    path = os.path.join(run_dir, name)
    if not os.path.exists(path):
        return {}
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}


def summarize(run_dir: str) -> dict:
    """Machine-readable summary (the ``--json`` output; the text renderer
    formats this)."""
    events = load_events(run_dir)
    meta = _load_json(run_dir, "meta.json")
    config = _load_json(run_dir, "config.json")

    by_kind: Dict[str, List[dict]] = {}
    for e in events:
        by_kind.setdefault(str(e.get("kind", "log")), []).append(e)

    heartbeats: Dict[str, dict] = {}
    for hb in by_kind.get("heartbeat", []):
        stage = str(hb.get("stage", "?"))
        s = heartbeats.setdefault(stage, {"beats": 0, "gens_per_sec": []})
        s["beats"] += 1
        s["last"] = {k: hb[k] for k in
                     ("generation", "total_generations", "gens_per_sec",
                      "rss_mb", "device_memory", "t") if k in hb}
        if "gens_per_sec" in hb:
            s["gens_per_sec"].append(float(hb["gens_per_sec"]))
    for s in heartbeats.values():
        gps = s.pop("gens_per_sec")
        if gps:
            s["gens_per_sec"] = {
                "min": min(gps), "max": max(gps),
                "p50": quantile_from_times(gps, 0.5),
            }

    spans: Dict[str, dict] = {}
    for sp in by_kind.get("span", []):
        name = str(sp.get("span", "?"))
        s = spans.setdefault(name, {"count": 0, "total_s": 0.0})
        s["count"] += 1
        s["total_s"] += float(sp.get("seconds", 0.0))
    for s in spans.values():
        s["total_s"] = round(s["total_s"], 3)

    metric_rows = by_kind.get("metrics", [])
    final_metrics = dict(metric_rows[-1].get("metrics", {})) \
        if metric_rows else {}

    return {
        "run_dir": os.path.abspath(run_dir),
        "meta": meta,
        "config": config,
        "event_counts": {k: len(v) for k, v in sorted(by_kind.items())},
        "heartbeats": heartbeats,
        "spans": spans,
        "metrics": final_metrics,
        "metrics_flushes": len(metric_rows),
        "has_prom_file": os.path.exists(
            os.path.join(run_dir, "metrics.prom")),
    }


def _render(s: dict, out) -> None:
    w = out.write
    meta = s["meta"]
    w(f"run: {s['run_dir']}\n")
    if meta:
        status = "FAILED: " + str(meta["error"]) if meta.get("error") \
            else "completed"
        w(f"  name={meta.get('name')} seed={meta.get('seed')} "
          f"wall={meta.get('wall_seconds', 0):.1f}s  {status}\n")
    elif not s["event_counts"]:
        w("  (no meta.json and no events.jsonl — not a telemetry run dir)\n")
    if s["config"]:
        knobs = " ".join(f"{k}={v}" for k, v in sorted(s["config"].items())
                         if not isinstance(v, (list, dict)))
        w(f"  config: {knobs}\n")
    if s["event_counts"]:
        w("  events: " + "  ".join(f"{k}={n}" for k, n
                                   in s["event_counts"].items()) + "\n")

    if s["heartbeats"]:
        w("heartbeats:\n")
        for stage, hb in sorted(s["heartbeats"].items()):
            last = hb.get("last", {})
            gen = last.get("generation")
            tot = last.get("total_generations")
            where = f"gen {gen}/{tot}" if gen is not None and tot \
                else (f"gen {gen}" if gen is not None else "")
            gps = hb.get("gens_per_sec")
            rate = (f"  gens/s p50={gps['p50']:.2f} "
                    f"[{gps['min']:.2f}..{gps['max']:.2f}]") if gps else ""
            mem = f"  rss={last['rss_mb']}MB" if "rss_mb" in last else ""
            dev = last.get("device_memory") or {}
            if "bytes_in_use" in dev:
                mem += f"  dev={dev['bytes_in_use'] / 2**20:.0f}MB"
            w(f"  {stage}: {hb['beats']} beats, last at {where}"
              f"{rate}{mem}\n")
    else:
        w("heartbeats: none recorded\n")

    if s["spans"]:
        w("spans (wall seconds):\n")
        for name, sp in sorted(s["spans"].items(),
                               key=lambda kv: -kv[1]["total_s"]):
            w(f"  {name}: {sp['total_s']}s over {sp['count']} blocks\n")

    if s["metrics"]:
        w(f"metrics (cumulative, {s['metrics_flushes']} flushes"
          + (", metrics.prom present" if s["has_prom_file"] else "")
          + "):\n")
        for name, value in sorted(s["metrics"].items()):
            w(f"  {name} = {value}\n")
    else:
        w("metrics: none recorded\n")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("run_dir", help="an Experiment run directory")
    p.add_argument("--json", action="store_true",
                   help="emit the machine-readable summary instead of text")
    args = p.parse_args(argv)
    if not os.path.isdir(args.run_dir):
        print(f"report: {args.run_dir}: not a directory", file=sys.stderr)
        return 2
    s = summarize(args.run_dir)
    if args.json:
        print(json.dumps(s, indent=1, default=str))
    else:
        _render(s, sys.stdout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
