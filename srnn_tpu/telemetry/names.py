"""The canonical metric-name table.

Every metric name registered anywhere under ``srnn_tpu/`` must be declared
here with its kind — the srnnlint ``metric-names`` pass
(``srnn_tpu/analysis/passes/metric_names.py``; run via ``python -m
srnn_tpu.analysis`` or the ``tests/test_metric_names.py`` wrapper) walks
the package AST (and the runtime ``EVENT_COUNTERS`` table) and fails on
any name that is missing, mis-kinded, or breaks the naming convention.  This is the
collection-time tripwire for the next ``zweo``-style drift: a typo'd or
ad-hoc name cannot ship, because it is not in this table.

Naming convention (:func:`check_name`):

  * ``snake_case`` throughout (``[a-z][a-z0-9_]*``).
  * Counters end in ``_total`` (Prometheus monotone-counter convention).
  * Unit-bearing suffixes are ``_seconds`` / ``_bytes`` (or the
    grandfathered short ``_s`` on the pipeline chunk gauges); never
    ``_sec`` / ``_secs`` / ``_ms``.

``GRANDFATHERED`` lists pre-convention names kept for dashboard
compatibility; do not add new entries — fix the name instead.

Liveness: the srnnlint pass also checks the REVERSE direction — every
name declared here must have at least one emission site in the package
(a registration call or the name spelled in a runtime table like
``EVENT_COUNTERS``), so the table cannot accumulate dead metrics as new
families land.
"""

import re
from typing import Dict

#: name -> kind ("counter" | "gauge" | "histogram"); exported with the
#: ``srnn_`` namespace prefix by ``telemetry.metrics``.
CANONICAL_METRICS: Dict[str, str] = {
    # -- soup science (telemetry.soup_metrics) ---------------------------
    "soup_generations_total": "counter",
    "soup_particle_generations_total": "counter",
    "soup_attacks_total": "counter",
    "soup_learns_total": "counter",
    "soup_train_events_total": "counter",
    "soup_respawns_divergent_total": "counter",
    "soup_respawns_zero_total": "counter",
    "soup_train_loss_sum": "counter",
    "soup_train_loss_nonfinite_flushes_total": "counter",
    "soup_class_particles": "gauge",
    "soup_class_delta": "gauge",
    # -- replication dynamics (telemetry.dynamics) -----------------------
    "soup_dynamics_windows_total": "counter",
    "soup_dynamics_edges_total": "counter",
    "soup_dynamics_edges_dropped_total": "counter",
    "soup_dynamics_births_total": "counter",
    "soup_dynamics_next_pid": "gauge",
    "soup_dynamics_basin_particles": "gauge",
    "soup_dynamics_basin_transitions_total": "counter",
    "soup_dynamics_fixpoint_l2_max": "gauge",
    "soup_dynamics_fixpoint_linf_max": "gauge",
    # -- fused generation & mixed precision (telemetry.soup_metrics) -----
    "soup_fused_generations_total": "counter",
    "soup_fused_fallback_generations_total": "counter",
    "soup_precision_weight_bits": "gauge",
    "soup_precision_population_bytes": "gauge",
    # -- block autotuner (srnn_tpu.autotune) -----------------------------
    "soup_autotune_cache_hits_total": "counter",
    "soup_autotune_measurements_total": "counter",
    "soup_autotune_block": "gauge",
    "soup_autotune_roofline_fraction": "gauge",
    # -- flight recorder (telemetry.flightrec) ---------------------------
    "soup_health_nonfinite_particles": "gauge",
    "soup_health_zero_particles": "gauge",
    "soup_health_nan_frac": "gauge",
    "soup_health_zero_frac": "gauge",
    "soup_health_weight_norm_min": "gauge",
    "soup_health_weight_norm_max": "gauge",
    "soup_watchdog_trips_total": "counter",
    # -- elastic run supervisor (resilience/, folded via
    #    telemetry.flightrec.record_recovery) -----------------------------
    "soup_restarts_total": "counter",
    "soup_topology_reramps_total": "counter",
    "soup_recovery_seconds": "histogram",
    # -- distributed runtime tier (srnn_tpu.distributed; set via
    #    setups.common.set_distributed_gauges / fetch_for_checkpoint,
    #    host-loss recoveries folded by telemetry.flightrec) -------------
    "soup_distributed_processes": "gauge",
    "soup_distributed_slices": "gauge",
    "soup_distributed_host_losses_total": "counter",
    "soup_distributed_gather_seconds": "histogram",
    # -- experiment service (srnn_tpu.serve) -----------------------------
    "serve_requests_total": "counter",
    "serve_requests_failed_total": "counter",
    "serve_dispatches_total": "counter",
    "serve_dispatch_tenants_total": "counter",
    "serve_queue_depth": "gauge",
    "serve_request_seconds": "histogram",
    "serve_dispatch_seconds": "histogram",
    # -- serve ticket tracing + SLO (fleet observatory; per-ticket
    #    queue/window/dispatch breakdown, serve/service.py) ---------------
    "serve_ticket_queue_seconds": "histogram",
    "serve_ticket_window_seconds": "histogram",
    "serve_ticket_dispatch_seconds": "histogram",
    "serve_slo_violations_total": "counter",
    # -- self-healing service (serve/journal.py durable replay, the
    #    supervised dispatch's retry/bisect-quarantine ladder, admission
    #    control, and results-retention eviction; serve/service.py) ------
    "serve_journal_replays_total": "counter",
    "serve_quarantined_tenants_total": "counter",
    "serve_dispatch_retries_total": "counter",
    "serve_overload_rejections_total": "counter",
    "serve_deadline_expirations_total": "counter",
    "serve_queue_rejected_depth": "gauge",
    "serve_results_evicted_total": "counter",
    # -- continuous batching + worker fleet (serve/controller.py adaptive
    #    windows set by serve/service.py's drain; serve/pool.py front:
    #    worker liveness, death/replay ladder, per-worker queue gauges) --
    "serve_window_seconds": "gauge",
    "serve_inflight_requests": "gauge",
    "serve_workers": "gauge",
    "serve_worker_deaths_total": "counter",
    "serve_worker_replays_total": "counter",
    "serve_worker_queue_depth": "gauge",
    # -- fleet observatory (telemetry.fleet: per-process gens/sec skew,
    #    folded live each chunk by the primary's finisher) ----------------
    "soup_straggler_process": "gauge",
    "soup_straggler_skew_ratio": "gauge",
    "soup_straggler_lag_generations": "gauge",
    "soup_straggler_gens_per_second": "gauge",
    # -- heartbeats (telemetry.heartbeat) --------------------------------
    "heartbeat_generation": "gauge",
    "gens_per_sec": "gauge",
    "rss_bytes": "gauge",
    # -- spans (telemetry.tracing) ---------------------------------------
    "span_seconds": "histogram",
    # -- async pipeline (utils.pipeline) ---------------------------------
    "pipeline_chunk_wall_s": "gauge",
    "pipeline_chunk_device_wait_s": "gauge",
    "pipeline_chunk_host_io_s": "gauge",
    "pipeline_chunk_device_idle_bound_s": "gauge",
    "pipeline_overlap_ratio": "gauge",
    "pipeline_wall_seconds_total": "counter",
    "pipeline_device_wait_seconds_total": "counter",
    "pipeline_host_io_seconds_total": "counter",
    # -- AOT subsystem (utils.aot) ---------------------------------------
    "aot_compiles_total": "counter",
    "aot_memo_hits_total": "counter",
    "aot_lower_seconds_total": "counter",
    "aot_compile_seconds_total": "counter",
    "aot_compile_seconds": "histogram",
    # -- cost observatory (telemetry.costs: the compile/FLOP/memory
    #    ledger folded into every run's metrics.prom; serve attributes
    #    dispatch flops across its stacked tenants) ----------------------
    "soup_compile_seconds_total": "counter",
    "soup_aot_cache_hits_total": "counter",
    "soup_aot_cache_misses_total": "counter",
    "soup_hlo_flops": "gauge",
    "soup_hbm_bytes": "gauge",
    "serve_tenant_flops_total": "counter",
    # -- live telemetry plane (telemetry.exporter scrape counter;
    #    telemetry.alerts firing transitions + active-rule gauge) --------
    "soup_scrapes_total": "counter",
    "soup_alerts_total": "counter",
    "soup_alerts_active": "gauge",
    # -- run archive & cross-run observatory (telemetry.archive: the
    #    longitudinal store's textfile exposition, written to
    #    <store>/archive.prom at each ingest pass) -----------------------
    "soup_archive_runs": "gauge",
    "soup_archive_runs_ingested_total": "counter",
    "soup_archive_drift_ratio": "gauge",
    "soup_archive_drift_legs": "gauge",
    # -- continuous profiling plane (telemetry.profiler: the 50Hz host
    #    sampler's own accounting, the per-chunk utilization
    #    decomposition, and the anomaly black-box capture counter) -------
    "soup_profile_samples_total": "counter",
    "soup_profile_overruns_total": "counter",
    "soup_profile_stacks_dropped_total": "counter",
    "soup_profile_threads": "gauge",
    "soup_profile_stacks": "gauge",
    "soup_utilization_device_busy": "gauge",
    "soup_utilization_host_blocked": "gauge",
    "soup_utilization_idle": "gauge",
    "soup_anomaly_captures_total": "counter",
}

#: pre-convention names kept for dashboard compatibility (do not extend):
#: the ``_s`` chunk gauges predate the ``_seconds`` rule; ``gens_per_sec``
#: and ``soup_train_loss_sum`` predate the suffix rules entirely.
GRANDFATHERED = frozenset({
    "soup_train_loss_sum",
    "gens_per_sec",
    "pipeline_chunk_wall_s",
    "pipeline_chunk_device_wait_s",
    "pipeline_chunk_host_io_s",
    "pipeline_chunk_device_idle_bound_s",
})

#: The canonical SPAN-name table — the tracing twin of
#: ``CANONICAL_METRICS``.  Every ``{"kind": "span"}`` row any module
#: emits (SpanStream ``emit``/``timed``, the serve tier's
#: ``_event_row(kind="span", span=...)`` families, the pool front's
#: ``_span_row``) must carry a name declared here; the srnnlint
#: ``span-names`` pass (S001/S002/S003) enforces both directions, the
#: same discipline M001/M005 apply to metrics.  Values describe the
#: emitting layer.  Span names are DOTTED lowercase
#: (:func:`check_span_name`); the f-string chunk spans
#: (``f"{stage}.chunk"``) are declared per concrete stage so a renamed
#: setup cannot silently orphan its trace lanes.
CANONICAL_SPANS: Dict[str, str] = {
    # -- mega chunk spans (setups.common.emit_chunk_spans f-strings) -----
    "mega_soup.chunk": "chunk root (mega_soup)",
    "mega_soup.device_wait": "chunk child (mega_soup)",
    "mega_soup.host_io": "chunk child (mega_soup)",
    "mega_multisoup.chunk": "chunk root (mega_multisoup)",
    "mega_multisoup.device_wait": "chunk child (mega_multisoup)",
    "mega_multisoup.host_io": "chunk child (mega_multisoup)",
    # -- distributed host I/O collectives (distributed.hostio sink) ------
    "hostio.fetch_tree": "host gather collective",
    "hostio.broadcast_run_dir": "run-dir broadcast collective",
    # -- serve ticket families (serve/service.py per-ticket traces) ------
    "serve.admit": "admission + journal fsync (durable-before-ack)",
    "serve.ticket": "per-request root span",
    "serve.ticket.queue": "backlog wait before the batching window",
    "serve.ticket.window": "batching-window share sat out",
    "serve.ticket.dispatch": "dispatch-group execution wall",
    "serve.ticket.publish": "result publication + waiter wake",
    # -- pool front hop (serve/pool.py; PR 17 fleet tracing) -------------
    "front.admit": "front admission + journal fsync",
    "front.assign": "worker selection (sticky round-robin)",
    "front.relay": "forward to the worker (trace-context propagated)",
    "front.replay": "re-forward after a worker death",
}

_SNAKE = re.compile(r"^[a-z][a-z0-9_]*$")
_SPAN_NAME = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z][a-z0-9_]*)+$")


def check_span_name(name: str) -> "list[str]":
    """Convention violations for one span name (empty = clean): dotted
    lowercase, at least one dot (the layer prefix is the lane contract —
    ``serve.``/``front.`` rows render in the serve lane)."""
    if not _SPAN_NAME.match(name):
        return [f"{name}: span names are dotted lowercase "
                "(layer.name[.child])"]
    return []
_BAD_UNIT_SUFFIXES = ("_sec", "_secs", "_ms", "_millis", "_mb", "_kb")


def check_name(name: str, kind: str) -> "list[str]":
    """Convention violations for one (name, kind) pair (empty = clean)."""
    problems = []
    if not _SNAKE.match(name):
        problems.append(f"{name}: not snake_case")
    if name in GRANDFATHERED:
        return problems
    if kind == "counter" and not name.endswith("_total"):
        problems.append(f"{name}: counter must end in _total")
    if kind != "counter" and name.endswith("_total"):
        problems.append(f"{name}: _total suffix is reserved for counters")
    if name.endswith(_BAD_UNIT_SUFFIXES):
        problems.append(
            f"{name}: use _seconds/_bytes unit suffixes, not "
            f"{name[name.rfind('_'):]}")
    return problems
