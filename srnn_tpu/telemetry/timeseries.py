"""Metric history: bounded in-memory rings + the append-only
``metrics_history.jsonl`` stream.

The registry's two sinks are cumulative snapshots — the LAST events row
(or the current ``metrics.prom``) is the whole story, which is exactly
right for post-mortems and exactly wrong for trajectories: "is gens/sec
degrading", "is the queue draining", "how fast is the SLO burn" all need
*history*.  :class:`MetricHistory` samples a registry once per
chunk/dispatch into a bounded per-series ring (newest wins, oldest
drops — the stream degrades to a window, never grows without bound) and
optionally appends each sample as one single-line JSON row to
``metrics_history.jsonl`` (flush-per-row, skip-unparseable readers —
the repo's jsonl contract), so ``report`` renders rate-over-time and
``watch`` gets real sparkline history instead of two-poll deltas.

Clocks: ring timestamps are monotonic seconds since the history was
created (≈ run start — safe for rates, immune to wall clock steps);
each jsonl row also carries the wall stamp for cross-run correlation.

Aggregation rule: the alert engine and the renderers address metrics by
their BARE registry name (``serve_queue_depth``); a lookup folds every
label set of that name by SUM.  Right for counters and for the
single-series gauges the default rules watch; a per-label rule would
need its own series key (documented limitation, not a trap — rules name
whole metrics).

Counter resets are not unwrapped: a fresh process starts a fresh
registry AND a fresh history, so within one history's lifetime counters
are monotone.
"""

import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

#: sparkline glyphs, one per level (flat series render as all-bottom)
_SPARK = "▁▂▃▄▅▆▇█"

#: the series the file-tail renderers (watch sparklines, report history
#: block) surface by default — bare registry names, summed across labels
DEFAULT_RENDER_SERIES = ("gens_per_sec", "soup_generations_total",
                         "serve_queue_depth", "serve_requests_total",
                         "soup_alerts_active")


def sparkline(values, width: int = 32) -> str:
    """Render a numeric series as a unicode sparkline (last ``width``
    points; empty string for an empty series)."""
    vals = [float(v) for v in values][-max(1, int(width)):]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return _SPARK[0] * len(vals)
    return "".join(_SPARK[int((v - lo) / span * (len(_SPARK) - 1))]
                   for v in vals)


class MetricHistory:
    """Bounded per-series history of one registry.

    >>> h = MetricHistory(registry, capacity=512,
    ...                   path=run_dir + "/metrics_history.jsonl")
    >>> h.sample()                      # once per chunk / dispatch
    >>> h.rate("serve_slo_violations_total", window_s=60.0)

    Ring overflow: each series keeps its newest ``capacity`` points
    (``deque(maxlen=...)``); evicted points are counted in
    ``dropped_points``.  The jsonl stream is append-only and unbounded —
    rotation is the operator's call, and every reader tail-bounds.

    Thread-safety: ``sample`` runs on the run's writer thread (or the
    serve dispatch thread) while exporter handler threads read
    ``latest_sum``/``age_s`` for /healthz — one lock covers the rings.
    """

    def __init__(self, registry, capacity: int = 512,
                 path: Optional[str] = None):
        self.registry = registry
        self.capacity = max(2, int(capacity))
        self.path = path
        self._file = None
        self._rings: Dict[str, deque] = {}
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self.samples_total = 0
        self.dropped_points = 0

    def now(self) -> float:
        return time.monotonic() - self._t0

    # -- producer --------------------------------------------------------

    def sample(self, t: Optional[float] = None, **extra) -> dict:
        """Take one snapshot of the registry: append every series'
        current value to its ring and (when ``path`` is set) one
        ``{"kind": "metrics_history"}`` row to the jsonl stream.
        ``t`` overrides the monotonic stamp (tests)."""
        t = self.now() if t is None else float(t)
        rows = self.registry.rows()
        with self._lock:
            for key, value in rows.items():
                ring = self._rings.get(key)
                if ring is None:
                    ring = self._rings[key] = deque(maxlen=self.capacity)
                if len(ring) == self.capacity:
                    self.dropped_points += 1
                ring.append((t, float(value)))
            self.samples_total += 1
        row = {"kind": "metrics_history", "t": round(t, 3),
               "wall": round(time.time(), 3), "metrics": rows}
        row.update(extra)
        if self.path is not None:
            if self._file is None:
                self._file = open(self.path, "a")
            self._file.write(json.dumps(row) + "\n")
            self._file.flush()
        return row

    # -- readers (bare-name lookups, label sets folded by sum) -----------

    def _matching(self, name: str) -> List[deque]:
        prefix = name if name.startswith("srnn_") else f"srnn_{name}"
        return [ring for key, ring in self._rings.items()
                if key == prefix or key.startswith(prefix + "{")]

    def series(self, name: str) -> List[Tuple[float, float]]:
        """The summed (t, value) trajectory of ``name`` across its label
        sets, on the union of sample stamps (series registered mid-run
        contribute from their first sample on)."""
        with self._lock:
            rings = [list(r) for r in self._matching(name)]
        folded: Dict[float, float] = {}
        for ring in rings:
            for t, v in ring:
                folded[t] = folded.get(t, 0.0) + v
        return sorted(folded.items())

    def latest_sum(self, name: str) -> Optional[float]:
        """Sum of each matching series' NEWEST point (None: never
        sampled)."""
        with self._lock:
            rings = [r for r in self._matching(name) if r]
        if not rings:
            return None
        return sum(r[-1][1] for r in rings)

    def age_s(self, name: str, now: Optional[float] = None) -> Optional[float]:
        """Seconds since ``name`` was last sampled (None: never)."""
        with self._lock:
            rings = [r for r in self._matching(name) if r]
        if not rings:
            return None
        now = self.now() if now is None else float(now)
        return now - max(r[-1][0] for r in rings)

    def rate(self, name: str, window_s: float,
             now: Optional[float] = None) -> Optional[float]:
        """Per-second rate of ``name`` over the trailing window: summed
        first-to-last delta of the in-window points divided by their
        span.  ``None`` until two in-window points exist — an absence of
        evidence, distinct from a measured 0.0."""
        now = self.now() if now is None else float(now)
        cutoff = now - max(1e-9, float(window_s))
        pts = [(t, v) for t, v in self.series(name) if t >= cutoff]
        if len(pts) < 2:
            return None
        span = pts[-1][0] - pts[0][0]
        if span <= 0:
            return None
        return (pts[-1][1] - pts[0][1]) / span

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None


# ---------------------------------------------------------------------------
# metrics_history.jsonl readers (watch sparklines, report history block)
# ---------------------------------------------------------------------------


def load_history_rows(path: str, tail_bytes: Optional[int] = None
                      ) -> List[dict]:
    """Parse ``metrics_history.jsonl`` rows (skip-unparseable — the torn
    tail of a killed run costs its last row, never the reader)."""
    from .fleet import load_rows

    rows, _bad = load_rows(path, 0, tail_bytes=tail_bytes)
    return [r for r in rows if r.get("kind") == "metrics_history"
            and isinstance(r.get("metrics"), dict)]


def _row_sum(row: dict, name: str) -> Optional[float]:
    prefix = f"srnn_{name}"
    vals = [v for k, v in row["metrics"].items()
            if (k == prefix or k.startswith(prefix + "{"))
            and isinstance(v, (int, float))]
    return sum(vals) if vals else None


def summarize_history(path: str, names=DEFAULT_RENDER_SERIES,
                      tail_bytes: Optional[int] = None) -> Optional[dict]:
    """Digest one history stream for the renderers: sample count, span,
    and per selected series first/last/min/max + sparkline (+ the
    first-to-last per-second rate for ``_total`` counters).  ``None``
    when the file is absent/empty — a pre-live-plane run dir is a normal
    state, not an error."""
    rows = load_history_rows(path, tail_bytes=tail_bytes)
    if not rows:
        return None
    t_first, t_last = rows[0].get("t", 0.0), rows[-1].get("t", 0.0)
    span = max(0.0, float(t_last) - float(t_first))
    series = {}
    for name in names:
        pts = [(r.get("t", 0.0), v) for r in rows
               for v in [_row_sum(r, name)] if v is not None]
        if not pts:
            continue
        vals = [v for _t, v in pts]
        d = {"first": round(vals[0], 3), "last": round(vals[-1], 3),
             "min": round(min(vals), 3), "max": round(max(vals), 3),
             "points": len(vals), "spark": sparkline(vals)}
        if name.endswith("_total") and len(pts) >= 2:
            pspan = pts[-1][0] - pts[0][0]
            if pspan > 0:
                d["rate_per_s"] = round((vals[-1] - vals[0]) / pspan, 3)
        series[name] = d
    return {"samples": len(rows), "span_s": round(span, 1),
            "series": series}
