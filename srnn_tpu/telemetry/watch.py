"""Live watch console for runs and services.

    python -m srnn_tpu.telemetry.watch <run_dir> [--interval S] [--once]
    python -m srnn_tpu.telemetry.watch --service SOCKET [--once]

The operator view `tail`-ing heartbeat files by hand used to
approximate: one refresh-loop screen of stage, generation, gens/sec,
health, restarts and last checkpoint across ALL processes of a run
(``telemetry.fleet``'s merged lanes), or — with ``--service`` — a
running experiment service's queue/throughput/SLO state.  ``--once``
prints a single machine-readable JSON snapshot instead (the CI
``observability_smoke`` group and ``scripts/tpu_watch.sh``'s opt-in
poll hook consume it).

Pure reader: file tails and one ``stats`` socket op — attaching a watch
to a live run can never perturb it.  Stdout is this module's product
(it is on the srnnlint prints allowlist).

A JUST-CREATED run dir (no ``events.jsonl`` yet, zero-length or
all-torn files) is a normal state, not an error: ``--once`` snapshots
carry ``no_data: true`` and the refresh view renders an explicit "no
data yet" line (``telemetry.fleet``) instead of a traceback or a
confusing empty table — the watch is typically attached BEFORE the run
heartbeats.
"""

import argparse
import json
import os
import sys
import time

from .fleet import event_paths, fleet_summary, load_rows

_HEALTH_PREFIX = "srnn_soup_health_"

#: the health scan only needs the LAST metrics row, which sits within a
#: handful of rows of the file's end — a bounded tail read keeps the
#: refresh loop off a week-long run's full events.jsonl
_HEALTH_TAIL_BYTES = 262144


def snapshot(run_dir: str) -> dict:
    """One machine-readable fleet snapshot: the merged per-process lanes
    plus liveness (seconds since ANY process wrote an event) and the
    last flushed health gauges.  Cost note: the lane summary reads every
    event file in full (beats/p50 are whole-run statistics); only the
    health scan is tail-bounded."""
    s = fleet_summary(run_dir, timeline_tail=0)
    s.pop("timeline_tail", None)
    mtimes = []
    for path in sorted(event_paths(run_dir).values()):
        try:
            mtimes.append(os.path.getmtime(path))
        except OSError:
            pass
    s["last_event_age_s"] = round(time.time() - max(mtimes), 1) \
        if mtimes else None
    rows, _bad = load_rows(os.path.join(run_dir, "events.jsonl"), 0,
                           tail_bytes=_HEALTH_TAIL_BYTES)
    s["health"] = None
    for row in reversed(rows):
        if row.get("kind") == "metrics":
            health = {k[len(_HEALTH_PREFIX):]: v
                      for k, v in (row.get("metrics") or {}).items()
                      if k.startswith(_HEALTH_PREFIX)}
            if health:
                s["health"] = health
            break
    return s


def render(s: dict, out) -> None:
    from .fleet import render_fleet

    age = s.get("last_event_age_s")
    out.write(time.strftime("-- watch %H:%M:%S ")
              + (f"(last event {age}s ago)" if age is not None
                 else "(no events yet)") + "\n")
    body = dict(s, timeline_tail=[])
    render_fleet(body, out)
    health = s.get("health")
    if health:
        cells = "  ".join(f"{k}={v}" for k, v in sorted(health.items()))
        out.write(f"health: {cells}\n")


# ---------------------------------------------------------------------------
# service mode
# ---------------------------------------------------------------------------


def service_snapshot(socket_path: str) -> dict:
    """One ``stats`` round trip to a running experiment service."""
    from ..serve.client import ServiceClient

    stats = ServiceClient(socket_path, timeout_s=10.0).stats()
    out = {"socket": socket_path,
           "completed": stats.get("completed"),
           "queue_depth": stats.get("queue_depth"),
           "distinct_programs": stats.get("distinct_programs"),
           "uptime_s": stats.get("uptime_s"),
           "slo": stats.get("slo"),
           "self_healing": stats.get("self_healing")}
    uptime = stats.get("uptime_s") or 0
    out["requests_per_sec"] = round(stats.get("completed", 0) / uptime, 3) \
        if uptime > 0 else 0.0
    return out


def render_service(s: dict, out) -> None:
    out.write(time.strftime("-- watch %H:%M:%S ")
              + f"service {s['socket']}\n")
    out.write(f"  completed={s['completed']}  queue={s['queue_depth']}  "
              f"{s['requests_per_sec']} req/s over {s['uptime_s']}s  "
              f"programs={s['distinct_programs']}\n")
    slo = s.get("slo")
    if slo:
        target = slo.get("target_p95_ms")
        p95 = slo.get("p95_ms")
        out.write("  SLO: "
                  + (f"p95<={target}ms target, " if target else "no target, ")
                  + (f"measured p95~{p95}ms, " if p95 is not None else "")
                  + f"{slo.get('violations', 0)} violation(s)\n")
    sh = s.get("self_healing")
    if sh:
        mq = sh.get("max_queue")
        out.write(f"  self-heal: journal={sh.get('journal_unfinished')} "
                  f"unfinished, {sh.get('replayed')} replayed, "
                  f"{sh.get('quarantined')} quarantined, "
                  f"{sh.get('dispatch_retries')} retries\n")
        out.write(f"  admission: "
                  + (f"max_queue={mq}, " if mq else "unbounded queue, ")
                  + f"{sh.get('overload_rejections')} overload "
                    f"rejection(s), {sh.get('deadline_expirations')} "
                    f"deadline expiration(s), "
                    f"{sh.get('results_evicted')} result(s) evicted\n")


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("run_dir", nargs="?", default=None,
                   help="an Experiment run directory (fleet lanes view)")
    p.add_argument("--service", default=None, metavar="SOCKET",
                   help="watch a running experiment service's stats/"
                        "queue/SLO state instead of (or as well as) a "
                        "run dir")
    p.add_argument("--interval", type=float, default=5.0, metavar="S",
                   help="refresh period of the watch loop")
    p.add_argument("--once", action="store_true",
                   help="print one JSON snapshot and exit (machine-"
                        "readable; what the CI smoke and the tpu_watch "
                        "poll hook consume)")
    args = p.parse_args(argv)
    if not args.run_dir and not args.service:
        p.error("give a run_dir, --service SOCKET, or both")
    if args.run_dir and not os.path.isdir(args.run_dir):
        print(f"watch: {args.run_dir}: not a directory", file=sys.stderr)
        return 2

    def take():
        snap = {}
        if args.run_dir:
            snap = snapshot(args.run_dir)
        if args.service:
            try:
                snap["service"] = service_snapshot(args.service)
            except Exception as e:
                snap["service"] = {"socket": args.service,
                                   "error": f"{type(e).__name__}: {e}"}
        return snap

    if args.once:
        print(json.dumps(take(), indent=1, default=str))
        return 0
    try:
        while True:
            snap = take()
            if args.run_dir:
                render(snap, sys.stdout)
            svc = snap.get("service")
            if svc:
                if "error" in svc:
                    print(f"service: {svc['error']}")
                else:
                    render_service(svc, sys.stdout)
            sys.stdout.flush()
            time.sleep(max(0.2, args.interval))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
