"""Live watch console for runs and services.

    python -m srnn_tpu.telemetry.watch <run_dir> [--interval S] [--once]
    python -m srnn_tpu.telemetry.watch --service SOCKET [--once]
    python -m srnn_tpu.telemetry.watch --url http://host:port [--once]
    python -m srnn_tpu.telemetry.watch <results_root> --archive [--once]

The operator view `tail`-ing heartbeat files by hand used to
approximate: one refresh-loop screen of stage, generation, gens/sec,
health, restarts and last checkpoint across ALL processes of a run
(``telemetry.fleet``'s merged lanes), or — with ``--service`` — a
running experiment service's queue/throughput/SLO state.  ``--once``
prints a single machine-readable JSON snapshot instead (the CI
``observability_smoke``/``alerts_smoke`` groups and
``scripts/tpu_watch.sh``'s opt-in poll hook consume it).

Live telemetry plane (PR 15): run dirs additionally render an
ACTIVE-ALERTS panel (the ``{"kind": "alert"}`` rows the alert engine
streams into events.jsonl — tail-bounded, last state per rule wins) and
real sparkline history from ``metrics_history.jsonl`` instead of
two-poll deltas.  ``--url`` consumes a live exporter endpoint
(``telemetry.exporter``: ``/healthz`` + ``/metrics``) as an alternative
to run-dir polling — same render loop, same ``--once`` JSON.
**Precedence**: when both a run_dir and ``--url`` are given, the URL is
the authority for liveness and active alerts (it reads the process's
registry directly; files lag by up to one chunk) and renders first; the
run-dir lanes view still follows for per-process detail.

``--archive`` flips the positional to a RESULTS ROOT and renders the
cross-run observatory live (``telemetry.archive``): each refresh runs
one incremental ingest (watermarked — an unchanged root costs stat
calls only) and redraws the run table, campaign rollups and drift
verdicts.  This is the fleet-level panel: which arms finished, which
wedged, which campaign is drifting — without attaching to any one run.

Pure reader: file tails, one ``stats`` socket op, or one HTTP GET pair —
attaching a watch to a live run can never perturb it (``--archive``
writes only to the store dir OUTSIDE every run dir).  Stdout is this
module's product (it is on the srnnlint prints allowlist).

A JUST-CREATED run dir (no ``events.jsonl`` yet, zero-length or
all-torn files) is a normal state, not an error: ``--once`` snapshots
carry ``no_data: true`` and the refresh view renders an explicit "no
data yet" line (``telemetry.fleet``) instead of a traceback or a
confusing empty table — the watch is typically attached BEFORE the run
heartbeats.
"""

import argparse
import json
import os
import sys
import time

from .fleet import event_paths, fleet_summary, load_rows

_HEALTH_PREFIX = "srnn_soup_health_"
_UTIL_PREFIX = "srnn_soup_utilization_"

#: the health/alert scan only needs the LAST rows, which sit within a
#: handful of rows of the file's end — a bounded tail read keeps the
#: refresh loop off a week-long run's full events.jsonl
_HEALTH_TAIL_BYTES = 262144

#: metrics_history.jsonl sparklines read the same bounded tail
_HISTORY_TAIL_BYTES = 262144


def _alerts_from_rows(rows) -> dict:
    """Fold alert transition rows (file order) into the panel state:
    last state per rule wins; ``fired`` counts the firing edges."""
    state = {}
    fired = 0
    for row in rows:
        if row.get("kind") != "alert" or not row.get("rule"):
            continue
        if row.get("state") == "firing":
            fired += 1
        state[str(row["rule"])] = row.get("state")
    return {"fired": fired,
            "active": sorted(r for r, st in state.items()
                             if st == "firing")}


def _alert_rows(path) -> list:
    """Every alert transition row of one events file — a FULL read, not
    a tail: rules LATCH, so a long-lived alert is exactly one firing
    row, and a tail bound would silently drop it from the panel while
    the condition still holds.  The substring filter keeps the scan one
    cheap pass (alert rows are rare; the lane summary already reads the
    same file in full), json-parsing only matching lines."""
    rows = []
    try:
        with open(path) as f:
            for line in f:
                if '"kind": "alert"' not in line:
                    continue
                try:
                    rows.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return rows


def snapshot(run_dir: str) -> dict:
    """One machine-readable fleet snapshot: the merged per-process lanes
    plus liveness (seconds since ANY process wrote an event), the last
    flushed health gauges, the active-alerts panel, and sparkline
    history when the run streams ``metrics_history.jsonl``.  Cost note:
    the lane summary reads every event file in full (beats/p50 are
    whole-run statistics) and the alert fold re-reads the primary's in
    full through a cheap line filter (rules latch — the one firing row
    of a long-lived alert must not scroll out of a tail); the
    health/history scans are tail-bounded."""
    from .timeseries import summarize_history

    s = fleet_summary(run_dir, timeline_tail=0)
    s.pop("timeline_tail", None)
    mtimes = []
    for path in sorted(event_paths(run_dir).values()):
        try:
            mtimes.append(os.path.getmtime(path))
        except OSError:
            pass
    s["last_event_age_s"] = round(time.time() - max(mtimes), 1) \
        if mtimes else None
    rows, _bad = load_rows(os.path.join(run_dir, "events.jsonl"), 0,
                           tail_bytes=_HEALTH_TAIL_BYTES)
    s["health"] = None
    s["utilization"] = None
    for row in reversed(rows):
        if row.get("kind") == "metrics":
            metrics = row.get("metrics") or {}
            health = {k[len(_HEALTH_PREFIX):]: v
                      for k, v in metrics.items()
                      if k.startswith(_HEALTH_PREFIX)}
            if health:
                s["health"] = health
            # the profiling plane's per-chunk decomposition (PR 20):
            # device-busy / host-blocked / idle fractions of the last
            # flushed chunk
            util = {k[len(_UTIL_PREFIX):]: v for k, v in metrics.items()
                    if k.startswith(_UTIL_PREFIX)}
            if util:
                s["utilization"] = util
            break
    # alert rows are primary-only (one alert stream per run) — full
    # line-filtered scan of events.jsonl, NOT the health tail above
    s["alerts"] = _alerts_from_rows(
        _alert_rows(os.path.join(run_dir, "events.jsonl")))
    s["history"] = summarize_history(
        os.path.join(run_dir, "metrics_history.jsonl"),
        tail_bytes=_HISTORY_TAIL_BYTES)
    return s


def render(s: dict, out) -> None:
    from .fleet import render_fleet

    age = s.get("last_event_age_s")
    out.write(time.strftime("-- watch %H:%M:%S ")
              + (f"(last event {age}s ago)" if age is not None
                 else "(no events yet)") + "\n")
    body = dict(s, timeline_tail=[])
    render_fleet(body, out)
    health = s.get("health")
    if health:
        cells = "  ".join(f"{k}={v}" for k, v in sorted(health.items()))
        out.write(f"health: {cells}\n")
    util = s.get("utilization")
    if util:
        cells = "  ".join(f"{k}={round(100 * v, 1)}%"
                          for k, v in sorted(util.items()))
        out.write(f"utilization: {cells}\n")
    render_alerts(s.get("alerts"), out)
    hist = s.get("history")
    if hist and hist.get("series"):
        for name, d in sorted(hist["series"].items()):
            out.write(f"history {name}: {d['spark']} last={d['last']}"
                      + (f" ({d['rate_per_s']}/s)"
                         if "rate_per_s" in d else "") + "\n")


def render_alerts(alerts, out) -> None:
    """The active-alerts panel (shared by the run-dir, service and URL
    views).  Accepts either the file-tail shape ({active: [names],
    fired: n}) or the engine/stats shape ({active: [dicts], fired: n});
    silent when the run has no alert trail at all."""
    if not alerts:
        return
    active = alerts.get("active") or []
    names = [a["rule"] if isinstance(a, dict) else str(a) for a in active]
    if names:
        out.write("ALERTS: " + ", ".join(names)
                  + f"  ({alerts.get('fired', len(names))} firing "
                    "transition(s))\n")
    elif alerts.get("fired"):
        out.write(f"alerts: none active ({alerts['fired']} fired, "
                  "all cleared)\n")


# ---------------------------------------------------------------------------
# service mode
# ---------------------------------------------------------------------------


def service_snapshot(socket_path: str) -> dict:
    """One ``stats`` round trip to a running experiment service."""
    from ..serve.client import ServiceClient

    stats = ServiceClient(socket_path, timeout_s=10.0).stats()
    out = {"socket": socket_path,
           "completed": stats.get("completed"),
           "queue_depth": stats.get("queue_depth"),
           "distinct_programs": stats.get("distinct_programs"),
           "uptime_s": stats.get("uptime_s"),
           "slo": stats.get("slo"),
           "alerts": stats.get("alerts"),
           "self_healing": stats.get("self_healing"),
           # continuous batching + fleet (PR 16): the adaptive window
           # snapshot, and — pool fronts — the per-worker rows
           "dispatch": stats.get("dispatch"),
           "front": stats.get("front"),
           "fleet": stats.get("fleet"),
           # fleet tracing (PR 17): the slowest retained traces
           "slowest": stats.get("slowest")}
    uptime = stats.get("uptime_s") or 0
    out["requests_per_sec"] = round(stats.get("completed", 0) / uptime, 3) \
        if uptime > 0 else 0.0
    return out


def render_service(s: dict, out) -> None:
    out.write(time.strftime("-- watch %H:%M:%S ")
              + f"service {s['socket']}\n")
    out.write(f"  completed={s['completed']}  queue={s['queue_depth']}  "
              f"{s['requests_per_sec']} req/s over {s['uptime_s']}s  "
              f"programs={s['distinct_programs']}\n")
    d = s.get("dispatch")
    if d:
        if d.get("adaptive"):
            lo, hi = d.get("window_min_s"), d.get("window_max_s")
            out.write(f"  dispatch: adaptive window "
                      f"[{lo if lo is not None else '-'}s"
                      f"..{hi if hi is not None else '-'}s] over "
                      f"{d.get('groups', 0)} group(s), "
                      f"ceiling={d.get('ceiling_s')}s"
                      + (", fair tenants" if d.get("fair_tenants")
                         else "") + "\n")
        else:
            out.write("  dispatch: fixed window (adaptive off)\n")
    front = s.get("front")
    if front:
        out.write(f"  front: {front.get('workers')} worker(s) live, "
                  f"{front.get('admitted')} admitted, "
                  f"{front.get('deaths')} death(s), "
                  f"{front.get('replayed')} ticket(s) replayed\n")
    fleet = s.get("fleet")
    if fleet:
        for name, w in sorted(fleet.items()):
            if not w.get("alive"):
                out.write(f"  {name}: DEAD (pid {w.get('pid')})\n")
                continue
            win = w.get("window_s")
            out.write(
                f"  {name}: queue={w.get('queue_depth')} "
                f"inflight={w.get('inflight') or 0:g} "
                f"window={win if win is not None else '-'}s "
                f"completed={w.get('completed')} "
                f"replayed={w.get('replayed')}\n")
    slo = s.get("slo")
    if slo:
        target = slo.get("target_p95_ms")
        p95 = slo.get("p95_ms")
        out.write("  SLO: "
                  + (f"p95<={target}ms target, " if target else "no target, ")
                  + (f"measured p95~{p95}ms, " if p95 is not None else "")
                  + f"{slo.get('violations', 0)} violation(s)\n")
    slowest = s.get("slowest")
    if slowest:
        out.write("  slowest traces (report --trace-request <ticket>):\n")
        for e in slowest:
            flags = "".join(
                tag for tag, on in ((" SLO", e.get("slo_violation")),
                                    (" FAILED", e.get("failed")),
                                    (" QUARANTINED", e.get("quarantined")),
                                    (" replayed", e.get("replays")))
                if on)
            where = f" @{e['worker']}" if e.get("worker") else ""
            out.write(f"    {e.get('ticket')}: "
                      f"{float(e.get('seconds') or 0.0):.4f}s "
                      f"{e.get('kind')}/{e.get('tenant')}{where}"
                      f"{flags}\n")
    render_alerts(s.get("alerts"), out)
    sh = s.get("self_healing")
    if sh:
        mq = sh.get("max_queue")
        out.write(f"  self-heal: journal={sh.get('journal_unfinished')} "
                  f"unfinished, {sh.get('replayed')} replayed, "
                  f"{sh.get('quarantined')} quarantined, "
                  f"{sh.get('dispatch_retries')} retries\n")
        out.write(f"  admission: "
                  + (f"max_queue={mq}, " if mq else "unbounded queue, ")
                  + f"{sh.get('overload_rejections')} overload "
                    f"rejection(s), {sh.get('deadline_expirations')} "
                    f"deadline expiration(s), "
                    f"{sh.get('results_evicted')} result(s) evicted\n")


# ---------------------------------------------------------------------------
# live endpoint mode (--url, telemetry.exporter)
# ---------------------------------------------------------------------------

#: exposition prefixes the URL view surfaces (a scrape carries hundreds
#: of series; the console shows the operator's first questions)
_URL_METRIC_PREFIXES = ("srnn_heartbeat_generation", "srnn_gens_per_sec",
                        "srnn_serve_queue_depth", "srnn_serve_requests",
                        "srnn_soup_generations_total",
                        "srnn_soup_alerts_active",
                        "srnn_soup_health_nan_frac")


def parse_prometheus(text: str) -> dict:
    """Minimal text-format parse: ``{name{labels}: float}`` rows, comment
    and malformed lines skipped (a live scrape is never torn — the
    exporter writes whole bodies — but the parser stays defensive)."""
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name, _sep, value = line.rpartition(" ")
        try:
            out[name] = float(value)
        except ValueError:
            continue
    return out


def url_snapshot(url: str, timeout_s: float = 5.0) -> dict:
    """One /healthz + /metrics round trip to a live exporter."""
    import urllib.error
    import urllib.request

    base = url.rstrip("/")
    try:
        with urllib.request.urlopen(base + "/healthz",
                                    timeout=timeout_s) as r:
            health = json.load(r)
    except urllib.error.HTTPError as e:
        # 503 = the endpoint is up and says NOT healthy — that IS a
        # snapshot, not a transport failure
        health = json.loads(e.read().decode("utf-8", "replace") or "{}")
    with urllib.request.urlopen(base + "/metrics", timeout=timeout_s) as r:
        series = parse_prometheus(r.read().decode("utf-8", "replace"))
    return {"url": base, "healthz": health,
            "metric_series": len(series),
            "metrics": {k: v for k, v in sorted(series.items())
                        if k.startswith(_URL_METRIC_PREFIXES)}}


def render_url(s: dict, out) -> None:
    hz = s.get("healthz") or {}
    out.write(time.strftime("-- watch %H:%M:%S ")
              + f"live {s['url']} "
              + ("[ok]" if hz.get("ok") else "[NOT OK]") + "\n")
    bits = [f"{k}={hz[k]}" for k in ("stage", "uptime_s", "scrapes")
            if hz.get(k) is not None]
    if bits:
        out.write("  " + "  ".join(bits) + "\n")
    workers = hz.get("workers")
    if workers:
        cells = "  ".join(
            f"p{p}:{'ok' if w.get('ok') else 'STALE'}"
            + (f"({w['age_s']}s)" if w.get("age_s") is not None else "")
            for p, w in sorted(workers.items(), key=lambda kv: int(kv[0])))
        out.write(f"  workers: {cells}\n")
    active = hz.get("active_alerts")
    if active is not None:
        render_alerts({"active": active, "fired": len(active)}, out)
    for name, value in (s.get("metrics") or {}).items():
        out.write(f"  {name} = {value}\n")


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("run_dir", nargs="?", default=None,
                   help="an Experiment run directory (fleet lanes view)")
    p.add_argument("--service", default=None, metavar="SOCKET",
                   help="watch a running experiment service's stats/"
                        "queue/SLO state instead of (or as well as) a "
                        "run dir")
    p.add_argument("--url", default=None, metavar="URL",
                   help="consume a live exporter endpoint "
                        "(http://host:port — telemetry.exporter's "
                        "/healthz + /metrics) instead of run-dir "
                        "polling; when BOTH are given the URL wins for "
                        "liveness and active alerts (the registry is "
                        "the authority; files lag by up to one chunk) "
                        "and the run-dir lanes render after it")
    p.add_argument("--archive", action="store_true",
                   help="treat run_dir as a RESULTS ROOT and render the "
                        "cross-run observatory (telemetry.archive): one "
                        "incremental ingest + run table per refresh")
    p.add_argument("--interval", type=float, default=5.0, metavar="S",
                   help="refresh period of the watch loop")
    p.add_argument("--once", action="store_true",
                   help="print one JSON snapshot and exit (machine-"
                        "readable; what the CI smokes and the tpu_watch "
                        "poll hook consume)")
    args = p.parse_args(argv)
    if not args.run_dir and not args.service and not args.url:
        p.error("give a run_dir, --service SOCKET, --url URL, or a "
                "combination")
    if args.run_dir and not os.path.isdir(args.run_dir):
        print(f"watch: {args.run_dir}: not a directory", file=sys.stderr)
        return 2

    if args.archive and not args.run_dir:
        p.error("--archive needs a results-root positional")

    def take():
        snap = {}
        if args.archive:
            # the root is a directory OF run dirs, not a run dir — the
            # archive doc replaces the lanes view entirely
            from .archive import runs_doc

            snap["archive"] = runs_doc(args.run_dir)
        elif args.run_dir:
            snap = snapshot(args.run_dir)
        if args.url:
            try:
                snap["live"] = url_snapshot(args.url)
            except Exception as e:
                snap["live"] = {"url": args.url,
                                "error": f"{type(e).__name__}: {e}"}
        if args.service:
            try:
                snap["service"] = service_snapshot(args.service)
            except Exception as e:
                snap["service"] = {"socket": args.service,
                                   "error": f"{type(e).__name__}: {e}"}
        return snap

    if args.once:
        print(json.dumps(take(), indent=1, default=str))
        return 0
    try:
        while True:
            snap = take()
            live = snap.get("live")
            if live:  # the URL is the liveness authority: renders first
                if "error" in live:
                    print(f"live: {live['error']}")
                else:
                    render_url(live, sys.stdout)
            if args.archive:
                from .archive import render_table

                sys.stdout.write(time.strftime("-- watch %H:%M:%S "
                                               "archive --\n"))
                render_table(snap["archive"], sys.stdout)
            elif args.run_dir:
                render(snap, sys.stdout)
            svc = snap.get("service")
            if svc:
                if "error" in svc:
                    print(f"service: {svc['error']}")
                else:
                    render_service(svc, sys.stdout)
            sys.stdout.flush()
            time.sleep(max(0.2, args.interval))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
