"""Span tracing: wall-clock phase timing layered on ``jax.named_scope`` +
``jax.profiler``, plus the fleet observatory's structured span records.

Four layers, cheapest first:

  * :func:`annotate` (= ``jax.named_scope``) — zero-cost trace-time
    annotation: phases show up as named scopes in HLO metadata and
    profiler traces.  The soup/engine step functions annotate their
    attack/learn/train/respawn phases with it directly.
  * :func:`span` — host-side wall-clock timing of a code block, recorded
    into a registry histogram (``srnn_span_seconds{span=...}``) and
    optionally as an ``events.jsonl`` row.  Synchronization is by scalar
    readback (``Span.sync``), not ``block_until_ready`` — on the tunneled
    axon platform the latter does not actually wait (the caveat
    documented in ``utils/profiling.py`` and ``bench.py``).
  * :class:`SpanStream` — run-scoped STRUCTURED span records
    (``trace_id``/``span_id``/``parent``, run-relative monotonic start +
    duration, emitting process) appended as ``{"kind": "span", ...}``
    rows through the run's event channel, riding the
    ``BackgroundWriter`` when one is attached so emission costs an
    enqueue on the hot path, never an fsync.  These are what
    ``telemetry.fleet`` merges into the cross-process run timeline.
  * ``trace`` (re-exported from ``utils.profiling``) — a full
    ``jax.profiler`` device/host trace into a TensorBoard-loadable
    directory, for when a span points at a phase worth opening up.
"""

import contextlib
import itertools
import threading
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..utils.profiling import trace  # noqa: F401  (re-export)
from .metrics import MetricsRegistry, RUNTIME

#: zero-cost phase annotation (alias of ``jax.named_scope``): visible in
#: profiler traces and HLO metadata, no runtime effect.
annotate = jax.named_scope


def _readback(value: Any) -> None:
    """Force completion of ``value``'s computation via a scalar readback
    (the axon-safe synchronization primitive)."""
    leaves = jax.tree.leaves(value)
    if leaves:
        float(jnp.asarray(leaves[0]).ravel()[0])


class Span:
    """The in-flight record :func:`span` yields; ``seconds`` is set on
    exit.  Call :meth:`sync` with any array/pytree whose computation the
    span must wait for — it is read back (one scalar) at exit."""

    __slots__ = ("name", "seconds", "_sync_value")

    def __init__(self, name: str):
        self.name = name
        self.seconds: Optional[float] = None
        self._sync_value: Any = None

    def sync(self, value):
        """Register ``value`` for completion-sync at span exit; returns it
        unchanged so call sites stay one-liners."""
        self._sync_value = value
        return value


@contextlib.contextmanager
def span(name: str, registry: Optional[MetricsRegistry] = None,
         exp=None, **labels):
    """Time a block of host code (usually: one or more jitted dispatches).

    >>> with span("soup.chunk", registry=reg, exp=exp) as s:
    ...     state = s.sync(evolve_donated(cfg, state, generations=100))

    Enters ``jax.named_scope(name)`` (so any tracing inside the block is
    annotated), measures wall seconds with the work force-completed via
    scalar readback when :meth:`Span.sync` was called, then records the
    duration into ``registry``'s ``span_seconds`` histogram (label
    ``span=name`` + any extra labels; default registry: the process
    ``RUNTIME``) and, when ``exp`` is given, appends a
    ``{"kind": "span", ...}`` row to its ``events.jsonl``.
    """
    reg = RUNTIME if registry is None else registry
    s = Span(name)
    with jax.named_scope(name):
        t0 = time.perf_counter()
        try:
            yield s
        finally:
            if s._sync_value is not None:
                _readback(s._sync_value)
            s.seconds = time.perf_counter() - t0
            reg.histogram(
                "span_seconds",
                help="wall-clock seconds of telemetry.span blocks",
                unit="seconds").observe(s.seconds, span=name, **labels)
            if exp is not None:
                exp.event(kind="span", span=name,
                          seconds=round(s.seconds, 6), **labels)


class SpanStream:
    """Run-scoped structured span emitter for the fleet observatory.

    Where :func:`span` records an anonymous wall-clock histogram sample,
    a :class:`SpanStream` row is a first-class trace record:

      * ``trace_id`` — stable for the whole run (the run-dir basename for
        mega runs, the ticket id for serve requests), so every process's
        rows correlate after the fleet merge;
      * ``span_id`` — monotone per (process, stream); ``parent`` links
        children (e.g. a chunk's ``device_wait``/``host_io`` halves) to
        their enclosing span;
      * ``start_s``/``seconds`` — run-relative MONOTONIC start and
        duration (``time.monotonic`` deltas, immune to wall-clock steps);
      * ``process`` — the emitting process, so a worker's rows (written
        to its ``events-p<i>.jsonl`` via ``WorkerLog``) stay attributable
        in the merged timeline.

    Rows ride ``exp.event`` (``Experiment`` or ``WorkerLog`` — both take
    ``kind=``/fields), optionally through a ``BackgroundWriter`` so the
    producing thread only enqueues; the ``span_seconds`` histogram
    (label ``span=name``) is folded on the same job.  Emission is
    host-only by construction — a stream never touches device values, so
    spans can NEVER perturb run results (asserted in
    ``tests/test_fleet.py``).
    """

    def __init__(self, exp, trace_id: str, process: int = 0,
                 writer=None, registry: Optional[MetricsRegistry] = None):
        self.exp = exp
        self.trace_id = str(trace_id)
        self.process = int(process)
        self.writer = writer
        self.registry = registry
        self._t0 = time.monotonic()
        self._ids = itertools.count(1)
        self._lock = threading.Lock()

    def now(self) -> float:
        """Run-relative monotonic seconds (the ``start_s`` clock)."""
        return time.monotonic() - self._t0

    def emit(self, name: str, start_s: float, dur_s: float,
             parent: Optional[int] = None, **labels) -> int:
        """Record one finished span; returns its ``span_id`` (for use as
        a later child's ``parent``).  All values are precomputed here —
        the sink job only appends."""
        with self._lock:
            sid = next(self._ids)
        row = dict(span=name, trace_id=self.trace_id, span_id=sid,
                   process=self.process, start_s=round(float(start_s), 6),
                   seconds=round(float(dur_s), 6), **labels)
        if parent is not None:
            row["parent"] = int(parent)

        def sink():
            self.exp.event(kind="span", **row)
            if self.registry is not None:
                self.registry.histogram(
                    "span_seconds",
                    help="wall-clock seconds of telemetry.span blocks",
                    unit="seconds").observe(row["seconds"], span=name)

        if self.writer is not None:
            self.writer.submit(sink)
        else:
            sink()
        return sid

    @contextlib.contextmanager
    def timed(self, name: str, parent: Optional[int] = None, **labels):
        """Context-manager spelling of :meth:`emit` for host code whose
        bounds are the block itself (collective gathers, store flushes).
        Yields a dict the block may add labels to."""
        start = self.now()
        extra: dict = {}
        try:
            yield extra
        finally:
            self.emit(name, start, self.now() - start, parent=parent,
                      **{**labels, **extra})
