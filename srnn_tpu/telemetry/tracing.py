"""Span tracing: wall-clock phase timing layered on ``jax.named_scope`` +
``jax.profiler``.

Three layers, cheapest first:

  * :func:`annotate` (= ``jax.named_scope``) — zero-cost trace-time
    annotation: phases show up as named scopes in HLO metadata and
    profiler traces.  The soup/engine step functions annotate their
    attack/learn/train/respawn phases with it directly.
  * :func:`span` — host-side wall-clock timing of a code block, recorded
    into a registry histogram (``srnn_span_seconds{span=...}``) and
    optionally as an ``events.jsonl`` row.  Synchronization is by scalar
    readback (``Span.sync``), not ``block_until_ready`` — on the tunneled
    axon platform the latter does not actually wait (the caveat
    documented in ``utils/profiling.py`` and ``bench.py``).
  * ``trace`` (re-exported from ``utils.profiling``) — a full
    ``jax.profiler`` device/host trace into a TensorBoard-loadable
    directory, for when a span points at a phase worth opening up.
"""

import contextlib
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..utils.profiling import trace  # noqa: F401  (re-export)
from .metrics import MetricsRegistry, RUNTIME

#: zero-cost phase annotation (alias of ``jax.named_scope``): visible in
#: profiler traces and HLO metadata, no runtime effect.
annotate = jax.named_scope


def _readback(value: Any) -> None:
    """Force completion of ``value``'s computation via a scalar readback
    (the axon-safe synchronization primitive)."""
    leaves = jax.tree.leaves(value)
    if leaves:
        float(jnp.asarray(leaves[0]).ravel()[0])


class Span:
    """The in-flight record :func:`span` yields; ``seconds`` is set on
    exit.  Call :meth:`sync` with any array/pytree whose computation the
    span must wait for — it is read back (one scalar) at exit."""

    __slots__ = ("name", "seconds", "_sync_value")

    def __init__(self, name: str):
        self.name = name
        self.seconds: Optional[float] = None
        self._sync_value: Any = None

    def sync(self, value):
        """Register ``value`` for completion-sync at span exit; returns it
        unchanged so call sites stay one-liners."""
        self._sync_value = value
        return value


@contextlib.contextmanager
def span(name: str, registry: Optional[MetricsRegistry] = None,
         exp=None, **labels):
    """Time a block of host code (usually: one or more jitted dispatches).

    >>> with span("soup.chunk", registry=reg, exp=exp) as s:
    ...     state = s.sync(evolve_donated(cfg, state, generations=100))

    Enters ``jax.named_scope(name)`` (so any tracing inside the block is
    annotated), measures wall seconds with the work force-completed via
    scalar readback when :meth:`Span.sync` was called, then records the
    duration into ``registry``'s ``span_seconds`` histogram (label
    ``span=name`` + any extra labels; default registry: the process
    ``RUNTIME``) and, when ``exp`` is given, appends a
    ``{"kind": "span", ...}`` row to its ``events.jsonl``.
    """
    reg = RUNTIME if registry is None else registry
    s = Span(name)
    with jax.named_scope(name):
        t0 = time.perf_counter()
        try:
            yield s
        finally:
            if s._sync_value is not None:
                _readback(s._sync_value)
            s.seconds = time.perf_counter() - t0
            reg.histogram(
                "span_seconds",
                help="wall-clock seconds of telemetry.span blocks",
                unit="seconds").observe(s.seconds, span=name, **labels)
            if exp is not None:
                exp.event(kind="span", span=name,
                          seconds=round(s.seconds, 6), **labels)
