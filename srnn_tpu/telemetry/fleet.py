"""Fleet aggregation: one ordered timeline from a distributed run dir.

A multi-process run (``distributed.launch``) writes its host artifacts
through process 0 (DESIGN §16) — but liveness is per process: process 0's
heartbeats/spans ride ``events.jsonl`` while every worker streams its own
``events-p<i>.jsonl``.  Until this module, nothing merged them: a run dir
rendered as a single-process run and straggler questions ("which process
holds the fleet back, and by how much?") needed hand-`tail`-ing files.

Three jobs, all host-side reads (no jax import, safe from any thread):

  * **Merge** — :func:`merged_timeline` folds process-0 events + all
    worker event files into ONE ordered timeline.  Ordering rule: rows
    sort by ``(t, process, file-order)`` where ``t`` is each process's
    run-relative stamp (processes start within the bring-up window of
    each other, so cross-process ``t`` is comparable to well under one
    chunk — good enough for lane views, documented as approximate for
    anything finer).  Unparseable lines (the torn tail of a killed or
    still-writing file) are SKIPPED and counted, never fatal.
  * **Straggler attribution** — per-process gens/sec skew from the
    heartbeat lanes: who is slowest, how far they trail the leader, and
    the per-process rates — exported as the ``soup_straggler_*`` gauges
    (:func:`update_straggler_gauges`; the mega loops fold them live each
    chunk via :func:`live_attribution`, so ``metrics.prom`` shows the
    CURRENT straggler during the run, not just post-mortem).
  * **Summaries** — :func:`fleet_summary` (the ``report --fleet`` and
    ``telemetry.watch`` backend) with a per-process lane view rendered
    by :func:`render_fleet`.
"""

import glob
import json
import os
import re
from typing import Dict, List, Optional, Tuple

from .metrics import quantile_from_times

_WORKER_RE = re.compile(r"^events-p(\d+)\.jsonl$")

#: live_attribution reads only this many trailing bytes per file — the
#: last few heartbeats are all it needs, and a week-long run's event file
#: must not be re-read in full every chunk
_TAIL_BYTES = 32768


def worker_event_paths(run_dir: str) -> Dict[int, str]:
    """``{process_id: path}`` for every ``events-p<i>.jsonl`` present."""
    out: Dict[int, str] = {}
    try:
        names = os.listdir(run_dir)
    except OSError:
        return out
    for name in names:
        m = _WORKER_RE.match(name)
        if m:
            out[int(m.group(1))] = os.path.join(run_dir, name)
    return out


_POOL_WORKER_RE = re.compile(r"^w(\d+)$")


def pool_worker_event_paths(run_dir: str) -> Dict[int, str]:
    """``{process_id: path}`` for a serve-POOL layout: the front's
    ``workers/w<i>/events.jsonl`` sub-roots map to process lanes
    ``i + 1`` (the front itself is process 0, like the mega primary).
    Empty for non-pool run dirs, so the mega layout is untouched."""
    out: Dict[int, str] = {}
    wdir = os.path.join(run_dir, "workers")
    try:
        names = os.listdir(wdir)
    except OSError:
        return out
    for name in names:
        m = _POOL_WORKER_RE.match(name)
        if not m:
            continue
        path = os.path.join(wdir, name, "events.jsonl")
        if os.path.exists(path):
            out[int(m.group(1)) + 1] = path
    return out


def load_rows(path: str, process: int, tail_bytes: Optional[int] = None,
              force_process: bool = False) -> Tuple[List[dict], int]:
    """Parse one jsonl event file into rows tagged with ``process``;
    returns ``(rows, skipped)`` where ``skipped`` counts unparseable
    lines (torn tails, mid-write reads).  ``tail_bytes`` reads only the
    file's end (the live-watch path); the first tail line is dropped as
    potentially clipped.  ``force_process`` OVERRIDES each row's own
    ``process`` field — pool workers are solo services that stamp
    ``process: 0`` into their rows, and their lane identity lives in
    the fleet's file layout, not the rows."""
    rows: List[dict] = []
    skipped = 0
    try:
        with open(path, "rb") as f:
            if tail_bytes:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - tail_bytes))
                data = f.read()
                if size > tail_bytes:
                    data = data.split(b"\n", 1)[-1]
            else:
                data = f.read()
    except OSError:
        return rows, skipped
    for line in data.decode("utf-8", "replace").splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            skipped += 1
            continue
        if not isinstance(row, dict):
            skipped += 1
            continue
        if force_process:
            row["process"] = process
        else:
            row.setdefault("process", process)
        rows.append(row)
    return rows, skipped


def event_paths(run_dir: str) -> Dict[int, str]:
    """Every process's event file, process 0's ``events.jsonl`` included
    — the ONE place the fleet's file layout is spelled (merge, live
    gauges and the watch console all read through this).  Covers both
    layouts: the mega fleet's ``events-p<i>.jsonl`` siblings and the
    serve pool's ``workers/w<i>/events.jsonl`` sub-roots (front = 0,
    worker i = lane i+1)."""
    paths = {0: os.path.join(run_dir, "events.jsonl")}
    paths.update(worker_event_paths(run_dir))
    paths.update(pool_worker_event_paths(run_dir))
    return paths


def merged_timeline(run_dir: str) -> Tuple[List[dict], int]:
    """All processes' event rows as one ordered timeline (see the module
    docstring for the ordering rule); returns ``(rows, skipped)``."""
    sources = sorted(event_paths(run_dir).items())
    stamped = []
    skipped = 0
    for process, path in sources:
        # a non-zero lane whose file is a bare events.jsonl is a pool
        # worker sub-root: its rows say process 0 (each worker is a solo
        # service) and the layout, not the row, names the lane
        force = process != 0 and os.path.basename(path) == "events.jsonl"
        rows, bad = load_rows(path, process, force_process=force)
        skipped += bad
        for seq, row in enumerate(rows):
            stamped.append((float(row.get("t", 0.0)),
                            int(row.get("process", process)), seq, row))
    stamped.sort(key=lambda item: item[:3])
    return [row for _t, _p, _s, row in stamped], skipped


# ---------------------------------------------------------------------------
# per-process lanes and straggler attribution
# ---------------------------------------------------------------------------


def _fold_lane(lanes: Dict[int, dict], row: dict) -> None:
    p = int(row.get("process", 0))
    lane = lanes.setdefault(p, {"beats": 0, "spans": 0, "restarts": 0,
                                "rates": []})
    kind = row.get("kind")
    if kind == "heartbeat":
        lane["beats"] += 1
        lane["stage"] = row.get("stage")
        lane["last_t"] = row.get("t")
        if row.get("generation") is not None:
            lane["generation"] = int(row["generation"])
        if row.get("total_generations") is not None:
            lane["total_generations"] = int(row["total_generations"])
        if row.get("gens_per_sec") is not None:
            lane["rates"].append(float(row["gens_per_sec"]))
            lane["gens_per_sec"] = float(row["gens_per_sec"])
        if row.get("rss_mb") is not None:
            lane["rss_mb"] = row["rss_mb"]
    elif kind == "span":
        lane["spans"] += 1
    elif kind == "restart":
        lane["restarts"] += 1


def _close_lanes(lanes: Dict[int, dict]) -> Dict[int, dict]:
    for lane in lanes.values():
        rates = lane.pop("rates")
        if rates:
            lane["gens_per_sec_p50"] = round(
                quantile_from_times(rates, 0.5), 3)
            lane["gens_per_sec_min"] = round(min(rates), 3)
            lane["gens_per_sec_max"] = round(max(rates), 3)
    return lanes


def straggler_attribution(rates: Dict[int, float],
                          generations: Dict[int, int]) -> Optional[dict]:
    """Who holds the fleet back: ``rates`` maps process -> gens/sec (the
    lane's p50 offline, the LAST beat live), ``generations`` maps
    process -> newest reported generation.  Returns ``None`` when no
    process has reported a rate yet; single-process runs return a
    degenerate (skew 1.0) attribution so callers need no mode split."""
    known = {p: float(r) for p, r in rates.items()
             if r is not None and float(r) > 0}
    if not known:
        return None
    slow = min(sorted(known), key=lambda p: known[p])
    fast = max(sorted(known), key=lambda p: known[p])
    lead = max(generations.values()) if generations else 0
    return {
        "straggler_process": slow,
        "fastest_process": fast,
        "skew_ratio": round(known[fast] / known[slow], 4),
        "lag_generations": int(lead - generations.get(slow, lead)),
        "gens_per_sec": {int(p): round(known[p], 3) for p in sorted(known)},
    }


def update_straggler_gauges(registry, attribution: dict) -> None:
    """Export one attribution as the ``soup_straggler_*`` gauges
    (``telemetry/names.py``)."""
    g = registry.gauge
    g("soup_straggler_process",
      help="process id currently slowest by gens/sec").set(
        attribution["straggler_process"])
    g("soup_straggler_skew_ratio",
      help="fastest/slowest per-process gens/sec ratio (1.0 = no "
           "skew)").set(attribution["skew_ratio"])
    g("soup_straggler_lag_generations",
      help="generations the straggler trails the fleet leader").set(
        attribution["lag_generations"])
    for p, rate in attribution["gens_per_sec"].items():
        g("soup_straggler_gens_per_second",
          help="per-process generation rate from the last heartbeat",
          unit="1/s").set(rate, process=str(p))


def live_attribution(run_dir: str,
                     num_processes: int) -> Optional[dict]:
    """Cheap in-run attribution for the chunk finisher: tail-read each
    process's event file (bounded bytes), take the LAST heartbeat's rate
    and generation per process.  Pure file reads — safe on the
    background writer thread, never a collective."""
    rates: Dict[int, float] = {}
    gens: Dict[int, int] = {}
    paths = event_paths(run_dir)
    for p in range(num_processes):
        path = paths.get(p)
        if path is None:
            continue
        rows, _bad = load_rows(path, p, tail_bytes=_TAIL_BYTES)
        for row in reversed(rows):
            if row.get("kind") == "heartbeat" \
                    and row.get("gens_per_sec") is not None:
                rates[p] = float(row["gens_per_sec"])
                if row.get("generation") is not None:
                    gens[p] = int(row["generation"])
                break
    return straggler_attribution(rates, gens)


# ---------------------------------------------------------------------------
# summaries + renderer (report --fleet / telemetry.watch backends)
# ---------------------------------------------------------------------------


def list_checkpoints(run_dir: str) -> List[str]:
    return sorted(
        os.path.basename(p)
        for p in glob.glob(os.path.join(run_dir, "ckpt-gen*"))
        if p.rsplit("gen", 1)[1].isdigit())


def fleet_summary(run_dir: str, timeline_tail: int = 16) -> dict:
    """Machine-readable fleet view of one run dir (the ``report --fleet
    --json`` output; :func:`render_fleet` formats it, ``telemetry.watch``
    refreshes it)."""
    timeline, skipped = merged_timeline(run_dir)
    lanes: Dict[int, dict] = {}
    for row in timeline:
        _fold_lane(lanes, row)
    _close_lanes(lanes)
    rates = {p: lane.get("gens_per_sec_p50", lane.get("gens_per_sec"))
             for p, lane in lanes.items()}
    gens = {p: lane["generation"] for p, lane in lanes.items()
            if "generation" in lane}
    ckpts = list_checkpoints(run_dir)
    # timeline_tail=0 means NO tail (the watch snapshot) — a bare [-0:]
    # would project every row of a long run only to be thrown away
    tail = [{k: r.get(k) for k in ("t", "process", "kind", "stage",
                                   "generation", "span", "seconds",
                                   "message")
             if r.get(k) is not None}
            for r in (timeline[-timeline_tail:] if timeline_tail else [])]
    return {
        "run_dir": os.path.abspath(run_dir),
        "processes": {str(p): lanes[p] for p in sorted(lanes)},
        "worker_files": [os.path.basename(p) for _i, p in
                         sorted(worker_event_paths(run_dir).items())]
                        + [os.path.relpath(p, run_dir) for _i, p in
                           sorted(pool_worker_event_paths(run_dir).items())],
        "straggler": straggler_attribution(rates, gens),
        "timeline_rows": len(timeline),
        "skipped_lines": skipped,
        "checkpoints": len(ckpts),
        "latest_checkpoint": ckpts[-1] if ckpts else None,
        # a just-created run dir (no events.jsonl yet, or only torn/empty
        # files) is a NORMAL state the watch loop and report must name,
        # not an implicit empty render
        "no_data": not timeline,
        "timeline_tail": tail,
    }


def _fmt_rate(lane: dict) -> str:
    p50 = lane.get("gens_per_sec_p50")
    if p50 is None:
        return ""
    return (f"gens/s p50={p50:.2f} "
            f"[{lane.get('gens_per_sec_min', 0):.2f}.."
            f"{lane.get('gens_per_sec_max', 0):.2f}]")


def render_fleet(s: dict, out) -> None:
    """The per-process lane view of one :func:`fleet_summary`."""
    w = out.write
    nproc = len(s["processes"])
    w(f"fleet: {s['run_dir']}\n")
    if s.get("no_data"):
        w("  no data yet — no parseable event rows in this run dir (a "
          "just-created run, or one killed before its first write); "
          "re-check once the run heartbeats\n")
        return
    w(f"  {nproc} process lane(s), {s['timeline_rows']} merged timeline "
      f"rows"
      + (f", {s['skipped_lines']} unparseable line(s) skipped"
         if s["skipped_lines"] else "")
      + (f"; worker files: {', '.join(s['worker_files'])}"
         if s["worker_files"] else "; no worker files (single-process "
                                   "run dir)")
      + "\n")
    if s["latest_checkpoint"]:
        w(f"  checkpoints: {s['checkpoints']} "
          f"(latest {s['latest_checkpoint']})\n")
    w("lanes:\n")
    for pid, lane in sorted(s["processes"].items(), key=lambda kv:
                            int(kv[0])):
        gen = lane.get("generation")
        tot = lane.get("total_generations")
        where = f"gen {gen}/{tot}" if gen is not None and tot \
            else (f"gen {gen}" if gen is not None else "(no heartbeat)")
        bits = [f"{lane.get('stage') or '?':<22}", f"{where:<12}",
                _fmt_rate(lane), f"beats={lane['beats']}"]
        if lane.get("spans"):
            bits.append(f"spans={lane['spans']}")
        if lane.get("restarts"):
            bits.append(f"restarts={lane['restarts']}")
        if lane.get("rss_mb") is not None:
            bits.append(f"rss={lane['rss_mb']}MB")
        w(f"  p{pid}  " + "  ".join(b for b in bits if b) + "\n")
    att = s.get("straggler")
    if att and len(s["processes"]) > 1:
        rates = "  ".join(f"p{p}={r:.2f}"
                          for p, r in att["gens_per_sec"].items())
        w(f"straggler: p{att['straggler_process']} — skew "
          f"{att['skew_ratio']}x vs p{att['fastest_process']}, trailing "
          f"{att['lag_generations']} generation(s)  ({rates} gens/s)\n")
    if s["timeline_tail"]:
        w("timeline tail (merged):\n")
        for r in s["timeline_tail"]:
            t = r.get("t")
            stamp = f"{t:8.2f}s" if isinstance(t, (int, float)) else "       ?"
            body = r.get("kind", "log")
            if r.get("span"):
                body += f" {r['span']} {r.get('seconds', 0):.4f}s"
            elif r.get("stage"):
                body += f" {r['stage']}"
            if r.get("generation") is not None:
                body += f" gen={r['generation']}"
            if r.get("message") and body == "log":
                body = str(r["message"])[:60]
            w(f"  [{stamp} p{r.get('process', 0)}] {body}\n")


# ---------------------------------------------------------------------------
# Perfetto / Chrome trace export (report --trace)
# ---------------------------------------------------------------------------

#: thread-lane ids inside each process's trace group: host spans (chunk
#: roots + device_wait/host_io children, hostio collectives) vs the
#: serve tier's per-ticket span families
_TID_SPANS = 1
_TID_SERVE = 2
_TID_EVENTS = 3


def profiler_trace_dirs(run_dir: str) -> List[str]:
    """Device-trace directories linked from this run: the armed
    ``jax.profiler`` traces inside the run's flight-recorder triage
    bundles (``triage-*/trace``) and anomaly-capture bundles
    (``anomaly/<rule>-<seq>/trace``), non-empty only.  A wedged TPU
    attempt's bundle thereby joins the same export instead of rotting
    unfound."""
    out = []
    for bundle in sorted(glob.glob(os.path.join(run_dir, "triage-*"))
                         + glob.glob(os.path.join(run_dir, "anomaly",
                                                  "*"))):
        trace = os.path.join(bundle, "trace")
        try:
            if os.path.isdir(trace) and any(os.scandir(trace)):
                out.append(os.path.abspath(trace))
        except OSError:
            continue
    return out


def _span_event(row: dict) -> Optional[dict]:
    """One span row -> a Chrome 'complete' event (``ph=X``).  Structured
    SpanStream rows carry ``start_s``; legacy span rows (PR 2 ``span()``)
    only ``t`` + ``seconds`` — their start is derived."""
    dur = row.get("seconds")
    if not isinstance(dur, (int, float)):
        return None
    start = row.get("start_s")
    if not isinstance(start, (int, float)):
        t = row.get("t")
        if not isinstance(t, (int, float)):
            return None
        start = max(0.0, float(t) - float(dur))
    name = str(row.get("span", "span"))
    args = {k: row[k] for k in ("trace_id", "tenant", "request_kind",
                                "generation", "generations", "stage",
                                "mode", "stack_k", "per_tenant_s", "error",
                                "ticket", "remote_parent", "worker",
                                "worker_ticket", "replays", "replayed")
            if row.get(k) is not None}
    serve_lane = name.startswith("serve.") or name.startswith("front.")
    return {"name": name, "ph": "X", "cat": "span",
            "ts": round(float(start) * 1e6, 1),
            "dur": round(float(dur) * 1e6, 1),
            "pid": int(row.get("process", 0)),
            "tid": _TID_SERVE if serve_lane else _TID_SPANS,
            "args": args}


def _flow_events(span_rows: List[Tuple[dict, dict]]) -> List[dict]:
    """Perfetto flow arrows for the pool hop: every span carrying a
    ``remote_parent`` (a propagated trace-context parent from ANOTHER
    process) is bound back to the span that minted that id — the front's
    ``front.relay``/``front.replay`` — as a paired ``ph:"s"`` (start, at
    the source span's end) / ``ph:"f", bp:"e"`` (finish, at the dest
    span's start) flow.  Span ids are only unique per process, so
    resolution keys on ``(trace_id, span_id)``, requires a DIFFERENT
    pid, and prefers a ``front.*`` source when ids collide across
    lanes.  Cross-process clocks are approximate (module docstring); the
    start stamp is clamped so an arrow never points backwards."""
    sources: Dict[Tuple[str, int], List[Tuple[dict, dict]]] = {}
    for row, ev in span_rows:
        if row.get("trace_id") is not None \
                and row.get("span_id") is not None:
            key = (str(row["trace_id"]), int(row["span_id"]))
            sources.setdefault(key, []).append((row, ev))
    out: List[dict] = []
    flow_id = 0
    for row, ev in span_rows:
        rp = row.get("remote_parent")
        if rp is None or row.get("trace_id") is None:
            continue
        cands = [s for s in sources.get((str(row["trace_id"]), int(rp)), [])
                 if s[1]["pid"] != ev["pid"]]
        if not cands:
            continue
        pref = [s for s in cands
                if str(s[0].get("span", "")).startswith("front.")]
        _src_row, src_ev = (pref or cands)[0]
        flow_id += 1
        start_ts = min(round(src_ev["ts"] + src_ev["dur"], 1), ev["ts"])
        out.append({"name": "hop", "cat": "flow", "ph": "s", "id": flow_id,
                    "ts": start_ts, "pid": src_ev["pid"],
                    "tid": src_ev["tid"],
                    "args": {"trace_id": row["trace_id"]}})
        out.append({"name": "hop", "cat": "flow", "ph": "f", "bp": "e",
                    "id": flow_id, "ts": ev["ts"], "pid": ev["pid"],
                    "tid": ev["tid"],
                    "args": {"trace_id": row["trace_id"]}})
    return out


def perfetto_trace(run_dir: str) -> dict:
    """The PR 12 merged fleet timeline as a Chrome/Perfetto-loadable
    trace document (``chrome://tracing`` / ui.perfetto.dev JSON object
    format): one ``pid`` group per process with named lanes — host spans,
    serve-ticket slices — plus gens/sec counter tracks from the
    heartbeats, utilization counter tracks (device-busy / host-blocked /
    idle fractions) from each flushed metrics row, and instant markers
    for restarts/watchdog trips/preempts.
    Timestamps are the run-relative monotonic seconds every process
    already stamps (microseconds in the export, per the trace format).

    The armed ``jax.profiler`` device traces of any triage bundle in the
    run dir are LINKED under ``otherData.device_traces`` — a wedged
    device attempt leaves a loadable trace pointer in the same bundle
    instead of a dead bench row."""
    timeline, skipped = merged_timeline(run_dir)
    events: List[dict] = []
    span_rows: List[Tuple[dict, dict]] = []
    pids = set()
    for row in timeline:
        pid = int(row.get("process", 0))
        kind = row.get("kind")
        if kind == "span":
            ev = _span_event(row)
            if ev is not None:
                pids.add(pid)
                events.append(ev)
                span_rows.append((row, ev))
        elif kind == "heartbeat":
            t = row.get("t")
            if isinstance(t, (int, float)) \
                    and row.get("gens_per_sec") is not None:
                pids.add(pid)
                events.append({
                    "name": "gens_per_sec", "ph": "C", "cat": "heartbeat",
                    "ts": round(float(t) * 1e6, 1), "pid": pid,
                    "args": {"gens_per_sec": float(row["gens_per_sec"])}})
        elif kind == "metrics":
            # the profiling plane's utilization decomposition as ONE
            # stacked counter track per process: device_busy /
            # host_blocked / idle fractions of each flushed chunk
            t = row.get("t")
            m = row.get("metrics") or {}
            util = {k[len("srnn_soup_utilization_"):]: float(v)
                    for k, v in m.items()
                    if k.startswith("srnn_soup_utilization_")}
            if util and isinstance(t, (int, float)):
                pids.add(pid)
                events.append({
                    "name": "utilization", "ph": "C", "cat": "profile",
                    "ts": round(float(t) * 1e6, 1), "pid": pid,
                    "args": util})
        elif kind in ("restart", "watchdog", "preempt", "cost", "alert"):
            t = row.get("t")
            if isinstance(t, (int, float)):
                pids.add(pid)
                name = kind if kind != "alert" \
                    else f"alert:{row.get('rule', '?')}:" \
                         f"{row.get('state', '?')}"
                events.append({
                    "name": name, "ph": "i", "s": "p", "cat": "marker",
                    "ts": round(float(t) * 1e6, 1), "pid": pid,
                    "tid": _TID_EVENTS,
                    "args": {k: row[k] for k in
                             ("reasons", "fault", "generation", "entry",
                              "flops", "bundle", "rule", "state", "value",
                              "threshold") if row.get(k) is not None}})
    events.extend(_flow_events(span_rows))
    for pid in sorted(pids):
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": f"p{pid}"}})
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": _TID_SPANS, "args": {"name": "host spans"}})
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": _TID_SERVE,
                       "args": {"name": "serve tickets"}})
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": _TID_EVENTS, "args": {"name": "markers"}})
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "run_dir": os.path.abspath(run_dir),
            "processes": sorted(pids),
            "skipped_lines": skipped,
            "device_traces": profiler_trace_dirs(run_dir),
        },
    }


# ---------------------------------------------------------------------------
# single-request traces (report --trace-request)
# ---------------------------------------------------------------------------


def _exemplar_family(run_dir: str,
                     want: str) -> Tuple[List[dict], Optional[str]]:
    """Span rows for ``want`` recovered from the exemplar rings — the
    fallback when the event files have rotated past the ticket but
    tail-retention kept it.  The front's ring sits at the run-dir root
    (lane 0), each pool worker's next to its own events file (lane i+1);
    a worker ring is keyed by the WORKER's ticket, so once any ring
    yields the trace id, the others are re-searched by it."""
    from .exemplars import EXEMPLARS_NAME, find_exemplar

    ex_paths = [(0, os.path.join(run_dir, EXEMPLARS_NAME))]
    for p, epath in sorted(pool_worker_event_paths(run_dir).items()):
        ex_paths.append((p, os.path.join(os.path.dirname(epath),
                                         EXEMPLARS_NAME)))
    recs: Dict[int, dict] = {}
    trace_id: Optional[str] = None
    for p, path in ex_paths:
        rec = find_exemplar(path, want)
        if rec is not None:
            recs[p] = rec
            if trace_id is None and rec.get("trace_id") is not None:
                trace_id = str(rec["trace_id"])
    if trace_id is not None and trace_id != want:
        for p, path in ex_paths:
            if p not in recs:
                rec = find_exemplar(path, trace_id)
                if rec is not None:
                    recs[p] = rec
    rows: List[dict] = []
    for p, rec in sorted(recs.items()):
        for s in rec.get("spans") or ():
            if isinstance(s, dict):
                row = dict(s)
                row["process"] = p
                rows.append(row)
    return rows, trace_id


_TRACE_SPAN_KEYS = ("process", "span", "span_id", "parent", "remote_parent",
                    "start_s", "seconds", "ticket", "worker",
                    "worker_ticket", "replays", "replayed", "error",
                    "tenant", "request_kind", "mode")


def trace_request(run_dir: str, ticket: str) -> Optional[dict]:
    """Everything known about ONE request's trace: resolve ``ticket`` (a
    front or worker ticket id, or a trace id) to its trace, collect the
    full cross-process span family, and compute the critical-path
    breakdown of the final ``serve.ticket`` root.  Primary source is the
    merged timeline; the exemplar rings are the fallback for tickets the
    event files no longer hold.  Returns ``None`` when nobody knows the
    ticket.  Per-lane clocks are each process's run-relative stamps, so
    cross-lane offsets are approximate (module docstring)."""
    want = str(ticket)
    timeline, _skipped = merged_timeline(run_dir)
    spans = [r for r in timeline if r.get("kind") == "span"]
    trace_id: Optional[str] = None
    for r in spans:
        if str(r.get("ticket")) == want or str(r.get("trace_id")) == want:
            trace_id = str(r.get("trace_id") or want)
            break
    family: List[dict] = []
    source = "events"
    if trace_id is not None:
        family = [r for r in spans if str(r.get("trace_id")) == trace_id]
    if not family:
        family, trace_id = _exemplar_family(run_dir, want)
        source = "exemplars"
    if not family:
        return None
    family.sort(key=lambda r: (int(r.get("process", 0)),
                               float(r.get("start_s") or r.get("t") or 0.0)))
    procs = sorted({int(r.get("process", 0)) for r in family})
    hops = sum(1 for r in family if r.get("remote_parent") is not None)
    by_name: Dict[str, dict] = {}
    for r in family:
        d = by_name.setdefault(str(r.get("span", "?")),
                               {"count": 0, "total_s": 0.0})
        d["count"] += 1
        d["total_s"] += float(r.get("seconds") or 0.0)
    for d in by_name.values():
        d["total_s"] = round(d["total_s"], 6)
    # critical path of the FINAL root (post-replay on a replayed ticket):
    # the serve.ticket wall split across its direct children
    crit: List[dict] = []
    root_s = None
    roots = [r for r in family if r.get("span") == "serve.ticket"]
    if roots:
        root = roots[-1]
        rid = root.get("span_id")
        rp = int(root.get("process", 0))
        root_s = float(root.get("seconds") or 0.0)
        for r in family:
            if r.get("parent") == rid and int(r.get("process", 0)) == rp \
                    and r is not root:
                sec = float(r.get("seconds") or 0.0)
                crit.append({
                    "span": str(r.get("span", "?")),
                    "seconds": round(sec, 6),
                    "fraction": round(sec / root_s, 4) if root_s > 0
                    else None})
    return {
        "run_dir": os.path.abspath(run_dir),
        "ticket": want,
        "trace_id": trace_id,
        "source": source,
        "processes": procs,
        "cross_process_links": hops,
        "spans": [{k: r.get(k) for k in _TRACE_SPAN_KEYS
                   if r.get(k) is not None} for r in family],
        "by_name": by_name,
        "root_seconds": round(root_s, 6) if root_s is not None else None,
        "critical_path": crit,
    }
